"""End-to-end TPC-H benchmark: Q1/Q3/Q5 through Session.execute.

Both sides of the comparison are MEASURED from this harness on the same
machine, the same store, and the same SQL (BASELINE.md: the reference
publishes no numbers, so the baseline is the host chunk executor — the
moral equivalent of the Go HashAggExec/HashJoinExec path, vectorized
numpy over the same columnar chunks):

  * device mode: tidb_tpu_device=1 + a process mesh over the visible
    chip(s) — scans feed the fused XLA kernels (filter/group/agg,
    lookup-join star pipelines), only group tables return to the host.
  * host mode: tidb_tpu_device=0, mesh disabled — identical plans run the
    vectorized numpy operators.

Timings are full Session.execute wall time: plan (cached), coprocessor
fan-out, storage scan + decode (served by the columnar chunk cache when
hot, exactly like repeated analytical queries in practice), kernel
execution, result formatting. The two modes must agree on results (checked
every iteration, approx-compare on floats).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
value = geometric mean over Q1/Q3/Q5 of end-to-end input rows/sec on the
device path; vs_baseline = geomean of per-query device/host speedups.

Env knobs: BENCH_SF (default 1.0), BENCH_ITERS (5), BENCH_HOST_ITERS (2),
BENCH_REGIONS (4), BENCH_KERNEL_MICRO (1), BENCH_SKIP_PROBE (0; 1 skips
the device-liveness probes and trusts the default platform),
BENCH_PROBE_ATTEMPTS (2) / BENCH_PROBE_TIMEOUT (120s) — the probe
retries with backoff (~4.5 min at the defaults) so one tunnel flap
doesn't condemn the run,
BENCH_CPU_SF (0.2; scale used when the chip tunnel is down and no
explicit BENCH_SF was given — CPU XLA is ~20-40x slower than a chip).

Reported alongside rows/s: per-query device_scan_gbps (input bytes over
device wall time) and roofline_fraction against the platform's memory
peak (chip: HBM datasheet number by device kind; CPU fallback: measured
memcpy bandwidth), so "fast" is judged against hardware limits.
"""

from __future__ import annotations

import json
import math
import os
import sys
import threading
import time

# Persistent XLA compilation cache: first-compile of the big fused query
# programs costs minutes through the chip tunnel; caching them on disk
# makes every later bench process (including the driver's round-end run)
# reuse the compiled executables. TIDB_TPU_COMPILE_CACHE routes the
# package's own wiring (tidb_tpu.util.compile_cache — which also counts
# hits/misses for the report) at the same repo-local directory; the
# JAX_* variables cover subprocess probes that never import the package.
_CACHE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          ".jax_cache")
os.environ.setdefault("TIDB_TPU_COMPILE_CACHE", _CACHE_DIR)
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.environ["TIDB_TPU_COMPILE_CACHE"])
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "2")


def _approx_rows_equal(a, b) -> bool:
    if len(a) != len(b):
        return False
    for ra, rb in zip(a, b):
        if len(ra) != len(rb):
            return False
        for x, y in zip(ra, rb):
            if isinstance(x, float) or isinstance(y, float):
                fx, fy = float(x), float(y)
                if abs(fx - fy) > max(1e-6, abs(fy) * 1e-9):
                    return False
            elif x != y:
                return False
    return True


def _time_query(session, sql: str, iters: int) -> tuple[float, list]:
    """-> (best seconds, rows). Best-of keeps scheduler noise out; every
    iteration runs the full Session.execute path."""
    best = math.inf
    rows = None
    for _ in range(iters):
        t0 = time.perf_counter()
        r = session.query(sql)
        dt = time.perf_counter() - t0
        best = min(best, dt)
        rows = r.rows
    return best, rows


def _kernel_micro() -> float:
    """Kernel-only dispatch number (the old benchmark), reported
    separately from the end-to-end figures. Each call includes the
    (small) group-table device->host read; the input chunk stays
    device-resident via the transfer memo."""
    from __graft_entry__ import _lineitem_chunk, _q1_exprs
    from tidb_tpu.ops.hashagg import HashAggKernel

    chunk = _lineitem_chunk(1 << 20)
    flt, groups, aggs = _q1_exprs()
    kernel = HashAggKernel(flt, groups, aggs, capacity=64)
    kernel(chunk)  # compile + fill the device transfer memo
    iters = 8
    t0 = time.perf_counter()
    for _ in range(iters):
        kernel(chunk)
    dt = time.perf_counter() - t0
    return chunk.num_rows * iters / dt


_PROBE_CODE = (
    "import json, jax\n"
    "ds = jax.devices()\n"
    "print('BENCH_PROBE ' + json.dumps({\n"
    "    'platform': ds[0].platform,\n"
    "    'device_count': len(ds),\n"
    "    'device_kinds': sorted({d.device_kind for d in ds}),\n"
    "}))\n"
)


def _probe_devices(timeout_s: int = 120):
    """-> device-inventory dict if jax.devices() answers within timeout
    in a THROWAWAY subprocess, else None. A dead chip tunnel makes any
    jax call in-process hang unrecoverably, so the probe must be
    expendable — the bench process itself NEVER touches backend init
    until a probe has answered (or it has pinned itself to CPU)."""
    import subprocess
    try:
        r = subprocess.run([sys.executable, "-c", _PROBE_CODE],
                           timeout=timeout_s, capture_output=True,
                           text=True)
    except (subprocess.TimeoutExpired, OSError):
        return None
    for line in r.stdout.splitlines():
        if line.startswith("BENCH_PROBE "):
            try:
                return json.loads(line[len("BENCH_PROBE "):])
            except ValueError:
                return None
    return None


class _DeviceProber:
    """Background chip acquisition: probes the TPU tunnel in short-lived
    subprocesses and KEEPS re-probing across the whole run, snapshotting
    the device inventory the moment the tunnel answers (VERDICT "Next
    round" #1 — the same expendable-subprocess trick as
    __graft_entry__.py:72-96). The bench decides device-vs-CPU once at
    the initial window; a late answer can't switch an initialized jax
    platform mid-process, but it IS recorded in the report so the driver
    knows the tunnel recovered and a re-run would land on chip."""

    def __init__(self):
        self.attempts = int(os.environ.get("BENCH_PROBE_ATTEMPTS", "2"))
        self.timeout_s = int(os.environ.get("BENCH_PROBE_TIMEOUT", "120"))
        self.reprobe_interval = int(
            os.environ.get("BENCH_REPROBE_INTERVAL", "60"))
        self.snapshot = None         # first successful inventory
        self.snapshot_at = None      # perf_counter of that success
        self._initial_done = threading.Event()
        self._stop = threading.Event()
        self._thread = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="bench-device-prober")
        self._thread.start()

    def _loop(self) -> None:
        # initial window: `attempts` probes with backoff (the decision
        # gate), then periodic re-probes until success or run end
        for i in range(self.attempts):
            if self._stop.is_set():
                self._initial_done.set()
                return
            got = _probe_devices(self.timeout_s)
            if got is not None:
                self._record(got)
                self._initial_done.set()
                return
            if i < self.attempts - 1:
                wait = 30 * (i + 1)
                print(f"[bench] device probe {i + 1}/{self.attempts} "
                      f"failed; retrying in {wait}s",
                      file=sys.stderr, flush=True)
                if self._stop.wait(wait):
                    self._initial_done.set()
                    return
        self._initial_done.set()
        while not self._stop.wait(self.reprobe_interval):
            got = _probe_devices(self.timeout_s)
            if got is not None and got.get("platform") != "cpu":
                # a REAL chip answered late — the recovery worth
                # reporting; cpu-only answers say nothing new about the
                # tunnel, so keep probing
                self._record(got)
                return

    def _record(self, got: dict) -> None:
        # order matters: main() reads `snapshot` unlocked as the
        # "did it answer" flag, so its timestamp must already be set
        self.snapshot_at = time.perf_counter()
        self.snapshot = got
        print(f"[bench] tunnel answered: {got}", file=sys.stderr,
              flush=True)

    def wait_initial(self) -> bool:
        """Block until the initial probe window resolves.
        -> True when a device answered within it."""
        self._initial_done.wait()
        return self.snapshot is not None

    def stop(self) -> None:
        self._stop.set()


def _memory_roofline_gbps() -> tuple[float, str]:
    """-> (peak GB/s, how it was obtained). Thin delegate: the estimator
    (datasheet table by device kind, measured memcpy on CPU) lives in
    tidb_tpu.profiler now, where the continuous per-kernel roofline
    fractions use the same peak the bench normalizes against."""
    from tidb_tpu import profiler
    return profiler.platform_peak_gbps()


def _hbm_counters() -> dict:
    """HBM region-block cache counters (store/device_cache.py): the
    warm/cold series' companion — warm runs should be all hits."""
    from tidb_tpu import metrics
    snap = metrics.snapshot()
    return {"hits": int(snap.get(metrics.HBM_CACHE_HITS, 0)),
            "misses": int(snap.get(metrics.HBM_CACHE_MISSES, 0)),
            "evictions": int(snap.get(metrics.HBM_CACHE_EVICTIONS, 0))}


_TABLE_PREFIX = {"region": "r_", "nation": "n_", "customer": "c_",
                 "supplier": "s_", "orders": "o_", "lineitem": "l_"}


def _query_bytes(data, qname: str) -> int:
    """Bytes the query's input tables occupy in the columnar chunk
    layout: 8-byte lanes for fixed-width columns, utf8 length for
    strings — the device path's scan traffic upper bound."""
    from tidb_tpu.benchmarks import tpch
    import numpy as _np
    total = 0
    for tname in tpch.QUERY_TABLES[qname]:
        pref = _TABLE_PREFIX[tname]
        for name in vars(data):
            if not name.startswith(pref):
                continue
            a = _np.asarray(getattr(data, name))
            if a.ndim != 1:
                continue
            if a.dtype == _np.dtype(object):
                total += int(sum(len(str(x)) for x in a))
            else:
                total += int(a.size * 8)
    return total


def _bytes_counters() -> dict:
    """Encoded-execution bytes-touched counters (ops/encoded.py):
    encoded bytes device agg/fragment dispatches actually staged or
    read vs the decoded-equivalent footprint of the same inputs — the
    per-query `bytes_touched` column diffs these around the warm
    iterations so the compression win is auditable."""
    from tidb_tpu import metrics
    snap = metrics.snapshot()
    return {"encoded": int(snap.get(metrics.BYTES_ENCODED, 0)),
            "decoded_equivalent": int(
                snap.get(metrics.BYTES_DECODED_EQUIV, 0))}


def _bytes_touched(b0: dict, b1: dict) -> dict:
    enc = b1["encoded"] - b0["encoded"]
    dec = b1["decoded_equivalent"] - b0["decoded_equivalent"]
    return {"decoded_equivalent_bytes": dec, "encoded_bytes": enc,
            "ratio": round(enc / dec, 4) if dec else None}


def _fallback_counters() -> dict:
    """Hybrid join/agg counters (ops/hybrid.py): device->host fallbacks
    (must stay 0 on the skewed workload), partitions spilled under
    quota, and heavy-hitter lane traffic."""
    from tidb_tpu import metrics
    snap = metrics.snapshot()

    def total(prefix):
        return int(sum(v for k, v in snap.items() if k.startswith(prefix)))

    return {"fallbacks": total(metrics.DEVICE_FALLBACKS),
            "partitions_spilled": total(metrics.JOIN_SPILL_PARTITIONS),
            "hot_lane_rows": total(metrics.JOIN_HOT_ROWS)}


def _skew_join_bench(session, storage, sf: float, iters: int,
                     host_iters: int, progress) -> dict:
    """Deliberately Zipf-skewed join + high-cardinality agg: the
    workload that used to fall off the device (invisible host fallback
    at the copr/executor except nets, quota cancel on the join build).
    The acceptance bar after the hybrid join/agg: the device run pays
    ZERO fallbacks, routes the heavy hitter through the broadcast lane,
    and beats the host path. -> the BENCH json `skew_join` block."""
    import numpy as _np
    from tidb_tpu import config
    from tidb_tpu.table import Table, bulkload

    rng = _np.random.default_rng(20260803)
    n_dim = max(4096, int(20000 * sf))
    n_fact = max(30000, int(400000 * sf))
    session.execute("CREATE TABLE skew_c (id BIGINT PRIMARY KEY, "
                    "seg BIGINT)")
    session.execute("CREATE TABLE skew_o (id BIGINT PRIMARY KEY, "
                    "cid BIGINT, amt DOUBLE)")
    # Zipf-ish cid: a handful of ultra-hot keys (the top one ~30% of
    # rows) over a uniform tail, plus dangling keys past the dim table
    cid = rng.integers(0, n_dim + n_dim // 8, n_fact)
    hot_keys = (7, 42, 1001)
    for frac, hk in zip((0.30, 0.08, 0.04), hot_keys):
        cid[rng.random(n_fact) < frac] = hk
    ischema = session.domain.info_schema()
    db = session.current_db
    bulkload.bulk_load(storage, Table(ischema.table(db, "skew_c"),
                                      storage), {
        "id": _np.arange(n_dim, dtype=_np.int64),
        "seg": _np.arange(n_dim, dtype=_np.int64) % 11})
    bulkload.bulk_load(storage, Table(ischema.table(db, "skew_o"),
                                      storage), {
        "id": _np.arange(n_fact, dtype=_np.int64),
        "cid": cid.astype(_np.int64),
        "amt": rng.uniform(1, 100, n_fact).round(2)})
    # ANALYZE builds the probe-side CMSketch the planner hands the
    # hybrid join for heavy-hitter seeding
    session.execute("ANALYZE TABLE skew_o")
    session.execute("ANALYZE TABLE skew_c")

    queries = {
        "skew_join": "SELECT c.seg, COUNT(*), SUM(o.amt) FROM skew_o o "
                     "JOIN skew_c c ON o.cid = c.id GROUP BY c.seg "
                     "ORDER BY c.seg",
        "skew_agg": "SELECT cid, COUNT(*), SUM(amt) FROM skew_o "
                    "GROUP BY cid ORDER BY cid LIMIT 10",
    }
    threshold = max(4096, n_fact // 50)
    out: dict = {"rows": n_fact + n_dim,
                 "skew_threshold": threshold,
                 "join_partitions": config.join_partitions()}
    thr_prev = config.get_var("tidb_tpu_skew_threshold")
    session.execute(f"SET tidb_tpu_skew_threshold = {threshold}")
    in_rows = n_fact + n_dim
    speedups = []
    for name, sql in queries.items():
        config.set_var("tidb_tpu_device", 1)
        progress(f"{name}: device cold run")
        session.query(sql)      # compile + cache fill
        c0 = _fallback_counters()
        d_secs, d_rows = _time_query(session, sql, iters)
        c1 = _fallback_counters()
        try:
            config.set_var("tidb_tpu_device", 0)
            session.query(sql)
            h_secs, h_rows = _time_query(session, sql, host_iters)
        finally:
            # a host-leg failure must not leave the device switch off
            # for the rest of the bench (main() treats this whole block
            # as advisory and keeps going)
            config.set_var("tidb_tpu_device", 1)
        if not _approx_rows_equal(d_rows, h_rows):
            # RuntimeError, not SystemExit: main()'s advisory except
            # must catch this and keep the headline TPC-H numbers
            raise RuntimeError(f"{name}: device and host disagree")
        d_rps, h_rps = in_rows / d_secs, in_rows / h_secs
        speedups.append(d_rps / h_rps)
        out[name] = {
            "device_secs": round(d_secs, 4),
            "host_secs": round(h_secs, 4),
            "device_rows_per_sec": round(d_rps, 1),
            "host_rows_per_sec": round(h_rps, 1),
            "speedup": round(d_rps / h_rps, 2),
            # the acceptance bar: 0 after the hybrid join/agg
            "fallbacks": c1["fallbacks"] - c0["fallbacks"],
            "partitions_spilled": c1["partitions_spilled"] -
            c0["partitions_spilled"],
            "hot_lane_rows": c1["hot_lane_rows"] - c0["hot_lane_rows"],
        }
        progress(f"{name}: device {d_secs:.3f}s host {h_secs:.3f}s "
                 f"fallbacks {out[name]['fallbacks']}")
    out["speedup_geomean"] = round(math.exp(
        sum(math.log(x) for x in speedups) / len(speedups)), 3)
    # spill leg: re-run the join under quotas pinched below the
    # unconstrained peak until the spill action visibly fires — the
    # join must COMPLETE via partition spill, not cancel. Small
    # superchunks keep the in-flight probe footprint (which nothing
    # can shed) minor next to the evictable build residency, widening
    # the band where the spill saves the query.
    sc_prev = config.get_var("tidb_tpu_superchunk_rows")
    session.execute("SET tidb_tpu_superchunk_rows = 4096")
    try:
        session.query(queries["skew_join"])     # peak under the leg's
        mem = getattr(session, "_last_mem", None)  # own settings
        peak = (mem.host_peak + mem.device_peak) if mem is not None \
            else 0
        if peak > 1 << 16:
            for quota in (peak - (1 << 12), peak - (1 << 14),
                          peak - (1 << 15), peak - (1 << 16),
                          peak - (1 << 17), peak - (1 << 18)):
                c0 = _fallback_counters()
                try:
                    session.execute(
                        f"SET tidb_tpu_mem_quota_query = {quota}")
                    session.query(queries["skew_join"])
                    spilled = (
                        _fallback_counters()["partitions_spilled"] -
                        c0["partitions_spilled"])
                    out["quota_spill"] = {"quota_bytes": quota,
                                          "completed": True,
                                          "partitions_spilled": spilled}
                    if spilled:
                        break
                except Exception as e:  # noqa: BLE001 - record it
                    out["quota_spill"] = {"quota_bytes": quota,
                                          "completed": False,
                                          "error": str(e)}
                    break
                finally:
                    session.execute("SET tidb_tpu_mem_quota_query = 0")
    finally:
        session.execute(f"SET tidb_tpu_superchunk_rows = {sc_prev}")
        session.execute(f"SET tidb_tpu_skew_threshold = {thr_prev}")
    return out


def _htap_bench(progress) -> dict:
    """HTAP under write pressure (ISSUE 11 / ROADMAP item 5): a
    TPC-C-style new-order/payment write mix runs concurrently with a
    warm analytic loop over the same table, swept across write rates.
    Before the MVCC delta store (store/delta.py) ANY committed write
    re-colded both cache tiers, so analytic throughput fell to
    cold-scan speed at the first nonzero rate; now cached blocks serve
    as base ⋈ delta. Reports, per write rate: analytic rows/sec, p99
    write latency, write-to-visible freshness lag, and the delta/HBM
    counters — the acceptance bar is warm analytic rows/sec at a
    nonzero rate within 2x of the rate-0 number.

    Env knobs: BENCH_HTAP_ROWS (60000), BENCH_HTAP_SECS (5: seconds
    per rate window), BENCH_HTAP_RATES ("0,20,100" writes/sec)."""
    import numpy as _np
    from tidb_tpu import metrics
    from tidb_tpu.session import Session, SQLError
    from tidb_tpu.store.storage import new_mock_storage
    from tidb_tpu.table import Table, bulkload

    n_rows = int(os.environ.get("BENCH_HTAP_ROWS", "60000"))
    window = float(os.environ.get("BENCH_HTAP_SECS", "5"))
    rates = [int(x) for x in os.environ.get(
        "BENCH_HTAP_RATES", "0,20,100").split(",")]

    storage = new_mock_storage()
    session = Session(storage)
    session.execute("CREATE DATABASE htap")
    session.execute("USE htap")
    session.execute("CREATE TABLE stock (s_id BIGINT PRIMARY KEY, "
                    "s_seg BIGINT, s_qty BIGINT, s_ytd DOUBLE, "
                    "s_cnt BIGINT)")
    session.execute("CREATE TABLE orders (o_id BIGINT PRIMARY KEY, "
                    "o_item BIGINT, o_amt DOUBLE)")
    rng = _np.random.default_rng(20260804)
    progress(f"htap: loading {n_rows} stock rows")
    bulkload.bulk_load(storage, Table(
        session.domain.info_schema().table("htap", "stock"), storage), {
        "s_id": _np.arange(n_rows, dtype=_np.int64),
        "s_seg": _np.arange(n_rows, dtype=_np.int64) % 11,
        "s_qty": rng.integers(10, 100, n_rows),
        "s_ytd": rng.uniform(0, 1000, n_rows).round(2),
        "s_cnt": _np.zeros(n_rows, dtype=_np.int64)})
    analytic = ("SELECT s_seg, COUNT(*), SUM(s_qty), SUM(s_ytd), "
                "MAX(s_cnt) FROM stock GROUP BY s_seg ORDER BY s_seg")
    progress("htap: warming (compile + cache fill)")
    session.query(analytic)
    session.query(analytic)

    def counters() -> dict:
        snap = metrics.snapshot()

        def total(prefix):
            return int(sum(v for k, v in snap.items()
                           if k.startswith(prefix)))
        return {"served_with_delta": total(metrics.CACHE_DELTA_SERVES),
                "delta_merges": total(metrics.DELTA_MERGES),
                "hbm_hits": total(metrics.HBM_CACHE_HITS),
                "hbm_misses": total(metrics.HBM_CACHE_MISSES)}

    out: dict = {"rows": n_rows, "window_secs": window,
                 "rates": {}}
    seq_commit: dict = {}            # write seq -> commit wall time
    baseline_rps = None
    from tidb_tpu import perfschema as _ps
    htap_digests = {
        _ps.sql_digest(analytic)[0]: "analytic",
        _ps.sql_digest("UPDATE stock SET s_qty = s_qty - 1, "
                       "s_cnt = 1 WHERE s_id = 1")[0]: "write",
        _ps.sql_digest("INSERT INTO orders VALUES (1, 1, 9.99)")[0]:
            "write",
        _ps.sql_digest("UPDATE stock SET s_ytd = s_ytd + 1.5, "
                       "s_cnt = 1 WHERE s_id = 1")[0]: "write",
    }
    util_mark = _meter_mark()
    try:
        for rate in rates:
            stop = threading.Event()
            write_lat: list = []
            write_errs: list = []
            written = [0]
            seq0 = max(seq_commit, default=0)

            def writer(rate=rate, seq0=seq0):
                ws = Session(storage, db="htap")
                period = 1.0 / rate
                nxt = time.perf_counter()
                seq = seq0
                while not stop.is_set():
                    seq += 1
                    k = int((seq * 7919) % n_rows)
                    t0 = time.perf_counter()
                    try:
                        if seq % 2:     # new-order: touch stock + log
                            ws.execute(
                                f"UPDATE stock SET s_qty = s_qty - 1, "
                                f"s_cnt = {seq} WHERE s_id = {k}")
                            ws.execute(
                                f"INSERT INTO orders VALUES "
                                f"({seq}, {k}, 9.99)")
                        else:           # payment: money moves
                            ws.execute(
                                f"UPDATE stock SET s_ytd = s_ytd + 1.5,"
                                f" s_cnt = {seq} WHERE s_id = {k}")
                        seq_commit[seq] = time.perf_counter()
                        written[0] += 1
                    except SQLError as exc:
                        write_errs.append(str(exc))
                    write_lat.append(time.perf_counter() - t0)
                    nxt += period
                    delay = nxt - time.perf_counter()
                    if delay > 0:
                        time.sleep(delay)
                    else:
                        nxt = time.perf_counter()   # fell behind
                ws.close()

            c0 = counters()
            wt = None
            if rate > 0:
                wt = threading.Thread(target=writer, name="htap-writer")
                wt.start()
            progress(f"htap: rate {rate}/s window {window}s")
            queries = 0
            lag_samples: list = []
            seen = seq0
            errs: list = []
            t_start2 = time.perf_counter()
            while time.perf_counter() - t_start2 < window:
                rows = session.query(analytic).rows
                t_read = time.perf_counter()
                queries += 1
                if sum(r[1] for r in rows) != n_rows:
                    errs.append(f"COUNT mismatch: {rows}")
                    break
                top = max(r[4] for r in rows)
                if top > seen:
                    seen = top
                    t_commit = seq_commit.get(top)
                    if t_commit is not None:
                        lag_samples.append(t_read - t_commit)
            secs = time.perf_counter() - t_start2
            stop.set()
            if wt is not None:
                wt.join()
            c1 = counters()
            rps = queries * n_rows / secs
            if rate == 0 and baseline_rps is None:
                baseline_rps = rps
            out["rates"][str(rate)] = {
                "target_writes_per_sec": rate,
                "achieved_writes_per_sec": round(written[0] / secs, 1),
                "write_p99_ms": round(
                    _percentile(write_lat, 99) * 1e3, 2)
                if write_lat else None,
                "analytic_queries": queries,
                "analytic_rows_per_sec": round(rps, 1),
                "vs_read_only": round(rps / baseline_rps, 3)
                if baseline_rps else None,
                "freshness_ms_avg": round(
                    1e3 * sum(lag_samples) / len(lag_samples), 1)
                if lag_samples else None,
                "freshness_ms_max": round(1e3 * max(lag_samples), 1)
                if lag_samples else None,
                "errors": (errs + write_errs)[:3],
                "delta": {k: c1[k] - c0[k] for k in c0},
            }
            progress(f"htap: rate {rate}: {rps:,.0f} analytic rows/s, "
                     f"{written[0]} writes, "
                     f"delta serves {c1['served_with_delta'] - c0['served_with_delta']}")
        out["read_only_rows_per_sec"] = round(baseline_rps or 0.0, 1)
        nz = [v for k, v in out["rates"].items() if int(k) > 0]
        if nz and baseline_rps:
            out["min_vs_read_only"] = min(
                v["vs_read_only"] for v in nz)
        out["delta_rows_staged_end"] = \
            storage.delta_store.rows_current()
        # device utilization across the whole sweep: how much of the
        # wall the analytics plane kept the device busy under writes,
        # split analytic-vs-write by digest
        out["utilization"] = _utilization_block(util_mark, htap_digests)
    finally:
        session.close()
        storage.close()
    return out


def htap_main() -> None:
    """`python bench.py htap`: ONLY the HTAP write-pressure sweep — the
    CI entry point (scripts/htap_bench.sh) with its own one-line
    JSON."""
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        _scope_cpu_compile_cache()
    t_start = time.perf_counter()

    def progress(msg: str) -> None:
        print(f"[htap +{time.perf_counter() - t_start:7.1f}s] {msg}",
              file=sys.stderr, flush=True)

    htap = _htap_bench(progress)
    rates = htap.get("rates", {})
    top = max((int(k) for k in rates), default=0)
    print(json.dumps({
        "metric": "htap_analytic_rows_per_sec_under_writes",
        "value": rates.get(str(top), {}).get(
            "analytic_rows_per_sec", 0.0),
        "unit": "rows/s",
        "vs_baseline": htap.get("min_vs_read_only", 0.0),
        "detail": htap,
    }))


def _encoded_bench(progress) -> dict:
    """Encoded-vs-decoded warm comparison (ISSUE 12 / ROADMAP item 4):
    Q1 (dict group keys + direct-indexed agg) and Q3 (string-filtered
    join chain: encoded join-key lanes + fragment fusion) run warm with
    the encoded feature pair (`tidb_tpu_encoded_exec` AND
    `tidb_tpu_fuse_fragments`) on vs BOTH off — the baseline leg must
    not keep fusing, or the comparison misattributes the win. The CI
    contract (scripts/encoded_bench.sh): identical results, ZERO
    fallbacks with reason="encoding" on the stock TPC-H schema, and a
    populated bytes_touched block.

    Env knobs: BENCH_ENCODED_SF (0.05), BENCH_ENCODED_ITERS (3)."""
    from tidb_tpu import config, metrics
    from tidb_tpu.benchmarks import tpch
    from tidb_tpu.session import Session
    from tidb_tpu.store.storage import new_mock_storage

    sf = float(os.environ.get("BENCH_ENCODED_SF", "0.05"))
    iters = int(os.environ.get("BENCH_ENCODED_ITERS", "3"))
    data = tpch.ScaledTpch(sf=sf)
    storage = new_mock_storage()
    session = Session(storage)
    session.execute("CREATE DATABASE tpch_enc")
    session.execute("USE tpch_enc")
    progress(f"encoded: loading sf={sf}")
    total = tpch.load(session, storage, data, regions_per_table=2)

    def enc_fallbacks() -> int:
        snap = metrics.snapshot()
        return int(sum(v for k, v in snap.items()
                       if k.startswith(metrics.DEVICE_FALLBACKS) and
                       'reason="encoding"' in k))

    out: dict = {"sf": sf, "iters": iters, "rows_loaded": total,
                 "queries": {}}
    try:
        for qname in ("q1", "q3"):
            sql = tpch.QUERIES[qname]
            in_rows = sum(data.counts[t]
                          for t in tpch.QUERY_TABLES[qname])
            config.set_var("tidb_tpu_encoded_exec", 1)
            config.set_var("tidb_tpu_fuse_fragments", 1)
            progress(f"encoded: {qname} warm (encoded)")
            session.query(sql)          # compile + chunk-cache fill
            session.query(sql)          # HBM tier fills on the 2nd serve
            f0 = enc_fallbacks()
            b0 = _bytes_counters()
            e_secs, e_rows = _time_query(session, sql, iters)
            b1 = _bytes_counters()
            f1 = enc_fallbacks()
            try:
                config.set_var("tidb_tpu_encoded_exec", 0)
                config.set_var("tidb_tpu_fuse_fragments", 0)
                progress(f"encoded: {qname} warm (decoded)")
                session.query(sql)
                session.query(sql)
                d_secs, d_rows = _time_query(session, sql, iters)
            finally:
                config.set_var("tidb_tpu_encoded_exec", 1)
                config.set_var("tidb_tpu_fuse_fragments", 1)
            if not _approx_rows_equal(e_rows, d_rows):
                raise RuntimeError(
                    f"{qname}: encoded and decoded disagree")
            out["queries"][qname] = {
                "input_rows": in_rows,
                "encoded_secs": round(e_secs, 4),
                "decoded_secs": round(d_secs, 4),
                "encoded_rows_per_sec": round(in_rows / e_secs, 1),
                "decoded_rows_per_sec": round(in_rows / d_secs, 1),
                "speedup": round(d_secs / e_secs, 3),
                "bytes_touched": _bytes_touched(b0, b1),
                # the CI contract: stock TPC-H never falls back
                "encoding_fallbacks": f1 - f0,
            }
            progress(f"encoded: {qname} encoded {e_secs:.3f}s decoded "
                     f"{d_secs:.3f}s fallbacks {f1 - f0}")
    finally:
        session.close()
        storage.close()
    return out


def encoded_main() -> None:
    """`python bench.py encoded`: ONLY the encoded-vs-decoded warm
    comparison — the CI entry point (scripts/encoded_bench.sh) with its
    own one-line JSON."""
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        _scope_cpu_compile_cache()
    t_start = time.perf_counter()

    def progress(msg: str) -> None:
        print(f"[encoded +{time.perf_counter() - t_start:7.1f}s] {msg}",
              file=sys.stderr, flush=True)

    enc = _encoded_bench(progress)
    qs = enc.get("queries", {})
    speedups = [q["speedup"] for q in qs.values() if q.get("speedup")]
    geo = math.exp(sum(math.log(x) for x in speedups) /
                   len(speedups)) if speedups else 0.0
    print(json.dumps({
        "metric": "encoded_vs_decoded_warm_speedup",
        "value": round(geo, 3),
        "unit": "x",
        "vs_baseline": round(geo, 3),
        "detail": enc,
    }))


def _scope_cpu_compile_cache() -> bool:
    """Re-point the persistent compile cache at the per-host-feature-set
    CPU subdirectory (compile_cache.scoped_cpu_dir): CPU runs must not
    load through-the-tunnel TPU entries (mismatched AOT results
    deoptimize scatter-heavy programs ~5x), and every CPU program
    persists (floor 0) so warm runs pay zero compiles. Returns False
    when the operator explicitly disabled the cache
    (TIDB_TPU_COMPILE_CACHE=0) — callers then leave it off."""
    from tidb_tpu.util import compile_cache
    base = os.environ.get("TIDB_TPU_COMPILE_CACHE", _CACHE_DIR)
    if not base or base == "0":
        return False
    scoped = compile_cache.scoped_cpu_dir(base)
    os.environ["TIDB_TPU_COMPILE_CACHE"] = scoped
    os.environ["JAX_COMPILATION_CACHE_DIR"] = scoped
    os.environ["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] = "0"
    compile_cache.enable(scoped, min_compile_secs=0.0)
    return True


def _percentile(xs: list, p: float) -> float:
    """Nearest-rank percentile over a non-empty list of seconds:
    the ceil(p/100 * n)-th smallest value."""
    ys = sorted(xs)
    i = min(math.ceil(p / 100.0 * len(ys)) - 1, len(ys) - 1)
    return ys[max(i, 0)]


def _lat_summary(lat: dict) -> dict:
    return {cls: {"count": len(xs),
                  "p50_ms": round(_percentile(xs, 50) * 1e3, 2),
                  "p99_ms": round(_percentile(xs, 99) * 1e3, 2)}
            for cls, xs in lat.items() if xs}


def _trace_mark() -> int:
    """Highest retained trace id right now (ids are monotone), so a
    later ring_records(mark) returns only traces from the leg between."""
    from tidb_tpu import trace
    return max((r["trace_id"] for r in trace.ring_records()), default=0)


def _trace_attribution(mark: int, class_digests: dict) -> dict:
    """Per-phase latency attribution from the statement traces retained
    since `mark` (tidb_tpu/trace.py phases_of): for each query class,
    p50/p99 per lifecycle phase — admission wait, scheduler stall,
    device dispatch, finalize, host-fallback, parse/plan/commit and the
    remainder — plus the traced statement total. The direct input
    ROADMAP item 2 needs: WHERE a p99 regression's microseconds went.
    `class_digests` maps normalized-SQL digest -> class name; traces
    whose digest matches no class land under "other_sql"."""
    from tidb_tpu import trace
    by_cls: dict = {}
    for rec in trace.ring_records(mark):
        cls = class_digests.get(rec["digest"], "other_sql")
        by_cls.setdefault(cls, []).append(trace.phases_of(rec["root"]))
    out: dict = {}
    for cls, phs in sorted(by_cls.items()):
        block: dict = {"traces": len(phs)}
        phase_keys = [k for k in phs[0] if k != "total"]
        for key in phase_keys:
            xs = [p[key] / 1e9 for p in phs]
            block[key] = {
                "p50_ms": round(_percentile(xs, 50) * 1e3, 3),
                "p99_ms": round(_percentile(xs, 99) * 1e3, 3)}
        totals = [p["total"] / 1e9 for p in phs]
        block["statement"] = {
            "p50_ms": round(_percentile(totals, 50) * 1e3, 3),
            "p99_ms": round(_percentile(totals, 99) * 1e3, 3)}
        # two consistency views of the tail. p99_coverage sums EVERY
        # phase incl. the "other" remainder, so it reads ~1.0 whenever
        # the trees are balanced (per-trace phases sum to the
        # statement total; worker overlap pushes it above 1).
        # p99_attributed excludes "other": it is the gap detector —
        # how much of the tail the NAMED phases explain; a low value
        # means the time went somewhere no span covers yet.
        p99 = block["statement"]["p99_ms"]
        if p99 > 0:
            block["p99_coverage"] = round(
                sum(block[k]["p99_ms"] for k in phase_keys) / p99, 3)
            block["p99_attributed"] = round(
                sum(block[k]["p99_ms"] for k in phase_keys
                    if k != "other") / p99, 3)
        out[cls] = block
    return out


def _meter_mark() -> dict:
    """Snapshot of the resource meter before a bench leg: SERVER
    totals, per-session and per-digest device time (meter.py) — the
    baseline _utilization_block diffs against."""
    from tidb_tpu import meter
    return {
        "t": time.perf_counter(),
        "server": meter.server_snapshot(),
        "sessions": {s["session_id"]: s["device_ns"]
                     for s in meter.sessions_snapshot()},
        "digests": {d["digest"]: d["device_ns"]
                    for d in meter.digests_snapshot()},
    }


def _utilization_block(mark: dict, class_digests: dict | None = None,
                       wall_secs: float | None = None) -> dict:
    """The BENCH `utilization` sub-block (serve/htap/chaos legs):
    device busy fraction over the leg's wall time, per-class
    device-seconds (digest meter deltas mapped through
    `class_digests`), and attribution coverage — the sum of
    per-session device-time over the SERVER total, which must sit in
    [0.9, 1.1] or attribution is leaking (scripts/serve_bench.sh
    enforces the bound)."""
    from tidb_tpu import meter, metrics_history
    # one explicit sample so the device-utilization series exists even
    # when the leg finished inside a single sampler cadence
    metrics_history.sample_now()
    wall = wall_secs if wall_secs is not None \
        else time.perf_counter() - mark["t"]
    server = meter.server_snapshot()
    busy_ns = server["device_ns"] - mark["server"]["device_ns"]
    host_ns = server["host_fallback_ns"] - \
        mark["server"]["host_fallback_ns"]
    prev_sessions = mark["sessions"]
    attributed_ns = 0
    for s in meter.sessions_snapshot():
        attributed_ns += s["device_ns"] - \
            prev_sessions.get(s["session_id"], 0)
    out = {
        "wall_secs": round(wall, 3),
        "device_busy_secs": round(busy_ns / 1e9, 4),
        "device_busy_fraction": round(busy_ns / (wall * 1e9), 4)
        if wall > 0 else 0.0,
        "host_fallback_secs": round(host_ns / 1e9, 4),
        "attributed_device_secs": round(attributed_ns / 1e9, 4),
        "attribution_coverage": round(attributed_ns / busy_ns, 4)
        if busy_ns > 0 else 1.0,
    }
    if class_digests:
        prev_digests = mark["digests"]
        per_class: dict = {}
        for d in meter.digests_snapshot():
            cls = class_digests.get(d["digest"])
            if cls is None:
                continue
            delta = d["device_ns"] - prev_digests.get(d["digest"], 0)
            per_class[cls] = round(
                per_class.get(cls, 0.0) + delta / 1e9, 4)
        out["per_class_device_secs"] = dict(sorted(per_class.items()))
    return out


def _serve_bench(progress) -> dict:
    """Multi-client wire-protocol load harness (ISSUE 10 / ROADMAP item
    1's second headline series): N real MySQL connections replay a mixed
    TPC-H Q1/Q3/Q5 + point-lookup workload against one server. Reports
    aggregate input rows/sec for the CONCURRENT replay vs the serialized
    one-connection replay of the same op multiset, p50/p99 per query
    class, admission outcomes and device-scheduler stall time — then a
    deliberately pinched `tidb_tpu_server_mem_quota` leg that must
    complete via shed/queue/retry (admission_shed > 0) with ZERO
    mid-query OOM cancels.

    Env knobs: BENCH_SERVE_CLIENTS (8), BENCH_SERVE_ROUNDS (2: analytic
    queries per client), BENCH_SERVE_LOOKUPS (8: point lookups per
    analytic), BENCH_SERVE_SF (0.02)."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from tests.mysql_client import MiniClient, MySQLError
    from tidb_tpu import config, errcode, memtrack, metrics, perfschema, \
        sched
    from tidb_tpu.benchmarks import tpch
    from tidb_tpu.server import Server
    from tidb_tpu.session import Session
    from tidb_tpu.store.storage import new_mock_storage

    n_clients = int(os.environ.get("BENCH_SERVE_CLIENTS", "8"))
    rounds = int(os.environ.get("BENCH_SERVE_ROUNDS", "2"))
    lookups = int(os.environ.get("BENCH_SERVE_LOOKUPS", "8"))
    sf = float(os.environ.get("BENCH_SERVE_SF", "0.02"))

    data = tpch.ScaledTpch(sf=sf)
    storage = new_mock_storage()
    session = Session(storage)
    session.execute("CREATE DATABASE tpch_serve")
    session.execute("USE tpch_serve")
    progress(f"serve: loading sf={sf} for {n_clients} clients")
    total_loaded = tpch.load(session, storage, data, regions_per_table=2)
    classes = list(tpch.QUERIES)
    class_rows = {q: sum(data.counts[t] for t in tpch.QUERY_TABLES[q])
                  for q in tpch.QUERIES}
    n_orders = data.counts["orders"]

    # per-client deterministic op lists: each round is one analytic
    # (rotating per client+round so the classes overlap ACROSS clients)
    # plus a burst of point lookups — the starvation-prone mix
    def client_ops(ci: int) -> list:
        ops = []
        for r in range(rounds):
            q = classes[(ci + r) % len(classes)]
            ops.append((q, tpch.QUERIES[q], class_rows[q]))
            for j in range(lookups):
                k = (ci * 7919 + r * 104729 + j * 131) % n_orders
                ops.append(("point", "SELECT o_custkey, o_orderpriority "
                            f"FROM orders WHERE o_orderkey = {k}", 1))
        return ops

    all_ops = [client_ops(ci) for ci in range(n_clients)]
    workload_rows = sum(rows for ops in all_ops for _c, _s, rows in ops)

    # warm through a direct session so neither leg pays first-compile
    progress("serve: warmup (compile + cache fill)")
    for q in classes:
        session.query(tpch.QUERIES[q])

    server = Server(storage)
    server.start()

    def new_client() -> MiniClient:
        c = MiniClient("127.0.0.1", server.port, db="tpch_serve")
        c.sock.settimeout(600)
        return c

    def run_ops(cli, ops, lat, errors) -> None:
        for cls, sql2, _rows in ops:
            t0 = time.perf_counter()
            tries = 0
            while True:
                try:
                    cli.query(sql2)
                    break
                except MySQLError as e:
                    # the admission contract: 9xxx server-busy is
                    # RETRYABLE verbatim after backoff; anything else
                    # is a workload bug worth surfacing
                    if e.code == errcode.ER_SERVER_BUSY_ADMISSION \
                            and tries < 200:
                        tries += 1
                        time.sleep(0.05)
                        continue
                    errors.append(f"{cls}: ({e.code}) {e}")
                    break
            lat.setdefault(cls, []).append(time.perf_counter() - t0)

    out: dict = {"clients": n_clients, "rounds": rounds,
                 "lookups_per_round": lookups, "sf": sf,
                 "rows_loaded": total_loaded,
                 "ops": sum(len(ops) for ops in all_ops),
                 "workload_rows": workload_rows}
    # resource-meter baseline for the utilization block: everything
    # from here (serialized + concurrent + pinched legs) is serving
    # work whose device time must attribute to wire sessions
    util_mark = _meter_mark()
    try:
        # serialized baseline: ONE connection replays every client's op
        # list back to back — the number concurrency must beat
        progress("serve: serialized replay")
        lat_ser: dict = {}
        errs: list = []
        cli = new_client()
        t0 = time.perf_counter()
        for ops in all_ops:
            run_ops(cli, ops, lat_ser, errs)
        ser_secs = time.perf_counter() - t0
        cli.close()
        if errs:
            raise RuntimeError(f"serialized replay errors: {errs[:3]}")
        out["serialized"] = {
            "secs": round(ser_secs, 3),
            "rows_per_sec": round(workload_rows / ser_secs, 1),
            "latency": _lat_summary(lat_ser)}

        # concurrent replay: same multiset, N wire connections. Trace
        # EVERY statement through the leg (tidb_tpu_trace_sample=1) so
        # the latency_attribution block below breaks the per-class
        # p50/p99 into lifecycle phases — the tail-latency attribution
        # ROADMAP item 2 runs on
        progress(f"serve: concurrent replay x{n_clients}")
        sched0 = sched.stats()
        lats = [dict() for _ in range(n_clients)]
        errlists = [list() for _ in range(n_clients)]
        clients = [new_client() for _ in range(n_clients)]
        start = threading.Barrier(n_clients + 1)

        def worker(ci: int) -> None:
            start.wait()
            run_ops(clients[ci], all_ops[ci], lats[ci], errlists[ci])

        threads = [threading.Thread(target=worker, args=(ci,),
                                    name=f"serve-client-{ci}")
                   for ci in range(n_clients)]
        trace_mark = _trace_mark()
        sample_prev = config.get_var("tidb_tpu_trace_sample")
        config.set_var("tidb_tpu_trace_sample", 1)
        try:
            for t in threads:
                t.start()
            start.wait()
            t0 = time.perf_counter()
            for t in threads:
                t.join()
            conc_secs = time.perf_counter() - t0
        finally:
            config.set_var("tidb_tpu_trace_sample", sample_prev)
        for c in clients:
            c.close()
        errs = [e for el in errlists for e in el]
        if errs:
            raise RuntimeError(f"concurrent replay errors: {errs[:3]}")
        class_digests = {perfschema.sql_digest(tpch.QUERIES[q])[0]: q
                         for q in classes}
        for cls0, sql0, _r in all_ops[0]:
            if cls0 == "point":     # literals normalize away, so ONE
                class_digests[perfschema.sql_digest(sql0)[0]] = "point"
                break               # digest covers every point lookup
        attribution = _trace_attribution(trace_mark, class_digests)
        sched1 = sched.stats()
        lat_conc: dict = {}
        for d in lats:
            for cls, xs in d.items():
                lat_conc.setdefault(cls, []).extend(xs)
        conc_rps = workload_rows / conc_secs
        out["concurrent"] = {
            "secs": round(conc_secs, 3),
            "rows_per_sec": round(conc_rps, 1),
            "speedup_vs_serialized": round(
                conc_rps / (workload_rows / ser_secs), 3),
            "latency": _lat_summary(lat_conc),
            "latency_attribution": attribution,
            "sched_stall_seconds": round(
                sched1["scheduler"]["stall_seconds"] -
                sched0["scheduler"]["stall_seconds"], 4),
            "sched_bypasses": sched1["scheduler"]["bypasses"] -
            sched0["scheduler"]["bypasses"]}

        # pinched leg: a server quota around one analytic's peak forces
        # admission to shed HBM residency and queue the rest; clients
        # retry the retryable 9008. The workload must COMPLETE with
        # shed > 0 and ZERO mid-query OOM cancels.
        peak = max(perfschema.digest_max_mem(tpch.QUERIES[q])
                   for q in classes)
        resident = memtrack.SERVER.host + memtrack.SERVER.device
        quota = max(peak, resident, 1 << 22)
        progress(f"serve: pinched leg quota={quota} "
                 f"(digest peak {peak}, resident {resident})")
        oom_key = ('tidb_tpu_mem_quota_exceeded_total'
                   '{action="cancel"}')
        oom0 = metrics.snapshot().get(oom_key, 0)
        adm0 = sched.stats()["admission"]
        quota_prev = config.get_var("tidb_tpu_server_mem_quota")
        config.set_var("tidb_tpu_server_mem_quota", quota)
        try:
            lats = [dict() for _ in range(n_clients)]
            errlists = [list() for _ in range(n_clients)]
            clients = [new_client() for _ in range(n_clients)]
            start = threading.Barrier(n_clients + 1)
            threads = [threading.Thread(target=worker, args=(ci,),
                                        name=f"serve-pinch-{ci}")
                       for ci in range(n_clients)]
            for t in threads:
                t.start()
            start.wait()
            t0 = time.perf_counter()
            for t in threads:
                t.join()
            pinch_secs = time.perf_counter() - t0
            for c in clients:
                c.close()
        finally:
            # restore, not zero: an operator-seeded quota
            # (TIDB_TPU_SERVER_MEM_QUOTA) must survive the leg
            config.set_var("tidb_tpu_server_mem_quota", quota_prev)
        errs = [e for el in errlists for e in el]
        adm1 = sched.stats()["admission"]
        oom1 = metrics.snapshot().get(oom_key, 0)
        lat_p: dict = {}
        for d in lats:
            for cls, xs in d.items():
                lat_p.setdefault(cls, []).extend(xs)
        out["pinched"] = {
            "quota_bytes": quota,
            "secs": round(pinch_secs, 3),
            "rows_per_sec": round(workload_rows / pinch_secs, 1),
            "latency": _lat_summary(lat_p),
            "errors": errs[:5],
            "admission": {k: adm1[k] - adm0[k]
                          for k in ("admitted", "queued", "shed",
                                    "rejected")},
            "admission_shed": adm1["shed"] - adm0["shed"],
            "shed_bytes": adm1["shed_bytes"] - adm0["shed_bytes"],
            # the acceptance bar: admission replaces the OOM cancel
            "oom_cancels": int(oom1 - oom0)}
        if errs:
            out["pinched"]["completed"] = False
        else:
            out["pinched"]["completed"] = True
        # resource-meter utilization over all three legs: busy
        # fraction, per-class device-seconds, and the attribution
        # coverage bar scripts/serve_bench.sh pins to [0.9, 1.1]
        out["utilization"] = _utilization_block(util_mark,
                                                class_digests)
        progress(f"serve: utilization busy="
                 f"{out['utilization']['device_busy_fraction']} "
                 f"coverage="
                 f"{out['utilization']['attribution_coverage']}")
    finally:
        server.close()
        session.close()
        storage.close()
    return out


def serve_main() -> None:
    """`python bench.py serve`: ONLY the multi-client load harness, on a
    small fixed workload — the CI entry point (scripts/serve_bench.sh)
    with its own one-line JSON."""
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # same per-host-feature-set CPU cache scoping as the full
        # bench's CPU fallback — one policy, one helper
        _scope_cpu_compile_cache()
    t_start = time.perf_counter()

    def progress(msg: str) -> None:
        print(f"[serve +{time.perf_counter() - t_start:7.1f}s] {msg}",
              file=sys.stderr, flush=True)

    serve = _serve_bench(progress)
    print(json.dumps({
        "metric": "serve_concurrent_rows_per_sec",
        "value": serve.get("concurrent", {}).get("rows_per_sec", 0.0),
        "unit": "rows/s",
        "vs_baseline": serve.get("concurrent", {}).get(
            "speedup_vs_serialized", 0.0),
        "detail": serve,
    }))


def _metric_total(snap: dict, name: str):
    """Sum one counter family over every label combination in a flat
    metrics.snapshot() dict (keys look like 'name{label="v"}')."""
    return sum(v for k, v in snap.items()
               if k == name or k.startswith(name + "{"))


def _fleet_bench(progress) -> dict:
    """Fleet scale-out harness (ISSUE 16 / ROADMAP item 4): one
    store-plane process + BENCH_FLEET_SERVERS stateless SQL-server
    processes, each with its own journal-coherent chunk/HBM caches
    (store/fleetcop.py). The same open-loop mixed workload (TPC-H
    Q1/Q3/Q5 + point lookups, BENCH_FLEET_CLIENTS wire connections)
    replays against the first 1, 2, ... N servers; reports aggregate
    statements/sec per leg, per-class p50/p99, and per-server meter
    utilization scraped from each member's /top endpoint — the
    scaling series scripts/fleet_bench.sh pins (N-server aggregate
    must be >= 2x single-server at N=4).

    Env knobs: BENCH_FLEET_SERVERS (4), BENCH_FLEET_CLIENTS (8),
    BENCH_FLEET_ROUNDS (2), BENCH_FLEET_LOOKUPS (8),
    BENCH_FLEET_SF (0.02)."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from tests.mysql_client import MiniClient, MySQLError
    from tidb_tpu import errcode
    from tidb_tpu.benchmarks import tpch
    from tidb_tpu.fleet import Fleet
    from tidb_tpu.session import Session
    from tidb_tpu.store.remote import connect
    from tidb_tpu.util import statusclient

    n_servers = int(os.environ.get("BENCH_FLEET_SERVERS", "4"))
    n_clients = int(os.environ.get("BENCH_FLEET_CLIENTS", "8"))
    rounds = int(os.environ.get("BENCH_FLEET_ROUNDS", "2"))
    lookups = int(os.environ.get("BENCH_FLEET_LOOKUPS", "8"))
    sf = float(os.environ.get("BENCH_FLEET_SF", "0.02"))
    leg_counts = [n for n in (1, 2, 4) if n <= n_servers]
    if leg_counts[-1] != n_servers:
        leg_counts.append(n_servers)

    data = tpch.ScaledTpch(sf=sf)
    classes = list(tpch.QUERIES)
    n_orders = data.counts["orders"]

    def client_ops(ci: int) -> list:
        ops = []
        for r in range(rounds):
            q = classes[(ci + r) % len(classes)]
            ops.append((q, tpch.QUERIES[q]))
            for j in range(lookups):
                k = (ci * 7919 + r * 104729 + j * 131) % n_orders
                ops.append(("point", "SELECT o_custkey, o_orderpriority "
                            f"FROM orders WHERE o_orderkey = {k}"))
        return ops

    all_ops = [client_ops(ci) for ci in range(n_clients)]
    total_stmts = sum(len(ops) for ops in all_ops)

    progress(f"fleet: starting store plane + {n_servers} SQL servers")
    fleet = Fleet(n_sql=n_servers)
    fleet.start()
    out: dict = {"servers": n_servers, "clients": n_clients,
                 "rounds": rounds, "lookups_per_round": lookups,
                 "sf": sf, "stmts_per_leg": total_stmts}
    try:
        fleet.wait_healthy(timeout=120)

        # load through a direct store-plane session (bulk import over
        # the wire); the DDL lands in the shared store, so every SQL
        # member converges within its schema lease
        progress(f"fleet: loading sf={sf} via the store plane")
        storage = connect(fleet.host, fleet.store_port)
        session = Session(storage)
        session.execute("CREATE DATABASE tpch_fleet")
        session.execute("USE tpch_fleet")
        out["rows_loaded"] = tpch.load(session, storage, data,
                                       regions_per_table=2)
        session.close()
        storage.close()

        def member_client(mi: int) -> MiniClient:
            c = MiniClient(fleet.host, fleet.members[mi].port,
                           db="tpch_fleet")
            c.sock.settimeout(600)
            return c

        def wait_schema(mi: int, timeout: float = 90.0) -> None:
            deadline = time.monotonic() + timeout
            while True:
                try:
                    c = member_client(mi)
                    try:
                        c.query("SELECT COUNT(*) FROM orders")
                        return
                    finally:
                        c.close()
                except (MySQLError, OSError):
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.25)

        # warm every member: schema convergence + first-compile + the
        # journal-coherent cache fill, so no leg pays cold-start costs
        progress("fleet: warmup (schema convergence + cache fill)")
        for mi in range(n_servers):
            wait_schema(mi)
            c = member_client(mi)
            for q in classes:
                c.query(tpch.QUERIES[q])
            c.query("SELECT o_custkey FROM orders WHERE o_orderkey = 1")
            c.close()

        def run_ops(cli, ops, lat, errors) -> None:
            for cls, sql2 in ops:
                t0 = time.perf_counter()
                tries = 0
                while True:
                    try:
                        cli.query(sql2)
                        break
                    except MySQLError as e:
                        if e.code in errcode.RETRYABLE and tries < 200:
                            tries += 1
                            time.sleep(0.05)
                            continue
                        errors.append(f"{cls}: ({e.code}) {e}")
                        break
                lat.setdefault(cls, []).append(time.perf_counter() - t0)

        def member_mark(mi: int) -> dict:
            m = fleet.members[mi]
            top = statusclient.get_json(fleet.host, m.status_port,
                                        "/top", timeout=15.0)
            status = fleet.health(mi)
            return {"device_ns": top["server"]["device_ns"],
                    "host_ns": top["server"]["host_fallback_ns"],
                    "stmts": _metric_total(status["metrics"],
                                           "tidb_tpu_queries_total")}

        legs = []
        for n in leg_counts:
            progress(f"fleet: leg x{n} server(s), "
                     f"{n_clients} clients, {total_stmts} stmts")
            marks = [member_mark(mi) for mi in range(n)]
            lats = [dict() for _ in range(n_clients)]
            errlists = [list() for _ in range(n_clients)]
            clients = [member_client(ci % n) for ci in range(n_clients)]
            start = threading.Barrier(n_clients + 1)

            def worker(ci: int) -> None:
                start.wait()
                run_ops(clients[ci], all_ops[ci], lats[ci],
                        errlists[ci])

            threads = [threading.Thread(target=worker, args=(ci,),
                                        name=f"fleet-client-{ci}")
                       for ci in range(n_clients)]
            for t in threads:
                t.start()
            start.wait()
            t0 = time.perf_counter()
            for t in threads:
                t.join()
            secs = time.perf_counter() - t0
            for c in clients:
                c.close()
            errs = [e for el in errlists for e in el]
            if errs:
                raise RuntimeError(f"fleet leg x{n} errors: {errs[:3]}")
            lat_all: dict = {}
            for d in lats:
                for cls, xs in d.items():
                    lat_all.setdefault(cls, []).extend(xs)
            per_server = {}
            for mi in range(n):
                after = member_mark(mi)
                busy = (after["device_ns"] -
                        marks[mi]["device_ns"]) / 1e9
                per_server[str(mi)] = {
                    "stmts": int(after["stmts"] - marks[mi]["stmts"]),
                    "device_busy_secs": round(busy, 4),
                    "device_busy_fraction": round(busy / secs, 4)
                    if secs > 0 else 0.0,
                    "host_fallback_secs": round(
                        (after["host_ns"] - marks[mi]["host_ns"]) / 1e9,
                        4)}
            legs.append({"servers": n, "secs": round(secs, 3),
                         "stmts_per_sec": round(total_stmts / secs, 1),
                         "latency": _lat_summary(lat_all),
                         "per_server": per_server})
        out["legs"] = legs
        out["scaling_max_vs_1"] = round(
            legs[-1]["stmts_per_sec"] / legs[0]["stmts_per_sec"], 3)

        # coherence counters per member: journal-window pulls by
        # outcome, rows patched into resident blocks, and the local
        # (cached) vs store-delegated coprocessor split
        coherence = {}
        for mi in range(n_servers):
            snap = fleet.health(mi)["metrics"]
            coherence[str(mi)] = {
                "journal_pulls": int(_metric_total(
                    snap, "tidb_tpu_fleet_journal_pulls_total")),
                "patched_rows": int(_metric_total(
                    snap, "tidb_tpu_fleet_journal_patched_rows_total")),
                "local_cop": int(snap.get(
                    'tidb_tpu_fleet_local_cop_total{path="cached"}',
                    0)),
                "store_cop": int(snap.get(
                    'tidb_tpu_fleet_local_cop_total{path="store"}',
                    0)),
                "delta_serves": int(_metric_total(
                    snap, "tidb_tpu_cache_served_with_delta_total"))}
        out["coherence"] = coherence

        # fleet attribution: the cluster observability plane end to
        # end — per-member utilization via the cluster_resource_usage
        # fan-out, then ONE traced statement on member 0 whose fleet
        # trace id provably stitches a store-plane span record when
        # looked up from a DIFFERENT member (cluster_statement_traces
        # joined on origin_trace_id). scripts/fleet_bench.sh pins both.
        progress("fleet: attribution via cluster_* tables")
        c0 = member_client(0)
        c1 = member_client(1 % n_servers)
        try:
            _cols, mrows = c0.query(
                "SELECT member_id, role FROM "
                "information_schema.cluster_members")
            store_ids = {r[0] for r in mrows if r[1] == "store"}
            _cols, urows = c0.query(
                "SELECT member, device_time_ns, statements, rows_sent "
                "FROM information_schema.cluster_resource_usage "
                "WHERE scope = 'server'")
            members_util = {r[0]: {"device_time_ns": int(r[1]),
                                   "statements": int(r[2]),
                                   "rows_sent": int(r[3])}
                            for r in urows}
            _cols, trows = c0.query(
                "TRACE FORMAT='json' SELECT o_custkey FROM orders "
                "WHERE o_orderkey = 1")
            tid = int(json.loads(trows[0][0])["trace_id"])
            deadline = time.monotonic() + 30
            stitched: list = []
            while True:
                _cols, srows = c1.query(
                    "SELECT member, origin_member, trace_id FROM "
                    "information_schema.cluster_statement_traces "
                    f"WHERE origin_trace_id = {tid}")
                stitched = [{"member": r[0], "origin_member": r[1],
                             "trace_id": int(r[2])} for r in srows]
                if any(r["member"] in store_ids for r in stitched):
                    break
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"fleet attribution: no store-plane trace "
                        f"record with origin_trace_id={tid} "
                        f"(got {stitched!r})")
                time.sleep(0.25)
            out["fleet_attribution"] = {
                "live_members": {r[0]: r[1] for r in mrows},
                "members": members_util,
                "trace_id": tid,
                "stitched_records": stitched,
                "stitched_store": True,
            }
        finally:
            c0.close()
            c1.close()
        progress(f"fleet: scaling x{leg_counts[-1]} vs x1 = "
                 f"{out['scaling_max_vs_1']}")
    finally:
        fleet.stop()
    return out


def fleet_main() -> None:
    """`python bench.py fleet`: ONLY the fleet scale-out harness — the
    CI entry point (scripts/fleet_bench.sh) with its own one-line
    JSON."""
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        _scope_cpu_compile_cache()
    t_start = time.perf_counter()

    def progress(msg: str) -> None:
        print(f"[fleet +{time.perf_counter() - t_start:7.1f}s] {msg}",
              file=sys.stderr, flush=True)

    fl = _fleet_bench(progress)
    legs = fl.get("legs", [])
    print(json.dumps({
        "metric": "fleet_stmts_per_sec",
        "value": legs[-1]["stmts_per_sec"] if legs else 0.0,
        "unit": "stmts/s",
        "vs_baseline": fl.get("scaling_max_vs_1", 0.0),
        "detail": fl,
    }))


def _validate_chrome(doc: dict) -> None:
    """Chrome trace-event schema check (the contract Perfetto /
    chrome://tracing loads): raises on violation."""
    evs = doc.get("traceEvents")
    if not isinstance(evs, list) or not evs:
        raise RuntimeError("chrome export: traceEvents missing/empty")
    if not any(e.get("ph") == "X" for e in evs):
        raise RuntimeError("chrome export: no complete (X) span events")
    for e in evs:
        if e.get("ph") not in ("X", "i", "M"):
            raise RuntimeError(f"chrome export: bad ph in {e!r}")
        if not isinstance(e.get("name"), str) or not \
                isinstance(e.get("pid"), int) or not \
                isinstance(e.get("tid"), int):
            raise RuntimeError(f"chrome export: bad name/pid/tid {e!r}")
        if e["ph"] in ("X", "i") and not isinstance(
                e.get("ts"), (int, float)):
            raise RuntimeError(f"chrome export: bad ts in {e!r}")
        if e["ph"] == "X" and (not isinstance(e.get("dur"), (int, float))
                               or e["dur"] < 0):
            raise RuntimeError(f"chrome export: bad dur in {e!r}")


def _trace_bench(progress) -> dict:
    """Traced warm Q1 + point-lookup mix (scripts/trace_bench.sh):
    every statement retains its tree, then the leg FAILS unless the
    latency_attribution block is populated, every retained span tree is
    balanced (no begin-without-end), the `TRACE FORMAT='json'` tree
    over warm Q1 carries admission / scheduler-slot / dispatch /
    copr-worker spans, and the Chrome export passes schema validation.

    Env knobs: BENCH_TRACE_SF (0.02), BENCH_TRACE_ITERS (3),
    BENCH_TRACE_LOOKUPS (16)."""
    import json as _json

    from tidb_tpu import config, perfschema, trace
    from tidb_tpu.benchmarks import tpch
    from tidb_tpu.session import Session
    from tidb_tpu.store.storage import new_mock_storage

    sf = float(os.environ.get("BENCH_TRACE_SF", "0.02"))
    iters = int(os.environ.get("BENCH_TRACE_ITERS", "3"))
    lookups = int(os.environ.get("BENCH_TRACE_LOOKUPS", "16"))

    data = tpch.ScaledTpch(sf=sf)
    storage = new_mock_storage()
    session = Session(storage)
    session.execute("CREATE DATABASE tpch_trace")
    session.execute("USE tpch_trace")
    progress(f"trace: loading sf={sf}")
    tpch.load(session, storage, data, regions_per_table=2)
    q1 = tpch.QUERIES["q1"]
    n_orders = data.counts["orders"]
    progress("trace: warmup (compile + cache fill)")
    session.query(q1)

    saved = {k: config.get_var(k) for k in
             ("tidb_tpu_trace_sample", "tidb_tpu_server_mem_quota")}
    out: dict = {"sf": sf, "iters": iters, "lookups": lookups}
    try:
        config.set_var("tidb_tpu_trace_sample", 1)
        # a (generous) server quota arms admission so the admission
        # span covers a real controller pass, not a no-op
        config.set_var("tidb_tpu_server_mem_quota", 8 << 30)
        mark = _trace_mark()
        progress(f"trace: {iters} warm Q1 + {lookups} point lookups")
        for i in range(iters):
            session.query(q1)
            for j in range(lookups // iters + 1):
                k = (i * 7919 + j * 131) % n_orders
                session.query("SELECT o_custkey, o_orderpriority FROM "
                              f"orders WHERE o_orderkey = {k}")
        # every retained tree must be balanced
        records = trace.ring_records(mark)
        unbalanced = [(r["trace_id"], p) for r in records
                      for p in trace.validate(r["root"])]
        if unbalanced:
            raise RuntimeError(f"unbalanced span trees: "
                               f"{unbalanced[:5]}")
        out["traces"] = len(records)

        # attribution must be populated with a traced device phase
        digests = {perfschema.sql_digest(q1)[0]: "q1",
                   perfschema.sql_digest(
                       "SELECT o_custkey, o_orderpriority FROM orders "
                       "WHERE o_orderkey = 0")[0]: "point"}
        attribution = _trace_attribution(mark, digests)
        out["latency_attribution"] = attribution
        q1a = attribution.get("q1")
        if not q1a or q1a["traces"] < iters:
            raise RuntimeError(
                f"latency_attribution unpopulated: {attribution}")
        if q1a["statement"]["p99_ms"] <= 0 or \
                q1a["device_dispatch"]["p99_ms"] + \
                q1a["finalize"]["p99_ms"] + \
                q1a["host_fallback"]["p99_ms"] <= 0:
            raise RuntimeError(
                f"no device/host execution phase attributed: {q1a}")

        # TRACE FORMAT='json' over warm Q1: one balanced tree with the
        # lifecycle + device-plane spans on it
        doc = _json.loads(session.query(
            f"TRACE FORMAT='json' {q1}").rows[0][0])
        names: set = set()

        def walk(d):
            names.add(d["name"])
            for c in d.get("children", ()):
                walk(c)

        walk(doc["spans"])
        need = {"statement", "parse", "plan", "admission", "execute",
                "sched.slot", "dispatch", "finalize"}
        missing = need - names
        if missing:
            raise RuntimeError(
                f"TRACE tree missing spans {sorted(missing)} "
                f"(got {sorted(names)})")
        if not ({"copr.task", "copr.stream"} & names):
            raise RuntimeError(
                f"TRACE tree has no copr worker spans: {sorted(names)}")
        out["trace_stmt_spans"] = sorted(names)

        # Chrome export of the TRACE'd statement passes schema checks
        rec = trace.ring_get(doc["trace_id"])
        if rec is None:
            raise RuntimeError("TRACE'd statement not in the ring")
        chrome = trace.to_chrome(rec)
        _validate_chrome(chrome)
        out["chrome_events"] = len(chrome["traceEvents"])
        out["passed"] = True
    finally:
        for k, v in saved.items():
            config.set_var(k, v)
        session.close()
        storage.close()
    progress(f"trace: {out.get('traces', 0)} traces, "
             f"passed={out.get('passed', False)}")
    return out


def trace_main() -> None:
    """`python bench.py trace`: ONLY the traced-mix leg — the CI entry
    point (scripts/trace_bench.sh) with its own one-line JSON."""
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        _scope_cpu_compile_cache()
    t_start = time.perf_counter()

    def progress(msg: str) -> None:
        print(f"[trace +{time.perf_counter() - t_start:7.1f}s] {msg}",
              file=sys.stderr, flush=True)

    detail = _trace_bench(progress)
    print(json.dumps({
        "metric": "trace_bench_traces_retained",
        "value": detail.get("traces", 0),
        "unit": "traces",
        "detail": detail,
    }))


def _profile_bench(progress) -> dict:
    """Kernel-profiling leg (scripts/profile_bench.sh): warm Q1/Q3/Q5
    under the continuous profiler, then FAIL unless the plane actually
    observed the run — information_schema.kernel_profile populated with
    dispatch counts, roofline_fraction present on every row that moved
    bytes, compile counts FLAT across the warm iterations (a warm
    iteration that recompiles is the regression this leg exists to
    catch), and every statement_profile memo row carrying the mode that
    ran.

    Env knobs: BENCH_PROFILE_SF (0.02), BENCH_PROFILE_ITERS (3)."""
    from tidb_tpu import config, profiler
    from tidb_tpu.benchmarks import tpch
    from tidb_tpu.parallel import config as mesh_config
    from tidb_tpu.session import Session
    from tidb_tpu.store.storage import new_mock_storage

    sf = float(os.environ.get("BENCH_PROFILE_SF", "0.02"))
    iters = int(os.environ.get("BENCH_PROFILE_ITERS", "3"))

    data = tpch.ScaledTpch(sf=sf)
    storage = new_mock_storage()
    session = Session(storage)
    session.execute("CREATE DATABASE tpch_profile")
    session.execute("USE tpch_profile")
    progress(f"profile: loading sf={sf}")
    tpch.load(session, storage, data, regions_per_table=2)
    queries = {q: tpch.QUERIES[q] for q in ("q1", "q3", "q5")}

    saved = config.get_var("tidb_tpu_device")
    out: dict = {"sf": sf, "iters": iters}
    failures: list[str] = []
    try:
        config.set_var("tidb_tpu_device", 1)
        mesh_config.enable_mesh()
        profiler.reset_for_tests()
        progress("profile: cold runs (compile + cache fill)")
        for sql in queries.values():
            session.query(sql)

        def total_compiles() -> int:
            return sum(p["compiles"] for p in profiler.snapshot())

        compiles_after_cold = total_compiles()
        progress(f"profile: {iters} warm iterations per query")
        compile_track = []
        for _i in range(iters):
            for sql in queries.values():
                session.query(sql)
            compile_track.append(total_compiles())
        out["compiles_after_cold"] = compiles_after_cold
        out["compiles_per_warm_iter"] = compile_track
        if compile_track and compile_track[-1] > compile_track[0]:
            failures.append(
                f"compile counts grew across warm iterations: "
                f"{compile_track} (warm runs must ride the caches)")

        rows = session.query(
            "SELECT family, compiles, dispatches, busy_ns, bytes_in, "
            "roofline_fraction FROM information_schema.kernel_profile"
        ).rows
        out["kernel_profile_rows"] = len(rows)
        out["kernel_profile_families"] = sorted({r[0] for r in rows})
        if not rows or not any(r[2] for r in rows):
            failures.append(
                f"kernel_profile unpopulated after {iters} warm "
                f"iterations: {rows!r}")
        missing_roof = [r[0] for r in rows
                        if r[2] and r[4] and r[5] is None]
        if missing_roof:
            failures.append(
                f"rows with dispatches+bytes but no roofline_fraction: "
                f"{missing_roof}")

        memo = session.query(
            "SELECT digest, op, mode, runs, device_ns FROM "
            "information_schema.statement_profile").rows
        out["statement_profile_rows"] = len(memo)
        out["statement_profile_modes"] = sorted({m[2] for m in memo})
        if not memo:
            failures.append("statement_profile memo is empty after a "
                            "warm TPC-H sweep")
        bad_mode = [(m[0][:8], m[1]) for m in memo if not m[2]]
        if bad_mode:
            failures.append(f"memo rows missing mode: {bad_mode}")

        gbps, src = profiler.platform_peak_gbps()
        out["roofline"] = {"peak_gbps": round(gbps, 1), "source": src}
        out["profiler_stats"] = profiler.stats()
    finally:
        config.set_var("tidb_tpu_device", saved)
        session.close()
    out["failures"] = failures
    out["passed"] = not failures
    return out


def profile_main() -> None:
    """`python bench.py profile`: ONLY the kernel-profiling leg — the
    CI entry point (scripts/profile_bench.sh) with its own one-line
    JSON; exits non-zero when the plane failed to observe the run."""
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        _scope_cpu_compile_cache()
    t_start = time.perf_counter()

    def progress(msg: str) -> None:
        print(f"[profile +{time.perf_counter() - t_start:7.1f}s] {msg}",
              file=sys.stderr, flush=True)

    detail = _profile_bench(progress)
    print(json.dumps({
        "metric": "profile_bench_kernel_profiles",
        "value": detail.get("kernel_profile_rows", 0),
        "unit": "profiles",
        "detail": detail,
    }))
    if not detail["passed"]:
        for f in detail["failures"]:
            print(f"[profile] FAIL: {f}", file=sys.stderr)
        sys.exit(1)


def _lintcheck_bench(progress) -> dict:
    """Static-vs-runtime cross-check (scripts/lint_device_bench.sh):
    the device dataflow pass (tidb_tpu/lint/flow/device.py) predicts
    per-family compile behavior from source alone; this leg runs warm
    Q1/Q3 under kernel profiling and FAILS on drift in either
    direction — a family the static model does not know (analysis
    fell behind the runtime), a fingerprinted row compiling more than
    the predicted bound or any family compiling on warm iterations
    (runtime fell behind the contract the lint rules enforce), or a
    non-clean `python -m tidb_tpu.lint --json` run.

    Env knobs: BENCH_LINTCHECK_SF (0.02), BENCH_LINTCHECK_ITERS (2)."""
    import subprocess

    from tidb_tpu import config, profiler
    from tidb_tpu.benchmarks import tpch
    from tidb_tpu.lint.engine import Forest
    from tidb_tpu.lint.flow.device import device_flow_of
    from tidb_tpu.parallel import config as mesh_config
    from tidb_tpu.session import Session
    from tidb_tpu.store.storage import new_mock_storage

    sf = float(os.environ.get("BENCH_LINTCHECK_SF", "0.02"))
    iters = int(os.environ.get("BENCH_LINTCHECK_ITERS", "2"))
    out: dict = {"sf": sf, "iters": iters}
    failures: list[str] = []

    progress("lintcheck: python -m tidb_tpu.lint --json")
    proc = subprocess.run(
        [sys.executable, "-m", "tidb_tpu.lint", "--json"],
        capture_output=True, text=True,
        cwd=os.path.dirname(os.path.abspath(__file__)))
    try:
        lint = json.loads(proc.stdout)
    except json.JSONDecodeError:
        lint = None
    if lint is None or proc.returncode not in (0, 1):
        failures.append(f"lint --json did not produce a report "
                        f"(rc={proc.returncode}): {proc.stderr[-500:]}")
        lint = {"clean": False, "rules": [], "findings": [],
                "timing": {}}
    out["lint_clean"] = lint["clean"]
    out["lint_rules"] = len(lint["rules"])
    out["lint_rule_ms"] = lint.get("timing", {}).get("rule_ms", {})
    if not lint["clean"]:
        failures.append(
            f"lint is not clean: {len(lint['findings'])} finding(s), "
            f"first: {lint['findings'][:3]}")

    progress("lintcheck: static compile predictions")
    df = device_flow_of(Forest.load())
    preds = df.compile_predictions()
    out["predictions"] = preds
    out["traced_sites"] = len(df.sites)
    missing_model = sorted(set(profiler.FAMILIES) - set(preds))
    if missing_model:
        failures.append(
            f"static model predicts nothing for profiler families "
            f"{missing_model} — the device pass fell behind the "
            f"profiler plane")

    data = tpch.ScaledTpch(sf=sf)
    storage = new_mock_storage()
    session = Session(storage)
    session.execute("CREATE DATABASE tpch_lintcheck")
    session.execute("USE tpch_lintcheck")
    progress(f"lintcheck: loading sf={sf}")
    tpch.load(session, storage, data, regions_per_table=2)
    queries = {q: tpch.QUERIES[q] for q in ("q1", "q3")}

    saved = config.get_var("tidb_tpu_device")
    try:
        config.set_var("tidb_tpu_device", 1)
        mesh_config.enable_mesh()
        profiler.reset_for_tests()
        progress("lintcheck: cold runs (compile + cache fill)")
        for sql in queries.values():
            session.query(sql)

        def fam_compiles() -> dict:
            fams: dict = {}
            for p in profiler.snapshot():
                fams[p["family"]] = fams.get(p["family"], 0) + \
                    p["compiles"]
            return fams

        cold = fam_compiles()
        progress(f"lintcheck: {iters} warm iterations per query")
        for _i in range(iters):
            for sql in queries.values():
                session.query(sql)
        warm = fam_compiles()
        out["compiles_after_cold"] = cold
        out["compiles_after_warm"] = warm

        checked = 0
        for fam, n in sorted(warm.items()):
            pred = preds.get(fam)
            if pred is None:
                failures.append(
                    f"family {fam!r} compiled {n} unit(s) but the "
                    f"static model has no prediction for it")
                continue
            checked += 1
            growth = n - cold.get(fam, 0)
            if growth > pred["warm_growth"]:
                failures.append(
                    f"family {fam!r} compiled {growth} unit(s) during "
                    f"warm iterations (predicted {pred['warm_growth']})")
        out["families_checked"] = checked
        if not checked:
            failures.append("no family compiled anything — the "
                            "cross-check exercised nothing")

        # per-fingerprint bound: a fingerprint-cached family builds at
        # most one executable per profile row ("~" rows are explicitly
        # unfingerprinted and exempt from the bound)
        over = []
        for p in profiler.snapshot():
            bound = (preds.get(p["family"]) or {}).get("per_row_bound")
            if bound is None or p["fingerprint"].startswith("~"):
                continue
            if p["compiles"] > bound:
                over.append((p["family"], p["fingerprint"][:16],
                             p["compiles"]))
        out["rows_over_bound"] = over
        if over:
            failures.append(
                f"fingerprinted rows compiled past the static "
                f"per-row bound: {over}")
    finally:
        config.set_var("tidb_tpu_device", saved)
        session.close()
    out["failures"] = failures
    out["passed"] = not failures
    return out


def lintcheck_main() -> None:
    """`python bench.py lintcheck`: the static-analysis cross-check
    leg — CI entry point (scripts/lint_device_bench.sh) with its own
    one-line JSON; exits non-zero when the static model and the
    profiler plane disagree (either direction) or lint is not clean."""
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        _scope_cpu_compile_cache()
    t_start = time.perf_counter()

    def progress(msg: str) -> None:
        print(f"[lintcheck +{time.perf_counter() - t_start:7.1f}s] "
              f"{msg}", file=sys.stderr, flush=True)

    detail = _lintcheck_bench(progress)
    print(json.dumps({
        "metric": "lintcheck_families_verified",
        "value": detail.get("families_checked", 0),
        "unit": "families",
        "detail": detail,
    }))
    if not detail["passed"]:
        for f in detail["failures"]:
            print(f"[lintcheck] FAIL: {f}", file=sys.stderr)
        sys.exit(1)


def _parse_cell(x):
    if isinstance(x, (bytes, bytearray)):
        x = x.decode()
    if isinstance(x, str):
        try:
            return int(x)
        except ValueError:
            pass
        try:
            return float(x)
        except ValueError:
            return x
    return x


def _rows_match(got, want, cols=None) -> bool:
    """Approximate row-set equality across the wire (string cells) and
    execution paths (device vs host float-sum ordering): numeric cells
    compare with relative tolerance, everything else exactly. With
    `cols`, only those column indexes are compared (write-invariant
    columns of a mutating table)."""
    if len(got) != len(want):
        return False
    for rg, rw in zip(got, want):
        if len(rg) != len(rw):
            return False
        idxs = range(len(rg)) if cols is None else cols
        for i in idxs:
            x, y = _parse_cell(rg[i]), _parse_cell(rw[i])
            if isinstance(x, float) or isinstance(y, float):
                try:
                    fx, fy = float(x), float(y)
                except (TypeError, ValueError):
                    return False
                if abs(fx - fy) > max(1e-5, abs(fy) * 1e-6):
                    return False
            elif x != y:
                return False
    return True


def _chaos_bench(progress) -> dict:
    """Chaos serve harness (ISSUE 13, docs/ROBUSTNESS.md): the PR-9
    serve mix (TPC-H analytics + point lookups over N wire clients)
    runs concurrently with PR-11-style HTAP writes while a SEEDED
    driver thread arms and disarms budgeted failpoints across the
    device plane (dispatch/finalize faults and delays, HBM fill/patch
    faults, RPC server-busy bursts, delta-merge crashes, slot-grant
    delays). Invariants recorded in the `chaos` block and asserted by
    scripts/chaos_bench.sh:

      * zero wrong results (analytics match the fault-free reference;
        the written table's write-invariant columns match);
      * zero non-retryable errors surfaced to clients, zero mid-query
        OOM cancels;
      * zero stuck statements (per-op deadline; the dispatch watchdog
        is armed, so nothing can hang past its timeout);
      * scheduler slots and the SERVER memtrack ledgers drain to zero
        at the end.

    Env knobs: BENCH_CHAOS_SEED (20260804), BENCH_CHAOS_CLIENTS (4),
    BENCH_CHAOS_SECS (15: chaos window), BENCH_CHAOS_SF (0.01),
    BENCH_CHAOS_WRITES_PER_SEC (25), BENCH_CHAOS_TIMEOUT_MS (3000:
    dispatch watchdog), BENCH_CHAOS_STUCK_SECS (90: per-op ceiling)."""
    import random

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from tests.mysql_client import MiniClient, MySQLError
    from tidb_tpu import config, errcode, memtrack, metrics, sched
    from tidb_tpu.benchmarks import tpch
    from tidb_tpu.server import Server
    from tidb_tpu.session import Session, SQLError
    from tidb_tpu.store.storage import new_mock_storage
    from tidb_tpu.table import Table, bulkload
    from tidb_tpu.util import failpoint
    import numpy as _np

    seed = int(os.environ.get("BENCH_CHAOS_SEED", "20260804"))
    n_clients = int(os.environ.get("BENCH_CHAOS_CLIENTS", "4"))
    window = float(os.environ.get("BENCH_CHAOS_SECS", "15"))
    sf = float(os.environ.get("BENCH_CHAOS_SF", "0.01"))
    write_rate = float(os.environ.get("BENCH_CHAOS_WRITES_PER_SEC",
                                      "25"))
    timeout_ms = int(os.environ.get("BENCH_CHAOS_TIMEOUT_MS", "3000"))
    stuck_s = float(os.environ.get("BENCH_CHAOS_STUCK_SECS", "90"))

    rng = random.Random(seed)
    saved = {k: config.get_var(k) for k in
             ("tidb_tpu_dispatch_timeout_ms", "tidb_tpu_delta_merge_rows",
              "tidb_tpu_failpoints", "tidb_tpu_trace_sample")}
    sched.reset_for_tests()
    storage = new_mock_storage()
    session = Session(storage)
    session.execute("CREATE DATABASE chaos")
    session.execute("USE chaos")
    progress(f"chaos: loading tpch sf={sf} + stock (seed {seed})")
    tpch.load(session, storage, tpch.ScaledTpch(sf=sf),
              regions_per_table=2)
    n_stock = 12000
    session.execute("CREATE TABLE stock (s_id BIGINT PRIMARY KEY, "
                    "s_seg BIGINT, s_qty BIGINT)")
    srng = _np.random.default_rng(seed)
    bulkload.bulk_load(storage, Table(
        session.domain.info_schema().table("chaos", "stock"), storage), {
        "s_id": _np.arange(n_stock, dtype=_np.int64),
        "s_seg": _np.arange(n_stock, dtype=_np.int64) % 11,
        "s_qty": srng.integers(10, 100, n_stock)})
    stock_sql = ("SELECT s_seg, COUNT(*), SUM(s_qty) FROM stock "
                 "GROUP BY s_seg ORDER BY s_seg")
    n_orders = tpch.ScaledTpch(sf=sf).counts["orders"]

    analytics = dict(tpch.QUERIES)
    analytics["stock"] = stock_sql
    progress("chaos: warmup + fault-free references")
    for sql2 in analytics.values():
        session.query(sql2)

    server = Server(storage)
    server.start()

    def new_client() -> MiniClient:
        c = MiniClient("127.0.0.1", server.port, db="chaos")
        c.sock.settimeout(stuck_s)
        return c

    # references through the SAME surface the clients use (text rows)
    ref_cli = new_client()
    refs = {cls: ref_cli.query(sql2)[1]
            for cls, sql2 in analytics.items()}
    point_keys = [(ci * 7919 + j * 131) % n_orders
                  for ci in range(n_clients) for j in range(8)]
    point_sql = ("SELECT o_custkey, o_orderpriority FROM orders "
                 "WHERE o_orderkey = {k}")
    point_refs = {k: ref_cli.query(point_sql.format(k=k))[1]
                  for k in set(point_keys)}
    ref_cli.close()

    # seeded chaos schedule: every spec carries a budget or rides a
    # short arm window, so no fault outlives its slice of the run
    # (point, spec factory, hold): hold=None arms for a short random
    # window; a float holds the arm until the budget fires (or the
    # hold expires) — the watchdog-tripping long delay would otherwise
    # almost never coincide with a device dispatch in a short CI run
    schedule = [
        ("device/dispatch", lambda: f"{rng.randint(2, 6)}*"
                                    f"raise(DeviceFaultError)", None),
        ("device/finalize", lambda: f"1-in-{rng.randint(3, 6)}:"
                                    f"delay({rng.randint(10, 60)})",
         None),
        ("device/finalize", lambda: f"1*delay({int(timeout_ms * 1.4)})",
         6.0),
        ("hbm/fill", lambda: f"{rng.randint(1, 4)}*"
                             f"raise(DeviceFaultError)", 2.0),
        ("hbm/patch", lambda: f"{rng.randint(1, 4)}*return(1)", None),
        ("rpc/request", lambda: f"{rng.randint(2, 6)}*"
                                f"raise(ServerBusyError)", None),
        ("delta/merge", lambda: "1*raise(RuntimeError:chaos-merge)",
         4.0),
        ("sched/slot", lambda: f"1-in-{rng.randint(4, 8)}:"
                               f"delay({rng.randint(5, 20)})", None),
    ]
    stop = threading.Event()
    armed_log: list = []

    def chaos_driver() -> None:
        # every epoch arms EVERY schedule entry once, in seeded-shuffled
        # order — pure random picks can starve the rare-but-load-bearing
        # entries (the watchdog-tripping long delay, the merge crash)
        # out of a short CI window
        while not stop.is_set():
            order = list(range(len(schedule)))
            rng.shuffle(order)
            for i in order:
                if stop.is_set():
                    return
                name, mk, hold = schedule[i]
                spec = mk()
                failpoint.enable(name, spec)
                armed_log.append(f"{name}={spec}")
                if hold is None:
                    stop.wait(rng.uniform(0.1, 0.4))
                else:
                    end = time.monotonic() + hold
                    while time.monotonic() < end and \
                            name in failpoint.armed() and \
                            not stop.is_set():
                        stop.wait(0.1)
                failpoint.disable(name)
                if stop.wait(rng.uniform(0.0, 0.05)):
                    return

    wrong: list = []
    non_retryable: list = []
    stuck: list = []
    ops_done = [0]
    retried = [0]

    def run_op(cli, cls, sql2, check) -> None:
        deadline = time.monotonic() + stuck_s
        while True:
            try:
                out = cli.query(sql2)
                rows = out[1] if isinstance(out, tuple) else []
                if not check(rows):
                    wrong.append(f"{cls}: {rows[:2]!r}")
                ops_done[0] += 1
                return
            except MySQLError as e:
                if not errcode.is_retryable(e.code):
                    non_retryable.append(f"{cls}: ({e.code}) {e}")
                    return
                retried[0] += 1
                if time.monotonic() >= deadline:
                    stuck.append(f"{cls}: retries past {stuck_s}s")
                    return
                time.sleep(0.03)
            except OSError as e:
                stuck.append(f"{cls}: socket {e}")
                return

    def client_worker(ci: int) -> None:
        cli = new_client()
        classes = list(analytics)
        j = 0
        try:
            while not stop.is_set():
                cls = classes[(ci + j) % len(classes)]
                if cls == "stock":
                    # the written table: only the write-invariant
                    # columns (seg, count) are comparable
                    run_op(cli, cls, analytics[cls],
                           lambda rows: _rows_match(
                               rows, refs["stock"], cols=(0, 1)))
                else:
                    run_op(cli, cls, analytics[cls],
                           lambda rows, c=cls: _rows_match(
                               rows, refs[c]))
                for pk in point_keys[ci * 8:(ci + 1) * 8]:
                    if stop.is_set():
                        break
                    run_op(cli, "point", point_sql.format(k=pk),
                           lambda rows, k=pk: _rows_match(
                               rows, point_refs[k]))
                j += 1
        finally:
            try:
                cli.close()
            except Exception:  # noqa: BLE001 - teardown best effort
                pass

    write_errs_nonretry: list = []
    writes_done = [0]

    def writer() -> None:
        ws = Session(storage, db="chaos")
        period = 1.0 / max(write_rate, 1e-6)
        seq = 0
        nxt = time.perf_counter()
        while not stop.is_set():
            seq += 1
            k = (seq * 7919) % n_stock
            try:
                ws.execute(f"UPDATE stock SET s_qty = s_qty + 1 "
                           f"WHERE s_id = {k}")
                writes_done[0] += 1
            except SQLError as exc:
                code = errcode.classify(exc)[0]
                if not errcode.is_retryable(code):
                    write_errs_nonretry.append(f"({code}) {exc}")
            nxt += period
            d = nxt - time.perf_counter()
            if d > 0:
                time.sleep(min(d, 0.25))
            else:
                nxt = time.perf_counter()
        ws.close()

    snap0 = metrics.snapshot()
    oom_key = 'tidb_tpu_mem_quota_exceeded_total{action="cancel"}'
    config.set_var("tidb_tpu_dispatch_timeout_ms", timeout_ms)
    config.set_var("tidb_tpu_delta_merge_rows", 64)
    # trace 1-in-2 statements through the chaos window so the
    # latency_attribution block can say where the fault-retry /
    # degraded-path microseconds went (the ring keeps the newest 256)
    config.set_var("tidb_tpu_trace_sample", 2)
    trace_mark = _trace_mark()
    util_mark = _meter_mark()
    progress(f"chaos: {n_clients} clients + writer + driver for "
             f"{window}s (watchdog {timeout_ms}ms)")
    threads = [threading.Thread(target=client_worker, args=(ci,),
                                name=f"chaos-client-{ci}")
               for ci in range(n_clients)]
    threads.append(threading.Thread(target=writer, name="chaos-writer"))
    driver = threading.Thread(target=chaos_driver, name="chaos-driver")
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    driver.start()
    stopped_at = t0 + window
    try:
        while time.perf_counter() < stopped_at:
            time.sleep(0.1)
    finally:
        stop.set()
        driver.join(timeout=10)
        failpoint.disable_all()
        for t in threads:
            t.join(timeout=stuck_s + 30)
            if t.is_alive():
                stuck.append(f"thread {t.name} did not drain")
    secs = time.perf_counter() - t0
    config.set_var("tidb_tpu_dispatch_timeout_ms", 0)
    # attribution over the traces sampled DURING the window (before the
    # post-chaos health queries add fault-free ones)
    from tidb_tpu import perfschema as _ps
    chaos_digests = {_ps.sql_digest(sql2)[0]: cls
                     for cls, sql2 in analytics.items()}
    chaos_digests[_ps.sql_digest(point_sql.format(k=0))[0]] = "point"
    attribution = _trace_attribution(trace_mark, chaos_digests)
    # utilization over the chaos window itself (before the post-chaos
    # health queries add fault-free device time)
    utilization = _utilization_block(util_mark, chaos_digests,
                                     wall_secs=secs)

    # post-chaos serving health: faults disarmed, every analytic must
    # answer correctly again through a fresh connection
    post_ok = True
    try:
        c = new_client()
        for cls, sql2 in analytics.items():
            rows = c.query(sql2)[1]
            cols = (0, 1) if cls == "stock" else None
            if not _rows_match(rows, refs[cls], cols=cols):
                post_ok = False
                wrong.append(f"post-chaos {cls}")
        c.close()
    except Exception as e:  # noqa: BLE001 - recorded, asserted below
        post_ok = False
        wrong.append(f"post-chaos: {e}")

    server.close()
    session.close()
    sched_snap = sched.device_scheduler().snapshot()
    # drain: dead sessions collect, forced merges + HBM sheds return
    # every server-scope residency; the ledgers must reach ZERO
    import gc
    deadline = time.time() + 10.0
    while (memtrack.SERVER.host or memtrack.SERVER.device) and \
            time.time() < deadline:
        gc.collect()
        sched.shed_server(0)
        time.sleep(0.05)
    ledger_host, ledger_device = memtrack.SERVER.host, \
        memtrack.SERVER.device
    storage.close()
    for k, v in saved.items():
        config.set_var(k, v)

    snap1 = metrics.snapshot()

    def delta_of(prefix: str) -> int:
        return int(sum(v for kk, v in snap1.items()
                       if kk.startswith(prefix)) -
                   sum(v for kk, v in snap0.items()
                       if kk.startswith(prefix)))

    fires = {kk.split('name="')[1].rstrip('"}'): int(
        v - snap0.get(kk, 0))
        for kk, v in snap1.items()
        if kk.startswith(metrics.FAILPOINT_FIRES) and
        v - snap0.get(kk, 0) > 0}
    fallbacks = {}
    for kk, v in snap1.items():
        if kk.startswith(metrics.DEVICE_FALLBACKS) and \
                'reason="' in kk:
            reason = kk.split('reason="')[1].rstrip('"}')
            d = int(v - snap0.get(kk, 0))
            if d:
                fallbacks[reason] = fallbacks.get(reason, 0) + d
    out = {
        "seed": seed,
        "clients": n_clients,
        "secs": round(secs, 2),
        "ops_completed": ops_done[0],
        "writes_completed": writes_done[0],
        "retries": retried[0],
        "failpoints_armed": len(armed_log),
        "failpoint_fires": fires,
        "wrong_results": wrong[:10],
        "non_retryable_errors": (non_retryable +
                                 write_errs_nonretry)[:10],
        "stuck_statements": stuck[:10],
        "oom_cancels": int(snap1.get(oom_key, 0) -
                           snap0.get(oom_key, 0)),
        "latency_attribution": attribution,
        "utilization": utilization,
        "watchdog_fires": delta_of(metrics.DISPATCH_TIMEOUTS),
        "device_fallbacks": fallbacks,
        "quarantines": delta_of(metrics.DEVICE_QUARANTINES),
        "worker_restarts": delta_of(metrics.WORKER_RESTARTS),
        "post_chaos_healthy": post_ok,
        "sched_inflight_end": sched_snap["inflight"],
        "sched_waiting_end": sched_snap["waiting"],
        "server_ledger_host_end": ledger_host,
        "server_ledger_device_end": ledger_device,
    }
    out["passed"] = (not wrong and not non_retryable and
                     not write_errs_nonretry and not stuck and
                     out["oom_cancels"] == 0 and post_ok and
                     sched_snap["inflight"] == 0 and
                     sched_snap["waiting"] == 0 and
                     ledger_host == 0 and ledger_device == 0 and
                     ops_done[0] > 0 and writes_done[0] > 0)
    progress(f"chaos: {ops_done[0]} ops, {writes_done[0]} writes, "
             f"{len(armed_log)} arms, fires={sum(fires.values())}, "
             f"passed={out['passed']}")
    return out


def chaos_main() -> None:
    """`python bench.py chaos`: ONLY the chaos serve harness — the CI
    entry point (scripts/chaos_bench.sh) with its own one-line JSON."""
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        _scope_cpu_compile_cache()
    t_start = time.perf_counter()

    def progress(msg: str) -> None:
        print(f"[chaos +{time.perf_counter() - t_start:7.1f}s] {msg}",
              file=sys.stderr, flush=True)

    chaos = _chaos_bench(progress)
    print(json.dumps({
        "metric": "chaos_ops_completed_under_faults",
        "value": chaos.get("ops_completed", 0),
        "unit": "ops",
        "vs_baseline": 1.0 if chaos.get("passed") else 0.0,
        "detail": chaos,
    }))


def _multichip_child_main() -> None:
    """`python bench.py multichip-child` (internal): ONE leg of the
    multichip series, in a fresh process whose XLA host-platform device
    count the parent pinned via XLA_FLAGS — the device count is fixed
    at backend init and cannot change inside a process.

    Reporting model (1-core CI host): the n shard executions of a
    sharded kernel SERIALIZE on one core, so the measured wall at n
    devices approximates n × the per-chip device time a real n-chip
    plane would overlap. Per-chip rows/sec is therefore input_rows /
    measured_wall at EVERY n — each chip processes rows/n in wall/n.
    What the series actually measures is per-chip EFFICIENCY: padding,
    collective merges, and dispatch overhead show up as a per-chip
    rows/sec drop from n=1 to n=8.

    The serve leg issues point-shaped statements (selective no-group
    aggregations — never mesh-routed, served fused from replicated HBM
    region blocks) and reads the per-chip busy-time the scheduler
    attributed to its least-loaded slot placement. Aggregate serving
    rows/sec = rows scanned / BUSIEST chip's busy time: statements on
    different chips overlap on real hardware, so the makespan is the
    most-loaded chip — the number that must grow with the mesh."""
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        _scope_cpu_compile_cache()
    ndev = int(os.environ["MULTICHIP_NDEV"])
    sf = float(os.environ.get("BENCH_MULTICHIP_SF", "0.05"))
    iters = int(os.environ.get("BENCH_MULTICHIP_ITERS", "3"))
    serve_rounds = int(os.environ.get("BENCH_MULTICHIP_SERVE_ROUNDS",
                                      "32"))

    import jax

    from tidb_tpu import config, devplane, metrics, sched
    from tidb_tpu.benchmarks import tpch
    from tidb_tpu.session import Session
    from tidb_tpu.store.storage import new_mock_storage

    avail = len(jax.devices())
    if avail < ndev:
        print(json.dumps({"n_devices": ndev, "ok": False,
                          "error": f"only {avail} XLA devices visible"}))
        return

    def progress(msg: str) -> None:
        print(f"[multichip n={ndev}] {msg}", file=sys.stderr, flush=True)

    data = tpch.ScaledTpch(sf=sf)
    storage = new_mock_storage()
    session = Session(storage)
    session.execute("CREATE DATABASE tpch")
    session.execute("USE tpch")
    total_rows = tpch.load(session, storage, data, regions_per_table=4)
    progress(f"loaded {total_rows} rows (sf={sf})")

    config.set_var("tidb_tpu_device", 1)
    if ndev > 1:
        devplane.enable_mesh(ndev)

    queries = {}
    for qname in ("q1", "q3"):
        sql = tpch.QUERIES[qname]
        in_rows = sum(data.counts[t] for t in tpch.QUERY_TABLES[qname])
        session.query(sql)          # compile + chunk/HBM cache fill
        secs, _rows = _time_query(session, sql, iters)
        queries[qname] = {
            "input_rows": in_rows,
            "best_secs": round(secs, 4),
            "per_chip_rows_per_sec": round(in_rows / secs, 1),
        }
        progress(f"{qname}: {queries[qname]['per_chip_rows_per_sec']} "
                 f"rows/s/chip")

    # -- serve leg: point statements spread over per-chip slot streams
    serve_sql = ("SELECT COUNT(*), SUM(o_orderdate) FROM orders "
                 "WHERE o_custkey = {k}")
    n_cust = data.counts["customer"]
    session.query(serve_sql.format(k=0))        # compile + HBM fill
    busy0 = sched.device_scheduler().chip_busy_ns()
    grants0 = sched.device_scheduler().snapshot()["grants"]
    t0 = time.perf_counter()
    for i in range(serve_rounds):
        session.query(serve_sql.format(k=(i * 131) % n_cust))
    serve_wall = time.perf_counter() - t0
    busy1 = sched.device_scheduler().chip_busy_ns()
    grants = sched.device_scheduler().snapshot()["grants"] - grants0
    busy = {c: (busy1.get(c, 0) - busy0.get(c, 0)) / 1e9
            for c in busy1 if busy1.get(c, 0) > busy0.get(c, 0)}
    max_busy = max(busy.values(), default=0.0)
    served_rows = data.counts["orders"] * serve_rounds
    serve = {
        "statements": serve_rounds,
        "slot_grants": grants,
        "rows_scanned": served_rows,
        "wall_secs": round(serve_wall, 3),
        "chips_used": len(busy),
        "per_chip_busy_secs": {str(c): round(s, 4)
                               for c, s in sorted(busy.items())},
        "max_chip_busy_secs": round(max_busy, 4),
        "aggregate_rows_per_sec": round(served_rows / max_busy, 1)
        if max_busy else 0.0,
    }
    progress(f"serve: {serve['aggregate_rows_per_sec']} rows/s over "
             f"{serve['chips_used']} chip(s)")

    # the unified plane has no mesh-specific fallback class left; any
    # reason="mesh" count is a regression the parent fails on
    snap = metrics.snapshot()
    mesh_fallbacks = int(sum(
        v for k, v in snap.items()
        if k.startswith(metrics.DEVICE_FALLBACKS)
        and 'reason="mesh"' in k))

    print(json.dumps({
        "n_devices": ndev,
        "platform": jax.devices()[0].platform,
        "sf": sf,
        "queries": queries,
        "serve": serve,
        "mesh_fallbacks": mesh_fallbacks,
        "ok": True,
    }))


def multichip_main() -> None:
    """`python bench.py multichip`: the MULTICHIP perf series — per-chip
    rows/sec and serving aggregate at 1/2/4/8 virtual devices, one
    subprocess per device count (XLA fixes the host-platform device
    count at backend init). Fails (vs_baseline=0, ok=false) on per-chip
    collapse (>25% drop 1→8), a serving aggregate that does not grow
    with the mesh, or any reason="mesh" fallback."""
    import re
    import subprocess

    dev_counts = [int(x) for x in
                  os.environ.get("BENCH_MULTICHIP_DEVS",
                                 "1,2,4,8").split(",")]
    t_start = time.perf_counter()

    def progress(msg: str) -> None:
        print(f"[multichip +{time.perf_counter() - t_start:7.1f}s] {msg}",
              file=sys.stderr, flush=True)

    legs = []
    for n in dev_counts:
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+",
                       "", env.get("XLA_FLAGS", "")).strip()
        env["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={n}".strip())
        env["MULTICHIP_NDEV"] = str(n)
        progress(f"leg n={n}: spawning child")
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "multichip-child"],
            env=env, capture_output=True, text=True)
        sys.stderr.write(proc.stderr)
        line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() \
            else ""
        try:
            leg = json.loads(line)
        except (ValueError, IndexError):
            leg = {"n_devices": n, "ok": False,
                   "error": f"rc={proc.returncode}: {line[:200]!r}"}
        legs.append(leg)

    by_n = {leg["n_devices"]: leg for leg in legs if leg.get("ok")}
    checks = {"per_chip_held": False, "serve_scales": False,
              "no_mesh_fallbacks": False}
    ratios = {}
    lo, hi = min(dev_counts), max(dev_counts)
    if lo in by_n and hi in by_n:
        for qname in by_n[lo]["queries"]:
            r1 = by_n[lo]["queries"][qname]["per_chip_rows_per_sec"]
            rn = by_n[hi]["queries"][qname]["per_chip_rows_per_sec"]
            ratios[qname] = round(rn / r1, 3) if r1 else 0.0
        checks["per_chip_held"] = bool(ratios) and \
            min(ratios.values()) >= 0.75
        s1 = by_n[lo]["serve"]["aggregate_rows_per_sec"]
        sn = by_n[hi]["serve"]["aggregate_rows_per_sec"]
        checks["serve_scales"] = sn > s1 > 0
        checks["no_mesh_fallbacks"] = all(
            leg.get("mesh_fallbacks", 1) == 0 for leg in legs)
    ok = all(checks.values()) and len(by_n) == len(dev_counts)

    print(json.dumps({
        "metric": "multichip_per_chip_rows_per_sec_ratio_1_to_n",
        "value": round(min(ratios.values()), 3) if ratios else 0.0,
        "unit": "ratio",
        "vs_baseline": 1.0 if ok else 0.0,
        "detail": {
            "device_counts": dev_counts,
            "legs": legs,
            "per_chip_ratio_1_to_n": ratios,
            "serve_aggregate_by_n": {
                str(n): by_n[n]["serve"]["aggregate_rows_per_sec"]
                for n in sorted(by_n)},
            "checks": checks,
            "ok": ok,
            "host_cpus": os.cpu_count(),
            "wall_model": "1-core host: sharded kernels serialize, so "
                          "per-chip rows/sec = input_rows / wall at "
                          "every n; serving makespan = busiest chip's "
                          "attributed busy time (see "
                          "_multichip_child_main)",
        },
    }))
    if not ok:
        raise SystemExit(1)


def main() -> None:
    sf = float(os.environ.get("BENCH_SF", "1.0"))
    iters = int(os.environ.get("BENCH_ITERS", "5"))
    host_iters = int(os.environ.get("BENCH_HOST_ITERS", "2"))
    regions = int(os.environ.get("BENCH_REGIONS", "4"))

    device_fallback = None
    prober = None

    def fallback_to_cpu(reason: str) -> None:
        nonlocal sf, iters, host_iters, device_fallback
        print(f"[bench] {reason}: falling back to CPU XLA",
              file=sys.stderr, flush=True)
        import jax
        jax.config.update("jax_platforms", "cpu")
        # the base cache dir holds through-the-tunnel TPU compiles; CPU
        # must not load AOT results built for a different virtualized
        # feature set. BENCH r05 solved that by DISABLING the cache —
        # which re-paid Q1's ~49s first compile in every bench process.
        # Instead: scope to the per-host-feature-set CPU subdirectory
        # (see _scope_cpu_compile_cache; warm-run contract misses == 0,
        # tests/test_compile_cache_warm.py). Importing the package here
        # is safe — jax_platforms is already pinned to cpu above.
        if not _scope_cpu_compile_cache():
            # explicit operator disable (TIDB_TPU_COMPILE_CACHE=0)
            # stays disabled — don't resurrect a cache the operator
            # just killed (e.g. after a poisoning incident)
            jax.config.update("jax_compilation_cache_dir", None)
        device_fallback = f"cpu ({reason})"
        if "BENCH_SF" not in os.environ:
            # CPU XLA runs the warm path ~20-40x slower than a chip;
            # full sf=1 would blow typical harness timeouts. The metric
            # is rows/s, so a smaller sf stays comparable.
            sf = float(os.environ.get("BENCH_CPU_SF", "0.2"))
            iters = min(iters, 2)
            host_iters = 1

    if os.environ.get("BENCH_SKIP_PROBE", "0") != "1":
        prober = _DeviceProber()
        prober.start()
        if not prober.wait_initial():
            # chip tunnel down: measure CPU-XLA vs numpy rather than
            # hang. The prober keeps re-probing in the background so the
            # report still records the moment the tunnel answers.
            fallback_to_cpu("chip tunnel unavailable")
        elif prober.snapshot.get("platform") == "cpu":
            # the probe ANSWERED but with host CPU only — no accelerator
            # behind the tunnel. Same CPU economics apply, and crucially
            # the persistent compile cache must not serve entries built
            # for a different host feature set.
            prober.stop()
            fallback_to_cpu("no accelerator visible")
        else:
            prober.stop()   # a real chip answered: run on it

    from tidb_tpu import config
    from tidb_tpu.benchmarks import tpch
    from tidb_tpu.parallel import config as mesh_config
    from tidb_tpu.session import Session
    from tidb_tpu.store.storage import new_mock_storage

    def progress(msg: str) -> None:
        print(f"[bench +{time.perf_counter() - t_start:8.1f}s] {msg}",
              file=sys.stderr, flush=True)

    t_start = t0 = time.perf_counter()
    progress(f"generating TPC-H sf={sf}")
    data = tpch.ScaledTpch(sf=sf)
    storage = new_mock_storage()
    session = Session(storage)
    session.execute("CREATE DATABASE tpch")
    session.execute("USE tpch")
    progress("loading")
    total_rows = tpch.load(session, storage, data,
                           regions_per_table=regions)
    load_secs = time.perf_counter() - t0
    progress(f"loaded {total_rows} rows in {load_secs:.1f}s")

    roof_gbps, roof_src = _memory_roofline_gbps()
    detail: dict = {"sf": sf, "iters": iters, "rows_loaded": total_rows,
                    "load_secs": round(load_secs, 1),
                    # vs_baseline is measured-vs-measured on this
                    # machine: device XLA path / numpy host path, same
                    # plans, same store. The Go reference cannot be
                    # built here (no Go toolchain in the image) — see
                    # BASELINE.md "Baseline calibration" for why the
                    # vectorized numpy host is a conservative stand-in
                    # for the reference's row-at-a-time chunk executor.
                    "baseline_kind": "measured numpy host executor "
                                     "(no Go toolchain; BASELINE.md)",
                    "memory_roofline_gbps": round(roof_gbps, 1),
                    "memory_roofline_source": roof_src,
                    # cross-round comparability: XLA device-path times
                    # scale with cores (numpy host baseline much less),
                    # so a rows/s move between rounds is only meaningful
                    # at equal core counts (r05 vs r06 showed a ~3x
                    # device-path swing from container size alone)
                    "host_cpus": os.cpu_count()}
    if device_fallback:
        detail["device_platform_fallback"] = device_fallback
    if prober is not None and prober.snapshot is not None:
        detail["device_probe"] = prober.snapshot
    speedups = []
    device_rps = []
    rooflines = []

    for qname, sql in tpch.QUERIES.items():
        in_rows = sum(data.counts[t] for t in tpch.QUERY_TABLES[qname])
        in_bytes = _query_bytes(data, qname)

        # device path: mesh over the visible chip(s) + device kernels
        config.set_var("tidb_tpu_device", 1)
        mesh_config.enable_mesh()
        progress(f"{qname}: device cold run (compile + cache fill)")
        hbm0 = _hbm_counters()
        warm0 = time.perf_counter()
        session.query(sql)   # compile + chunk/HBM cache fill
        cold_secs = time.perf_counter() - warm0
        hbm_cold = _hbm_counters()
        progress(f"{qname}: device cold took {cold_secs:.1f}s; timing "
                 f"warm")
        bytes0 = _bytes_counters()
        d_secs, d_rows = _time_query(session, sql, iters)
        hbm_warm = _hbm_counters()
        bytes1 = _bytes_counters()

        # per-operator device-time attribution: one extra instrumented
        # run with tidb_tpu_runtime_stats_device on (block_until_ready
        # serializes dispatch, so it must never run inside the timed
        # iterations). Future rounds diff these totals to pin a
        # regression on the operator that caused it.
        config.set_var("tidb_tpu_runtime_stats_device", 1)
        mem_host_peak = mem_device_peak = 0
        try:
            session.query(sql)
            coll = getattr(session, "_last_stats", None)
            # per-query tracked memory peaks (memtrack statement root):
            # future rounds correlate a rows/sec regression with the
            # footprint move that caused it
            mem = getattr(session, "_last_mem", None)
            if mem is not None:
                mem_host_peak = mem.host_peak
                mem_device_peak = mem.device_peak
            if coll is not None:
                # sum per operator NAME: Q3/Q5 plans hold several
                # HashJoin/TableReader nodes and a dict comprehension
                # would keep only the last one's numbers
                op_detail = {}
                for s in coll.ops():
                    if not s.loops:
                        continue
                    a = op_detail.setdefault(
                        s.name, {"time_ns": 0, "device_time_ns": 0,
                                 "act_rows": 0, "superchunks": 0,
                                 "coalesced_chunks": 0,
                                 "superchunk_fill_rows": 0,
                                 "superchunk_bucket_rows": 0,
                                 "pipeline_stall_ns": 0})
                    a["time_ns"] += s.time_ns
                    a["device_time_ns"] += s.device_time_ns
                    a["act_rows"] += s.act_rows
                    a["superchunks"] += s.superchunks
                    a["coalesced_chunks"] += s.coalesced_chunks
                    a["superchunk_fill_rows"] += s.superchunk_fill_rows
                    a["superchunk_bucket_rows"] += s.superchunk_bucket_rows
                    a["pipeline_stall_ns"] += s.pipeline_stall_ns
                op_device = {k: v["device_time_ns"]
                             for k, v in op_detail.items()
                             if v["device_time_ns"]}
            else:
                op_detail, op_device = {}, {}
        except Exception as e:  # noqa: BLE001 - attribution is advisory
            # keep op_device_time_ns shape-stable (op -> int ns) so
            # cross-round diff tooling never chokes on an error string
            op_detail, op_device = {}, {}
            detail.setdefault("op_stats_errors", {})[qname] = str(e)
        finally:
            config.set_var("tidb_tpu_runtime_stats_device", 0)

        # measured host baseline: same SQL, same store, numpy operators
        config.set_var("tidb_tpu_device", 0)
        mesh_config.disable_mesh()
        progress(f"{qname}: device best {d_secs:.3f}s; host baseline")
        session.query(sql)   # chunk-cache fill for fairness
        h_secs, h_rows = _time_query(session, sql, host_iters)
        progress(f"{qname}: host best {h_secs:.3f}s")

        if not _approx_rows_equal(d_rows, h_rows):
            raise SystemExit(
                f"{qname}: device and host disagree: "
                f"{d_rows[:3]} vs {h_rows[:3]}")

        d_rps = in_rows / d_secs
        h_rps = in_rows / h_secs
        d_gbps = in_bytes / d_secs / 1e9
        speedups.append(d_rps / h_rps)
        device_rps.append(d_rps)
        rooflines.append(d_gbps / roof_gbps)
        # superchunk pipeline attribution (from the instrumented run):
        # how coalesced the device execution was and how long the host
        # sat stalled on readback — the numbers the next BENCH round
        # diffs to attribute a roofline move
        sc_count = sum(v["superchunks"] for v in op_detail.values())
        sc_src = sum(v["coalesced_chunks"] for v in op_detail.values())
        sc_fill = sum(v["superchunk_fill_rows"] for v in op_detail.values())
        sc_bucket = sum(v["superchunk_bucket_rows"]
                        for v in op_detail.values())
        sc_stall = sum(v["pipeline_stall_ns"] for v in op_detail.values())
        detail[qname] = {
            "input_rows": in_rows,
            "input_bytes": in_bytes,
            "device_secs": round(d_secs, 4),
            "host_secs": round(h_secs, 4),
            "device_rows_per_sec": round(d_rps, 1),
            "host_rows_per_sec": round(h_rps, 1),
            "device_scan_gbps": round(d_gbps, 3),
            "roofline_fraction": round(d_gbps / roof_gbps, 4),
            "speedup": round(d_rps / h_rps, 2),
            # warm/cold split: cold_* is the first execution (compile
            # load + scan + decode + cache fill), warm_* the best of the
            # timed iterations serving from the chunk/HBM caches —
            # device_secs/roofline_fraction remain the warm numbers for
            # cross-round diffing, first_run_secs the cold alias
            "cold_secs": round(cold_secs, 4),
            "warm_secs": round(d_secs, 4),
            "cold_rows_per_sec": round(in_rows / cold_secs, 1),
            "warm_rows_per_sec": round(d_rps, 1),
            "cold_roofline_fraction": round(
                in_bytes / cold_secs / 1e9 / roof_gbps, 4),
            "warm_roofline_fraction": round(d_gbps / roof_gbps, 4),
            "first_run_secs": round(cold_secs, 2),
            # HBM region-block cache traffic, split at the cold/warm
            # boundary: a healthy warm phase is all hits
            "hbm_cache": {
                "cold": {k: hbm_cold[k] - hbm0[k] for k in hbm0},
                "warm": {k: hbm_warm[k] - hbm_cold[k] for k in hbm0},
            },
            "result_rows": len(d_rows),
            # encoded vs decoded-equivalent input bytes the warm
            # iterations' device dispatches touched (all iters summed):
            # the auditable compression win of encoded execution
            "bytes_touched": _bytes_touched(bytes0, bytes1),
            "op_device_time_ns": op_device,
            "op_stats": op_detail,
            "peak_mem_host_bytes": mem_host_peak,
            "peak_mem_device_bytes": mem_device_peak,
            "superchunk": {
                "count": sc_count,
                "coalesced_chunks": sc_src,
                "fill_ratio": round(sc_fill / sc_bucket, 4)
                if sc_bucket else 0.0,
                "pipeline_stall_ns": sc_stall,
            },
        }

    config.set_var("tidb_tpu_device", 1)
    mesh_config.enable_mesh()
    if os.environ.get("BENCH_SKEW", "1") != "0":
        progress("skew_join: loading the Zipf-skewed workload")
        try:
            detail["skew_join"] = _skew_join_bench(
                session, storage, sf, iters, host_iters, progress)
        except Exception as e:  # noqa: BLE001 - advisory block: the
            # headline TPC-H numbers must survive a skew-bench failure
            detail["skew_join_error"] = str(e)

    if os.environ.get("BENCH_SERVE", "1") != "0":
        progress("serve: multi-client wire load harness")
        # the serve harness brings its own storage/server; the mesh
        # executors stay out of it (concurrent mesh collectives belong
        # to the MULTICHIP series, not the serving series)
        mesh_config.disable_mesh()
        try:
            detail["serve"] = _serve_bench(progress)
        except Exception as e:  # noqa: BLE001 - advisory block: the
            # headline TPC-H numbers must survive a serve-bench failure
            detail["serve_error"] = str(e)
        finally:
            mesh_config.enable_mesh()

    if os.environ.get("BENCH_HTAP", "1") != "0":
        progress("htap: write-pressure sweep")
        mesh_config.disable_mesh()
        try:
            detail["htap"] = _htap_bench(progress)
        except Exception as e:  # noqa: BLE001 - advisory block: the
            # headline TPC-H numbers must survive an htap-bench failure
            detail["htap_error"] = str(e)
        finally:
            mesh_config.enable_mesh()

    if os.environ.get("BENCH_CHAOS", "1") != "0":
        progress("chaos: serve+HTAP mix under the seeded fault schedule")
        mesh_config.disable_mesh()
        try:
            detail["chaos"] = _chaos_bench(progress)
        except Exception as e:  # noqa: BLE001 - advisory block: the
            # headline TPC-H numbers must survive a chaos-bench failure
            detail["chaos_error"] = str(e)
        finally:
            mesh_config.enable_mesh()
            from tidb_tpu.util import failpoint as _fp
            _fp.disable_all()

    if os.environ.get("BENCH_KERNEL_MICRO", "1") != "0":
        try:
            detail["kernel_only_q1_rows_per_sec"] = round(_kernel_micro(), 1)
        except Exception as e:  # noqa: BLE001 - micro is informational
            detail["kernel_only_error"] = str(e)

    if prober is not None:
        prober.stop()
        if device_fallback and prober.snapshot is not None and \
                prober.snapshot.get("platform") != "cpu":
            # a real chip answered AFTER the CPU decision: too late to
            # switch an initialized platform, but the driver should know
            # a re-run would land on chip (and which one)
            detail["device_probe_late"] = prober.snapshot
            detail["device_probe_late_after_secs"] = round(
                prober.snapshot_at - t_start, 1)

    # persistent compile cache accounting: misses are fresh XLA compiles
    # this run paid, hits are executables loaded from disk (the 48.8s
    # first-run stall of BENCH_r05 becomes a hit on every warm run)
    from tidb_tpu.util import compile_cache
    detail["compile_cache"] = compile_cache.stats()
    # process-cumulative HBM cache counters (per-query splits above)
    detail["hbm_cache_totals"] = _hbm_counters()

    geo_rps = math.exp(sum(math.log(x) for x in device_rps)
                       / len(device_rps))
    geo_speedup = math.exp(sum(math.log(x) for x in speedups)
                           / len(speedups))
    detail["roofline_fraction_geomean"] = round(
        math.exp(sum(math.log(x) for x in rooflines) / len(rooflines)), 4)
    print(json.dumps({
        "metric": "tpch_q1_q3_q5_e2e_rows_per_sec_per_chip",
        "value": round(geo_rps, 1),
        "unit": "rows/s",
        "vs_baseline": round(geo_speedup, 3),
        "detail": detail,
    }))


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "serve":
        serve_main()
    elif len(sys.argv) > 1 and sys.argv[1] == "htap":
        htap_main()
    elif len(sys.argv) > 1 and sys.argv[1] == "encoded":
        encoded_main()
    elif len(sys.argv) > 1 and sys.argv[1] == "fleet":
        fleet_main()
    elif len(sys.argv) > 1 and sys.argv[1] == "chaos":
        chaos_main()
    elif len(sys.argv) > 1 and sys.argv[1] == "trace":
        trace_main()
    elif len(sys.argv) > 1 and sys.argv[1] == "profile":
        profile_main()
    elif len(sys.argv) > 1 and sys.argv[1] == "lintcheck":
        lintcheck_main()
    elif len(sys.argv) > 1 and sys.argv[1] == "multichip":
        multichip_main()
    elif len(sys.argv) > 1 and sys.argv[1] == "multichip-child":
        _multichip_child_main()
    else:
        main()
