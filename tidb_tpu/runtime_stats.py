"""Per-operator runtime statistics: the RuntimeStatsColl analogue.

Reference: the reference's execdetails.RuntimeStatsColl — every executor
registers basic stats (actual rows, loop count, wall time) keyed by plan
node, EXPLAIN ANALYZE renders them next to the plan tree, and the slow
log / statement summary embed them per statement.

Here a `StatsCollector` lives for one statement execution. The session
installs it in a thread-local around build_executor + execution;
`instrument()` (called from build_executor) wraps each executor's
`chunks`/`partials`/`execute` so every batch yielded records
rows/loops/host-time into the node's `OpStats`. The coprocessor fan-out
re-installs the collector inside its pool workers (like the sysvar
overlay) so storage-side device kernels can attribute device time to the
reader node that issued them.

Device time is EXPENSIVE to observe — `jax.block_until_ready` serializes
dispatch — so it is gated behind the `tidb_tpu_runtime_stats_device`
sysvar and collected only at explicit kernel call sites via
`device_call()` / `device_section()`. Host-side counts stay on by
default (`tidb_tpu_runtime_stats`): the per-chunk cost is one
perf_counter read and three integer adds, amortized over 64k-row chunks.
"""

from __future__ import annotations

import contextlib
import threading
import time

__all__ = ["OpStats", "StatsCollector", "collecting", "current",
           "instrument", "device_call", "device_section", "fmt_ns",
           "fmt_bytes", "note_superchunk", "note_pipeline_stall",
           "note_finalize_wait", "note_fallback", "note_encoding",
           "note_bytes_touched", "note_kernel", "note_mode",
           "device_watermark"]

_tl = threading.local()


_mem_stats_available: bool | None = None   # None = not yet probed


def device_watermark() -> int:
    """Backend peak-memory watermark, 0 when the platform doesn't report
    one (CPU jax has no allocator stats). PROCESS-WIDE: concurrent
    statements' allocations inflate it for each other, so it feeds only
    the server-root gauge (tidb_tpu_device_peak_bytes) — per-operator
    `mem` comes from memtrack's per-statement trackers. The availability
    probe is cached so CPU backends never pay a raised-and-swallowed
    exception per call."""
    global _mem_stats_available
    if _mem_stats_available is False:
        return 0
    try:
        import jax
        ms = jax.local_devices()[0].memory_stats()
        if ms:
            _mem_stats_available = True
            return int(ms.get("peak_bytes_in_use", 0) or 0)
        _mem_stats_available = False
    except Exception:  # noqa: BLE001 - stats must never break execution
        _mem_stats_available = False
    return 0


class OpStats:
    """One physical operator's actuals for one statement execution."""

    __slots__ = ("name", "act_rows", "loops", "time_ns",
                 "device_time_ns", "cop_tasks",
                 "superchunks", "coalesced_chunks", "superchunk_fill_rows",
                 "superchunk_bucket_rows", "pipeline_stall_ns",
                 "fallbacks", "encoding", "kernel_family",
                 "kernel_compile", "kernel_bytes", "kernel_busy_ns",
                 "kernel_dispatches", "mode")

    def __init__(self, name: str):
        self.name = name
        self.act_rows = 0
        self.loops = 0
        self.time_ns = 0           # host wall, inclusive of children
        self.device_time_ns = 0    # sum around block_until_ready
        self.cop_tasks = 0
        # superchunk pipeline (ops/runtime.py): how the operator's device
        # work was batched and how long the host sat blocked on readback
        self.superchunks = 0            # coalesced device dispatches
        self.coalesced_chunks = 0       # source chunks folded into them
        self.superchunk_fill_rows = 0   # live rows across superchunks
        self.superchunk_bucket_rows = 0  # padded bucket rows (>= fill)
        self.pipeline_stall_ns = 0      # host blocked in finalize
        # device->host fallbacks: batches this operator planned for the
        # device but executed on the host (capacity/collision miss that
        # survived the partition retry, or a non-device-safe plan)
        self.fallbacks = 0
        # encoded-execution mode this operator last ran in (EXPLAIN
        # ANALYZE pipeline column): "" = nothing noted, else one of
        # encoded | decoded | direct-agg | fused:<fragment>
        self.encoding = ""
        # kernel-profile feed (tidb_tpu/profiler.py, EXPLAIN ANALYZE
        # `kernel` column): which kernel family served this operator's
        # dispatches this statement, how its compile was satisfied
        # (hit|miss|cached, the persistent-cache attribution) and the
        # bytes/busy-ns this statement's dispatches contributed — the
        # per-statement slice of the process-wide profile row, from
        # which the online roofline_fraction is rendered
        self.kernel_family = ""
        self.kernel_compile = ""
        self.kernel_bytes = 0
        self.kernel_busy_ns = 0
        self.kernel_dispatches = 0
        # execution mode that actually ran (the perfschema mode-history
        # memo's vocabulary): "" = nothing noted, else one of
        # direct | hash | sort | fused | hybrid | host
        self.mode = ""

    def fill_ratio(self) -> float:
        """Live rows over padded bucket rows (0.0 when no superchunks)."""
        if not self.superchunk_bucket_rows:
            return 0.0
        return self.superchunk_fill_rows / self.superchunk_bucket_rows

    def to_dict(self) -> dict:
        return {"name": self.name, "act_rows": self.act_rows,
                "loops": self.loops, "time_ns": self.time_ns,
                "device_time_ns": self.device_time_ns,
                "cop_tasks": self.cop_tasks,
                "superchunks": self.superchunks,
                "coalesced_chunks": self.coalesced_chunks,
                "superchunk_fill_rows": self.superchunk_fill_rows,
                "superchunk_bucket_rows": self.superchunk_bucket_rows,
                "pipeline_stall_ns": self.pipeline_stall_ns,
                "fallbacks": self.fallbacks,
                "encoding": self.encoding,
                "kernel_family": self.kernel_family,
                "kernel_compile": self.kernel_compile,
                "kernel_bytes": self.kernel_bytes,
                "kernel_busy_ns": self.kernel_busy_ns,
                "kernel_dispatches": self.kernel_dispatches,
                "mode": self.mode}


class StatsCollector:
    """Stats for one statement: OpStats keyed by plan-node identity.

    The entry pins the plan node, so ids cannot be recycled while the
    collector lives. `link()` routes records made against a secondary
    key (a reader's CopPlan, executed storage-side) onto the owning
    node's OpStats. Device notes may arrive from cop pool workers, so
    those go through a lock; the host counters are only touched by the
    session thread that drives the executor tree."""

    def __init__(self, device: bool = False):
        self.device = device
        # guarded-by: _lock
        self._nodes: dict[int, tuple[object, OpStats]] = {}
        self._lock = threading.Lock()

    def node(self, plan, name: str | None = None) -> OpStats:
        ent = self._nodes.get(id(plan))
        if ent is not None:
            return ent[1]
        if name is None:
            name = type(plan).__name__.removeprefix("Phys")
        st = OpStats(name)
        with self._lock:
            self._nodes.setdefault(id(plan), (plan, st))
        return self._nodes[id(plan)][1]

    def link(self, alias_plan, stats: OpStats) -> None:
        """Route records against `alias_plan` onto `stats`."""
        with self._lock:
            self._nodes[id(alias_plan)] = (alias_plan, stats)

    def get(self, plan) -> OpStats | None:
        ent = self._nodes.get(id(plan))
        return ent[1] if ent is not None else None

    def note_device(self, plan, elapsed_ns: int) -> None:
        # NO watermark read here: the backend's peak-bytes gauge is
        # process-wide, so a concurrent statement's build would bleed
        # into this operator's mem — tracked bytes (memtrack) carry the
        # per-op attribution instead
        st = self.node(plan)
        with self._lock:
            st.device_time_ns += elapsed_ns

    def note_cop_tasks(self, plan, n: int) -> None:
        st = self.node(plan)
        with self._lock:
            st.cop_tasks += n

    def note_superchunk(self, plan, rows: int, bucket: int,
                        sources: int) -> None:
        """One coalesced device dispatch: `sources` chunks folded into
        `rows` live rows padded to a `bucket`-row shape. May arrive from
        cop pool workers, hence the lock."""
        st = self.node(plan)
        with self._lock:
            st.superchunks += 1
            st.coalesced_chunks += sources
            st.superchunk_fill_rows += rows
            st.superchunk_bucket_rows += bucket

    def note_pipeline_stall(self, plan, ns: int) -> None:
        st = self.node(plan)
        with self._lock:
            st.pipeline_stall_ns += ns

    def note_fallback(self, plan) -> "OpStats":
        """One device->host fallback on this operator (may arrive from
        cop pool workers, hence the lock). Returns the OpStats so the
        caller can label the metric with the operator name."""
        st = self.node(plan)
        with self._lock:
            st.fallbacks += 1
        return st

    def note_encoding(self, plan, mode: str) -> None:
        """Record the operator's encoded-execution mode (encoded /
        decoded / direct-agg / fused:<fragment>) for the EXPLAIN
        ANALYZE pipeline column. May arrive from cop pool workers."""
        st = self.node(plan)
        with self._lock:
            st.encoding = mode

    def note_kernel(self, plan, family: str, compile_src: str,
                    nbytes: int, busy_ns: int) -> None:
        """Fold one kernel dispatch's profile slice onto the operator
        (EXPLAIN ANALYZE `kernel` column + the slow log's roofline
        line). May arrive from cop pool workers, hence the lock."""
        st = self.node(plan)
        with self._lock:
            st.kernel_family = family
            if compile_src:
                st.kernel_compile = compile_src
            st.kernel_bytes += nbytes
            st.kernel_busy_ns += busy_ns
            st.kernel_dispatches += 1

    def note_mode(self, plan, mode: str) -> None:
        """Record the execution mode that actually ran (direct / hash /
        sort / fused / hybrid / host) — the perfschema mode-history
        memo's per-operator feed."""
        st = self.node(plan)
        with self._lock:
            st.mode = mode

    def ops(self) -> list[OpStats]:
        """Distinct OpStats (aliases deduped), insertion order."""
        sealed = getattr(self, "_sealed_ops", None)
        if sealed is not None:
            return list(sealed)
        seen: list[OpStats] = []
        for _plan, st in self._nodes.values():
            if all(st is not s for s in seen):
                seen.append(st)
        return seen

    def seal(self) -> None:
        """Drop the plan-object references once the statement is done:
        the collector outlives the statement on the session (bench reads
        it), and it must not pin the executed plan tree. ops() keeps
        answering from the sealed snapshot."""
        ops = self.ops()
        with self._lock:
            self._sealed_ops = ops
            self._nodes = {}


@contextlib.contextmanager
def collecting(coll: StatsCollector | None):
    """Install `coll` as this thread's active collector. Passing the
    already-active collector (or None) nests transparently."""
    prev = getattr(_tl, "coll", None)
    _tl.coll = coll if coll is not None else prev
    try:
        yield _tl.coll
    finally:
        _tl.coll = prev


def current() -> StatsCollector | None:
    return getattr(_tl, "coll", None)


def note_superchunk(plan, rows: int, bucket: int, sources: int) -> None:
    """Record a coalesced dispatch against the active collector (no-op
    without one) — the call-site form for executors and the cop handler."""
    coll = getattr(_tl, "coll", None)
    if coll is not None:
        coll.note_superchunk(plan, rows, bucket, sources)


def note_pipeline_stall(plan, ns: int) -> None:
    coll = getattr(_tl, "coll", None)
    if coll is not None:
        coll.note_pipeline_stall(plan, ns)


def note_encoding(plan, mode: str) -> None:
    """Record the operator's encoded-execution mode against the active
    collector (no-op without one): EXPLAIN ANALYZE's enc= note."""
    coll = getattr(_tl, "coll", None)
    if coll is not None and plan is not None:
        coll.note_encoding(plan, mode)


def note_kernel(plan, family: str, compile_src: str, nbytes: int,
                busy_ns: int) -> None:
    """Record a kernel dispatch's profile slice against the active
    collector (no-op without one) — called from profiler.note_dispatch
    so every instrumented seam feeds both the process-wide registry row
    and the statement's per-operator view with one call."""
    coll = getattr(_tl, "coll", None)
    if coll is not None and plan is not None:
        coll.note_kernel(plan, family, compile_src, nbytes, busy_ns)


def note_mode(plan, mode: str) -> None:
    """Record the operator's actually-run execution mode against the
    active collector (no-op without one): the memo's vocabulary
    (direct | hash | sort | fused | hybrid | host)."""
    coll = getattr(_tl, "coll", None)
    if coll is not None and plan is not None:
        coll.note_mode(plan, mode)


def note_bytes_touched(decoded_equiv: int, encoded: int) -> None:
    """Account one device dispatch's input bytes on the two
    bytes-touched counter families: `encoded` is what the dispatch
    actually staged/read (dict codes + validity at the padded bucket),
    `decoded_equiv` is what the same input would occupy decoded into
    wide host vectors — the auditable compression win BENCH reports as
    the per-query bytes_touched column. Also the per-tenant bytes
    ledger's single chokepoint (meter.py)."""
    from tidb_tpu import meter, metrics
    metrics.counter(metrics.BYTES_DECODED_EQUIV, inc=decoded_equiv)
    metrics.counter(metrics.BYTES_ENCODED, inc=encoded)
    meter.note_bytes(encoded, decoded_equiv)


def note_fallback(plan, reason: str) -> None:
    """Record one device->host fallback: counted on the operator's
    OpStats (EXPLAIN ANALYZE `pipeline` column) and on the
    tidb_tpu_device_fallback_total{op,reason} metric family. `reason`
    is one of capacity|collision|unsupported|encoding (single-chip),
    mesh (a mesh stream batch served by the host), or the device-fault
    recovery pair fault|quarantine (tidb_tpu/sched.py DeviceHealth) —
    the designed fallback causes; anything else should RAISE, not
    fall back."""
    from tidb_tpu import metrics
    coll = getattr(_tl, "coll", None)
    name = None
    if coll is not None and plan is not None:
        name = coll.note_fallback(plan).name
    if name is None:
        name = type(plan).__name__.removeprefix("Phys") \
            if plan is not None else "?"
    metrics.counter(metrics.DEVICE_FALLBACKS,
                    {"op": name, "reason": reason})


def note_finalize_wait(plan, ns: int) -> None:
    """Blocked-readback time at a pipeline's output boundary: always
    recorded as pipeline stall; with the device-profiling sysvar on it
    doubles as the operator's device time (under dispatch overlap,
    per-launch timing is meaningless — the honest number is the wait at
    the boundary where the host actually needed the result)."""
    coll = getattr(_tl, "coll", None)
    if coll is None:
        return
    coll.note_pipeline_stall(plan, ns)
    if coll.device:
        coll.note_device(plan, ns)


@contextlib.contextmanager
def suspended():
    """Hide the active collector (internal bookkeeping sessions run
    inside a client statement but must not pollute its operator stats —
    the stats twin of trace.detach())."""
    prev = getattr(_tl, "coll", None)
    _tl.coll = None
    try:
        yield
    finally:
        _tl.coll = prev


# -- executor instrumentation (wired from build_executor) -------------------


def instrument(exe, plan) -> None:
    """Wrap the executor's production methods so each yielded batch
    records rows/loops/time into the active collector's node for `plan`.
    Also pre-registers the plan node (and its pushed CopPlans) with the
    active memory tracker, so storage-side allocations credit the
    issuing reader. No-op when neither is active (internal sessions,
    stats off)."""
    from tidb_tpu import memtrack
    mt = memtrack.current()
    if mt is not None:
        mnode = mt.node(plan)
        for attr in ("cop", "index_cop", "table_cop"):
            cop = getattr(plan, attr, None)
            if cop is not None:
                mt.link(cop, mnode)
    coll = current()
    if coll is None:
        return
    st = coll.node(plan)
    # storage-side execution of a reader's pushed subplan records against
    # the CopPlan object; route those onto the reader's stats
    for attr in ("cop", "index_cop", "table_cop"):
        cop = getattr(plan, attr, None)
        if cop is not None:
            coll.link(cop, st)

    if hasattr(exe, "chunks"):
        exe.chunks = _wrap_iter(exe.chunks, st)
    if hasattr(exe, "partials"):
        exe.partials = _wrap_iter(exe.partials, st)
    if hasattr(exe, "execute"):
        inner_exec = exe.execute

        def execute(ctx):
            t0 = time.perf_counter_ns()
            try:
                n = inner_exec(ctx)
            finally:
                st.time_ns += time.perf_counter_ns() - t0
            st.loops += 1
            if isinstance(n, int):
                st.act_rows += n
            return n

        exe.execute = execute


def _wrap_iter(fn, st: OpStats):
    def produce(ctx):
        it = fn(ctx)
        while True:
            t0 = time.perf_counter_ns()
            try:
                out = next(it)
            except StopIteration:
                st.time_ns += time.perf_counter_ns() - t0
                return
            st.time_ns += time.perf_counter_ns() - t0
            st.loops += 1
            n = getattr(out, "num_rows", None)
            if n is None:
                # agg-pushdown readers yield GroupResult partials: count
                # the groups they carry, not zero
                n = len(getattr(out, "keys", ()) or ())
            st.act_rows += n
            yield out

    return produce


# -- device timing (gated: block_until_ready serializes dispatch) -----------


def device_call(plan, fn, *args):
    """Run a device kernel call, attributing its completion time to
    `plan`'s stats when device timing is on. With the sysvar off (or no
    collector) this is one attribute read + one call — cheap enough for
    the hot loop."""
    coll = getattr(_tl, "coll", None)
    if coll is None or not coll.device:
        return fn(*args)
    t0 = time.perf_counter_ns()
    out = fn(*args)
    try:
        import jax
        jax.block_until_ready(out)
    except Exception:  # noqa: BLE001 - host results pass through
        pass
    coll.note_device(plan, time.perf_counter_ns() - t0)
    return out


@contextlib.contextmanager
def device_section(plan, errors: bool = True):
    """Time a whole device region (mesh pipelines overlap async launches,
    so per-launch timing is meaningless — the region's wall time, which
    ends on the blocking readback, is the honest number). With
    errors=False the section records only on SUCCESS — device_call's
    contract, for call sites whose failures retry through an escalated
    kernel (the failed attempt's time would double against the
    retry's)."""
    coll = getattr(_tl, "coll", None)
    if coll is None or not coll.device:
        yield
        return
    t0 = time.perf_counter_ns()
    try:
        yield
    except BaseException:
        if errors:
            coll.note_device(plan, time.perf_counter_ns() - t0)
        raise
    coll.note_device(plan, time.perf_counter_ns() - t0)


# -- rendering helpers ------------------------------------------------------


def fmt_ns(ns: int) -> str:
    if ns >= 1_000_000_000:
        return f"{ns / 1e9:.2f}s"
    if ns >= 1_000_000:
        return f"{ns / 1e6:.2f}ms"
    if ns >= 1_000:
        return f"{ns / 1e3:.1f}us"
    return f"{ns}ns"


def fmt_bytes(n: int) -> str:
    if n >= 1 << 30:
        return f"{n / (1 << 30):.2f}GB"
    if n >= 1 << 20:
        return f"{n / (1 << 20):.2f}MB"
    if n >= 1 << 10:
        return f"{n / (1 << 10):.1f}KB"
    return f"{n}B" if n else "0B"
