"""MySQL X-Protocol server skeleton.

Reference: /root/reference/x-server/server.go (275 LoC, vestigial in the
reference too: an accept loop importing the X-protocol protobufs blank,
never wired to a session). Parity skeleton: accepts connections, parses
the X-Protocol frame header (little-endian u32 length + u8 message
type), answers CON_CAPABILITIES_GET with an empty capabilities frame
and everything else with an X-Protocol ERROR frame stating the protocol
is not implemented, then closes on CON_CLOSE. Exists so X-Protocol
clients fail fast with a protocol-level message instead of a hang."""

from __future__ import annotations

import socket
import struct
import threading

__all__ = ["XServer"]

# X Protocol client message types (Mysqlx.ClientMessages.Type)
CON_CAPABILITIES_GET = 1
CON_CLOSE = 3

MAX_FRAME = 1 << 16     # nothing legitimate is bigger on this skeleton

# server message types (Mysqlx.ServerMessages.Type)
SV_OK = 0
SV_ERROR = 1
SV_CONN_CAPABILITIES = 2


def _frame(tp: int, payload: bytes = b"") -> bytes:
    return struct.pack("<IB", len(payload) + 1, tp) + payload


class XServer:
    """Accept loop only (matching the reference's x-server scope)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self.port = self._sock.getsockname()[1]
        self._closing = threading.Event()

    def start(self) -> None:
        threading.Thread(target=self._accept, daemon=True,
                         name="x-server-accept").start()

    def _accept(self) -> None:
        while not self._closing.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True, name="x-server-conn").start()

    def _serve(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(30)
            while True:
                hdr = self._read_exact(conn, 5)
                if hdr is None:
                    return
                length, tp = struct.unpack("<IB", hdr)
                if length > MAX_FRAME:   # don't buffer attacker-sized frames
                    return
                payload = self._read_exact(conn, length - 1) \
                    if length > 1 else b""
                if payload is None:
                    return
                if tp == CON_CLOSE:
                    conn.sendall(_frame(SV_OK))
                    return
                if tp == CON_CAPABILITIES_GET:
                    # empty Capabilities message (no fields set)
                    conn.sendall(_frame(SV_CONN_CAPABILITIES))
                    continue
                conn.sendall(_frame(SV_ERROR,
                                    b"X Protocol not implemented; "
                                    b"use the classic MySQL protocol"))
        except (OSError, ValueError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    @staticmethod
    def _read_exact(conn: socket.socket, n: int) -> bytes | None:
        buf = b""
        while len(buf) < n:
            part = conn.recv(n - len(buf))
            if not part:
                return None
            buf += part
        return buf

    def close(self) -> None:
        self._closing.set()
        try:
            self._sock.close()
        except OSError:
            pass
