"""MySQL wire packet layer.

Reference: /root/reference/server/packetio.go (4-byte header framing:
3-byte little-endian length + 1-byte sequence) and server/util.go
(length-encoded integers/strings). Pure host control-plane code.
"""

from __future__ import annotations

import socket
import struct

MAX_PAYLOAD = 0xFFFFFF


class PacketIO:
    """Framed packet reader/writer over a socket with sequence tracking."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.seq = 0

    def _recv_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("client closed connection")
            buf += chunk
        return buf

    def read_packet(self) -> bytes:
        payload = b""
        while True:
            header = self._recv_exact(4)
            length = header[0] | (header[1] << 8) | (header[2] << 16)
            self.seq = (header[3] + 1) & 0xFF
            payload += self._recv_exact(length)
            if length < MAX_PAYLOAD:
                return payload

    def write_packet(self, payload: bytes) -> None:
        off = 0
        while True:
            chunk = payload[off:off + MAX_PAYLOAD]
            header = struct.pack("<I", len(chunk))[:3] + bytes([self.seq])
            self.sock.sendall(header + chunk)
            self.seq = (self.seq + 1) & 0xFF
            off += len(chunk)
            if len(chunk) < MAX_PAYLOAD:
                return

    def reset_seq(self) -> None:
        self.seq = 0


# -- length-encoded primitives (server/util.go) ------------------------------


def lenenc_int(v: int) -> bytes:
    if v < 251:
        return bytes([v])
    if v < 1 << 16:
        return b"\xfc" + struct.pack("<H", v)
    if v < 1 << 24:
        return b"\xfd" + struct.pack("<I", v)[:3]
    return b"\xfe" + struct.pack("<Q", v)


def read_lenenc_int(b: bytes, off: int) -> tuple[int, int]:
    first = b[off]
    if first < 251:
        return first, off + 1
    if first == 0xFC:
        return struct.unpack_from("<H", b, off + 1)[0], off + 3
    if first == 0xFD:
        return int.from_bytes(b[off + 1:off + 4], "little"), off + 4
    return struct.unpack_from("<Q", b, off + 1)[0], off + 9


def lenenc_bytes(v: bytes) -> bytes:
    return lenenc_int(len(v)) + v


def lenenc_str(v: str) -> bytes:
    return lenenc_bytes(v.encode("utf8"))


def read_lenenc_bytes(b: bytes, off: int) -> tuple[bytes, int]:
    n, off = read_lenenc_int(b, off)
    return b[off:off + n], off + n


def read_nullterm(b: bytes, off: int) -> tuple[bytes, int]:
    end = b.index(0, off)
    return b[off:end], end + 1
