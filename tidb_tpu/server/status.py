"""HTTP status server: /status, /metrics, and the region/MVCC debug API.

Ref: server/http_status.go (the :10080 admin API; Prometheus text on
/metrics) and server/region_handler.go:73-91 (table regions, MVCC
forensics by key and by start_ts — the tools an operator uses to answer
"which region holds row X?" and "what did txn T touch?")."""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from tidb_tpu import __version__, metrics, tablecodec

__all__ = ["StatusServer"]


def _hex(b: bytes) -> str:
    return b.hex()


def _region_json(r) -> dict:
    return {"id": r.id, "start_key": _hex(r.start), "end_key": _hex(r.end),
            "version": r.version, "conf_ver": r.conf_ver,
            "leader_store": r.leader_store,
            "peer_stores": list(r.peer_stores)}


def _jsonable(v):
    if isinstance(v, bytes):
        return _hex(v)
    if isinstance(v, dict):
        return {k: _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    return v


def _all_regions(storage) -> list:
    cluster = storage.cluster
    fn = getattr(cluster, "all_regions", None)
    return fn() if fn is not None else []


class _Handler(BaseHTTPRequestHandler):
    server_version = "tidb-tpu-status"

    def log_message(self, fmt, *args):  # quiet
        pass

    # -- route helpers -------------------------------------------------------

    def _json(self, obj, code: int = 200) -> None:
        body = json.dumps(obj, indent=2).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _table_info(self, db: str, name: str):
        from tidb_tpu.session import Domain
        dom = Domain.get(self.server.ctx_storage)
        return dom.info_schema().table(db, name)

    def _table_regions(self, db: str, name: str):
        info = self._table_info(db, name)
        lo, hi = tablecodec.table_prefix_range(info.id)
        out = []
        for r in _all_regions(self.server.ctx_storage):
            if (not r.end or r.end > lo) and (not hi or r.start < hi):
                out.append(_region_json(r))
        return {"table": f"{db}.{name}", "table_id": info.id,
                "record_prefix": _hex(tablecodec.record_prefix(info.id)),
                "regions": out}

    def _mvcc_key(self, db: str, name: str, handle: int):
        info = self._table_info(db, name)
        key = tablecodec.record_key(info.id, handle)
        st = self.server.ctx_storage
        out = st.shim.mvcc_by_key(key)
        out = _jsonable(out)
        out["table"] = f"{db}.{name}"
        out["handle"] = handle
        return out

    # -- dispatch ------------------------------------------------------------

    def _fleet_members(self) -> list[dict]:
        """Live fleet membership, degraded to just this process when
        the registry is empty (standalone in-process servers are a
        one-member fleet)."""
        from tidb_tpu import member
        members = member.live_members(self.server.ctx_storage)
        return members or [member.identity()]

    def do_GET(self):  # noqa: N802 - stdlib API
        st = self.server.ctx_storage
        parts = [p for p in self.path.split("/") if p]
        try:
            if self.path == "/metrics":
                from tidb_tpu import member
                ident = member.identity()
                # member identity stamp, hand-rendered: the id is
                # per-process (exactly what the cardinality rule keeps
                # out of the registry), but ONE series per exposition
                # makes multi-member scrapes joinable
                stamp = (
                    f"# HELP {metrics.MEMBER_START_TIME} This member's "
                    f"process start time (unix seconds).\n"
                    f"# TYPE {metrics.MEMBER_START_TIME} gauge\n"
                    f"{metrics.MEMBER_START_TIME}"
                    f"{{member=\"{ident['id']}\","
                    f"role=\"{ident['role']}\"}} "
                    f"{ident['start_unix']:.3f}\n")
                body = (stamp + metrics.expose()).encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if self.path in ("/", "/status"):
                from tidb_tpu import member, profiler, sched
                from tidb_tpu.util import compile_cache
                self._json({
                    "version": __version__,
                    "member": member.identity(),
                    "connections": len(getattr(self.server.ctx_server,
                                               "_conns", ())),
                    "regions": len(_all_regions(st)),
                    "serving": sched.stats(),
                    "compile_cache": compile_cache.counters(),
                    "kernel_profile": profiler.stats(),
                    "metrics": metrics.snapshot(),
                })
                return
            if self.path == "/cluster/state":
                # this member's cluster-state document — the one fetch
                # peers' cluster_* memtables and /fleet/* fan-outs make
                from tidb_tpu import member
                self._json(member.local_state())
                return
            if parts and parts[0] == "fleet":
                # fleet-wide views from ANY member: fan out over the
                # live membership with the shared bounded-timeout
                # client; unreachable members land in "errors"
                from tidb_tpu.util import statusclient
                members = self._fleet_members()
                if parts[1:] == ["top"]:
                    docs, errors = statusclient.fetch_all(members,
                                                          "/top")
                    self._json({"members": docs, "errors": errors})
                    return
                if len(parts) == 3 and parts[1] == "trace":
                    tid = int(parts[2])
                    docs, errors = statusclient.fetch_all(
                        members, "/cluster/state")
                    hits = []
                    for mid, doc in sorted(docs.items()):
                        for rec in doc.get("traces", ()):
                            if rec.get("trace_id") == tid or \
                                    rec.get("origin_trace_id") == tid:
                                hits.append(dict(rec, member=mid))
                    from tidb_tpu import trace
                    local = trace.ring_get(tid)
                    code = 200 if hits else 404
                    self._json({"trace_id": tid, "found": hits,
                                "spans": trace.tree(local["root"])
                                if local is not None else None,
                                "errors": errors}, code)
                    return
            if self.path == "/profile":
                # the kernel profiling plane (profiler.py): per-kernel
                # compile/dispatch/roofline rows, the compile-cache
                # counters they attribute against, the per-digest
                # mode-history memo, and the platform roofline estimate
                # the fractions are normalized by
                from tidb_tpu import perfschema, profiler
                from tidb_tpu.util import compile_cache
                gbps, src = profiler.platform_peak_gbps()
                self._json({
                    "stats": profiler.stats(),
                    "kernel_profile": profiler.snapshot(),
                    "compile_cache": compile_cache.counters(),
                    "statement_profile": perfschema.memo_snapshot(),
                    "roofline": {"peak_gbps": gbps, "source": src},
                })
                return
            if self.path == "/failpoint":
                # the failpoint registry + armed state (POST arms)
                from tidb_tpu.util import failpoint
                self._json({"registry": failpoint.REGISTRY,
                            "armed": failpoint.armed()})
                return
            if parts and parts[0] == "trace":
                # retained statement traces (tidb_tpu/trace.py ring):
                # /trace lists summaries, /trace/<id> serves the full
                # span tree, /trace/<id>/chrome the trace-event JSON
                # for Perfetto / chrome://tracing
                from tidb_tpu import trace
                if len(parts) == 1:
                    self._json({"ring": trace.ring_stats(),
                                "traces": trace.ring_snapshot()})
                    return
                rec = trace.ring_get(int(parts[1]))
                if rec is None:
                    self._json({"error": f"no trace {parts[1]} "
                                         f"(evicted or never retained)"},
                               404)
                    return
                if len(parts) == 3 and parts[2] == "chrome":
                    self._json(trace.to_chrome(rec))
                    return
                self._json({"trace_id": rec["trace_id"],
                            "sql": rec["sql"], "digest": rec["digest"],
                            "duration_ns": rec["duration_ns"],
                            "reason": rec["reason"],
                            "spans": trace.tree(rec["root"])})
                return
            if self.path.startswith("/metrics/history"):
                # the in-process time-series ring (metrics_history.py):
                # registered gauges + derived device-utilization / HBM
                # occupancy / hit-rate series sampled on the
                # tidb_tpu_metrics_history_interval_ms cadence
                from tidb_tpu import metrics_history
                self._json({"history": metrics_history.stats(),
                            "series": metrics_history.series()})
                return
            if self.path.startswith("/top"):
                # live utilization: top sessions and statement digests
                # by device busy-time (meter.py) — ranked by the last
                # sampler interval, cumulative as the tiebreak
                from tidb_tpu import meter
                self._json({
                    "server": meter.server_snapshot(),
                    "attributed_device_ns":
                        meter.attributed_device_ns(),
                    "sessions": meter.top_sessions(),
                    "users": meter.users_snapshot(),
                    "digests": meter.top_digests(),
                })
                return
            if self.path == "/shed":
                # administrative shed hook (the KILL-style escape hatch):
                # drives the SERVER memtrack root's registered shed chain
                # — HBM cache blocks, running statements' spill actions —
                # the same chain admission control fires on projected
                # overflow, here on operator demand
                from tidb_tpu import sched
                self._json({"freed_bytes": sched.shed_server(0)})
                return
            if parts == ["regions"]:
                self._json([_region_json(r) for r in _all_regions(st)])
                return
            if len(parts) == 2 and parts[0] == "regions":
                rid = int(parts[1])
                for r in _all_regions(st):
                    if r.id == rid:
                        self._json(_region_json(r))
                        return
                self._json({"error": f"no region {rid}"}, 404)
                return
            if len(parts) == 4 and parts[0] == "tables" \
                    and parts[3] == "regions":
                self._json(self._table_regions(parts[1], parts[2]))
                return
            if len(parts) == 5 and parts[:2] == ["mvcc", "key"]:
                self._json(self._mvcc_key(parts[2], parts[3],
                                          int(parts[4])))
                return
            if len(parts) == 3 and parts[:2] == ["mvcc", "txn"]:
                hits = st.shim.mvcc_by_start_ts(int(parts[2]))
                self._json([{"key": _hex(k), "mvcc": _jsonable(m)}
                            for k, m in hits])
                return
        except Exception as e:  # noqa: BLE001 - debug API reports errors
            self._json({"error": str(e)}, 500)
            return
        self.send_error(404)

    def do_POST(self):  # noqa: N802 - stdlib API
        """POST /failpoint {"name": ..., "spec": ...} arms one declared
        failpoint (util/failpoint.py); spec null/"" disarms it. The
        HTTP face of the same registry env/SET arming drives — the
        gofail-endpoint analogue for chaos tooling."""
        if self.path != "/failpoint":
            self.send_error(404)
            return
        from tidb_tpu.util import failpoint
        try:
            n = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(n) or b"{}")
            name = body["name"]
            spec = body.get("spec")
            if spec:
                # lint: exempt[failpoint-discipline] HTTP front end: the name arrives off the wire and enable() itself rejects undeclared ones
                failpoint.enable(name, spec)
            else:
                # lint: exempt[failpoint-discipline] HTTP front end: dynamic name, validated by the registry at runtime
                failpoint.disable(name)
            self._json({"ok": True, "armed": failpoint.armed()})
        except failpoint.UnknownFailpointError as e:
            self._json({"error": f"unknown failpoint {e}"}, 404)
        except Exception as e:  # noqa: BLE001 - admin API reports errors
            self._json({"error": str(e)}, 400)


class StatusServer:
    def __init__(self, storage, sql_server=None, host: str = "127.0.0.1",
                 port: int = 0):
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.ctx_storage = storage
        self._httpd.ctx_server = sql_server
        self.port = self._httpd.server_address[1]
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        # a status port implies an operator watching: make sure the
        # history sampler is recording for /metrics/history
        from tidb_tpu import metrics_history
        metrics_history.ensure_started()
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="status-http")
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
