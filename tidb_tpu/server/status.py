"""HTTP status server: /status, /metrics (ref: server/http_status.go —
the :10080 admin API; Prometheus text on /metrics)."""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from tidb_tpu import __version__, metrics

__all__ = ["StatusServer"]


class _Handler(BaseHTTPRequestHandler):
    server_version = "tidb-tpu-status"

    def log_message(self, fmt, *args):  # quiet
        pass

    def do_GET(self):  # noqa: N802 - stdlib API
        if self.path == "/metrics":
            body = metrics.expose().encode()
            ctype = "text/plain; version=0.0.4"
        elif self.path in ("/", "/status"):
            st = self.server.ctx_storage
            body = json.dumps({
                "version": __version__,
                "connections": len(getattr(self.server.ctx_server,
                                           "_conns", ())),
                "regions": len(st.cluster._regions),
                "metrics": metrics.snapshot(),
            }, indent=2).encode()
            ctype = "application/json"
        else:
            self.send_error(404)
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class StatusServer:
    def __init__(self, storage, sql_server=None, host: str = "127.0.0.1",
                 port: int = 0):
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.ctx_storage = storage
        self._httpd.ctx_server = sql_server
        self.port = self._httpd.server_address[1]
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="status-http")
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
