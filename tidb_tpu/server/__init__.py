"""MySQL wire protocol server.

Reference: /root/reference/server/ — accept loop + connection tokens
(server.go:234-295), handshake/auth + command dispatch (conn.go:401-610),
textual resultset writer (conn.go:932 writeChunks), error packets.

The compute path stays unchanged: each connection owns a Session over the
shared storage; this layer only speaks the protocol. Auth accepts any
credentials until the privilege subsystem lands (the reference checks
mysql.user via privilege/privileges)."""

from __future__ import annotations

import socket
import struct
import threading
from decimal import Decimal

from tidb_tpu.server.packet import (PacketIO, lenenc_bytes, lenenc_int,
                                    lenenc_str, read_lenenc_bytes,
                                    read_nullterm)
from tidb_tpu.session import ResultSet, Session, SQLError
from tidb_tpu.sqltypes import EvalType, TypeCode

__all__ = ["Server"]

SERVER_VERSION = "8.0.11-tidb-tpu-1.0"
PROTOCOL_VERSION = 10
CHARSET_UTF8MB4 = 33

# capability bits (mysql/const.go)
CLIENT_LONG_PASSWORD = 1
CLIENT_FOUND_ROWS = 2
CLIENT_LONG_FLAG = 4
CLIENT_CONNECT_WITH_DB = 8
CLIENT_PROTOCOL_41 = 0x200
CLIENT_TRANSACTIONS = 0x2000
CLIENT_SECURE_CONNECTION = 0x8000
CLIENT_MULTI_STATEMENTS = 0x10000
CLIENT_PLUGIN_AUTH = 0x80000
CLIENT_PLUGIN_AUTH_LENENC = 0x200000

# CLIENT_MULTI_STATEMENTS is deliberately NOT advertised: _handle_query
# writes exactly one response per COM_QUERY (no MORE_RESULTS chaining yet)
SERVER_CAPS = (CLIENT_LONG_PASSWORD | CLIENT_FOUND_ROWS | CLIENT_LONG_FLAG
               | CLIENT_CONNECT_WITH_DB | CLIENT_PROTOCOL_41
               | CLIENT_TRANSACTIONS | CLIENT_SECURE_CONNECTION
               | CLIENT_PLUGIN_AUTH)

SERVER_STATUS_AUTOCOMMIT = 0x0002

# commands (mysql/const.go ComXxx)
COM_QUIT = 0x01
COM_INIT_DB = 0x02
COM_QUERY = 0x03
COM_FIELD_LIST = 0x04
COM_PING = 0x0E

ER_UNKNOWN = 1105


class Server:
    """Accept loop with a connection-token limiter (ref: server.go:234)."""

    def __init__(self, storage, host: str = "127.0.0.1", port: int = 0,
                 token_limit: int = 1000):
        self.storage = storage
        self._listener = socket.create_server((host, port))
        self.addr = self._listener.getsockname()
        self._tokens = threading.Semaphore(token_limit)
        self._closing = threading.Event()
        self._thread: threading.Thread | None = None
        self._conn_id = 0
        self._conns: set = set()
        self._conn_threads: set = set()
        self._mu = threading.Lock()

    @property
    def port(self) -> int:
        return self.addr[1]

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="mysql-accept")
        self._thread.start()

    def _run(self) -> None:
        while not self._closing.is_set():
            try:
                sock, _peer = self._listener.accept()
            except OSError:
                return   # listener closed
            # token acquired in the ACCEPT loop so thread/socket count is
            # actually bounded (ref: server.go:295 getToken before onConn)
            self._tokens.acquire()
            with self._mu:
                self._conn_id += 1
                cid = self._conn_id
            t = threading.Thread(target=self._serve_conn, args=(sock, cid),
                                 daemon=True, name=f"mysql-conn-{cid}")
            with self._mu:
                self._conn_threads.add(t)
            t.start()

    def _serve_conn(self, sock: socket.socket, conn_id: int) -> None:
        conn = ClientConn(self, sock, conn_id)
        with self._mu:
            self._conns.add(conn)
        try:
            conn.run()
        except (ConnectionError, OSError):
            pass   # peer went away; engine errors surface via ERR packets
        finally:
            with self._mu:
                self._conns.discard(conn)
                self._conn_threads.discard(threading.current_thread())
            conn.close()
            self._tokens.release()

    def close(self) -> None:
        self._closing.set()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._mu:
            conns = list(self._conns)
            threads = list(self._conn_threads)
        for c in conns:
            # only unblock the socket; the connection thread owns the
            # session and cleans it up in its finally block
            c.shutdown()
        # drain before the caller tears down shared state (the storage)
        if self._thread is not None:
            self._thread.join(timeout=5)
        for t in threads:
            t.join(timeout=5)


class ClientConn:
    """One connection: handshake, then dispatch loop (ref: conn.go:401)."""

    def __init__(self, server: Server, sock: socket.socket, conn_id: int):
        self.server = server
        self.sock = sock
        self.pkt = PacketIO(sock)
        self.conn_id = conn_id
        self.session: Session | None = None
        self.capabilities = 0
        self._close_mu = threading.Lock()

    # -- lifecycle -----------------------------------------------------------

    def run(self) -> None:
        try:
            self._handshake()
        except (ValueError, IndexError, struct.error):
            return   # malformed handshake (port scanner / non-MySQL peer)
        self.session = Session(self.server.storage)
        while True:
            self.pkt.reset_seq()
            try:
                payload = self.pkt.read_packet()
            except ConnectionError:
                return
            if not payload:
                continue
            cmd, data = payload[0], payload[1:]
            if cmd == COM_QUIT:
                return
            try:
                self._dispatch(cmd, data)
            except SQLError as e:
                self._write_err(str(e))
            except Exception as e:  # noqa: BLE001 - never kill the conn
                self._write_err(f"internal error: {e}")

    def shutdown(self) -> None:
        """Unblock the connection thread's read; safe from any thread."""
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass

    def close(self) -> None:
        with self._close_mu:
            session, self.session = self.session, None
        if session is not None:
            session.close()
        try:
            self.sock.close()
        except OSError:
            pass

    # -- handshake (conn.go writeInitialHandshake/readHandshakeResponse) ----

    def _handshake(self) -> None:
        salt = b"01234567" + b"890123456789"      # fixed: auth unchecked
        pkt = bytes([PROTOCOL_VERSION])
        pkt += SERVER_VERSION.encode() + b"\0"
        pkt += struct.pack("<I", self.conn_id)
        pkt += salt[:8] + b"\0"
        pkt += struct.pack("<H", SERVER_CAPS & 0xFFFF)
        pkt += bytes([CHARSET_UTF8MB4])
        pkt += struct.pack("<H", SERVER_STATUS_AUTOCOMMIT)
        pkt += struct.pack("<H", (SERVER_CAPS >> 16) & 0xFFFF)
        pkt += bytes([21])                        # auth data length
        pkt += b"\0" * 10
        pkt += salt[8:] + b"\0"
        pkt += b"mysql_native_password\0"
        self.pkt.write_packet(pkt)

        resp = self.pkt.read_packet()
        caps = struct.unpack_from("<I", resp, 0)[0]
        self.capabilities = caps
        off = 4 + 4 + 1 + 23                      # caps, maxpkt, charset, fill
        user, off = read_nullterm(resp, off)
        if caps & CLIENT_PLUGIN_AUTH_LENENC:
            _auth, off = read_lenenc_bytes(resp, off)
        else:
            alen = resp[off]
            off += 1
            _auth, off = resp[off:off + alen], off + alen
        db = b""
        if caps & CLIENT_CONNECT_WITH_DB and off < len(resp):
            db, off = read_nullterm(resp, off)
        self.user = user.decode()
        self._write_ok(0, 0)
        if db:
            # select the startup database once the session exists
            self._pending_db = db.decode()
        else:
            self._pending_db = None

    # -- dispatch ------------------------------------------------------------

    def _dispatch(self, cmd: int, data: bytes) -> None:
        if self.session is not None and self._pending_db:
            self.session.execute(f"USE `{self._pending_db}`")
            self._pending_db = None
        if cmd == COM_PING:
            self._write_ok(0, 0)
        elif cmd == COM_INIT_DB:
            self.session.execute(f"USE `{data.decode()}`")
            self._write_ok(0, 0)
        elif cmd == COM_QUERY:
            self._handle_query(data.decode())
        elif cmd == COM_FIELD_LIST:
            self._write_eof()
        else:
            self._write_err(f"unsupported command 0x{cmd:02x}")

    def _handle_query(self, sql: str) -> None:
        results = self.session.execute(sql)
        # one response per query packet: the first resultset wins, else an
        # OK carrying the last affected-rows count
        rs = next((r for r in results if isinstance(r, ResultSet)), None)
        if rs is not None:
            self._write_resultset(rs)
            return
        affected = 0
        for r in results:
            if isinstance(r, int):
                affected = r
        self._write_ok(affected, 0)

    # -- response writers (conn.go writeOK/writeError/writeResultset) -------

    def _write_ok(self, affected: int, last_insert_id: int) -> None:
        pkt = b"\x00" + lenenc_int(affected) + lenenc_int(last_insert_id)
        pkt += struct.pack("<H", SERVER_STATUS_AUTOCOMMIT)
        pkt += struct.pack("<H", 0)               # warnings
        self.pkt.write_packet(pkt)

    def _write_eof(self) -> None:
        self.pkt.write_packet(
            b"\xfe" + struct.pack("<H", 0)
            + struct.pack("<H", SERVER_STATUS_AUTOCOMMIT))

    def _write_err(self, msg: str, code: int = ER_UNKNOWN) -> None:
        pkt = b"\xff" + struct.pack("<H", code) + b"#HY000"
        pkt += msg.encode("utf8", "replace")
        self.pkt.write_packet(pkt)

    def _write_resultset(self, rs: ResultSet) -> None:
        self.pkt.write_packet(lenenc_int(len(rs.columns)))
        fts = getattr(rs, "field_types", None)
        for i, name in enumerate(rs.columns):
            self.pkt.write_packet(self._column_def(
                name, fts[i] if fts else None))
        self._write_eof()
        for row in rs.rows:
            self.pkt.write_packet(self._encode_row(row))
        self._write_eof()

    @staticmethod
    def _column_def(name: str, ft) -> bytes:
        tp = int(ft.tp) if ft is not None else int(TypeCode.VARCHAR)
        flen = (ft.flen if ft is not None and ft.flen > 0 else 255)
        dec = (ft.frac if ft is not None and 0 <= ft.frac <= 30 else 0)
        pkt = lenenc_str("def")                   # catalog
        pkt += lenenc_str("") * 3                 # schema, table, org_table
        pkt += lenenc_str(name) + lenenc_str(name)
        pkt += bytes([0x0C])
        pkt += struct.pack("<H", CHARSET_UTF8MB4)
        pkt += struct.pack("<I", flen)
        pkt += bytes([tp])
        pkt += struct.pack("<H", 0)               # flags
        pkt += bytes([dec])
        pkt += b"\0\0"
        return pkt

    @staticmethod
    def _encode_row(row) -> bytes:
        out = b""
        for v in row:
            if v is None:
                out += b"\xfb"
            elif isinstance(v, bytes):
                out += lenenc_bytes(v)
            elif isinstance(v, bool):
                out += lenenc_str("1" if v else "0")
            elif isinstance(v, float):
                out += lenenc_str(repr(v))
            elif isinstance(v, Decimal):
                out += lenenc_str(str(v))
            else:
                out += lenenc_str(str(v))
        return out
