"""MySQL wire protocol server.

Reference: /root/reference/server/ — accept loop + connection tokens
(server.go:234-295), handshake/auth + command dispatch (conn.go:401-610),
textual resultset writer (conn.go:932 writeChunks), error packets.

The compute path stays unchanged: each connection owns a Session over the
shared storage; this layer only speaks the protocol. The handshake
verifies mysql_native_password credentials against the mysql.user grant
table (tidb_tpu/privilege.py; ref: privileges.go ConnectionVerification),
bootstrapping the system catalog on first server start."""

from __future__ import annotations

import os
import socket
import struct
import threading
from decimal import Decimal

from tidb_tpu.server.packet import (PacketIO, lenenc_bytes, lenenc_int,
                                    lenenc_str, read_lenenc_bytes,
                                    read_nullterm)
from tidb_tpu.session import ResultSet, Session, SQLError
from tidb_tpu.sqltypes import EvalType, TypeCode

__all__ = ["Server"]

SERVER_VERSION = "8.0.11-tidb-tpu-1.0"
PROTOCOL_VERSION = 10
CHARSET_UTF8MB4 = 33

# capability bits (mysql/const.go)
CLIENT_LONG_PASSWORD = 1
CLIENT_FOUND_ROWS = 2
CLIENT_LONG_FLAG = 4
CLIENT_CONNECT_WITH_DB = 8
CLIENT_PROTOCOL_41 = 0x200
CLIENT_TRANSACTIONS = 0x2000
CLIENT_SECURE_CONNECTION = 0x8000
CLIENT_MULTI_STATEMENTS = 0x10000
CLIENT_PLUGIN_AUTH = 0x80000
CLIENT_PLUGIN_AUTH_LENENC = 0x200000

# CLIENT_MULTI_STATEMENTS is deliberately NOT advertised: _handle_query
# writes exactly one response per COM_QUERY (no MORE_RESULTS chaining yet)
SERVER_CAPS = (CLIENT_LONG_PASSWORD | CLIENT_FOUND_ROWS | CLIENT_LONG_FLAG
               | CLIENT_CONNECT_WITH_DB | CLIENT_PROTOCOL_41
               | CLIENT_TRANSACTIONS | CLIENT_SECURE_CONNECTION
               | CLIENT_PLUGIN_AUTH)

SERVER_STATUS_AUTOCOMMIT = 0x0002

# commands (mysql/const.go ComXxx)
COM_QUIT = 0x01
COM_INIT_DB = 0x02
COM_QUERY = 0x03
COM_FIELD_LIST = 0x04
COM_PING = 0x0E
COM_STMT_PREPARE = 0x16
COM_STMT_EXECUTE = 0x17
COM_STMT_CLOSE = 0x19
COM_STMT_RESET = 0x1A

from tidb_tpu.errcode import (ER_ACCESS_DENIED_ERROR as ER_ACCESS_DENIED,
                              ER_UNKNOWN, classify)


class Server:
    """Accept loop with a connection-token limiter (ref: server.go:234)."""

    def __init__(self, storage, host: str = "127.0.0.1", port: int = 0,
                 token_limit: int = 1000):
        self.storage = storage
        from tidb_tpu.bootstrap import bootstrap, load_global_variables
        bootstrap(storage)   # system catalog + root account (idempotent)
        load_global_variables(storage)
        from tidb_tpu.session import Domain
        Domain.get(storage).start_stats_worker()
        Domain.get(storage).start_schema_worker()
        self._listener = socket.create_server((host, port))
        self.addr = self._listener.getsockname()
        self._tokens = threading.Semaphore(token_limit)
        self._closing = threading.Event()
        self._thread: threading.Thread | None = None
        self._conn_id = 0
        self._conns: set = set()
        self._conn_threads: set = set()
        self._mu = threading.Lock()

    @property
    def port(self) -> int:
        return self.addr[1]

    def start(self) -> None:
        # a serving process keeps its metrics history recording (the
        # supervised sampler in tidb_tpu/metrics_history.py; idempotent)
        from tidb_tpu import metrics_history
        metrics_history.ensure_started()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="mysql-accept")
        self._thread.start()

    def _run(self) -> None:
        while not self._closing.is_set():
            try:
                sock, _peer = self._listener.accept()
            except OSError:
                return   # listener closed
            # token acquired in the ACCEPT loop so thread/socket count is
            # actually bounded (ref: server.go:295 getToken before onConn)
            self._tokens.acquire()
            with self._mu:
                self._conn_id += 1
                cid = self._conn_id
            t = threading.Thread(target=self._serve_conn, args=(sock, cid),
                                 daemon=True, name=f"mysql-conn-{cid}")
            with self._mu:
                self._conn_threads.add(t)
            t.start()

    def _serve_conn(self, sock: socket.socket, conn_id: int) -> None:
        from tidb_tpu import metrics
        conn = ClientConn(self, sock, conn_id)
        with self._mu:
            self._conns.add(conn)
            # gauge published under _mu: racing connect/disconnect must
            # not let a stale count overwrite a newer one (metrics._lock
            # is a leaf — see docs/CONCURRENCY.md)
            metrics.gauge(metrics.CONNECTIONS_CURRENT, len(self._conns))
        metrics.counter(metrics.CONNECTIONS)
        try:
            conn.run()
        except (ConnectionError, OSError):
            pass   # peer went away; engine errors surface via ERR packets
        finally:
            with self._mu:
                self._conns.discard(conn)
                self._conn_threads.discard(threading.current_thread())
                metrics.gauge(metrics.CONNECTIONS_CURRENT,
                              len(self._conns))
            conn.close()
            self._tokens.release()

    def close(self) -> None:
        self._closing.set()
        from tidb_tpu.session import Domain
        Domain.get(self.storage).stop_stats_worker()
        Domain.get(self.storage).stop_schema_worker()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._mu:
            conns = list(self._conns)
            threads = list(self._conn_threads)
        for c in conns:
            # only unblock the socket; the connection thread owns the
            # session and cleans it up in its finally block
            c.shutdown()
        # drain before the caller tears down shared state (the storage)
        if self._thread is not None:
            self._thread.join(timeout=5)
        for t in threads:
            t.join(timeout=5)


def _binary_datetime(s: str) -> bytes:
    """'YYYY-MM-DD[ HH:MM:SS[.ffffff]]' -> binary date/datetime value."""
    date_part, _, time_part = s.partition(" ")
    y, mo, d = (int(x) for x in date_part.split("-"))
    if not time_part:
        return bytes([4]) + struct.pack("<HBB", y, mo, d)
    hms, _, frac = time_part.partition(".")
    h, mi, sec = (int(x) for x in hms.split(":"))
    if frac:
        micros = int(frac.ljust(6, "0")[:6])
        return bytes([11]) + struct.pack("<HBBBBBI", y, mo, d, h, mi, sec,
                                         micros)
    return bytes([7]) + struct.pack("<HBBBBB", y, mo, d, h, mi, sec)


class ClientConn:
    """One connection: handshake, then dispatch loop (ref: conn.go:401)."""

    def __init__(self, server: Server, sock: socket.socket, conn_id: int):
        self.server = server
        self.sock = sock
        self.pkt = PacketIO(sock)
        self.conn_id = conn_id
        self.session: Session | None = None
        self.capabilities = 0
        self._close_mu = threading.Lock()
        self._param_counts: dict[int, int] = {}   # stmt_id -> num params
        self._param_types: dict[int, list] = {}   # stmt_id -> bound types

    # -- lifecycle -----------------------------------------------------------

    def run(self) -> None:
        try:
            if not self._handshake():
                return   # auth failed (ERR already written)
        except (ValueError, IndexError, struct.error):
            return   # malformed handshake (port scanner / non-MySQL peer)
        self.session = Session(self.server.storage, user=self.user,
                               host=self.peer_host)
        # KILL CONNECTION unblocks this conn's read and ends the loop
        # (ref: server.go:333 Kill -> cancel + close)
        self.session.kill_hook = self.shutdown
        while True:
            self.pkt.reset_seq()
            try:
                payload = self.pkt.read_packet()
            except ConnectionError:
                return
            if not payload:
                continue
            cmd, data = payload[0], payload[1:]
            if cmd == COM_QUIT:
                return
            try:
                self._dispatch(cmd, data)
            except Exception as e:  # noqa: BLE001 - never kill the conn
                # typed errors carry standard MySQL codes on the wire
                # (ref: terror.go:152 error-class -> code mapping)
                code, state, msg = classify(e)
                if code == ER_UNKNOWN and not isinstance(e, SQLError):
                    msg = f"internal error: {msg}"
                self._write_err(msg, code=code, sqlstate=state)

    def shutdown(self) -> None:
        """Unblock the connection thread's read; safe from any thread."""
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass

    def close(self) -> None:
        with self._close_mu:
            session, self.session = self.session, None
        if session is not None:
            session.close()
        try:
            self.sock.close()
        except OSError:
            pass

    # -- handshake (conn.go writeInitialHandshake/readHandshakeResponse) ----

    def _handshake(self) -> bool:
        # 20-byte random salt; NUL bytes would truncate the wire encoding
        salt = bytes(b % 255 + 1 for b in os.urandom(20))
        pkt = bytes([PROTOCOL_VERSION])
        pkt += SERVER_VERSION.encode() + b"\0"
        pkt += struct.pack("<I", self.conn_id)
        pkt += salt[:8] + b"\0"
        pkt += struct.pack("<H", SERVER_CAPS & 0xFFFF)
        pkt += bytes([CHARSET_UTF8MB4])
        pkt += struct.pack("<H", SERVER_STATUS_AUTOCOMMIT)
        pkt += struct.pack("<H", (SERVER_CAPS >> 16) & 0xFFFF)
        pkt += bytes([21])                        # auth data length
        pkt += b"\0" * 10
        pkt += salt[8:] + b"\0"
        pkt += b"mysql_native_password\0"
        self.pkt.write_packet(pkt)

        resp = self.pkt.read_packet()
        caps = struct.unpack_from("<I", resp, 0)[0]
        self.capabilities = caps
        off = 4 + 4 + 1 + 23                      # caps, maxpkt, charset, fill
        user, off = read_nullterm(resp, off)
        if caps & CLIENT_PLUGIN_AUTH_LENENC:
            auth, off = read_lenenc_bytes(resp, off)
        else:
            alen = resp[off]
            off += 1
            auth, off = resp[off:off + alen], off + alen
        db = b""
        if caps & CLIENT_CONNECT_WITH_DB and off < len(resp):
            db, off = read_nullterm(resp, off)
        self.user = user.decode()
        try:
            self.peer_host = self.sock.getpeername()[0]
        except OSError:
            self.peer_host = "localhost"
        # verify against mysql.user (ref: session.go:928 Auth ->
        # privileges.go ConnectionVerification)
        cache = self.session_domain().priv_cache()
        if not cache.connection_verify(self.user, self.peer_host,
                                       bytes(auth), salt):
            self._write_err(
                f"Access denied for user '{self.user}'@"
                f"'{self.peer_host}' (using password: "
                f"{'YES' if auth else 'NO'})", code=ER_ACCESS_DENIED,
                sqlstate="28000")
            return False
        self._write_ok(0, 0)
        if db:
            # select the startup database once the session exists
            self._pending_db = db.decode()
        else:
            self._pending_db = None
        return True

    def session_domain(self):
        from tidb_tpu.session import Domain
        return Domain.get(self.server.storage)

    # -- dispatch ------------------------------------------------------------

    def _dispatch(self, cmd: int, data: bytes) -> None:
        if self.session is not None and self._pending_db:
            self.session.execute(f"USE `{self._pending_db}`")
            self._pending_db = None
        if cmd == COM_PING:
            self._write_ok(0, 0)
        elif cmd == COM_INIT_DB:
            self.session.execute(f"USE `{data.decode()}`")
            self._write_ok(0, 0)
        elif cmd == COM_QUERY:
            self._handle_query(data.decode())
        elif cmd == COM_FIELD_LIST:
            self._write_eof()
        elif cmd == COM_STMT_PREPARE:
            self._handle_stmt_prepare(data.decode())
        elif cmd == COM_STMT_EXECUTE:
            self._handle_stmt_execute(data)
        elif cmd == COM_STMT_CLOSE:
            sid = struct.unpack_from("<I", data, 0)[0]
            self.session.deallocate_prepared(sid)
            self._param_counts.pop(sid, None)   # no response per protocol
            self._param_types.pop(sid, None)
        elif cmd == COM_STMT_RESET:
            self._write_ok(0, 0)
        else:
            self._write_err(f"unsupported command 0x{cmd:02x}")

    def _handle_query(self, sql: str) -> None:
        results = self.session.execute(sql)
        # one response per query packet: the first resultset wins, else an
        # OK carrying the last affected-rows count
        rs = next((r for r in results if isinstance(r, ResultSet)), None)
        if rs is not None:
            self._write_resultset(rs)
            return
        affected = 0
        for r in results:
            if isinstance(r, int):
                affected = r
        self._write_ok(affected, 0)

    # -- prepared statements / binary protocol (conn_stmt.go) ----------------

    def _handle_stmt_prepare(self, sql: str) -> None:
        sid, nparams = self.session.prepare(sql)
        self._param_counts[sid] = nparams
        # COM_STMT_PREPARE_OK with real prepare-time column definitions:
        # standard drivers (libmysqlclient, Connector/J) read result
        # metadata here, not at execute time (conn_stmt.go).
        names, fts = self.session.prepared_columns(sid)
        ncols = len(names) if names else 0
        pkt = b"\x00" + struct.pack("<I", sid)
        pkt += struct.pack("<H", ncols)
        pkt += struct.pack("<H", nparams)
        pkt += b"\x00" + struct.pack("<H", 0)    # filler, warnings
        self.pkt.write_packet(pkt)
        if nparams:
            for _ in range(nparams):
                self.pkt.write_packet(self._column_def("?", None))
            self._write_eof()
        if ncols:
            for i, name in enumerate(names):
                self.pkt.write_packet(self._column_def(
                    name, fts[i] if fts else None))
            self._write_eof()

    def _handle_stmt_execute(self, data: bytes) -> None:
        sid = struct.unpack_from("<I", data, 0)[0]
        nparams = self._param_counts.get(sid)
        if nparams is None:
            self._write_err(f"unknown statement handler {sid}")
            return
        params = self._decode_params(data, sid, nparams)
        results = self.session.execute_prepared(sid, params)
        rs = results if isinstance(results, ResultSet) else None
        if rs is None:
            self._write_ok(results if isinstance(results, int) else 0, 0)
            return
        self.pkt.write_packet(lenenc_int(len(rs.columns)))
        fts = rs.field_types
        for i, name in enumerate(rs.columns):
            self.pkt.write_packet(self._column_def(
                name, fts[i] if fts else None))
        self._write_eof()
        for row in rs.rows:
            self.pkt.write_packet(self._encode_binary_row(row, fts))
        self._write_eof()

    def _decode_params(self, data: bytes, sid: int, nparams: int) -> list:
        """Binary parameter values (conn_stmt.go parseStmtArgs). Types
        arrive only when new_params_bound_flag is set; later executes
        reuse the types cached per statement (boundParams semantics)."""
        if nparams == 0:
            return []
        off = 4 + 1 + 4                      # stmt_id, flags, iterations
        nb = (nparams + 7) // 8
        null_bitmap = data[off:off + nb]
        off += nb
        new_bound = data[off]
        off += 1
        if new_bound:
            types = []
            for _ in range(nparams):
                types.append((data[off], data[off + 1]))
                off += 2
            self._param_types[sid] = types
        else:
            types = self._param_types.get(sid)
            if types is None:
                raise SQLError("parameter types were never bound")
        params: list = []
        for i in range(nparams):
            if null_bitmap[i // 8] & (1 << (i % 8)):
                params.append(None)
                continue
            tp, flag = types[i]
            unsigned = bool(flag & 0x80)
            if tp in (int(TypeCode.LONGLONG),):
                v = struct.unpack_from("<Q" if unsigned else "<q",
                                       data, off)[0]
                off += 8
            elif tp in (int(TypeCode.LONG), int(TypeCode.INT24)):
                v = struct.unpack_from("<I" if unsigned else "<i",
                                       data, off)[0]
                off += 4
            elif tp in (int(TypeCode.SHORT), int(TypeCode.YEAR)):
                v = struct.unpack_from("<H" if unsigned else "<h",
                                       data, off)[0]
                off += 2
            elif tp == int(TypeCode.TINY):
                v = data[off] if unsigned else \
                    struct.unpack_from("<b", data, off)[0]
                off += 1
            elif tp == int(TypeCode.DOUBLE):
                v = struct.unpack_from("<d", data, off)[0]
                off += 8
            elif tp == int(TypeCode.FLOAT):
                v = struct.unpack_from("<f", data, off)[0]
                off += 4
            elif tp in (int(TypeCode.DATE), int(TypeCode.DATETIME),
                        int(TypeCode.TIMESTAMP)):
                ln = data[off]
                off += 1
                y = mo = d = h = mi = s = 0
                if ln >= 4:
                    y, mo, d = struct.unpack_from("<HBB", data, off)
                if ln >= 7:
                    h, mi, s = struct.unpack_from("<BBB", data, off + 4)
                off += ln
                v = f"{y:04d}-{mo:02d}-{d:02d} {h:02d}:{mi:02d}:{s:02d}"
            else:                            # strings / decimals / blobs
                raw, off = read_lenenc_bytes(data, off)
                v = raw.decode("utf8", "replace")
            params.append(v)
        return params

    @staticmethod
    def _encode_binary_row(row, fts) -> bytes:
        """Binary resultset row (conn.go writeBinaryRow)."""
        ncols = len(row)
        null_bitmap = bytearray((ncols + 9) // 8)
        out = b""
        for i, v in enumerate(row):
            if v is None:
                null_bitmap[(i + 2) // 8] |= 1 << ((i + 2) % 8)
                continue
            tp = int(fts[i].tp) if fts else int(TypeCode.VARCHAR)
            # width follows the DECLARED column type (protocol rule)
            if tp == int(TypeCode.LONGLONG):
                out += struct.pack("<q", int(v))
            elif tp in (int(TypeCode.LONG), int(TypeCode.INT24)):
                out += struct.pack("<i", int(v))
            elif tp in (int(TypeCode.SHORT), int(TypeCode.YEAR)):
                out += struct.pack("<h", int(v))
            elif tp == int(TypeCode.TINY):
                out += struct.pack("<b", int(v))
            elif tp == int(TypeCode.DOUBLE):
                out += struct.pack("<d", float(v))
            elif tp == int(TypeCode.FLOAT):
                out += struct.pack("<f", float(v))
            elif tp in (int(TypeCode.DATE), int(TypeCode.DATETIME),
                        int(TypeCode.TIMESTAMP)):
                out += _binary_datetime(str(v))
            else:                            # varchar/char/blob/decimal
                s = v if isinstance(v, bytes) else str(v).encode("utf8")
                out += lenenc_bytes(s)
        return b"\x00" + bytes(null_bitmap) + out

    # -- response writers (conn.go writeOK/writeError/writeResultset) -------

    def _write_ok(self, affected: int, last_insert_id: int) -> None:
        pkt = b"\x00" + lenenc_int(affected) + lenenc_int(last_insert_id)
        pkt += struct.pack("<H", SERVER_STATUS_AUTOCOMMIT)
        pkt += struct.pack("<H", 0)               # warnings
        self.pkt.write_packet(pkt)

    def _write_eof(self) -> None:
        self.pkt.write_packet(
            b"\xfe" + struct.pack("<H", 0)
            + struct.pack("<H", SERVER_STATUS_AUTOCOMMIT))

    def _write_err(self, msg: str, code: int = ER_UNKNOWN,
                   sqlstate: str = "HY000") -> None:
        pkt = b"\xff" + struct.pack("<H", code) + b"#" + \
            sqlstate.encode()[:5].ljust(5, b"0")
        pkt += msg.encode("utf8", "replace")
        self.pkt.write_packet(pkt)

    def _write_resultset(self, rs: ResultSet) -> None:
        from tidb_tpu.util import failpoint
        self.pkt.write_packet(lenenc_int(len(rs.columns)))
        fts = getattr(rs, "field_types", None)
        for i, name in enumerate(rs.columns):
            self.pkt.write_packet(self._column_def(
                name, fts[i] if fts else None))
        self._write_eof()
        for n, row in enumerate(rs.rows):
            # injectable connection teardown MID-resultset (after the
            # header, between rows): a callable action can close the
            # socket / raise, proving a half-shipped resultset tears
            # the connection down without wedging the session's slots
            # or ledgers
            failpoint.eval("wire/resultset", self, n)
            self.pkt.write_packet(self._encode_row(row))
        self._write_eof()

    @staticmethod
    def _column_def(name: str, ft) -> bytes:
        tp = int(ft.tp) if ft is not None else int(TypeCode.VARCHAR)
        flen = (ft.flen if ft is not None and ft.flen > 0 else 255)
        dec = (ft.frac if ft is not None and 0 <= ft.frac <= 30 else 0)
        pkt = lenenc_str("def")                   # catalog
        pkt += lenenc_str("") * 3                 # schema, table, org_table
        pkt += lenenc_str(name) + lenenc_str(name)
        pkt += bytes([0x0C])
        pkt += struct.pack("<H", CHARSET_UTF8MB4)
        pkt += struct.pack("<I", flen)
        pkt += bytes([tp])
        pkt += struct.pack("<H", 0)               # flags
        pkt += bytes([dec])
        pkt += b"\0\0"
        return pkt

    @staticmethod
    def _encode_row(row) -> bytes:
        out = b""
        for v in row:
            if v is None:
                out += b"\xfb"
            elif isinstance(v, bytes):
                out += lenenc_bytes(v)
            elif isinstance(v, bool):
                out += lenenc_str("1" if v else "0")
            elif isinstance(v, float):
                out += lenenc_str(repr(v))
            elif isinstance(v, Decimal):
                out += lenenc_str(str(v))
            else:
                out += lenenc_str(str(v))
        return out
