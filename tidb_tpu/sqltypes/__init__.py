"""SQL type system: field types, eval types, numpy/JAX dtype mapping.

Reference: /root/reference/types/ (FieldType types/field_type.go, EvalType
types/eval_type.go, Datum types/datum.go:57-65, MyDecimal types/mydecimal.go,
Time types/time.go).

TPU-first design departures from the reference:

* No tagged-union Datum in the hot path. Columns are numpy arrays with a
  validity bitmap (Arrow convention); a light `Datum`-like Python value is
  used only on the row-at-a-time control plane (codec, membuffer, DDL).
* DECIMAL is a scaled int64 on the compute path ("decimal-as-scaled-int",
  SURVEY.md §7 stage 1): value = unscaled // 10**frac. Exact arithmetic
  beyond int64 range falls back to the host path (python decimal).
* DATETIME/DATE/TIMESTAMP are int64 microseconds since unix epoch;
  DURATION is int64 microseconds. All fixed-width -> device-transferable.
"""

from __future__ import annotations

import datetime as _dt
import decimal as _pydec
from dataclasses import dataclass, field, replace
from enum import IntEnum

import numpy as np

__all__ = [
    "TypeCode", "EvalType", "FieldType", "Flag",
    "new_int_field", "new_uint_field", "new_double_field",
    "new_decimal_field", "new_string_field", "new_datetime_field",
    "new_date_field", "new_duration_field",
    "np_dtype_for", "eval_type_of",
    "decimal_to_scaled", "scaled_to_decimal",
    "datetime_to_micros", "micros_to_datetime", "date_to_micros",
    "parse_datetime", "format_datetime",
    "parse_duration", "format_duration",
    "collation_key", "fold_column", "bytes_to_str",
    "NULL",
]


class TypeCode(IntEnum):
    """MySQL column type codes (subset). Ref: mysql/type.go."""

    NULL = 6
    TINY = 1
    SHORT = 2
    LONG = 3
    LONGLONG = 8
    INT24 = 9
    FLOAT = 4
    DOUBLE = 5
    NEWDECIMAL = 246
    VARCHAR = 15
    STRING = 254
    VARSTRING = 253
    BLOB = 252
    DATE = 10
    DATETIME = 12
    TIMESTAMP = 7
    DURATION = 11
    YEAR = 13
    BIT = 16
    ENUM = 247
    SET = 248
    JSON = 245


class Flag(IntEnum):
    """Column flags (subset of mysql/const.go flag bits)."""

    NOT_NULL = 1
    PRI_KEY = 2
    UNIQUE_KEY = 4
    MULTIPLE_KEY = 8
    UNSIGNED = 32
    BINARY = 128
    AUTO_INCREMENT = 512


class EvalType(IntEnum):
    """Evaluation type classes. Ref: types/eval_type.go."""

    INT = 0
    REAL = 1
    DECIMAL = 2
    STRING = 3
    DATETIME = 4
    DURATION = 5
    JSON = 6


_INT_TYPES = {TypeCode.TINY, TypeCode.SHORT, TypeCode.LONG, TypeCode.LONGLONG,
              TypeCode.INT24, TypeCode.YEAR, TypeCode.BIT}
_REAL_TYPES = {TypeCode.FLOAT, TypeCode.DOUBLE}
_STRING_TYPES = {TypeCode.VARCHAR, TypeCode.STRING, TypeCode.VARSTRING,
                 TypeCode.BLOB, TypeCode.ENUM, TypeCode.SET}
_TIME_TYPES = {TypeCode.DATE, TypeCode.DATETIME, TypeCode.TIMESTAMP}


NULL = None  # SQL NULL is Python None throughout the row-wise host code


@dataclass(frozen=True)
class FieldType:
    """Column type descriptor. Ref: types/field_type.go FieldType."""

    tp: TypeCode
    flags: int = 0
    flen: int = -1       # display length / max bytes for strings
    frac: int = -1       # decimal digits after the point (NEWDECIMAL, DURATION)
    charset: str = "utf8"
    elems: tuple = ()    # ENUM/SET members
    # collation drives compare/group/sort/unique for string columns
    # (ref: util/charset/charset.go; _ci approximated by str.casefold —
    # unicode simple case folding, docs/DEVIATIONS.md)
    collation: str = "utf8mb4_bin"

    @property
    def is_unsigned(self) -> bool:
        return bool(self.flags & Flag.UNSIGNED)

    @property
    def is_ci(self) -> bool:
        """Case-insensitive collation on a string-typed column."""
        return self.collation.endswith("_ci") and \
            self.eval_type == EvalType.STRING

    @property
    def is_wide_decimal(self) -> bool:
        """DECIMAL(p>18): scaled PYTHON ints in an object column — the
        exact host lane (arbitrary precision, like mydecimal.go's
        9-digit words but with bignum arithmetic); p<=18 stays the
        int64 device fast path."""
        return self.tp == TypeCode.NEWDECIMAL and self.flen > 18

    @property
    def not_null(self) -> bool:
        return bool(self.flags & Flag.NOT_NULL)

    @property
    def eval_type(self) -> EvalType:
        return eval_type_of(self.tp)

    def with_flags(self, extra: int) -> "FieldType":
        return replace(self, flags=self.flags | extra)

    def np_dtype(self):
        return np_dtype_for(self.tp, self.flen)

    @property
    def fixed_width(self) -> bool:
        """True if values are a fixed-width numeric representation
        (device-transferable without dictionary encoding)."""
        return self.eval_type != EvalType.STRING and \
            self.tp != TypeCode.JSON and not self.is_wide_decimal


def object_fill(ft) -> object:
    """Dead-slot filler for object-dtype columns: wide decimals hold
    scaled python ints (0), varlen strings hold ''."""
    return 0 if ft.tp == TypeCode.NEWDECIMAL else ""


def bytes_to_str(x) -> str:
    """Total byte/str-to-str conversion: utf-8 when valid, latin-1
    otherwise (1 byte per char, so LENGTH() still counts bytes and byte
    ordering is preserved). Single home for the binary-string decode
    policy used by builtins and string ops."""
    if isinstance(x, str):
        return x
    if isinstance(x, (bytes, bytearray)):
        try:
            return bytes(x).decode("utf-8")
        except UnicodeDecodeError:
            return bytes(x).decode("latin-1")
    return str(x)


def collation_key(x):
    """The comparison key of one string value under a _ci collation
    (approximates utf8mb4_general_ci by unicode simple case folding —
    docs/DEVIATIONS.md). Non-strings pass through."""
    if isinstance(x, str):
        return x.casefold()
    if isinstance(x, bytes):
        try:
            return x.decode("utf8").casefold()
        except UnicodeDecodeError:
            return x
    return x


def fold_column(d):
    """Vectorized collation_key over an object column."""
    out = np.empty(len(d), dtype=object)
    for i, x in enumerate(d):
        out[i] = collation_key(x)
    return out


def eval_type_of(tp: TypeCode) -> EvalType:
    if tp in _INT_TYPES:
        return EvalType.INT
    if tp in _REAL_TYPES:
        return EvalType.REAL
    if tp == TypeCode.NEWDECIMAL:
        return EvalType.DECIMAL
    if tp in _TIME_TYPES:
        return EvalType.DATETIME
    if tp == TypeCode.DURATION:
        return EvalType.DURATION
    if tp == TypeCode.JSON:
        return EvalType.JSON
    return EvalType.STRING


def np_dtype_for(tp: TypeCode, flen: int = -1):
    """Fixed storage dtype per type (ref: util/chunk/chunk.go:81-97 chooses
    fixed widths per MySQL type; we use 8-byte lanes uniformly so columns map
    directly onto TPU-friendly int64/float64/float32 arrays). DECIMAL with
    p>18 (pass `flen`) overflows int64: object lane of scaled python ints."""
    if tp == TypeCode.NEWDECIMAL and flen > 18:
        return np.dtype(object)
    et = eval_type_of(tp)
    if et in (EvalType.INT, EvalType.DECIMAL, EvalType.DATETIME, EvalType.DURATION):
        return np.dtype(np.int64)
    if et == EvalType.REAL:
        return np.dtype(np.float64)
    return np.dtype(object)  # varlen: held host-side / dictionary-encoded


# ---------------------------------------------------------------------------
# Constructors

def new_int_field(flags: int = 0) -> FieldType:
    return FieldType(TypeCode.LONGLONG, flags=flags, flen=20)


def new_uint_field(flags: int = 0) -> FieldType:
    return FieldType(TypeCode.LONGLONG, flags=flags | Flag.UNSIGNED, flen=20)


def new_double_field(flags: int = 0) -> FieldType:
    return FieldType(TypeCode.DOUBLE, flags=flags, flen=22)


def new_decimal_field(flen: int = 15, frac: int = 2, flags: int = 0) -> FieldType:
    return FieldType(TypeCode.NEWDECIMAL, flags=flags, flen=flen, frac=frac)


def new_string_field(flen: int = 255, flags: int = 0) -> FieldType:
    return FieldType(TypeCode.VARCHAR, flags=flags, flen=flen)


def new_datetime_field(flags: int = 0) -> FieldType:
    return FieldType(TypeCode.DATETIME, flags=flags, flen=19)


def new_date_field(flags: int = 0) -> FieldType:
    return FieldType(TypeCode.DATE, flags=flags, flen=10)


def new_duration_field(flags: int = 0, frac: int = 0) -> FieldType:
    return FieldType(TypeCode.DURATION, flags=flags, flen=10, frac=frac)


# ---------------------------------------------------------------------------
# Decimal <-> scaled int64

def decimal_to_scaled(v, frac: int, wide: bool = False) -> int:
    """Encode a decimal value as an unscaled int with `frac` fractional
    digits.

    Replaces the reference's MyDecimal 9-digit-word representation
    (types/mydecimal.go) with a single int64 lane for the device path.
    Raises OverflowError outside int64 unless `wide` (DECIMAL(p>18)
    columns keep exact scaled PYTHON ints on the host object lane) —
    narrow callers fall back to host decimal on overflow.
    """
    if isinstance(v, float):
        d = _pydec.Decimal(repr(v))
    elif isinstance(v, _pydec.Decimal):
        d = v
    else:
        d = _pydec.Decimal(str(v))
    try:
        with _pydec.localcontext() as ctx:
            ctx.prec = 70        # MySQL max precision is 65 digits
            q = d.scaleb(frac).quantize(_pydec.Decimal(1),
                                        rounding=_pydec.ROUND_HALF_UP)
    except _pydec.InvalidOperation as e:
        raise OverflowError(
            f"decimal {v} does not fit frac={frac}") from e
    i = int(q)
    if not wide and not (-(1 << 63) <= i < (1 << 63)):
        raise OverflowError(f"decimal {v} does not fit scaled int64 frac={frac}")
    return i


def scaled_to_decimal(i: int, frac: int) -> _pydec.Decimal:
    with _pydec.localcontext() as ctx:
        ctx.prec = 70            # wide lane: don't round at 28 digits
        return _pydec.Decimal(int(i)).scaleb(-frac)


# ---------------------------------------------------------------------------
# Time <-> int64 microseconds (ref: types/time.go packs into a custom uint64;
# we use unix-epoch micros so device arithmetic is plain int64 ops)

_EPOCH = _dt.datetime(1970, 1, 1)


def datetime_to_micros(dt: _dt.datetime) -> int:
    # exact integer arithmetic — total_seconds() is float64 and corrupts µs
    return (dt - _EPOCH) // _dt.timedelta(microseconds=1)


def date_to_micros(d: _dt.date) -> int:
    return (d - _EPOCH.date()).days * 86_400_000_000


def micros_to_datetime(us: int) -> _dt.datetime:
    return _EPOCH + _dt.timedelta(microseconds=int(us))


def parse_datetime(s: str) -> int:
    """Parse 'YYYY-MM-DD[ HH:MM:SS[.ffffff]]' to epoch micros."""
    s = s.strip()
    for fmt in ("%Y-%m-%d %H:%M:%S.%f", "%Y-%m-%d %H:%M:%S", "%Y-%m-%d"):
        try:
            return datetime_to_micros(_dt.datetime.strptime(s, fmt))
        except ValueError:
            continue
    raise ValueError(f"invalid datetime literal: {s!r}")


def format_datetime(us: int, tp: TypeCode = TypeCode.DATETIME) -> str:
    dt = micros_to_datetime(us)
    if tp == TypeCode.DATE:
        return dt.strftime("%Y-%m-%d")
    if dt.microsecond:
        return dt.strftime("%Y-%m-%d %H:%M:%S.%f")
    return dt.strftime("%Y-%m-%d %H:%M:%S")


# MySQL TIME range is [-838:59:59, 838:59:59] (ref: types/time.go MaxTime)
MAX_DURATION_US = ((838 * 3600 + 59 * 60 + 59) * 1_000_000)


def clamp_duration(us: int) -> int:
    return max(-MAX_DURATION_US, min(MAX_DURATION_US, int(us)))


def parse_duration(s: str) -> int:
    """MySQL TIME literal -> signed microseconds.
    Accepts '[-][D ]HH:MM:SS[.ffffff]', 'HH:MM', 'SS', and the packed
    numeric form HHMMSS (ref: types/time.go ParseDuration)."""
    s = s.strip()
    neg = s.startswith("-")
    if neg:
        s = s[1:].strip()
    days = 0
    if " " in s:
        d, s = s.split(" ", 1)
        days = int(d)
    frac_us = 0
    if "." in s:
        s, f = s.split(".", 1)
        frac_us = int((f + "000000")[:6]) if f else 0
    if ":" in s:
        parts = [int(p or 0) for p in s.split(":")]
        if len(parts) == 2:
            h, m, sec = parts[0], parts[1], 0
        elif len(parts) == 3:
            h, m, sec = parts
        else:
            raise ValueError(f"invalid time literal: {s!r}")
    else:
        packed = int(s or 0)        # HHMMSS
        h, m, sec = packed // 10000, (packed // 100) % 100, packed % 100
    if m > 59 or sec > 59:
        raise ValueError(f"invalid time literal: {s!r}")
    us = ((days * 24 + h) * 3600 + m * 60 + sec) * 1_000_000 + frac_us
    return clamp_duration(-us if neg else us)


def format_duration(us: int, frac: int = -1) -> str:
    """Signed microseconds -> 'HH:MM:SS[.ffffff]'."""
    us = int(us)
    sign = "-" if us < 0 else ""
    us = abs(us)
    micro = us % 1_000_000
    sec = us // 1_000_000
    h, m, s = sec // 3600, (sec // 60) % 60, sec % 60
    out = f"{sign}{h:02d}:{m:02d}:{s:02d}"
    if frac > 0:
        out += "." + f"{micro:06d}"[:frac]
    elif frac < 0 and micro:
        out += f".{micro:06d}"
    return out
