"""Immutable in-memory schema snapshot keyed by version.

Reference: /root/reference/infoschema/infoschema.go:63-76 — name -> DB/Table
maps built from a meta snapshot; sessions hold one consistent snapshot per
statement/txn.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

if TYPE_CHECKING:   # avoid meta <-> schema circular import at runtime
    from tidb_tpu.meta import Meta
from tidb_tpu.schema.model import DBInfo, TableInfo

__all__ = ["InfoSchema", "SchemaError"]


class SchemaError(Exception):
    pass


class InfoSchema:
    def __init__(self, version: int, dbs: dict[str, DBInfo],
                 tables: dict[str, dict[str, TableInfo]],
                 db_ids: dict[str, int]):
        self.version = version
        self._dbs = dbs               # lower name -> DBInfo
        self._tables = tables         # lower db name -> lower tbl -> info
        self._db_ids = db_ids
        self._by_id = {t.id: (dbn, t) for dbn, ts in tables.items()
                       for t in ts.values()}

    @staticmethod
    def load(meta: Meta) -> "InfoSchema":
        """Full load from a meta snapshot (ref: domain loadInfoSchema)."""
        dbs, tables, db_ids = {}, {}, {}
        for db in meta.list_databases():
            key = db.name.lower()
            dbs[key] = db
            db_ids[key] = db.id
            tables[key] = {t.name.lower(): t for t in meta.list_tables(db.id)}
        return InfoSchema(meta.schema_version(), dbs, tables, db_ids)

    def db_names(self) -> list[str]:
        return sorted(d.name for d in self._dbs.values())

    def has_db(self, name: str) -> bool:
        return name.lower() in self._dbs

    def db_id(self, name: str) -> int:
        try:
            return self._db_ids[name.lower()]
        except KeyError:
            raise SchemaError(f"Unknown database '{name}'") from None

    def table_names(self, db: str) -> list[str]:
        ts = self._tables.get(db.lower())
        if ts is None:
            raise SchemaError(f"Unknown database '{db}'")
        return sorted(t.name for t in ts.values())

    def table(self, db: str, name: str) -> TableInfo:
        ts = self._tables.get(db.lower())
        if ts is None:
            raise SchemaError(f"Unknown database '{db}'")
        t = ts.get(name.lower())
        if t is None:
            raise SchemaError(f"Table '{db}.{name}' doesn't exist")
        return t

    def has_table(self, db: str, name: str) -> bool:
        ts = self._tables.get(db.lower())
        return ts is not None and name.lower() in ts

    def table_by_id(self, tid: int) -> tuple[str, TableInfo] | None:
        return self._by_id.get(tid)
