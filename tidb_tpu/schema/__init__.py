from tidb_tpu.schema.model import (ColumnInfo, DBInfo, IndexInfo,
                                   SchemaState, TableInfo)
from tidb_tpu.schema.infoschema import InfoSchema

__all__ = ["ColumnInfo", "DBInfo", "IndexInfo", "SchemaState", "TableInfo",
           "InfoSchema"]
