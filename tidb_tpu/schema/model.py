"""Serializable schema model.

Reference: /root/reference/model/model.go — DBInfo/TableInfo/ColumnInfo/
IndexInfo and the F1 online-schema-change states (model.go:27-37). JSON
(de)serialization so metadata lives in the KV meta plane exactly like the
reference's json-marshaled infos.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from enum import IntEnum
from typing import Optional

from tidb_tpu.sqltypes import FieldType, TypeCode

__all__ = ["SchemaState", "ColumnInfo", "IndexInfo", "TableInfo", "DBInfo"]


class SchemaState(IntEnum):
    """F1 schema-change states (model/model.go:27-37)."""

    NONE = 0
    DELETE_ONLY = 1
    WRITE_ONLY = 2
    WRITE_REORG = 3
    DELETE_REORG = 4
    PUBLIC = 5


@dataclass
class ColumnInfo:
    id: int
    name: str
    offset: int
    ft: FieldType
    default: Optional[object] = None
    has_default: bool = False
    auto_increment: bool = False
    state: SchemaState = SchemaState.PUBLIC
    comment: str = ""

    def to_json(self) -> dict:
        return {
            "id": self.id, "name": self.name, "offset": self.offset,
            "tp": int(self.ft.tp), "flags": self.ft.flags,
            "elems": list(self.ft.elems),
            "flen": self.ft.flen, "frac": self.ft.frac,
            "collation": self.ft.collation,
            "default": _jsonable(self.default),
            "has_default": self.has_default,
            "auto_increment": self.auto_increment,
            "state": int(self.state), "comment": self.comment,
        }

    @staticmethod
    def from_json(d: dict) -> "ColumnInfo":
        return ColumnInfo(
            id=d["id"], name=d["name"], offset=d["offset"],
            ft=FieldType(TypeCode(d["tp"]), d["flags"], d["flen"],
                         d["frac"], elems=tuple(d.get("elems") or ()),
                         collation=d.get("collation", "utf8mb4_bin")),
            default=_unjsonable(d.get("default")),
            has_default=d.get("has_default", False),
            auto_increment=d.get("auto_increment", False),
            state=SchemaState(d.get("state", SchemaState.PUBLIC)),
            comment=d.get("comment", ""),
        )


@dataclass
class IndexInfo:
    id: int
    name: str
    columns: list[str]
    unique: bool = False
    primary: bool = False
    state: SchemaState = SchemaState.PUBLIC

    def to_json(self) -> dict:
        return {"id": self.id, "name": self.name, "columns": self.columns,
                "unique": self.unique, "primary": self.primary,
                "state": int(self.state)}

    @staticmethod
    def from_json(d: dict) -> "IndexInfo":
        return IndexInfo(id=d["id"], name=d["name"], columns=d["columns"],
                         unique=d.get("unique", False),
                         primary=d.get("primary", False),
                         state=SchemaState(d.get("state", SchemaState.PUBLIC)))


@dataclass
class TableInfo:
    id: int
    name: str
    columns: list[ColumnInfo] = field(default_factory=list)
    indexes: list[IndexInfo] = field(default_factory=list)
    pk_is_handle: bool = False     # int PK stored as the row handle
    pk_col_name: str = ""
    auto_inc_id: int = 0           # next auto-increment base (meta-managed)
    state: SchemaState = SchemaState.PUBLIC
    comment: str = ""
    # Monotonic id allocators (ref: model.TableInfo MaxColumnID/MaxIndexID):
    # ids are never reused, so data of dropped columns/indexes awaiting GC
    # can never alias a new object's.
    max_column_id: int = 0
    max_index_id: int = 0

    def alloc_column_id(self) -> int:
        self.max_column_id = max(self.max_column_id,
                                 max((c.id for c in self.columns),
                                     default=0)) + 1
        return self.max_column_id

    def alloc_index_id(self) -> int:
        self.max_index_id = max(self.max_index_id,
                                max((i.id for i in self.indexes),
                                    default=0)) + 1
        return self.max_index_id

    def col_by_name(self, name: str) -> Optional[ColumnInfo]:
        lname = name.lower()
        for c in self.columns:
            if c.name.lower() == lname:
                return c
        return None

    def index_by_name(self, name: str) -> Optional[IndexInfo]:
        lname = name.lower()
        for i in self.indexes:
            if i.name.lower() == lname:
                return i
        return None

    def public_columns(self) -> list[ColumnInfo]:
        return [c for c in self.columns if c.state == SchemaState.PUBLIC]

    def writable_columns(self) -> list[ColumnInfo]:
        """Columns DML must fill (WRITE_ONLY and up — but NOT DELETE_REORG,
        which sorts above WRITE_ONLY in the enum yet means the column is on
        its way out). Ref: table/table.go:89 WritableCols excludes both
        DeleteOnly and DeleteReorganization."""
        return [c for c in self.columns
                if c.state >= SchemaState.WRITE_ONLY
                and c.state != SchemaState.DELETE_REORG]

    def writable_indexes(self) -> list[IndexInfo]:
        return [i for i in self.indexes
                if i.state >= SchemaState.WRITE_ONLY
                and i.state != SchemaState.DELETE_REORG]

    def deletable_indexes(self) -> list[IndexInfo]:
        """Indexes that must see deletions (DELETE_ONLY+).
        Ref: table/table.go:100 DeletableIndices."""
        return [i for i in self.indexes
                if i.state >= SchemaState.DELETE_ONLY]

    def to_json(self) -> dict:
        return {
            "id": self.id, "name": self.name,
            "columns": [c.to_json() for c in self.columns],
            "indexes": [i.to_json() for i in self.indexes],
            "pk_is_handle": self.pk_is_handle,
            "pk_col_name": self.pk_col_name,
            "state": int(self.state), "comment": self.comment,
            "max_column_id": self.max_column_id,
            "max_index_id": self.max_index_id,
        }

    @staticmethod
    def from_json(d: dict) -> "TableInfo":
        return TableInfo(
            id=d["id"], name=d["name"],
            columns=[ColumnInfo.from_json(c) for c in d["columns"]],
            indexes=[IndexInfo.from_json(i) for i in d.get("indexes", [])],
            pk_is_handle=d.get("pk_is_handle", False),
            pk_col_name=d.get("pk_col_name", ""),
            state=SchemaState(d.get("state", SchemaState.PUBLIC)),
            comment=d.get("comment", ""),
            max_column_id=d.get("max_column_id", 0),
            max_index_id=d.get("max_index_id", 0),
        )

    def dumps(self) -> bytes:
        return json.dumps(self.to_json()).encode()

    @staticmethod
    def loads(b: bytes) -> "TableInfo":
        return TableInfo.from_json(json.loads(b))


@dataclass
class DBInfo:
    id: int
    name: str
    state: SchemaState = SchemaState.PUBLIC

    def to_json(self) -> dict:
        return {"id": self.id, "name": self.name, "state": int(self.state)}

    @staticmethod
    def from_json(d: dict) -> "DBInfo":
        return DBInfo(id=d["id"], name=d["name"],
                      state=SchemaState(d.get("state", SchemaState.PUBLIC)))

    def dumps(self) -> bytes:
        return json.dumps(self.to_json()).encode()

    @staticmethod
    def loads(b: bytes) -> "DBInfo":
        return DBInfo.from_json(json.loads(b))


def _jsonable(v):
    import decimal
    if isinstance(v, decimal.Decimal):
        return {"__dec__": str(v)}
    if isinstance(v, bytes):
        return {"__b__": v.decode("latin1")}
    return v


def _unjsonable(v):
    import decimal
    if isinstance(v, dict):
        if "__dec__" in v:
            return decimal.Decimal(v["__dec__"])
        if "__b__" in v:
            return v["__b__"].encode("latin1")
    return v
