"""MySQL error-code catalog and exception classification.

Reference: /root/reference/mysql/errcode.go (the code constants),
mysql/errname.go, terror/terror.go:152 (error class -> MySQL code
mapping surfaced on the wire). The server's ERR packet carries
(errno, sqlstate, message); classify() maps the framework's typed
exceptions onto the right pair so MySQL clients and drivers see
standard codes (1062 duplicate key, 1146 missing table, ...)."""

from __future__ import annotations

import re

__all__ = ["classify", "is_retryable", "ER_UNKNOWN"]

# -- the catalog (ref: mysql/errcode.go; MySQL range 1xxx/3xxx plus the
# reference's own 8xxx planner/DDL and 9xxx storage ranges) ------------------

ER_DUP_ENTRY = 1062
ER_NO_SUCH_TABLE = 1146
ER_BAD_DB_ERROR = 1049
ER_DB_CREATE_EXISTS = 1007
ER_TABLE_EXISTS_ERROR = 1050
ER_PARSE_ERROR = 1064
ER_ACCESS_DENIED_ERROR = 1045
ER_TABLEACCESS_DENIED_ERROR = 1142
ER_BAD_FIELD_ERROR = 1054
ER_DUP_FIELDNAME = 1060
ER_DUP_KEYNAME = 1061
ER_CANNOT_USER = 1396
ER_NON_UNIQ_ERROR = 1052          # ambiguous column
ER_UNKNOWN_SYSTEM_VARIABLE = 1193
ER_LOCK_WAIT_TIMEOUT = 1205
ER_LOCK_DEADLOCK = 1213
ER_NO_DB_ERROR = 1046
ER_WRONG_VALUE_COUNT = 1136
ER_TRUNCATED_WRONG_VALUE = 1292
ER_DATA_TOO_LONG = 1406
ER_BAD_NULL_ERROR = 1048
ER_QUERY_INTERRUPTED = 1317
ER_NO_SUCH_THREAD = 1094
ER_UNKNOWN = 1105

# server / connection
ER_CON_COUNT_ERROR = 1040
ER_OUT_OF_RESOURCES = 1041
ER_ABORTING_CONNECTION = 1152
ER_NET_PACKET_TOO_LARGE = 1153
ER_NEW_ABORTING_CONNECTION = 1184
ER_TOO_MANY_USER_CONNECTIONS = 1203
ER_UNKNOWN_COM_ERROR = 1047

# schema / DDL
ER_BAD_TABLE_ERROR = 1051
ER_WRONG_DB_NAME = 1102
ER_WRONG_TABLE_NAME = 1103
ER_WRONG_COLUMN_NAME = 1166
ER_TOO_LONG_IDENT = 1059
ER_TOO_LONG_KEY = 1071
ER_TOO_MANY_FIELDS = 1117
ER_TOO_MANY_KEYS = 1069
ER_KEY_COLUMN_DOES_NOT_EXITS = 1072
ER_WRONG_AUTO_KEY = 1075
ER_PRIMARY_CANT_HAVE_NULL = 1171
ER_CANT_DROP_FIELD_OR_KEY = 1091
ER_KEY_DOES_NOT_EXIST = 1176
ER_TABLE_MUST_HAVE_COLUMNS = 1113
ER_BLOB_USED_AS_KEY = 1073
ER_TOO_BIG_FIELDLENGTH = 1074
ER_INVALID_DEFAULT = 1067
ER_MULTIPLE_PRI_KEY = 1068
ER_TOO_BIG_PRECISION = 1426
ER_TOO_BIG_SCALE = 1425
ER_TOO_BIG_DISPLAYWIDTH = 1439
ER_UNSUPPORTED_DDL_OPERATION = 8200

# planner / resolver
ER_EMPTY_QUERY = 1065
ER_NONUNIQ_TABLE = 1066
ER_WRONG_FIELD_WITH_GROUP = 1055
ER_INVALID_GROUP_FUNC_USE = 1111
ER_MIX_OF_GROUP_FUNC_AND_FIELDS = 1140
ER_FIELD_SPECIFIED_TWICE = 1110
ER_OPERAND_COLUMNS = 1241
ER_SUBQUERY_NO_1_ROW = 1242
ER_ILLEGAL_REFERENCE = 1247
ER_DERIVED_MUST_HAVE_ALIAS = 1248
ER_TABLENAME_NOT_ALLOWED_HERE = 1250
ER_NOT_SUPPORTED_YET = 1235
ER_UNKNOWN_PROCEDURE = 1305
ER_WRONG_PARAMCOUNT_TO_PROCEDURE = 1318

# values / types
ER_DIVISION_BY_ZERO = 1365
ER_WARN_DATA_OUT_OF_RANGE = 1264
ER_DATA_OUT_OF_RANGE = 1690
ER_TRUNCATED_WRONG_VALUE_FOR_FIELD = 1366
ER_NO_DEFAULT_FOR_FIELD = 1364
ER_WARN_NULL_TO_NOTNULL = 1263
ER_INVALID_USE_OF_NULL = 1138
ER_UNKNOWN_CHARACTER_SET = 1115
ER_UNKNOWN_COLLATION = 1273
ER_WRONG_VALUE_FOR_VAR = 1231
ER_GLOBAL_VARIABLE = 1229
ER_LOCAL_VARIABLE = 1228
ER_INCORRECT_GLOBAL_LOCAL_VAR = 1238

# prepared statements / transactions
ER_UNKNOWN_STMT_HANDLER = 1243
ER_NEED_REPREPARE = 1615
ER_MAX_PREPARED_STMT_COUNT_REACHED = 1461
ER_READ_ONLY_TRANSACTION = 1207
ER_CANT_CHANGE_TX_CHARACTERISTICS = 1568
ER_SPECIFIC_ACCESS_DENIED = 1227

# storage / distributed (the reference's own 9xxx range, terror.go):
# every one of these is RETRYABLE at the client — the statement may be
# re-run verbatim once the cluster heals
ER_PD_SERVER_TIMEOUT = 9001
ER_TIKV_SERVER_TIMEOUT = 9002
ER_TIKV_SERVER_BUSY = 9003
ER_RESOLVE_LOCK_TIMEOUT = 9004
ER_REGION_UNAVAILABLE = 9005
ER_GC_TOO_EARLY = 9006
# region-stream-interrupted: a streamed coprocessor reply died
# mid-region and exhausted its resume budget (store/stream.py); same
# retryable class as region unavailability
ER_REGION_STREAM_INTERRUPTED = 9007
# statement refused at admission (tidb_tpu/sched.py): the server sits
# over tidb_tpu_server_mem_quota, the shed chain freed too little, and
# the bounded queue wait expired. RETRYABLE like ER_TIKV_SERVER_BUSY —
# nothing ran, the session and its transaction are untouched, a
# verbatim replay after backoff is always safe
ER_SERVER_BUSY_ADMISSION = 9008
# device-plane fault (tidb_tpu/util/failpoint.py DeviceFaultError): a
# kernel dispatch/finalize, HBM cache fill/patch failed or tripped the
# dispatch watchdog (tidb_tpu_dispatch_timeout_ms). RETRYABLE — the
# statement was cancelled before producing anything partial, its
# scheduler slots and device-ledger bytes were released, and the
# recovery chain (host fallback, device quarantine + re-probe) means a
# verbatim replay lands on a working path
ER_DEVICE_FAULT = 9009
# store-plane member unreachable (kv.StoreUnavailableError: the node a
# fleet SQL server dialed is down/partitioned). RETRYABLE — nothing of
# the statement's effect is ambiguous (connection-level failure before
# a response); a verbatim replay after the client re-routes is safe
ER_STORE_UNAVAILABLE = 9010
# commit outcome unknown (network error on the primary commit,
# 2pc.go:421-431): NOT retryable — the write may have landed, so a
# verbatim replay risks applying it twice
ER_RESULT_UNDETERMINED = 8501

# per-statement memory quota exceeded with no spill action left
# (memtrack.py; ref: the reference's "Out Of Memory Quota!" cancel in
# its executor 8xxx range): the query was cancelled, the session lives
ER_MEM_EXCEED_QUOTA = 8175

# codes a client may retry verbatim after backoff (the reference's
# terror retryable classes + lock waits/deadlocks)
RETRYABLE = frozenset({
    ER_LOCK_WAIT_TIMEOUT, ER_LOCK_DEADLOCK, ER_NEED_REPREPARE,
    ER_PD_SERVER_TIMEOUT, ER_TIKV_SERVER_TIMEOUT, ER_TIKV_SERVER_BUSY,
    ER_RESOLVE_LOCK_TIMEOUT, ER_REGION_UNAVAILABLE,
    ER_REGION_STREAM_INTERRUPTED, ER_SERVER_BUSY_ADMISSION,
    ER_DEVICE_FAULT, ER_STORE_UNAVAILABLE,
})


def is_retryable(errno: int) -> bool:
    """True when a MySQL client may safely re-issue the statement."""
    return errno in RETRYABLE


_SQLSTATE = {
    ER_DUP_ENTRY: "23000",
    ER_BAD_NULL_ERROR: "23000",
    ER_QUERY_INTERRUPTED: "70100",
    ER_NO_SUCH_THREAD: "HY000",
    ER_NO_SUCH_TABLE: "42S02",
    ER_BAD_DB_ERROR: "42000",
    ER_DB_CREATE_EXISTS: "HY000",
    ER_TABLE_EXISTS_ERROR: "42S01",
    ER_PARSE_ERROR: "42000",
    ER_ACCESS_DENIED_ERROR: "28000",
    ER_TABLEACCESS_DENIED_ERROR: "42000",
    ER_BAD_FIELD_ERROR: "42S22",
    ER_DUP_FIELDNAME: "42S21",
    ER_DUP_KEYNAME: "42000",
    ER_CANNOT_USER: "HY000",
    ER_NON_UNIQ_ERROR: "23000",
    ER_UNKNOWN_SYSTEM_VARIABLE: "HY000",
    ER_LOCK_WAIT_TIMEOUT: "HY000",
    ER_LOCK_DEADLOCK: "40001",
    ER_NO_DB_ERROR: "3D000",
    ER_WRONG_VALUE_COUNT: "21S01",
    ER_TRUNCATED_WRONG_VALUE: "22007",
    ER_DATA_TOO_LONG: "22001",
    ER_UNKNOWN: "HY000",
    # server / connection
    ER_CON_COUNT_ERROR: "08004",
    ER_OUT_OF_RESOURCES: "08004",
    ER_ABORTING_CONNECTION: "08S01",
    ER_NET_PACKET_TOO_LARGE: "08S01",
    ER_NEW_ABORTING_CONNECTION: "08S01",
    ER_TOO_MANY_USER_CONNECTIONS: "42000",
    ER_UNKNOWN_COM_ERROR: "08S01",
    # schema / DDL
    ER_BAD_TABLE_ERROR: "42S02",
    ER_WRONG_DB_NAME: "42000",
    ER_WRONG_TABLE_NAME: "42000",
    ER_WRONG_COLUMN_NAME: "42000",
    ER_TOO_LONG_IDENT: "42000",
    ER_TOO_LONG_KEY: "42000",
    ER_TOO_MANY_FIELDS: "42000",
    ER_TOO_MANY_KEYS: "42000",
    ER_KEY_COLUMN_DOES_NOT_EXITS: "42000",
    ER_WRONG_AUTO_KEY: "42000",
    ER_PRIMARY_CANT_HAVE_NULL: "42000",
    ER_CANT_DROP_FIELD_OR_KEY: "42000",
    ER_KEY_DOES_NOT_EXIST: "42000",
    ER_TABLE_MUST_HAVE_COLUMNS: "42000",
    ER_BLOB_USED_AS_KEY: "42000",
    ER_TOO_BIG_FIELDLENGTH: "42000",
    ER_INVALID_DEFAULT: "42000",
    ER_MULTIPLE_PRI_KEY: "42000",
    ER_TOO_BIG_PRECISION: "42000",
    ER_TOO_BIG_SCALE: "42000",
    ER_TOO_BIG_DISPLAYWIDTH: "42000",
    ER_UNSUPPORTED_DDL_OPERATION: "HY000",
    # planner / resolver
    ER_EMPTY_QUERY: "42000",
    ER_NONUNIQ_TABLE: "42000",
    ER_WRONG_FIELD_WITH_GROUP: "42000",
    ER_INVALID_GROUP_FUNC_USE: "HY000",
    ER_MIX_OF_GROUP_FUNC_AND_FIELDS: "42000",
    ER_FIELD_SPECIFIED_TWICE: "42000",
    ER_OPERAND_COLUMNS: "21000",
    ER_SUBQUERY_NO_1_ROW: "21000",
    ER_ILLEGAL_REFERENCE: "42S22",
    ER_DERIVED_MUST_HAVE_ALIAS: "42000",
    ER_TABLENAME_NOT_ALLOWED_HERE: "42000",
    ER_NOT_SUPPORTED_YET: "42000",
    ER_UNKNOWN_PROCEDURE: "42000",
    ER_WRONG_PARAMCOUNT_TO_PROCEDURE: "42000",
    # values / types
    ER_DIVISION_BY_ZERO: "22012",
    ER_WARN_DATA_OUT_OF_RANGE: "22003",
    ER_DATA_OUT_OF_RANGE: "22003",
    ER_TRUNCATED_WRONG_VALUE_FOR_FIELD: "HY000",
    ER_NO_DEFAULT_FOR_FIELD: "HY000",
    ER_WARN_NULL_TO_NOTNULL: "22004",
    ER_INVALID_USE_OF_NULL: "22004",
    ER_UNKNOWN_CHARACTER_SET: "42000",
    ER_UNKNOWN_COLLATION: "HY000",
    ER_WRONG_VALUE_FOR_VAR: "42000",
    ER_GLOBAL_VARIABLE: "HY000",
    ER_LOCAL_VARIABLE: "HY000",
    ER_INCORRECT_GLOBAL_LOCAL_VAR: "HY000",
    # prepared statements / transactions
    ER_UNKNOWN_STMT_HANDLER: "HY000",
    ER_NEED_REPREPARE: "HY000",
    ER_MAX_PREPARED_STMT_COUNT_REACHED: "42000",
    ER_READ_ONLY_TRANSACTION: "25000",
    ER_CANT_CHANGE_TX_CHARACTERISTICS: "25001",
    ER_SPECIFIC_ACCESS_DENIED: "42000",
    # storage / distributed
    ER_PD_SERVER_TIMEOUT: "HY000",
    ER_TIKV_SERVER_TIMEOUT: "HY000",
    ER_TIKV_SERVER_BUSY: "HY000",
    ER_RESOLVE_LOCK_TIMEOUT: "HY000",
    ER_REGION_UNAVAILABLE: "HY000",
    ER_GC_TOO_EARLY: "HY000",
    ER_REGION_STREAM_INTERRUPTED: "HY000",
    ER_SERVER_BUSY_ADMISSION: "HY000",
    ER_DEVICE_FAULT: "HY000",
    ER_STORE_UNAVAILABLE: "HY000",
    ER_RESULT_UNDETERMINED: "HY000",
    ER_MEM_EXCEED_QUOTA: "HY000",
}

# message-shape fallbacks for SQLError strings raised deep in the stack
_PATTERNS = [
    (re.compile(r"Unknown database", re.I), ER_BAD_DB_ERROR),
    (re.compile(r"doesn't exist|Unknown table", re.I), ER_NO_SUCH_TABLE),
    (re.compile(r"database '[^']*' (already )?exists", re.I),
     ER_DB_CREATE_EXISTS),
    (re.compile(r"index '[^']*' (already )?exists", re.I), ER_DUP_KEYNAME),
    (re.compile(r"column '[^']*' (already )?exists", re.I),
     ER_DUP_FIELDNAME),
    (re.compile(r"user .* (already )?exists", re.I), ER_CANNOT_USER),
    (re.compile(r"(already )?exists", re.I), ER_TABLE_EXISTS_ERROR),
    (re.compile(r"Unknown column", re.I), ER_BAD_FIELD_ERROR),
    (re.compile(r"ambiguous", re.I), ER_NON_UNIQ_ERROR),
    (re.compile(r"denied", re.I), ER_TABLEACCESS_DENIED_ERROR),
    (re.compile(r"Unknown system variable|unknown variable", re.I),
     ER_UNKNOWN_SYSTEM_VARIABLE),
    (re.compile(r"is a GLOBAL variable", re.I), ER_GLOBAL_VARIABLE),
    (re.compile(r"No database selected", re.I), ER_NO_DB_ERROR),
    (re.compile(r"parameter count|column count", re.I),
     ER_WRONG_VALUE_COUNT),
    (re.compile(r"cannot be null", re.I), ER_BAD_NULL_ERROR),
    # memory quota before the generic "interrupted" net: the OOM cancel
    # rides the cooperative-kill path but must keep its own code
    (re.compile(r"Out Of Memory Quota", re.I), ER_MEM_EXCEED_QUOTA),
    # device-fault/watchdog cancels ride the same cooperative-kill path
    # and must keep their retryable 9009 — matched before "interrupted"
    (re.compile(r"device fault|dispatch watchdog", re.I),
     ER_DEVICE_FAULT),
    (re.compile(r"interrupted", re.I), ER_QUERY_INTERRUPTED),
    (re.compile(r"Unknown thread id", re.I), ER_NO_SUCH_THREAD),
    (re.compile(r"incorrect value", re.I), ER_TRUNCATED_WRONG_VALUE),
    (re.compile(r"division by zero|divide by zero", re.I),
     ER_DIVISION_BY_ZERO),
    (re.compile(r"Unknown collation", re.I), ER_UNKNOWN_COLLATION),
    (re.compile(r"Unknown character set|unknown charset", re.I),
     ER_UNKNOWN_CHARACTER_SET),
    (re.compile(r"returns more than 1 row", re.I), ER_SUBQUERY_NO_1_ROW),
    (re.compile(r"out of range", re.I), ER_DATA_OUT_OF_RANGE),
    (re.compile(r"not supported|unsupported", re.I), ER_NOT_SUPPORTED_YET),
    (re.compile(r"Unknown prepared statement", re.I),
     ER_UNKNOWN_STMT_HANDLER),
    (re.compile(r"Region is unavailable", re.I), ER_REGION_UNAVAILABLE),
]


def _is_sql_layer(exc: BaseException) -> bool:
    from tidb_tpu import kv
    from tidb_tpu.session import SQLError
    return isinstance(exc, (SQLError, kv.KVError))


def _is_admission_reject(exc: BaseException) -> bool:
    from tidb_tpu.sched import AdmissionRejectedError
    return isinstance(exc, AdmissionRejectedError)


def _is_device_fault(exc: BaseException) -> bool:
    from tidb_tpu.util.failpoint import DeviceFaultError
    return isinstance(exc, DeviceFaultError)


def classify(exc: BaseException) -> tuple[int, str, str]:
    """exception -> (errno, sqlstate, message) for the wire ERR packet."""
    from tidb_tpu import kv
    from tidb_tpu.parser import ParseError
    from tidb_tpu.schema.infoschema import SchemaError
    from tidb_tpu.table import DupKeyError

    msg = str(exc)
    code = None
    if isinstance(exc, DupKeyError):
        code = ER_DUP_ENTRY
    elif isinstance(exc, ParseError):
        code = ER_PARSE_ERROR
        msg = f"You have an error in your SQL syntax; {msg}"
    elif isinstance(exc, SchemaError):
        # infoschema raises exactly "Unknown database '<db>'" for a bad
        # db; anything else is a missing table (whose NAME may contain
        # the word "database")
        code = ER_BAD_DB_ERROR if msg.startswith("Unknown database") \
            else ER_NO_SUCH_TABLE
    elif isinstance(exc, kv.KeyLockedError):
        code = ER_LOCK_WAIT_TIMEOUT
    elif isinstance(exc, kv.WriteConflictError):
        code = ER_LOCK_DEADLOCK
    elif _is_admission_reject(exc):
        # refused BEFORE anything ran (tidb_tpu/sched.py): retryable
        # server-busy class, same contract as ER_TIKV_SERVER_BUSY
        code = ER_SERVER_BUSY_ADMISSION
    elif _is_device_fault(exc):
        # device-plane fault past the in-process recovery chain
        # (retry/fallback/quarantine, tidb_tpu/sched.py): retryable —
        # a replay lands on the host path or a re-probed device
        code = ER_DEVICE_FAULT
    elif isinstance(exc, kv.StreamInterruptedError):
        # streamed coprocessor reply died past its resume budget: the
        # retryable region-stream class (store/stream.py subsystem)
        code = ER_REGION_STREAM_INTERRUPTED
    elif isinstance(exc, kv.StoreUnavailableError):
        # before the generic RegionError arm: StoreUnavailableError IS
        # a RegionError, but a dead store-plane member deserves its own
        # retryable code (fleet clients re-route on it)
        code = ER_STORE_UNAVAILABLE
    elif isinstance(exc, kv.RegionError):
        code = ER_REGION_UNAVAILABLE
    elif isinstance(exc, kv.ServerBusyError):
        code = ER_TIKV_SERVER_BUSY
    elif isinstance(exc, kv.GCTooEarlyError):
        code = ER_GC_TOO_EARLY
    elif isinstance(exc, kv.UndeterminedError):
        # commit may or may not have landed: must NOT look retryable
        code = ER_RESULT_UNDETERMINED
    elif isinstance(exc, kv.TxnAbortedError):
        code = ER_TIKV_SERVER_TIMEOUT
    else:
        try:
            from tidb_tpu.config import UnknownVariableError
            if isinstance(exc, UnknownVariableError):
                code = ER_UNKNOWN_SYSTEM_VARIABLE
                msg = f"Unknown system variable '{msg}'"
        except ImportError:
            pass
    if code is None and _is_sql_layer(exc):
        # message patterns apply ONLY to SQL-layer errors; an arbitrary
        # internal exception must surface as ER_UNKNOWN ("internal
        # error"), never masquerade as a user mistake
        for pat, c in _PATTERNS:
            if pat.search(msg):
                code = c
                break
    if code is None:
        code = ER_UNKNOWN
    return code, _SQLSTATE.get(code, "HY000"), msg
