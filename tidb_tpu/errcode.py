"""MySQL error-code catalog and exception classification.

Reference: /root/reference/mysql/errcode.go (the code constants),
mysql/errname.go, terror/terror.go:152 (error class -> MySQL code
mapping surfaced on the wire). The server's ERR packet carries
(errno, sqlstate, message); classify() maps the framework's typed
exceptions onto the right pair so MySQL clients and drivers see
standard codes (1062 duplicate key, 1146 missing table, ...)."""

from __future__ import annotations

import re

__all__ = ["classify", "ER_UNKNOWN"]

# -- the catalog (subset the engine can actually raise) ----------------------

ER_DUP_ENTRY = 1062
ER_NO_SUCH_TABLE = 1146
ER_BAD_DB_ERROR = 1049
ER_DB_CREATE_EXISTS = 1007
ER_TABLE_EXISTS_ERROR = 1050
ER_PARSE_ERROR = 1064
ER_ACCESS_DENIED_ERROR = 1045
ER_TABLEACCESS_DENIED_ERROR = 1142
ER_BAD_FIELD_ERROR = 1054
ER_DUP_FIELDNAME = 1060
ER_DUP_KEYNAME = 1061
ER_CANNOT_USER = 1396
ER_NON_UNIQ_ERROR = 1052          # ambiguous column
ER_UNKNOWN_SYSTEM_VARIABLE = 1193
ER_LOCK_WAIT_TIMEOUT = 1205
ER_LOCK_DEADLOCK = 1213
ER_NO_DB_ERROR = 1046
ER_WRONG_VALUE_COUNT = 1136
ER_TRUNCATED_WRONG_VALUE = 1292
ER_DATA_TOO_LONG = 1406
ER_BAD_NULL_ERROR = 1048
ER_QUERY_INTERRUPTED = 1317
ER_NO_SUCH_THREAD = 1094
ER_UNKNOWN = 1105

_SQLSTATE = {
    ER_DUP_ENTRY: "23000",
    ER_BAD_NULL_ERROR: "23000",
    ER_QUERY_INTERRUPTED: "70100",
    ER_NO_SUCH_THREAD: "HY000",
    ER_NO_SUCH_TABLE: "42S02",
    ER_BAD_DB_ERROR: "42000",
    ER_DB_CREATE_EXISTS: "HY000",
    ER_TABLE_EXISTS_ERROR: "42S01",
    ER_PARSE_ERROR: "42000",
    ER_ACCESS_DENIED_ERROR: "28000",
    ER_TABLEACCESS_DENIED_ERROR: "42000",
    ER_BAD_FIELD_ERROR: "42S22",
    ER_DUP_FIELDNAME: "42S21",
    ER_DUP_KEYNAME: "42000",
    ER_CANNOT_USER: "HY000",
    ER_NON_UNIQ_ERROR: "23000",
    ER_UNKNOWN_SYSTEM_VARIABLE: "HY000",
    ER_LOCK_WAIT_TIMEOUT: "HY000",
    ER_LOCK_DEADLOCK: "40001",
    ER_NO_DB_ERROR: "3D000",
    ER_WRONG_VALUE_COUNT: "21S01",
    ER_TRUNCATED_WRONG_VALUE: "22007",
    ER_DATA_TOO_LONG: "22001",
    ER_UNKNOWN: "HY000",
}

# message-shape fallbacks for SQLError strings raised deep in the stack
_PATTERNS = [
    (re.compile(r"Unknown database", re.I), ER_BAD_DB_ERROR),
    (re.compile(r"doesn't exist|Unknown table", re.I), ER_NO_SUCH_TABLE),
    (re.compile(r"database '[^']*' (already )?exists", re.I),
     ER_DB_CREATE_EXISTS),
    (re.compile(r"index '[^']*' (already )?exists", re.I), ER_DUP_KEYNAME),
    (re.compile(r"column '[^']*' (already )?exists", re.I),
     ER_DUP_FIELDNAME),
    (re.compile(r"user .* (already )?exists", re.I), ER_CANNOT_USER),
    (re.compile(r"(already )?exists", re.I), ER_TABLE_EXISTS_ERROR),
    (re.compile(r"Unknown column", re.I), ER_BAD_FIELD_ERROR),
    (re.compile(r"ambiguous", re.I), ER_NON_UNIQ_ERROR),
    (re.compile(r"denied", re.I), ER_TABLEACCESS_DENIED_ERROR),
    (re.compile(r"Unknown system variable|unknown variable", re.I),
     ER_UNKNOWN_SYSTEM_VARIABLE),
    (re.compile(r"No database selected", re.I), ER_NO_DB_ERROR),
    (re.compile(r"parameter count|column count", re.I),
     ER_WRONG_VALUE_COUNT),
    (re.compile(r"cannot be null", re.I), ER_BAD_NULL_ERROR),
    (re.compile(r"interrupted", re.I), ER_QUERY_INTERRUPTED),
    (re.compile(r"Unknown thread id", re.I), ER_NO_SUCH_THREAD),
    (re.compile(r"incorrect value", re.I), ER_TRUNCATED_WRONG_VALUE),
]


def _is_sql_layer(exc: BaseException) -> bool:
    from tidb_tpu import kv
    from tidb_tpu.session import SQLError
    return isinstance(exc, (SQLError, kv.KVError))


def classify(exc: BaseException) -> tuple[int, str, str]:
    """exception -> (errno, sqlstate, message) for the wire ERR packet."""
    from tidb_tpu import kv
    from tidb_tpu.parser import ParseError
    from tidb_tpu.schema.infoschema import SchemaError
    from tidb_tpu.table import DupKeyError

    msg = str(exc)
    code = None
    if isinstance(exc, DupKeyError):
        code = ER_DUP_ENTRY
    elif isinstance(exc, ParseError):
        code = ER_PARSE_ERROR
        msg = f"You have an error in your SQL syntax; {msg}"
    elif isinstance(exc, SchemaError):
        # infoschema raises exactly "Unknown database '<db>'" for a bad
        # db; anything else is a missing table (whose NAME may contain
        # the word "database")
        code = ER_BAD_DB_ERROR if msg.startswith("Unknown database") \
            else ER_NO_SUCH_TABLE
    elif isinstance(exc, kv.KeyLockedError):
        code = ER_LOCK_WAIT_TIMEOUT
    elif isinstance(exc, kv.WriteConflictError):
        code = ER_LOCK_DEADLOCK
    else:
        try:
            from tidb_tpu.config import UnknownVariableError
            if isinstance(exc, UnknownVariableError):
                code = ER_UNKNOWN_SYSTEM_VARIABLE
                msg = f"Unknown system variable '{msg}'"
        except ImportError:
            pass
    if code is None and _is_sql_layer(exc):
        # message patterns apply ONLY to SQL-layer errors; an arbitrary
        # internal exception must surface as ER_UNKNOWN ("internal
        # error"), never masquerade as a user mistake
        for pat, c in _PATTERNS:
            if pat.search(msg):
                code = c
                break
    if code is None:
        code = ER_UNKNOWN
    return code, _SQLSTATE.get(code, "HY000"), msg
