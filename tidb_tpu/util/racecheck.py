"""Race-detection harness for the threaded store/DDL paths.

The reference leans on Go's -race (Makefile:124). CPython has no
equivalent sanitizer; what catches the same bug class in practice is
maximizing thread interleavings while asserting SEMANTIC invariants
(no lost updates, monotonic TSO, one unique-insert winner...):
`stress()` drops the interpreter's switch interval to its floor — the
standard CPython trick for surfacing races — and
tests/test_race_harness.py runs the store workloads under it.
"""

from __future__ import annotations

import contextlib
import sys

__all__ = ["stress"]


@contextlib.contextmanager
def stress(interval: float = 1e-6):
    """Minimize the GIL switch interval to maximize interleavings."""
    old = sys.getswitchinterval()
    sys.setswitchinterval(interval)
    try:
        yield
    finally:
        sys.setswitchinterval(old)
