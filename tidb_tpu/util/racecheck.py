"""Race-detection harness for the threaded store/DDL paths.

The reference leans on Go's -race (Makefile:124). CPython has no
equivalent sanitizer, so this module provides the two pieces that
catch the same bug class in practice:

1. `stress()` — a context manager that drops the interpreter's thread
   switch interval to its floor, multiplying the interleavings a test
   explores (the standard CPython trick for surfacing races).
2. `LockDiscipline` — instruments chosen methods of an object so each
   call asserts a declared lock is HELD by the caller; any path that
   reaches shared state without its lock fails the test instead of
   corrupting memory silently.

tests/test_race_harness.py uses both to hammer MVCC commit, TSO,
region-cache churn, and the replication ship path.
"""

from __future__ import annotations

import contextlib
import functools
import sys
import threading

__all__ = ["stress", "LockDiscipline"]


@contextlib.contextmanager
def stress(interval: float = 1e-6):
    """Minimize the GIL switch interval to maximize interleavings."""
    old = sys.getswitchinterval()
    sys.setswitchinterval(interval)
    try:
        yield
    finally:
        sys.setswitchinterval(old)


class LockDiscipline:
    """Asserts a lock-held invariant on instrumented methods.

    discipline = LockDiscipline(engine, engine._mu,
                                ["prewrite", "commit", "rollback"])
    ... run workload ...
    discipline.restore()
    assert discipline.violations == []
    """

    def __init__(self, obj, lock, methods: list[str]):
        self.obj = obj
        self.lock = lock
        self.violations: list[str] = []
        self._orig: dict[str, object] = {}
        self._concurrent = 0
        self._mu = threading.Lock()
        for name in methods:
            orig = getattr(obj, name)
            self._orig[name] = orig
            setattr(obj, name, self._wrap(name, orig))

    def _wrap(self, name, orig):
        @functools.wraps(orig)
        def wrapper(*a, **k):
            # entering the method itself takes the lock internally; what
            # we check is EXCLUSION: no two instrumented calls may run
            # their critical section at once if the object's own locking
            # is correct. We detect overlap of lock-free windows.
            with self._mu:
                self._concurrent += 1
                if self._concurrent > 1 and not self._locked_elsewhere():
                    self.violations.append(
                        f"{name}: {self._concurrent} concurrent entries "
                        "with the object lock free")
            try:
                return orig(*a, **k)
            finally:
                with self._mu:
                    self._concurrent -= 1
        return wrapper

    def _locked_elsewhere(self) -> bool:
        # a held lock means the overlapping callers are serialized by it
        acquired = self.lock.acquire(blocking=False)
        if acquired:
            self.lock.release()
            return False
        return True

    def restore(self) -> None:
        for name, orig in self._orig.items():
            setattr(self.obj, name, orig)
