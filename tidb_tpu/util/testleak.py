"""Thread-leak detection for tests.

Reference: /root/reference/util/testleak/leaktest.go — AfterTest
snapshots goroutines and fails a test that leaves new ones running,
with an allowlist for long-lived infrastructure. Python analogue over
threading.enumerate(): long-lived daemon loops this framework starts
deliberately (schema/stats workers, server accept loops, status HTTP)
are allowlisted by thread name; anything else left running after a test
is a leak."""

from __future__ import annotations

import threading
import time

__all__ = ["snapshot", "check", "ALLOWED_PREFIXES"]

# deliberate long-lived loops (started once, daemon, never joined)
ALLOWED_PREFIXES = (
    "MainThread", "pytest", "schema-worker", "stats-worker",
    "stats-auto-analyze", "storage-accept", "storage-conn",
    "status-http", "server-accept", "x-server", "gc-worker",
    "ThreadPoolExecutor", "delta-merge", "dispatch-watchdog",
    "metrics-history",
)


def _interesting(t: threading.Thread) -> bool:
    if not t.is_alive():
        return False
    return not any(t.name.startswith(p) for p in ALLOWED_PREFIXES)


def snapshot() -> set[str]:
    """Names of live, non-allowlisted threads."""
    return {t.name for t in threading.enumerate() if _interesting(t)}


def check(before: set[str], timeout: float = 2.0) -> list[str]:
    """-> names of threads alive now but not in `before`, after giving
    short-lived workers `timeout` seconds to drain (the reference polls
    the same way, leaktest.go checkLeakAfterTest)."""
    deadline = time.time() + timeout
    while True:
        leaked = sorted(snapshot() - before)
        if not leaked or time.time() >= deadline:
            return leaked
        time.sleep(0.05)
