"""Central gofail-style failpoint registry: every injectable fault in
one table, armed by name, free when disarmed.

Reference: the reference system's gofail sites (mocktikv rpc.go:465-521
`rpcServerBusy`/`rpcCommitResult`/..., armed via the failpoint HTTP
endpoint) — the pattern this module ports. Before it, the only fault
machinery in-tree was the store-level Backoffer and one ad-hoc `inject`
hook on the mockstore RPC shim; the entire device plane (kernel
dispatch/finalize, HBM fill/patch, the delta-merge worker, scheduler
slots, the admission shed chain, wire teardown) had no injectable
faults and therefore no proof of recovery. Now each seam declares one
named point in `REGISTRY` below and calls

    failpoint.eval("name", *args)

which costs ONE dict lookup while the point is disarmed — production
paths stay free. Armed points run an action:

  * ``raise`` / ``raise(ExcName)`` / ``raise(ExcName:message)`` — raise
    an exception from the safe class table (`_EXC_TABLE`);
  * ``delay(ms)``       — sleep, then continue (slow-path injection);
  * ``return(value)``   — eval returns the parsed int/str value;
  * a Python callable   — called with eval's args (test hooks; the
    successor of the deleted `RPCShim.inject`).

Action prefixes compose: ``3*raise(DeviceFaultError)`` fires three
times then self-disarms (fire-count budget); ``1-in-4:delay(20)``
fires on every 4th evaluation (deterministic, so chaos schedules
replay). Arming surfaces:

  * environment: ``TIDB_TPU_FAILPOINTS="hbm/fill=raise;..."`` at
    import (CI / chaos harness);
  * SET-style sysvar: ``SET GLOBAL tidb_tpu_failpoints =
    'name=spec;...'`` — the sysvar's value IS the armed-via-SET set
    (setting it disarms points a previous SET armed);
  * HTTP: ``POST /failpoint {"name":..., "spec":...}`` on the status
    port (spec null/"" disarms), ``GET /failpoint`` lists registry +
    armed state — see server/status.py;
  * Python: `enable()` / `disable()` / `disable_all()` (tests).

The `failpoint-discipline` lint rule keeps the table honest: every
in-tree eval site must use a declared name, and a declared name no
eval site fires is a finding. See docs/ROBUSTNESS.md for the catalog
and the recovery machinery (watchdog / quarantine / supervisor) the
faults prove out.
"""

from __future__ import annotations

import os
import threading
import time

from tidb_tpu import metrics

__all__ = ["REGISTRY", "eval", "enable", "disable", "disable_all",
           "armed", "parse_spec", "arm_from_string",
           "FailpointError", "DeviceFaultError", "DispatchTimeoutError",
           "UnknownFailpointError", "BadFailpointSpecError"]


# -- the declared points (the failpoint-discipline lint table) ---------------
# name -> where it fires / what arming it simulates. Declaring here is
# the ONLY way to add a failpoint: eval() of an undeclared name is a
# lint finding, enable() of one raises.
REGISTRY: dict[str, str] = {
    # mockstore RPC shim, before every command's region check (the
    # migrated `inject` hook): args (cmd, ctx). Streaming re-checks per
    # frame, so arming it mid-stream drives the client resume path.
    "rpc/request": "mockstore/rpc.py _check — every RPC command, "
                   "including the per-frame CopStream re-check",
    # storage-side streaming producer, before each frame is yielded:
    # args (region_id,). Distinct from rpc/request: fires on the remote
    # transport too.
    "copr/stream-frame": "store/stream.py region_stream — before each "
                         "framed partial response is emitted",
    # device kernel dispatch: sync sites (store/copr.py) and the
    # pipelined dispatch wrapper (ops/runtime.pipeline_map)
    "device/dispatch": "kernel dispatch (copr sync sites + "
                       "pipeline_map) — a raise here is a device fault "
                       "the retry/degrade/quarantine chain handles",
    # device kernel finalize (the blocking readback): pipeline_map's
    # pop_finalize and the device_slot-guarded sync calls
    "device/finalize": "kernel finalize / readback — delay(ms) here "
                       "exercises the dispatch watchdog",
    "hbm/fill": "store/device_cache.py fill — the HBM region-block "
                "upload path",
    "hbm/patch": "store/device_cache.py _patch_locked — the in-place "
                 "delta patch of a resident block",
    "delta/merge": "store/delta.py _merge_table — the background "
                   "delta-merge worker loop (supervisor restarts it)",
    "sched/slot": "sched.device_slot acquire — the global dispatch-"
                  "slot grant",
    "admission/shed": "sched.shed_server — the admission/operator shed "
                      "chain drive",
    "wire/resultset": "server _write_resultset — between result rows "
                      "(connection teardown mid-resultset)",
    "worker/tick": "util/supervisor.py — each supervised background-"
                   "worker beat (schema worker, delta merge); args "
                   "(worker_name,)",
    # cluster observability fan-out, before each per-member status-port
    # fetch: args (member_id, path). Arming it simulates a wedged or
    # partitioned member — cluster_* queries must degrade to partial
    # rows + a warning, never hang or error.
    "cluster/fetch": "util/statusclient.py _fetch_one — before each "
                     "per-member fetch of the cluster_* / /fleet/* "
                     "fan-out",
    # kernel-profile registry record fold, before each completed
    # dispatch is folded into its profile row: args (family,). Lets
    # tests fault/delay exactly the profiler's own bookkeeping without
    # touching the kernel dispatch it shadows.
    "profiler/record": "profiler.KernelProfileRegistry.record_dispatch "
                       "— before a completed dispatch folds into its "
                       "profile row",
}


class FailpointError(RuntimeError):
    """Generic injected failure (the default `raise` action)."""


class DeviceFaultError(Exception):
    """A device-plane operation (kernel dispatch/finalize, HBM
    fill/patch) failed or timed out. RETRYABLE: surfaced to clients as
    ER_DEVICE_FAULT (9009) — nothing partial is visible, the statement
    may be re-run verbatim; in-process the recovery chain (retry once,
    degrade the statement to the host path, quarantine the device on
    repeated faults) usually absorbs it first. Raised by armed
    failpoints, by the dispatch watchdog (sched.py), and available to
    real device backends for transport-level failures."""


class DispatchTimeoutError(DeviceFaultError):
    """The dispatch watchdog's flavor of DeviceFaultError: the
    statement is already cancel-latched, so the per-dispatch recovery
    chain must NOT retry it — it propagates straight out (still
    retryable at the client)."""


class UnknownFailpointError(KeyError):
    """enable()/POST of a name not declared in REGISTRY."""


class BadFailpointSpecError(ValueError):
    """Unparseable action spec."""


# exceptions `raise(Name)` may construct: message-only / no-arg classes
# (region errors need ids — inject those through a callable action)
def _exc_table() -> dict:
    from tidb_tpu import kv
    return {
        "FailpointError": FailpointError,
        "DeviceFaultError": DeviceFaultError,
        "DispatchTimeoutError": DispatchTimeoutError,
        "KVError": kv.KVError,
        "ServerBusyError": kv.ServerBusyError,
        "RetryableError": kv.RetryableError,
        "StreamInterruptedError": kv.StreamInterruptedError,
        "RuntimeError": RuntimeError,
        "IOError": IOError,
        "TimeoutError": TimeoutError,
    }


class _Armed:
    """One armed point. Counters are guarded by the module _mu; the
    action fields are immutable after construction."""

    __slots__ = ("spec", "action", "arg", "budget", "period", "hits",
                 "fired")

    def __init__(self, spec, action, arg, budget, period):
        self.spec = spec            # original string (None for callables)
        self.action = action        # "raise"|"delay"|"return"|"call"
        self.arg = arg
        self.budget = budget        # guarded-by: _mu  remaining fires
        self.period = period        # fire every Nth eval (None = every)
        self.hits = 0               # guarded-by: _mu
        self.fired = 0              # guarded-by: _mu


_mu = threading.Lock()
_ARMED: dict[str, _Armed] = {}      # guarded-by: _mu (reads lock-free)
_SYSVAR_ARMED: set[str] = set()     # guarded-by: _mu  names the sysvar owns


def parse_spec(spec: str) -> _Armed:
    """``[N*][1-in-M:]action[(arg)]`` -> an _Armed (unbound).
    Raises BadFailpointSpecError on anything else."""
    raw = spec
    spec = spec.strip()
    budget = None
    period = None
    if "*" in spec:
        head, spec = spec.split("*", 1)
        try:
            budget = int(head)
        except ValueError:
            raise BadFailpointSpecError(raw) from None
        if budget <= 0:
            raise BadFailpointSpecError(raw)
    if spec.startswith("1-in-"):
        head, _, spec = spec.partition(":")
        try:
            period = int(head[len("1-in-"):])
        except ValueError:
            raise BadFailpointSpecError(raw) from None
        if period <= 0 or not spec:
            raise BadFailpointSpecError(raw)
    arg = None
    if "(" in spec:
        if not spec.endswith(")"):
            raise BadFailpointSpecError(raw)
        spec, arg = spec[:-1].split("(", 1)
    action = spec.strip()
    if action == "raise":
        exc_name, _, msg = (arg or "FailpointError").partition(":")
        cls = _exc_table().get(exc_name.strip())
        if cls is None:
            raise BadFailpointSpecError(
                f"{raw}: unknown exception {exc_name!r} (see "
                f"failpoint._exc_table)")
        arg = (cls, msg or f"failpoint {exc_name.strip()}")
    elif action == "delay":
        try:
            arg = float(arg)
        except (TypeError, ValueError):
            raise BadFailpointSpecError(raw) from None
    elif action == "return":
        if not arg:
            raise BadFailpointSpecError(raw)
        try:
            arg = int(arg)
        except ValueError:
            pass                    # strings pass through verbatim
    else:
        raise BadFailpointSpecError(raw)
    return _Armed(raw, action, arg, budget, period)


def enable(name: str, spec) -> None:
    """Arm `name` with a spec string or a callable (called with eval's
    args; its return value is eval's). Re-arming replaces."""
    if name not in REGISTRY:
        raise UnknownFailpointError(name)
    if callable(spec):
        ap = _Armed(None, "call", spec, None, None)
    else:
        ap = parse_spec(spec)
    with _mu:
        _ARMED[name] = ap


def disable(name: str) -> None:
    with _mu:
        _ARMED.pop(name, None)
        _SYSVAR_ARMED.discard(name)


def disable_all() -> None:
    with _mu:
        _ARMED.clear()
        _SYSVAR_ARMED.clear()


def armed() -> dict[str, dict]:
    """Snapshot of armed points (status endpoint / tests)."""
    with _mu:
        return {name: {"spec": ap.spec or "<callable>",
                       "hits": ap.hits, "fired": ap.fired,
                       "budget": ap.budget}
                for name, ap in _ARMED.items()}


def eval(name: str, *args):  # noqa: A001 - gofail's verb, deliberately
    """The instrumented-seam hook: one dict lookup when `name` is
    disarmed (returns None); otherwise runs the armed action — which
    may raise, sleep, or hand back a value."""
    ap = _ARMED.get(name)       # lock-free read: benign race with
    if ap is None:              # enable/disable, re-checked under _mu
        return None
    return _fire(name, ap, args)


def _fire(name: str, ap: _Armed, args):
    with _mu:
        if _ARMED.get(name) is not ap:
            return None         # disarmed/re-armed since the fast read
        ap.hits += 1
        if ap.period is not None and ap.hits % ap.period != 0:
            return None
        if ap.budget is not None:
            if ap.budget <= 0:
                _ARMED.pop(name, None)
                return None
            ap.budget -= 1
            if ap.budget == 0:
                _ARMED.pop(name, None)   # last fire: self-disarm
        ap.fired += 1
        action, arg = ap.action, ap.arg
    # the action itself runs with _mu dropped: callables may re-enter
    # the registry, raises unwind arbitrary stacks, delays sleep
    metrics.counter(metrics.FAILPOINT_FIRES, {"name": name})
    if action == "raise":
        cls, msg = arg
        raise cls(msg)
    if action == "delay":
        time.sleep(arg / 1e3)
        return None
    if action == "return":
        return arg
    return arg(*args)           # "call"


# -- bulk arming (env / sysvar) ----------------------------------------------

def arm_from_string(specs: str, owner_sysvar: bool = False) -> list[str]:
    """Parse ``name=spec;name=spec`` and arm each point; with
    owner_sysvar=True the listed set REPLACES whatever a previous
    sysvar write armed (the sysvar's value is declarative). Returns the
    armed names. Raises on unknown names / bad specs — arming must fail
    loudly, a typo'd chaos schedule that silently arms nothing would
    fake a green run."""
    pairs = []
    for part in specs.split(";"):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise BadFailpointSpecError(part)
        name, spec = part.split("=", 1)
        pairs.append((name.strip(), spec.strip()))
    # validate EVERYTHING before arming ANYTHING: a bad entry halfway
    # through must not leave earlier points armed (and, on the sysvar
    # surface, un-owned — a subsequent SET '' could then never disarm
    # a fault a rejected SET half-applied)
    parsed = []
    for name, spec in pairs:
        if name not in REGISTRY:
            raise UnknownFailpointError(name)
        parsed.append((name, parse_spec(spec)))
    names = [name for name, _ap in parsed]
    with _mu:
        for name, ap in parsed:
            _ARMED[name] = ap
        if owner_sysvar:
            for old in _SYSVAR_ARMED - set(names):
                _ARMED.pop(old, None)
            _SYSVAR_ARMED.clear()
            _SYSVAR_ARMED.update(names)
    return names


def _sysvar_changed(value) -> None:
    """config.on_change hook for `tidb_tpu_failpoints`: the sysvar's
    string IS the SET-armed set."""
    arm_from_string(str(value or ""), owner_sysvar=True)


def _install() -> None:
    from tidb_tpu import config
    config.on_change("tidb_tpu_failpoints", _sysvar_changed)
    env = os.environ.get("TIDB_TPU_FAILPOINTS")
    if env:
        arm_from_string(env)


_install()
