"""Pure-python AES-128 ECB block ops: fallback for MySQL AES_ENCRYPT /
AES_DECRYPT when the `cryptography` package is absent from the image.

MySQL's key folding (expression/builtins_ext.py) always produces a
16-byte key, so only AES-128 is needed. This is a straight FIPS-197
implementation — table-driven S-box built from the GF(2^8) inverse plus
the affine map, so no 256-constant blob to get subtly wrong; verified
against the FIPS-197 appendix vector in tests/test_builtins_ext.py.
Performance is irrelevant here (a per-row SQL builtin on a mock store),
correctness and zero dependencies are the point.
"""

from __future__ import annotations

__all__ = ["encrypt_block", "decrypt_block"]

# -- GF(2^8) tables -----------------------------------------------------------

_EXP = [0] * 512
_LOG = [0] * 256
_x = 1
for _i in range(255):
    _EXP[_i] = _x
    _LOG[_x] = _i
    # multiply by the generator 0x03 = x * 2 ^ x
    _x ^= (_x << 1) ^ (0x11B if _x & 0x80 else 0)
    _x &= 0xFF
for _i in range(255, 512):
    _EXP[_i] = _EXP[_i - 255]


def _gmul(a: int, b: int) -> int:
    if a == 0 or b == 0:
        return 0
    return _EXP[_LOG[a] + _LOG[b]]


def _rotl8(b: int, n: int) -> int:
    return ((b << n) | (b >> (8 - n))) & 0xFF


_SBOX = [0] * 256
for _i in range(256):
    _inv = 0 if _i == 0 else _EXP[255 - _LOG[_i]]
    _SBOX[_i] = (_inv ^ _rotl8(_inv, 1) ^ _rotl8(_inv, 2) ^
                 _rotl8(_inv, 3) ^ _rotl8(_inv, 4) ^ 0x63)
_INV_SBOX = [0] * 256
for _i, _v in enumerate(_SBOX):
    _INV_SBOX[_v] = _i

_RCON = [0x01, 0x02, 0x04, 0x08, 0x10, 0x20, 0x40, 0x80, 0x1B, 0x36]


def _expand_key(key: bytes) -> list[list[int]]:
    """16-byte key -> 11 round keys of 16 ints each."""
    if len(key) != 16:
        raise ValueError("AES-128 needs a 16-byte key")
    words = [list(key[i:i + 4]) for i in range(0, 16, 4)]
    for i in range(4, 44):
        w = list(words[i - 1])
        if i % 4 == 0:
            w = [_SBOX[w[1]] ^ _RCON[i // 4 - 1], _SBOX[w[2]],
                 _SBOX[w[3]], _SBOX[w[0]]]
        words.append([a ^ b for a, b in zip(words[i - 4], w)])
    return [sum(words[4 * r:4 * r + 4], []) for r in range(11)]


def _shift_rows(s: list[int]) -> list[int]:
    # state is column-major (FIPS-197): byte r + 4c
    return [s[(i + 4 * (i % 4)) % 16] for i in range(16)]


def _inv_shift_rows(s: list[int]) -> list[int]:
    return [s[(i - 4 * (i % 4)) % 16] for i in range(16)]


def _mix_columns(s: list[int], inv: bool) -> list[int]:
    out = [0] * 16
    m = ((14, 11, 13, 9) if inv else (2, 3, 1, 1))
    for c in range(4):
        col = s[4 * c:4 * c + 4]
        for r in range(4):
            out[4 * c + r] = (_gmul(col[0], m[(0 - r) % 4]) ^
                              _gmul(col[1], m[(1 - r) % 4]) ^
                              _gmul(col[2], m[(2 - r) % 4]) ^
                              _gmul(col[3], m[(3 - r) % 4]))
    return out


def encrypt_block(key: bytes, block: bytes) -> bytes:
    if len(block) != 16:
        raise ValueError("AES block must be 16 bytes")
    rk = _expand_key(key)
    s = [b ^ k for b, k in zip(block, rk[0])]
    for rnd in range(1, 10):
        s = [_SBOX[b] for b in s]
        s = _shift_rows(s)
        s = _mix_columns(s, inv=False)
        s = [b ^ k for b, k in zip(s, rk[rnd])]
    s = [_SBOX[b] for b in s]
    s = _shift_rows(s)
    return bytes(b ^ k for b, k in zip(s, rk[10]))


def decrypt_block(key: bytes, block: bytes) -> bytes:
    if len(block) != 16:
        raise ValueError("AES block must be 16 bytes")
    rk = _expand_key(key)
    s = [b ^ k for b, k in zip(block, rk[10])]
    for rnd in range(9, 0, -1):
        s = _inv_shift_rows(s)
        s = [_INV_SBOX[b] for b in s]
        s = [b ^ k for b, k in zip(s, rk[rnd])]
        s = _mix_columns(s, inv=True)
    s = _inv_shift_rows(s)
    s = [_INV_SBOX[b] for b in s]
    return bytes(b ^ k for b, k in zip(s, rk[0]))
