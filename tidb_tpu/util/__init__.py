"""Small shared utilities (ref: /root/reference/util/)."""

from __future__ import annotations

import threading
from collections import OrderedDict

__all__ = ["LRUCache"]


class LRUCache:
    """Thread-safe LRU (ref: util/kvcache sharded LRU — one shard is
    plenty in-process; the lock is uncontended off the hot path)."""

    def __init__(self, capacity: int = 100):
        self.capacity = capacity
        self._d: OrderedDict = OrderedDict()
        self._mu = threading.Lock()
        self.hits = 0
        self.misses = 0

    def get(self, key):
        with self._mu:
            v = self._d.get(key)
            if v is None:
                self.misses += 1
                return None
            self._d.move_to_end(key)
            self.hits += 1
            return v

    def put(self, key, value) -> None:
        with self._mu:
            self._d[key] = value
            self._d.move_to_end(key)
            while len(self._d) > self.capacity:
                self._d.popitem(last=False)

    def clear(self) -> None:
        with self._mu:
            self._d.clear()

    def __len__(self):
        return len(self._d)
