"""SortedDict: prefer the real `sortedcontainers`, else a bisect shim.

The storage stack (MVCC engine, cluster topology, region cache, memdb)
keys everything on sorted byte strings. The container image does not
always ship `sortedcontainers` (and nothing may be pip-installed), so
this module provides the subset the repo uses as a pure-stdlib fallback:
a dict paired with a bisect-maintained key list. Insert/delete are
O(n) memmove (fine at mock-store scale — the hot analytical path reads
through `irange`, which is O(log n) + slice); iteration orders are
identical to the real library for every operation used here.

`irange` snapshots the key range before yielding (the real library
iterates the live tree): every repo call site holds the owning lock for
the full iteration, so the semantics difference is unobservable, and a
snapshot can never corrupt mid-iteration.
"""

from __future__ import annotations

import bisect

__all__ = ["SortedDict"]

try:                                        # pragma: no cover
    from sortedcontainers import SortedDict  # type: ignore  # noqa: F401
except ImportError:

    class _KeysView:
        """Live, indexable, ordered key view (sortedcontainers shape)."""

        __slots__ = ("_keys",)

        def __init__(self, keys: list):
            self._keys = keys

        def __len__(self) -> int:
            return len(self._keys)

        def __getitem__(self, i):
            return self._keys[i]

        def __iter__(self):
            return iter(self._keys)

        def __contains__(self, k) -> bool:
            i = bisect.bisect_left(self._keys, k)
            return i < len(self._keys) and self._keys[i] == k

    class _ValuesView:
        __slots__ = ("_sd",)

        def __init__(self, sd: "SortedDict"):
            self._sd = sd

        def __len__(self) -> int:
            return len(self._sd._keys)

        def __getitem__(self, i):
            return self._sd._map[self._sd._keys[i]]

        def __iter__(self):
            m = self._sd._map
            return (m[k] for k in self._sd._keys)

    class _ItemsView:
        __slots__ = ("_sd",)

        def __init__(self, sd: "SortedDict"):
            self._sd = sd

        def __len__(self) -> int:
            return len(self._sd._keys)

        def __getitem__(self, i):
            k = self._sd._keys[i]
            return (k, self._sd._map[k])

        def __iter__(self):
            m = self._sd._map
            return ((k, m[k]) for k in self._sd._keys)

    class SortedDict:                        # type: ignore[no-redef]
        __slots__ = ("_map", "_keys")

        def __init__(self, *args, **kwargs):
            self._map: dict = {}
            self._keys: list = []
            if args or kwargs:
                self.update(*args, **kwargs)

        # -- core mapping protocol ----------------------------------------

        def __setitem__(self, key, value) -> None:
            if key not in self._map:
                bisect.insort(self._keys, key)
            self._map[key] = value

        def __getitem__(self, key):
            return self._map[key]

        def __delitem__(self, key) -> None:
            del self._map[key]          # raises KeyError before key-list edit
            i = bisect.bisect_left(self._keys, key)
            del self._keys[i]

        def __contains__(self, key) -> bool:
            return key in self._map

        def __len__(self) -> int:
            return len(self._map)

        def __iter__(self):
            return iter(self._keys)

        def __repr__(self) -> str:
            return f"SortedDict({dict(self.items())!r})"

        def __eq__(self, other) -> bool:
            if isinstance(other, SortedDict):
                return self._map == other._map
            return self._map == other

        # -- dict surface -------------------------------------------------

        def get(self, key, default=None):
            return self._map.get(key, default)

        def pop(self, key, *default):
            if key in self._map or not default:
                v = self._map.pop(key)
                i = bisect.bisect_left(self._keys, key)
                del self._keys[i]
                return v
            return default[0]

        def setdefault(self, key, default=None):
            if key not in self._map:
                self[key] = default
            return self._map[key]

        def update(self, *args, **kwargs) -> None:
            # bulk path: merge then re-sort wholesale (cheaper than n
            # insorts for large ingests — the mvcc bulk_import shape)
            staged = dict(*args, **kwargs) if args or kwargs else {}
            fresh = [k for k in staged if k not in self._map]
            self._map.update(staged)
            if fresh:
                self._keys.extend(fresh)
                self._keys.sort()

        def clear(self) -> None:
            self._map.clear()
            self._keys.clear()

        def copy(self) -> "SortedDict":
            out = SortedDict()
            out._map = dict(self._map)
            out._keys = list(self._keys)
            return out

        def keys(self) -> "_KeysView":
            return _KeysView(self._keys)

        def values(self) -> "_ValuesView":
            return _ValuesView(self)

        def items(self) -> "_ItemsView":
            return _ItemsView(self)

        # -- sorted surface -----------------------------------------------

        def bisect_left(self, key) -> int:
            return bisect.bisect_left(self._keys, key)

        def bisect_right(self, key) -> int:
            return bisect.bisect_right(self._keys, key)

        def peekitem(self, index: int = -1):
            k = self._keys[index]
            return (k, self._map[k])

        def irange(self, minimum=None, maximum=None,
                   inclusive=(True, True), reverse=False):
            """Iterate keys in [minimum, maximum] honoring `inclusive`
            bounds, optionally reversed. None bounds are open."""
            if minimum is None:
                lo = 0
            elif inclusive[0]:
                lo = bisect.bisect_left(self._keys, minimum)
            else:
                lo = bisect.bisect_right(self._keys, minimum)
            if maximum is None:
                hi = len(self._keys)
            elif inclusive[1]:
                hi = bisect.bisect_right(self._keys, maximum)
            else:
                hi = bisect.bisect_left(self._keys, maximum)
            span = self._keys[lo:hi]
            if reverse:
                span.reverse()
            return iter(span)

        # -- pickling (on-disk snapshots, store/snapshot.py) ---------------

        def __reduce__(self):
            return (SortedDict, (self._map,))
