"""Background-worker supervisor: crashed workers restart with backoff
and a counted metric instead of dying silently.

Before this module every long-lived loop in the tree protected itself
with a blanket ``except Exception: pass`` per tick — a worker whose
tick started failing deterministically (schema reload against a
wedged store, a delta merge tripping a device fault) would spin
uncounted, and a crash OUTSIDE the netted region killed the thread
with no trace: the delta journal would grow unmerged forever. The
supervisor owns that policy in one place:

* `supervise(name, beat, stop, interval)` — a daemon loop calling
  `beat()` every `interval` seconds until `stop` is set. A beat that
  raises counts `tidb_tpu_worker_restarts_total{worker=name}` and the
  NEXT beat waits an exponential backoff (capped) instead of the plain
  interval, so a deterministically-failing beat cannot busy-spin; a
  beat that succeeds resets the backoff.

* `run_once(name, fn, retries)` — one-shot background jobs (the
  delta-merge trigger): run `fn`, retrying a crash up to `retries`
  times with the same counted backoff, then give up loudly (logged)
  rather than silently.

Each supervised beat first evaluates the `worker/tick` failpoint
(util/failpoint.py) with the worker's name, so tests and the chaos
harness can crash any worker by name and watch it come back.
"""

from __future__ import annotations

import logging
import threading
import time

from tidb_tpu import metrics
from tidb_tpu.util import failpoint

__all__ = ["supervise", "run_once", "BACKOFF_BASE_S", "BACKOFF_CAP_S"]

log = logging.getLogger("tidb_tpu.supervisor")

BACKOFF_BASE_S = 0.05
BACKOFF_CAP_S = 5.0


def _backoff_s(crashes: int) -> float:
    return min(BACKOFF_BASE_S * (2 ** max(crashes - 1, 0)),
               BACKOFF_CAP_S)


def supervise(name: str, beat, stop: threading.Event,
              interval: float) -> threading.Thread:
    """Start (and return) a daemon thread running `beat()` every
    `interval` seconds until `stop` is set, restarting crashed beats
    with counted exponential backoff. The thread is named `name` so
    the testleak allowlist and thread dumps identify it."""

    def loop() -> None:
        crashes = 0
        # backoff SLOWS a crashing beat, never accelerates it: a 30s
        # worker that starts failing must not retry every 5s
        while not stop.wait(interval if crashes == 0
                            else max(interval, _backoff_s(crashes))):
            try:
                failpoint.eval("worker/tick", name)
                beat()
                crashes = 0
            except Exception as e:  # noqa: BLE001 - the supervisor IS
                # the crash handler: count + back off + keep the worker
                # alive (the pre-supervisor blanket nets did the same,
                # silently and without backoff)
                crashes += 1
                metrics.counter(metrics.WORKER_RESTARTS,
                                {"worker": name})
                log.warning("worker %s crashed (restart %d, backoff "
                            "%.2fs): %s", name, crashes,
                            _backoff_s(crashes), e)

    t = threading.Thread(target=loop, daemon=True, name=name)
    t.start()
    return t


def run_once(name: str, fn, retries: int = 2) -> bool:
    """Run a one-shot background job with crash-restart semantics:
    `fn()` retried up to `retries` times after a crash, each retry
    counted in tidb_tpu_worker_restarts_total{worker=name} and backed
    off. -> True when an attempt completed. Called on the job's own
    (already background) thread."""
    for attempt in range(retries + 1):
        try:
            failpoint.eval("worker/tick", name)
            fn()
            return True
        except Exception as e:  # noqa: BLE001 - counted crash-restart
            metrics.counter(metrics.WORKER_RESTARTS, {"worker": name})
            if attempt >= retries:
                log.error("worker %s gave up after %d attempts: %s",
                          name, attempt + 1, e)
                return False
            time.sleep(_backoff_s(attempt + 1))
    return False
