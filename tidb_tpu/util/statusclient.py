"""Status-port HTTP client: one bounded-timeout JSON fetch helper.

Before this module, every consumer of the status API hand-rolled its
own `urllib.request.urlopen` — fleet.py's health probe, bench.py's
fleet scrapes, and half a dozen test files, each with its own timeout
(or none). One shared client keeps the contract in one place:

  * every request carries an explicit bounded timeout — a dead or
    wedged member costs at most the budget, never a hang;
  * JSON decoding and error classification live here, so callers see
    `(doc, None)` or `(None, "timeout"|"error: ...")`, not six
    flavors of URLError.

`fetch_all` is the cluster fan-out built on top: one concurrent sweep
over live members' status ports (member.live_members), used by the
`information_schema.cluster_*` memtables and the `/fleet/*` endpoints.
Per-member outcomes count `tidb_tpu_cluster_scrape_total{outcome=...}`
and an unreachable member degrades to a partial result plus its error
— the caller renders rows for who answered and a warning for who
didn't, never a statement error."""

from __future__ import annotations

import json
import socket
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor

__all__ = ["get_json", "get_text", "post_json", "fetch_all"]

DEFAULT_TIMEOUT = 10.0


def _url(host: str, port: int, path: str) -> str:
    if not path.startswith("/"):
        path = "/" + path
    return f"http://{host}:{int(port)}{path}"


def get_text(host: str, port: int, path: str,
             timeout: float = DEFAULT_TIMEOUT) -> str:
    """GET -> decoded body text (the /metrics Prometheus exposition)."""
    with urllib.request.urlopen(_url(host, port, path),
                                timeout=timeout) as r:
        return r.read().decode()


def get_json(host: str, port: int, path: str,
             timeout: float = DEFAULT_TIMEOUT):
    """GET -> decoded JSON document. Raises like urlopen (OSError
    family) or ValueError on a non-JSON body — callers that must not
    fail use fetch_all's classified form."""
    return json.loads(get_text(host, port, path, timeout=timeout))


def post_json(host: str, port: int, path: str, obj,
              timeout: float = DEFAULT_TIMEOUT):
    """POST a JSON document -> decoded JSON reply (the /failpoint
    arming surface)."""
    req = urllib.request.Request(
        _url(host, port, path), data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read().decode())


def _classify(e: BaseException) -> str:
    if isinstance(e, (socket.timeout, TimeoutError)):
        return "timeout"
    if isinstance(e, urllib.error.URLError) and \
            isinstance(getattr(e, "reason", None),
                       (socket.timeout, TimeoutError)):
        return "timeout"
    return "error"


def _fetch_one(member: dict, path: str, timeout: float):
    from tidb_tpu import metrics
    from tidb_tpu.util import failpoint
    mid = member.get("id", "?")
    try:
        # chaos hook: tests arm this to simulate a wedged/partitioned
        # member without killing the process; args (member_id, path)
        failpoint.eval("cluster/fetch", mid, path)
        doc = get_json(member["host"], member["status_port"], path,
                       timeout=timeout)
    except Exception as e:  # noqa: BLE001 — degrade, never propagate:
        # a dead member yields partial fleet results plus a warning
        outcome = _classify(e)
        if outcome == "timeout":
            metrics.counter(metrics.CLUSTER_SCRAPES,
                            {"outcome": "timeout"})
        else:
            metrics.counter(metrics.CLUSTER_SCRAPES,
                            {"outcome": "error"})
        return mid, None, f"{outcome}: {type(e).__name__}: {e}"
    metrics.counter(metrics.CLUSTER_SCRAPES, {"outcome": "ok"})
    return mid, doc, None


def fetch_all(members: list[dict], path: str,
              timeout: float | None = None):
    """Concurrent bounded sweep: GET `path` from every member's status
    port. -> (docs, errors): docs maps member id -> decoded JSON for
    members that answered inside the budget, errors maps member id ->
    classification string for those that didn't. The sweep's wall time
    is ~one timeout, not members x timeout."""
    from tidb_tpu import config, trace
    if timeout is None:
        timeout = config.cluster_fetch_timeout_ms() / 1000.0
    docs: dict[str, dict] = {}
    errors: dict[str, str] = {}
    if not members:
        return docs, errors
    with trace.span("cluster.fetch", members=len(members), path=path):
        with ThreadPoolExecutor(
                max_workers=min(8, len(members)),
                thread_name_prefix="cluster-fetch") as pool:
            for mid, doc, err in pool.map(
                    lambda m: _fetch_one(m, path, timeout), members):
                if err is None:
                    docs[mid] = doc
                else:
                    errors[mid] = err
    return docs, errors
