"""Runtime lock-order sanitizer: the dynamic half of the lint suite's
whole-program concurrency analysis (tidb_tpu/lint/flow).

The static side derives a lock acquisition-order DAG over every
`threading.Lock/RLock/Condition` construction site in the package,
named `<module>:<Class.>attr` (docs/CONCURRENCY.md holds the
inventory). This module replays real executions against that DAG:

* `enable()` patches the `threading` Lock/RLock/Condition factories.
  While enabled, every such lock constructed AT A REGISTERED SITE
  (caller file:line is looked up in the registry — stdlib and
  test-local locks pass through untouched) comes back wrapped in a
  proxy that reports acquire and release to the sanitizer. Semaphores
  are registered statically but deliberately NOT wrapped: a permit is
  routinely released by a different thread than acquired it
  (admission tokens handed across the accept loop), so per-thread
  held-order tracking would fabricate edges — their orderings are
  covered by the static rule only.
* Each thread keeps its ordered held-lock list. Acquiring B while
  holding H observes the edge H -> B; the edge is checked against the
  union of the static DAG and everything observed so far, and any
  ordering that closes a cycle is recorded as a violation — the
  dynamic witness of a deadlock the static rule would call
  `lock-order`. A same-instance re-acquire of a non-reentrant lock
  raises immediately instead of hanging the suite.
* Same-NAME nested acquires of DISTINCT instances (the memtracker
  tree walking parent/child `_mu`s) are hierarchical locking the
  static names cannot order; they are skipped, mirroring the static
  analysis's reentrant-kind self-edge rule.

Gating: default OFF — zero production overhead. Turn it on with
`TIDB_TPU_LOCK_SANITIZER=1` in the environment (patched at
`import tidb_tpu`, so per-object locks constructed after that are
tracked) or the `sanitize()` context manager, which is how
tests/test_race_harness.py runs: the race harness stress-executes the
store paths under the sanitizer, so the dynamic run validates the
static model and the static DAG gives the dynamic run its oracle.

Limitations, by design: locks constructed BEFORE enabling (module
globals of already-imported modules) are not wrapped, and the checker
sees only orders the workload actually executes — it is a sanitizer,
not a prover. The prover half is `python -m tidb_tpu.lint --rule
lock-order`.
"""

from __future__ import annotations

import contextlib
import os
import sys
import threading
from dataclasses import dataclass, field

__all__ = ["LockOrderError", "Violation", "LockOrderSanitizer",
           "static_dag", "enable", "disable", "sanitize", "active"]

_REENTRANT = frozenset({"RLock", "Condition", "Semaphore"})


class LockOrderError(AssertionError):
    """Raised for orderings the DAG forbids (see Violation list)."""


@dataclass(frozen=True)
class Violation:
    kind: str            # "cycle" | "self-deadlock"
    edge: tuple          # (held name, acquired name)
    thread: str
    detail: str

    def __str__(self):
        return f"[{self.kind}] {self.edge[0]} -> {self.edge[1]} " \
               f"on {self.thread}: {self.detail}"


@dataclass
class _Held:
    proxy: object
    name: str
    count: int = 1


class _TrackedLock:
    """Proxy over a real Lock/RLock: context-manager + acquire/release
    + locked(), reporting transitions to the sanitizer."""

    __slots__ = ("_inner", "_lo_name", "_lo_kind", "_san")

    def __init__(self, inner, name: str, kind: str, san):
        self._inner = inner
        self._lo_name = name
        self._lo_kind = kind
        self._san = san

    def acquire(self, *a, **kw):
        blocking = a[0] if a else kw.get("blocking", True)
        timeout = a[1] if len(a) > 1 else kw.get("timeout", -1)
        if blocking and timeout == -1:
            # plain blocking acquire: note at ATTEMPT time — a real
            # deadlock would hang before success, and the self-deadlock
            # check must fire before the hang
            self._san.note_acquire(self)
            return self._inner.acquire(*a, **kw)
        # trylock / timed form: deliberate deadlock AVOIDANCE — a miss
        # must record nothing (the program backed off exactly so this
        # ordering would not happen)
        got = self._inner.acquire(*a, **kw)
        if got:
            self._san.note_acquire(self)
        return got

    def release(self):
        self._san.note_release(self)
        self._inner.release()

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<sanitized {self._lo_kind} {self._lo_name}>"


class _TrackedCondition(_TrackedLock):
    """Condition proxy: wait/notify delegate to the inner condition,
    with the held entry popped around wait()'s internal release and
    re-checked on re-acquisition."""

    __slots__ = ()

    def wait(self, timeout=None):
        self._san.note_release(self)
        try:
            return self._inner.wait(timeout)
        finally:
            self._san.note_acquire(self)

    def wait_for(self, predicate, timeout=None):
        self._san.note_release(self)
        try:
            return self._inner.wait_for(predicate, timeout)
        finally:
            self._san.note_acquire(self)

    def notify(self, n=1):
        self._inner.notify(n)

    def notify_all(self):
        self._inner.notify_all()

    def locked(self):
        return self._inner._lock.locked()


class LockOrderSanitizer:
    """Order checker over the statically-derived DAG (dag_export() of
    tidb_tpu/lint/flow/analysis.py)."""

    def __init__(self, dag: dict):
        self.sites = dict(dag.get("sites", {}))
        self.kinds = dict(dag.get("kinds", {}))
        self._mu = threading.Lock()
        self.observed: set = set()      # guarded-by: _mu
        self.violations: list[Violation] = []   # guarded-by: _mu
        self.acquires = 0               # guarded-by: _mu  (tracked ops)
        # adjacency over the union of static + observed edges
        self._adj: dict = {}            # guarded-by: _mu
        for a, b in dag.get("edges", ()):
            self._adj.setdefault(a, set()).add(b)
        self._tls = threading.local()

    # -- per-thread held list ------------------------------------------------

    def _held(self) -> list:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def note_acquire(self, proxy) -> None:
        held = self._held()
        name = proxy._lo_name
        for h in held:
            if h.proxy is proxy:
                if proxy._lo_kind in _REENTRANT:
                    h.count += 1
                    return
                v = Violation(
                    "self-deadlock", (name, name),
                    threading.current_thread().name,
                    "non-reentrant lock re-acquired by its holder — "
                    "this blocks forever; raising instead of hanging")
                with self._mu:
                    self.violations.append(v)
                raise LockOrderError(str(v))
        with self._mu:
            self.acquires += 1
            for h in held:
                if h.name != name:      # same-name = hierarchy, skip
                    self._check_edge_locked(h.name, name)
        held.append(_Held(proxy, name))

    def note_release(self, proxy) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i].proxy is proxy:
                held[i].count -= 1
                if held[i].count == 0:
                    del held[i]
                return
        # releasing a lock this thread never tracked (e.g. acquired
        # before enable, or cross-thread release): nothing to unwind

    # -- the DAG check -------------------------------------------------------

    def _check_edge_locked(self, src: str, dst: str) -> None:
        if (src, dst) in self.observed:
            return
        if self._reaches(dst, src):
            self.violations.append(Violation(
                "cycle", (src, dst), threading.current_thread().name,
                f"acquiring {dst} while holding {src} closes a cycle: "
                f"the DAG (static edges + observed orders) already "
                f"requires {dst} before {src}"))
            return                      # don't poison the graph
        self.observed.add((src, dst))
        self._adj.setdefault(src, set()).add(dst)

    def _reaches(self, src: str, dst: str) -> bool:
        seen = {src}
        frontier = [src]
        while frontier:
            node = frontier.pop()
            if node == dst:
                return True
            for nxt in self._adj.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    frontier.append(nxt)
        return False

    # -- wrapping ------------------------------------------------------------

    def wrap(self, inner, name: str, kind: str = "Lock"):
        """Explicitly wrap a lock under a registry name (tests; code
        paths that want tracking without factory patching)."""
        cls = _TrackedCondition if kind == "Condition" else _TrackedLock
        return cls(inner, name, kind, self)

    def site(self, filename: str, lineno: int):
        """Registry entry for a construction site, or None."""
        rel = os.path.relpath(filename, _REPO)
        return self.sites.get((rel, lineno))


# -- factory patching --------------------------------------------------------

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_active: LockOrderSanitizer | None = None
_originals: dict = {}


def active() -> LockOrderSanitizer | None:
    return _active


def _factory(orig, kind):
    def make(*args, **kwargs):
        inner = orig(*args, **kwargs)
        san = _active
        if san is None:
            return inner
        frame = sys._getframe(1)
        hit = san.site(frame.f_code.co_filename, frame.f_lineno)
        if hit is None:
            return inner
        name, _site_kind = hit
        return san.wrap(inner, name, kind)
    make._lockorder_patch = True
    return make


def static_dag() -> dict:
    """The statically-derived order DAG (one forest parse + flow
    analysis, cached for the process)."""
    global _dag_cache
    if _dag_cache is None:
        from tidb_tpu.lint.engine import Forest
        from tidb_tpu.lint.flow import flow_of
        _dag_cache = flow_of(Forest.load()).dag_export()
    return _dag_cache


_dag_cache: dict | None = None


def enable(dag: dict | None = None) -> LockOrderSanitizer:
    """Patch the threading factories; idempotent while enabled."""
    global _active
    if _active is not None:
        return _active
    san = LockOrderSanitizer(static_dag() if dag is None else dag)
    for attr, kind in (("Lock", "Lock"), ("RLock", "RLock"),
                       ("Condition", "Condition")):
        _originals[attr] = getattr(threading, attr)
        setattr(threading, attr, _factory(_originals[attr], kind))
    _active = san
    return san


def disable() -> None:
    global _active
    if _active is None:
        return
    for attr, orig in _originals.items():
        setattr(threading, attr, orig)
    _originals.clear()
    _active = None


@contextlib.contextmanager
def sanitize(dag: dict | None = None):
    """Enable for a scope; raise LockOrderError on exit if any ordering
    observed WITHIN the scope contradicted the DAG.

    If a sanitizer is already active (the env gate, or an outer
    sanitize()), the scope joins it instead of replacing it: `dag` is
    ignored, the factories stay patched on exit, and only violations
    that appeared during this scope are raised — pre-existing ones
    belong to whoever enabled it."""
    created = _active is None
    san = enable(dag)
    base = len(san.violations)
    try:
        yield san
    finally:
        if created:
            disable()
    fresh = san.violations[base:]
    if fresh:
        raise LockOrderError(
            "lock-order sanitizer: %d violation(s):\n%s" % (
                len(fresh), "\n".join(str(v) for v in fresh)))


def enable_from_env() -> LockOrderSanitizer | None:
    """`TIDB_TPU_LOCK_SANITIZER=1` turns the sanitizer on at package
    import (tidb_tpu/__init__.py calls this). Anything else: no-op."""
    if os.environ.get("TIDB_TPU_LOCK_SANITIZER", "0") != "1":
        return None
    return enable()
