"""Persistent XLA compilation cache: enablement + hit/miss accounting.

First-compile of the big fused query programs costs tens of seconds (and
through a chip tunnel, minutes — BENCH_r05 measured a 48.8s first-run
stall on Q1). The persistent cache turns every later process's compiles
into disk loads. One place owns the wiring so the package import, the
server entrypoint and bench.py all agree on the directory and so the
hit/miss counters (via jax.monitoring events) land in BENCH json.

Directory resolution: the TIDB_TPU_COMPILE_CACHE environment variable,
else ~/.cache/tidb_tpu_xla. "0" or empty disables.
"""

from __future__ import annotations

import os
import threading

__all__ = ["enable", "default_dir", "stats", "counters",
           "reset_counters", "cpu_feature_tag", "scoped_cpu_dir",
           "plane_tag", "scoped_plane_dir"]

_lock = threading.Lock()
_counts = {"hits": 0, "misses": 0}
_listener_installed = False
_enabled_dir: str | None = None
_plane_listener_installed = False


def default_dir() -> str:
    return os.environ.get(
        "TIDB_TPU_COMPILE_CACHE",
        # lint: exempt[sysvar-registry] cache directory name, not a sysvar
        os.path.join(os.path.expanduser("~"), ".cache", "tidb_tpu_xla"))


def cpu_feature_tag() -> str:
    """Stable fingerprint of the host CPU execution environment: machine
    arch + jax version + the kernel-reported CPU feature flags. Entries
    compiled under a DIFFERENT feature set (a chip tunnel's virtualized
    host, another machine) must not be loaded — jax warns but loads
    them, and AOT results built with e.g. prefer-no-scatter deoptimize
    scatter-heavy programs ~5x (measured on Q3, BENCH r03 note)."""
    import hashlib
    import platform as _platform
    bits = [_platform.machine()]
    try:
        import jax
        bits.append(jax.__version__)
    except Exception:  # noqa: BLE001 - tag still useful without jax
        pass
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.lower().startswith(("flags", "features")):
                    bits.append(" ".join(sorted(
                        line.split(":", 1)[1].split())))
                    break
    except OSError:
        pass
    return hashlib.sha256("|".join(bits).encode()).hexdigest()[:12]


def scoped_cpu_dir(base: str) -> str:
    """The per-host-feature-set CPU subdirectory of a cache `base`: CPU
    processes share warm entries with each other but never with entries
    compiled for a different platform/feature set. This is what lets the
    bench CPU fallback KEEP a persistent cache (killing the ~49s Q1
    first-compile stall of BENCH r05) instead of disabling it to avoid
    cross-feature-set poisoning."""
    return os.path.join(base, "cpu-" + cpu_feature_tag())


def plane_tag() -> str:
    """Device-plane subdirectory name from `devplane.mesh_fingerprint`
    (e.g. ``plane-batch-8-cpu``). Executables traced against an N-chip
    ``("batch",)`` mesh bake the partitioned program into the cache
    entry; loading one into a process with a different topology is the
    same poisoning failure the CPU feature scoping exists for."""
    from tidb_tpu import devplane
    fp = devplane.mesh_fingerprint(process=True)
    return "plane-" + "-".join(str(p) for p in fp)


def scoped_plane_dir(base: str) -> str:
    """The per-device-plane subdirectory of a cache `base` for the
    CURRENT process mesh. A no-mesh process uses `base` itself (the
    historical layout: single-chip entries stay warm across upgrades)."""
    from tidb_tpu import devplane
    if devplane.active_mesh() is None:
        return base
    return os.path.join(base, plane_tag())


def _repoint_for_plane() -> None:
    """Topology-change hook: re-point jax at the plane-scoped
    subdirectory of the enabled base so a later `enable_mesh(8)` cannot
    keep writing into (or loading from) the 1-chip entry pool."""
    if _enabled_dir is None:
        return
    try:
        import jax
        jax.config.update("jax_compilation_cache_dir",
                          scoped_plane_dir(_enabled_dir))
    except Exception:  # noqa: BLE001 - older jax without the knob
        pass


def _install_plane_listener() -> None:
    global _plane_listener_installed
    if _plane_listener_installed:
        return
    from tidb_tpu import devplane
    devplane.on_topology_change(_repoint_for_plane)
    _plane_listener_installed = True


def _install_listener() -> None:
    """Count persistent-cache hits/misses from jax's monitoring events
    ('/jax/compilation_cache/cache_hits' / 'cache_misses'). Must run
    before the first compile; idempotent."""
    global _listener_installed
    if _listener_installed:
        return
    try:
        from jax import monitoring
    except Exception:  # noqa: BLE001 - no monitoring: counters stay 0
        return

    def _on_event(event: str, **_kw) -> None:
        if not event.startswith("/jax/compilation_cache/"):
            return
        hit = event.endswith("cache_hits")
        miss = event.endswith("cache_misses")
        if not (hit or miss):
            return
        with _lock:
            if hit:
                _counts["hits"] += 1
            else:
                _counts["misses"] += 1
        # promote to first-class /metrics families (BENCH-json-only
        # before): lazy import — this module must load without the
        # package (bench.py imports it before configuring jax)
        try:
            from tidb_tpu import metrics
            if hit:
                metrics.counter(metrics.COMPILE_CACHE_HITS)
            else:
                metrics.counter(metrics.COMPILE_CACHE_MISSES)
        except Exception:  # noqa: BLE001 - counters must never raise
            pass

    try:
        monitoring.register_event_listener(_on_event)
        _listener_installed = True
    except Exception:  # noqa: BLE001 - older jax without listeners
        pass


def enable(path: str | None = None,
           min_compile_secs: float = 1.0) -> str | None:
    """Point jax at the persistent compile cache and start counting
    hits/misses. -> the active directory, or None when disabled."""
    global _enabled_dir
    path = default_dir() if path is None else path
    if not path or path == "0":
        return None
    import jax
    try:
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_secs))
    except Exception:  # older jax without the knobs
        return None
    _install_listener()
    _enabled_dir = path
    _install_plane_listener()
    # plane-scope the active directory from the start (a mesh may
    # already be installed when enable() is called explicitly)
    _repoint_for_plane()
    return path


def stats() -> dict:
    """Snapshot for BENCH json / the status API: the configured
    directory (None once disabled, e.g. the bench CPU fallback), how
    many compiled executables it currently holds, and this process's
    hit/miss counts."""
    try:
        import jax
        cur = jax.config.jax_compilation_cache_dir
    except Exception:  # noqa: BLE001
        cur = _enabled_dir
    entries = None
    if cur:
        try:
            entries = sum(1 for f in os.listdir(cur)
                          if not f.startswith("."))
        except OSError:
            entries = None
    with _lock:
        return {"dir": cur, "entries": entries,
                "hits": _counts["hits"], "misses": _counts["misses"]}


def counters() -> dict:
    """Just the hit/miss counts — no directory listing. The profiler
    diffs these around a kernel's compile dispatch to attribute it
    hit|miss|cached; stats() costs a listdir and stays off hot paths."""
    with _lock:
        return {"hits": _counts["hits"], "misses": _counts["misses"]}


def reset_counters() -> None:
    with _lock:
        _counts["hits"] = 0
        _counts["misses"] = 0
