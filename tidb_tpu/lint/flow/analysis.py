"""The shared flow facts: lock-acquisition order, held-lock sets, and
`# guarded-by:` annotations, computed once per forest.

Three passes over the already-parsed forest (no re-parse — the
engine's single-parse contract):

1. **Per-function walk.** Every function body is walked with the
   ordered list of locks lexically held. `with lock:` blocks and
   statement-form `lock.acquire()` push resolved locks; each
   acquisition under a non-empty held set records an ORDER EDGE
   (held -> acquired). Call sites and attribute/global writes are
   recorded with the held set at the site.

2. **Interprocedural propagation.** `trans_acq(F)` — the locks F may
   acquire, transitively through the call graph — is a fixpoint; a
   call made while holding H adds edges H -> trans_acq(callee).
   Dually, `caller_held(F)` — locks held at EVERY known call site of
   F — is a meet-over-callers fixpoint, so a helper only ever invoked
   under its owner's lock (`DeviceCache._drop_locked`,
   `RegionCache._insert`) checks as guarded without a lexical `with`.

3. **Annotations.** `# guarded-by: <lock-attr>` on an attribute's
   initialization line (or directly above it) declares the lock that
   must be held to WRITE the attribute anywhere in that module.
   `__init__` bodies and module top level are construction-time and
   exempt by definition.

The lock-order DAG (edges over registry names) is exported to the
runtime sanitizer (util/lockorder.py), which asserts observed
acquisition orders stay consistent with it — the dynamic harness
validates the static model and vice versa.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field

from tidb_tpu.lint.flow.callgraph import CallGraph, FuncInfo
from tidb_tpu.lint.flow.lockreg import LockRegistry, discover

__all__ = ["FlowAnalysis", "GuardAnnotation", "MUTATORS"]

_GUARD_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")

# container mutations that count as writes to the annotated attribute
MUTATORS = frozenset({
    "append", "appendleft", "add", "pop", "popleft", "popitem", "clear",
    "update", "remove", "discard", "extend", "insert", "setdefault",
    "move_to_end", "sort", "reverse",
})

# reentrant kinds: a self-edge (same lock name on both sides) is the
# point of an RLock, not a deadlock; Condition's default lock is an
# RLock, and Semaphore permits are counted, not owned
_REENTRANT = frozenset({"RLock", "Condition", "Semaphore"})


@dataclass
class GuardAnnotation:
    rel: str
    lineno: int
    cls: str | None            # owning class (None: module global)
    attr: str                  # the guarded attribute / global
    lock_text: str             # the annotation's lock spelling
    lock: str | None           # resolved registry name (None = bad)


@dataclass
class _WriteSite:
    func: FuncInfo
    base: str                  # "attr" | "name"
    name: str
    lineno: int
    held: frozenset


@dataclass
class _CallSite:
    func: FuncInfo
    call: ast.Call
    callee: FuncInfo | None
    held: tuple
    lineno: int


@dataclass
class _FuncFacts:
    acquisitions: list = field(default_factory=list)   # (lock, lineno)
    calls: list = field(default_factory=list)          # _CallSite
    writes: list = field(default_factory=list)         # _WriteSite


class FlowAnalysis:
    def __init__(self, forest):
        self.forest = forest
        self.registry: LockRegistry = discover(forest)
        self.graph = CallGraph(forest)
        self.facts: dict[tuple, _FuncFacts] = {}
        # (src, dst) -> (rel, lineno, note): first site proving the edge
        self.edges: dict[tuple, tuple] = {}
        self.annotations: list[GuardAnnotation] = []
        self._cls_spans: dict[str, list] = {}
        for pf in forest:
            self._cls_spans[pf.rel] = self._class_spans(pf)
        for fi in self.graph.funcs.values():
            self.facts[fi.key] = self._walk_function(fi)
        self.trans_acq = self._trans_acq()
        self._interproc_edges()
        self.caller_held = self._caller_held()
        for pf in forest:
            self._collect_annotations(pf)

    # -- class spans (lineno -> innermost class) -----------------------------

    @staticmethod
    def _class_spans(pf) -> list:
        spans = []
        for node in pf.nodes:
            if isinstance(node, ast.ClassDef):
                spans.append((node.lineno, node.end_lineno or node.lineno,
                              node.name))
        return spans

    def class_at(self, rel: str, lineno: int) -> str | None:
        best = None
        for a, b, name in self._cls_spans.get(rel, ()):
            if a <= lineno <= b and (best is None or a >= best[0]):
                best = (a, name)
        return best[1] if best else None

    # -- pass 1: per-function walk -------------------------------------------

    def _walk_function(self, fi: FuncInfo) -> _FuncFacts:
        facts = _FuncFacts()
        self._walk_block(fi, fi.node.body, [], facts)
        return facts

    def _resolve(self, fi: FuncInfo, expr):
        site = self.registry.resolve(fi.rel, fi.cls, expr)
        return site.name if site is not None else None

    def _note_acquire(self, fi, facts, lock: str, held: list,
                      lineno: int) -> None:
        facts.acquisitions.append((lock, lineno))
        for h in held:
            self._add_edge(h, lock, fi.rel, lineno,
                           f"nested acquisition in {fi.qualname}")

    def _add_edge(self, src: str, dst: str, rel: str, lineno: int,
                  note: str) -> None:
        if src == dst:
            if self.registry.kinds.get(src) in _REENTRANT:
                return
        self.edges.setdefault((src, dst), (rel, lineno, note))

    def _scan_exprs(self, fi, facts, exprs, held) -> None:
        """Collect calls (and lambda bodies, which run inline at call
        sites near here) from the expression parts of one statement."""
        for e in exprs:
            if e is None:
                continue
            for n in ast.walk(e):
                if isinstance(n, ast.Call):
                    callee = self.graph.resolve_call(n, fi.rel, fi)
                    facts.calls.append(_CallSite(
                        fi, n, callee, tuple(held), n.lineno))

    def _note_writes(self, fi, facts, targets, held, lineno) -> None:
        for t in targets:
            base = t
            while isinstance(base, (ast.Subscript, ast.Starred)):
                base = base.value
            if isinstance(base, (ast.Tuple, ast.List)):
                self._note_writes(fi, facts, base.elts, held, lineno)
                continue
            if isinstance(base, ast.Attribute):
                facts.writes.append(_WriteSite(
                    fi, "attr", base.attr, lineno, frozenset(held)))
            elif isinstance(base, ast.Name):
                facts.writes.append(_WriteSite(
                    fi, "name", base.id, lineno, frozenset(held)))

    def _walk_block(self, fi, stmts, held: list, facts) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue            # separate function in the graph
            if isinstance(stmt, ast.ClassDef):
                continue            # methods indexed separately
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                acquired = []
                for item in stmt.items:
                    self._scan_exprs(fi, facts, [item.context_expr], held)
                    lock = self._resolve(fi, item.context_expr)
                    if lock is not None:
                        self._note_acquire(fi, facts, lock, held,
                                           item.context_expr.lineno)
                        held.append(lock)
                        acquired.append(lock)
                self._walk_block(fi, stmt.body, held, facts)
                for _ in acquired:
                    held.pop()
                continue
            if isinstance(stmt, ast.Try):
                self._walk_block(fi, stmt.body, held, facts)
                for h in stmt.handlers:
                    self._walk_block(fi, h.body, held, facts)
                self._walk_block(fi, stmt.orelse, held, facts)
                self._walk_block(fi, stmt.finalbody, held, facts)
                continue
            if isinstance(stmt, (ast.If, ast.While)):
                self._scan_exprs(fi, facts, [stmt.test], held)
                self._walk_block(fi, stmt.body, held, facts)
                self._walk_block(fi, stmt.orelse, held, facts)
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._scan_exprs(fi, facts, [stmt.iter], held)
                self._note_writes(fi, facts, [stmt.target], held,
                                  stmt.lineno)
                self._walk_block(fi, stmt.body, held, facts)
                self._walk_block(fi, stmt.orelse, held, facts)
                continue
            if isinstance(stmt, ast.Match):
                self._scan_exprs(fi, facts, [stmt.subject], held)
                for case in stmt.cases:
                    self._walk_block(fi, case.body, held, facts)
                continue
            # simple statements: writes, then acquire/release bookkeeping
            if isinstance(stmt, ast.Assign):
                self._note_writes(fi, facts, stmt.targets, held,
                                  stmt.lineno)
            elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                if getattr(stmt, "value", True) is not None:
                    self._note_writes(fi, facts, [stmt.target], held,
                                      stmt.lineno)
            elif isinstance(stmt, ast.Delete):
                self._note_writes(fi, facts, stmt.targets, held,
                                  stmt.lineno)
            self._scan_exprs(fi, facts, [stmt], held)
            call = getattr(stmt, "value", None)
            if isinstance(stmt, ast.Expr):
                call = stmt.value
            if isinstance(call, ast.Call) and \
                    isinstance(call.func, ast.Attribute):
                if call.func.attr == "acquire":
                    lock = self._resolve(fi, call.func.value)
                    if lock is not None:
                        self._note_acquire(fi, facts, lock, held,
                                           call.lineno)
                        held.append(lock)
                elif call.func.attr == "release":
                    lock = self._resolve(fi, call.func.value)
                    if lock is not None and lock in held:
                        held.reverse()
                        held.remove(lock)
                        held.reverse()

    # -- pass 2: interprocedural fixpoints -----------------------------------

    def _trans_acq(self) -> dict:
        ta = {key: {a for a, _ in f.acquisitions}
              for key, f in self.facts.items()}
        changed = True
        while changed:
            changed = False
            for key, f in self.facts.items():
                cur = ta[key]
                for cs in f.calls:
                    if cs.callee is None:
                        continue
                    extra = ta.get(cs.callee.key, set()) - cur
                    if extra:
                        cur |= extra
                        changed = True
        return ta

    def _interproc_edges(self) -> None:
        for f in self.facts.values():
            for cs in f.calls:
                if cs.callee is None or not cs.held:
                    continue
                for lock in self.trans_acq.get(cs.callee.key, ()):
                    for h in cs.held:
                        self._add_edge(
                            h, lock, cs.func.rel, cs.lineno,
                            f"{cs.func.qualname} calls "
                            f"{cs.callee.qualname} while holding")

    def _caller_held(self) -> dict:
        callers: dict[tuple, list] = {}
        for f in self.facts.values():
            for cs in f.calls:
                if cs.callee is not None:
                    callers.setdefault(cs.callee.key, []).append(cs)
        # None = top (no information yet); meet is set intersection
        ch: dict[tuple, frozenset | None] = {}
        for key in self.facts:
            ch[key] = None if callers.get(key) else frozenset()
        changed = True
        while changed:
            changed = False
            for key, sites in callers.items():
                acc: frozenset | None = None
                for cs in sites:
                    caller_ch = ch.get(cs.func.key)
                    if caller_ch is None and not cs.held:
                        continue    # caller unresolved yet: skip this site
                    site_held = frozenset(cs.held) | (caller_ch or
                                                      frozenset())
                    acc = site_held if acc is None else (acc & site_held)
                if acc is not None and acc != ch[key]:
                    ch[key] = acc
                    changed = True
        return {k: (v or frozenset()) for k, v in ch.items()}

    def held_at(self, write: _WriteSite) -> frozenset:
        return write.held | self.caller_held.get(write.func.key,
                                                 frozenset())

    # -- pass 3: guarded-by annotations --------------------------------------

    def _collect_annotations(self, pf) -> None:
        if "guarded-by" not in pf.source:
            return
        comments: dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(pf.source).readline):
                if tok.type == tokenize.COMMENT:
                    comments[tok.start[0]] = tok.string
        except (tokenize.TokenError, IndentationError, SyntaxError):
            for i, text in enumerate(pf.lines, start=1):
                if "#" in text:
                    comments[i] = text[text.index("#"):]
        assigns: dict[int, ast.stmt] = {}
        for node in pf.nodes:
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                # index the WHOLE span: a trailing tag on the
                # continuation line of a wrapped assignment must bind
                # to this assignment, not fall through to the next one
                for ln in range(node.lineno,
                                (node.end_lineno or node.lineno) + 1):
                    assigns.setdefault(ln, node)
        for lineno, text in sorted(comments.items()):
            m = _GUARD_RE.search(text)
            if m is None:
                continue
            stmt = assigns.get(lineno)
            if stmt is None:        # standalone comment: covers the
                ln = lineno + 1     # next code line
                while ln <= len(pf.lines) and \
                        pf.lines[ln - 1].lstrip().startswith("#"):
                    ln += 1
                stmt = assigns.get(ln)
            attr = cls = None
            if stmt is not None:
                targets = stmt.targets if isinstance(stmt, ast.Assign) \
                    else [stmt.target]
                if len(targets) == 1:
                    t = targets[0]
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self":
                        attr = t.attr
                        cls = self.class_at(pf.rel, stmt.lineno)
                    elif isinstance(t, ast.Name):
                        attr = t.id
                        cls = self.class_at(pf.rel, stmt.lineno)
            lock_text = m.group(1)
            lock = None
            if attr is not None:
                if cls is not None:
                    site = self.registry.class_attr(pf.rel, cls,
                                                    lock_text)
                else:
                    site = None
                site = site or self.registry.module_level(pf.rel,
                                                          lock_text) \
                    or self.registry.unique_in_module(pf.rel, lock_text)
                lock = site.name if site is not None else None
            self.annotations.append(GuardAnnotation(
                pf.rel, lineno, cls, attr if attr is not None else "",
                lock_text, lock))

    # -- lock-order results --------------------------------------------------

    def cycles(self) -> list:
        """Strongly connected components of the order graph with more
        than one lock, plus non-reentrant self-edges. Each entry:
        (ordered lock names, [(src, dst, rel, lineno, note), ...])."""
        adj: dict[str, set] = {}
        for (a, b) in self.edges:
            adj.setdefault(a, set()).add(b)
            adj.setdefault(b, set())
        index: dict[str, int] = {}
        low: dict[str, int] = {}
        onstack: set = set()
        stack: list = []
        sccs: list = []
        counter = [0]

        def strongconnect(v):
            # iterative Tarjan (the package graph is shallow, but the
            # engine must not rely on recursion depth)
            work = [(v, iter(sorted(adj[v])))]
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            onstack.add(v)
            while work:
                node, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        onstack.add(w)
                        work.append((w, iter(sorted(adj[w]))))
                        advanced = True
                        break
                    if w in onstack:
                        low[node] = min(low[node], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index[node]:
                    comp = []
                    while True:
                        w = stack.pop()
                        onstack.discard(w)
                        comp.append(w)
                        if w == node:
                            break
                    sccs.append(comp)

        for v in sorted(adj):
            if v not in index:
                strongconnect(v)

        out = []
        for comp in sccs:
            comp_set = set(comp)
            if len(comp) > 1:
                proof = [(a, b, *self.edges[(a, b)])
                         for (a, b) in sorted(self.edges)
                         if a in comp_set and b in comp_set]
                out.append((sorted(comp), proof))
        for (a, b), (rel, lineno, note) in sorted(self.edges.items()):
            if a == b:          # non-reentrant self-edge (_add_edge
                out.append(([a], [(a, b, rel, lineno, note)]))
        return out

    def dag_export(self) -> dict:
        """The statically-derived order DAG for the runtime sanitizer:
        edges over registry names, lock kinds, and construction sites
        so live locks can be mapped back to their static identity."""
        return {
            "edges": set(self.edges),
            "kinds": dict(self.registry.kinds),
            "sites": {(s.rel, s.lineno): (s.name, s.kind)
                      for s in self.registry.sites},
        }
