"""Device-plane dataflow analysis over the lint forest.

Tier-1 runs on `JAX_PLATFORMS=cpu`, where the two nastiest device-plane
bug classes are structurally invisible: use-after-donate (silent
corruption on TPU, a harmless no-op on CPU) and retrace/recompile
hazards (visible only as the compile stalls the kernel profiler
measures after the fact, on silicon). This pass proves their absence
statically, BEFORE dispatch:

* **discovery** — every traced-program construction site in the
  package: `jax.jit(f, ...)`, `functools.partial(jax.jit, ...)` used
  as a decorator, and `devplane.plane_jit(...)` (unwrapping the
  `shard_map(fn, ...)` plumbing to the real traced callable), plus the
  kernel classes that own them and where each program is stored
  (self attribute, module global, bounded bucket dict, factory return);
* **donation analysis** — for every dispatch through a
  `donate_argnums` program: the donated operand must be a locally
  owned name with no live use after the dispatch on any path (reads
  through aliases, closure captures, and enclosing retry loops that
  would re-dispatch the freed buffer all count), and a donated
  `device_put_chunk` transfer must explicitly opt out of the chunk
  memo (a memoized donated buffer is a read-after-free);
* **cache-key analysis** — every `self` attribute / config read /
  module global reachable from a traced kernel body must be an
  operand or provably folded into the owning cache key
  (`FingerprintCache.get_or_create`, the executor/mesh dict cache,
  and the profiler-registration fingerprint), with
  `devplane.mesh_fingerprint` present in every key (PR 18's
  plane-identity contract);
* **retrace analysis** — dispatch operands must flow through the pow2
  superchunk bucketing (or a bounded bucket-map program memo, the
  `meshjoin._stage2_jits[bucket]` shape), static arguments must be
  hashable, and `float()`/`bool()`/`int()`/`.item()`/`np.asarray`
  coercions inside traced bodies are findings;
* **compile prediction** — a static per-kernel-family compile-count
  model (every construction site sits behind a cache/memo, so warm
  runs compile nothing) that `bench.py lintcheck` cross-checks against
  `information_schema.kernel_profile`'s observed counters — static
  analysis the profiler plane can falsify, and vice versa.

Zero extra parses: the pass walks the shared forest and reuses the
PR 7 call graph (`flow_of(forest).graph`); `device_flow_of(forest)` is
memoized on the forest like `flow_of` itself.  The three rules
consuming this live in tidb_tpu/lint/rules/device.py.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from tidb_tpu.lint.flow import flow_of

__all__ = ["DeviceFlow", "device_flow_of", "TracedSite", "DispatchSite"]

# helpers whose presence sanctions a dispatch's operand shaping: they
# are the pow2 superchunk bucketing seams (ops/runtime.py) and the
# per-kernel shard/pad entry points built on them
SHAPERS = frozenset({
    "bucket_size", "pad_column", "device_put_chunk", "prepare_build",
    "_shard_probe", "_put_side", "superchunk_batches", "_bucket",
})

# callables whose results are trace-time Python values: calling them on
# traced values inside a kernel body forces a device sync / retrace
COERCIONS = frozenset({"float", "int", "bool"})
HOST_ARRAY_FNS = frozenset({("np", "asarray"), ("np", "array"),
                            ("numpy", "asarray"), ("numpy", "array"),
                            ("jax", "device_get")})

_MESH_ROOT = "<mesh>"          # pseudo-root: value derives from the
#                                device plane (covered by the mesh
#                                fingerprint in the cache key)


def _root_names(expr) -> set:
    """Bare Name roots of an expression (the base of attribute /
    subscript chains; call args recursed)."""
    out: set = set()
    for node in ast.walk(expr):
        if isinstance(node, ast.Name):
            out.add(node.id)
    return out


def _is_const(name: str) -> bool:
    return name.isupper() or name.lstrip("_").isupper()


def _call_name(call: ast.Call) -> str | None:
    """Trailing name of the callee: `runtime.bucket_size` ->
    'bucket_size', `self._bucket` -> '_bucket'."""
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


def _is_jax_jit(expr) -> bool:
    return (isinstance(expr, ast.Attribute) and expr.attr == "jit"
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "jax")


def _is_plane_jit(expr) -> bool:
    if isinstance(expr, ast.Name):
        return expr.id == "plane_jit"
    return isinstance(expr, ast.Attribute) and expr.attr == "plane_jit"


def _is_mesh_fp(call: ast.Call) -> bool:
    return _call_name(call) in ("mesh_fingerprint", "mesh_generation")


def _int_tuple(expr) -> tuple:
    """Literal donate_argnums/static_argnums value -> tuple of ints."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, int):
        return (expr.value,)
    if isinstance(expr, (ast.Tuple, ast.List)):
        return tuple(e.value for e in expr.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, int))
    return ()


def _str_tuple(expr) -> tuple:
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return (expr.value,)
    if isinstance(expr, (ast.Tuple, ast.List)):
        return tuple(e.value for e in expr.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, str))
    return ()


@dataclass
class TracedSite:
    """One traced-program construction site."""
    rel: str
    line: int
    form: str                     # "jit" | "partial_jit" | "plane_jit"
    call: ast.Call | None         # the construction call (None for
    #                               decorator form)
    fns: list = field(default_factory=list)   # resolved traced
    #                               callables (FuncInfo), possibly
    #                               several (self._kernel fan-out)
    fn_name: str = ""             # display name of the traced callable
    owner: object = None          # FuncInfo of the enclosing function
    cls: str | None = None        # class owning the stored program
    store: tuple = ("anon", None)  # ("attr"|"global"|"dict"|"local"
    #                                |"decorator"|"return", name)
    donate: tuple = ()            # donated positions
    static_names: tuple = ()
    static_nums: tuple = ()

    @property
    def donating(self) -> bool:
        return bool(self.donate)


@dataclass
class DispatchSite:
    """One call of a traced program."""
    rel: str
    line: int
    call: ast.Call
    site: TracedSite              # the program being dispatched
    func: object = None           # enclosing FuncInfo
    via_factory: ast.Call | None = None   # inner factory/getter call
    #                               whose args key a program memo


class DeviceFlow:
    """The device-plane facts for one forest (see module docstring)."""

    def __init__(self, forest):
        self.forest = forest
        self.graph = flow_of(forest).graph
        self.sites: list[TracedSite] = []
        # program stores, for dispatch resolution
        self._attr_sites: dict[tuple, TracedSite] = {}   # (rel, attr)
        self._name_sites: dict[tuple, TracedSite] = {}   # (rel, name)
        self._factory_sites: dict[tuple, TracedSite] = {}  # FuncInfo.key
        self._node_func: dict[int, object] = {}          # id(def node)
        for fi in self.graph.funcs.values():
            self._node_func[id(fi.node)] = fi
        self._parents: dict[str, dict[int, ast.AST]] = {}
        self._discover()
        self.dispatches: list[DispatchSite] = self._find_dispatches()
        self._reachable_memo: dict[tuple, set] = {}

    # -- plumbing ------------------------------------------------------------

    def _parent_map(self, rel: str) -> dict[int, ast.AST]:
        pm = self._parents.get(rel)
        if pm is None:
            pf = self.forest.get(rel)
            pm = {}
            for node in pf.nodes:
                for child in ast.iter_child_nodes(node):
                    pm[id(child)] = node
            self._parents[rel] = pm
        return pm

    def enclosing_function(self, rel: str, node) -> object:
        """Innermost FuncInfo containing `node` (by parent walk)."""
        pm = self._parent_map(rel)
        cur = node
        while cur is not None:
            fi = self._node_func.get(id(cur))
            if fi is not None:
                return fi
            cur = pm.get(id(cur))
        return None

    def enclosing_class(self, rel: str, node) -> str | None:
        pm = self._parent_map(rel)
        cur = node
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur.name
            cur = pm.get(id(cur))
        return None

    def _resolve_callable(self, expr, rel: str, enclosing) -> list:
        """Resolve the traced-callable expression of a jit construction
        to FuncInfo(s). `self.X` that misses in the enclosing class
        fans out to every same-module method named X (base-class
        plumbing like MeshKernelBase._setup_mesh wraps the subclass's
        `_kernel`)."""

        class _Fake:
            func = expr
        hit = self.graph.resolve_call(_Fake, rel, enclosing)
        if hit is not None:
            return [hit]
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self":
            return [fi for (r, c, n), fi in self.graph._method.items()
                    if r == rel and n == expr.attr]
        return []

    def _unwrap_traced(self, expr, rel: str, owner) -> tuple:
        """-> (fns, display_name) for the first argument of a jit
        construction, unwrapping `shard_map(fn, ...)` wrappers, local
        names bound to them, and closure factories that `return` a
        nested def (the `_stage2_fn(bucket)` shape)."""
        if isinstance(expr, ast.Call):
            name = _call_name(expr)
            if name == "shard_map" and expr.args:
                return self._unwrap_traced(expr.args[0], rel, owner)
            hits = self._resolve_callable(expr.func, rel, owner)
            # a factory that returns one of its nested defs: trace the
            # nested def
            out = []
            for fi in hits:
                ret = [n for n in ast.walk(fi.node)
                       if isinstance(n, ast.Return)]
                for r in ret:
                    if isinstance(r.value, ast.Name) and \
                            r.value.id in fi.nested:
                        out.append(fi.nested[r.value.id])
            if out:
                return out, out[0].node.name
            return [], ast.unparse(expr)[:40]
        if isinstance(expr, ast.Name) and owner is not None:
            # local bound to a shard_map(...) / traced fn
            for node in ast.walk(owner.node):
                if isinstance(node, ast.Assign) and \
                        any(isinstance(t, ast.Name) and t.id == expr.id
                            for t in node.targets):
                    if isinstance(node.value, ast.Call):
                        return self._unwrap_traced(node.value, rel,
                                                   owner)
        fns = self._resolve_callable(expr, rel, owner)
        name = expr.attr if isinstance(expr, ast.Attribute) else \
            (expr.id if isinstance(expr, ast.Name) else
             ast.unparse(expr)[:40])
        return fns, name

    # -- discovery -----------------------------------------------------------

    def _discover(self) -> None:
        for pf in self.forest:
            for node in pf.nodes:
                if isinstance(node, ast.Call):
                    if _is_jax_jit(node.func):
                        self._add_site(pf, node, "jit")
                    elif _is_plane_jit(node.func):
                        self._add_site(pf, node, "plane_jit")
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    for dec in node.decorator_list:
                        if isinstance(dec, ast.Call) and \
                                _call_name(dec) == "partial" and \
                                dec.args and _is_jax_jit(dec.args[0]):
                            self._add_decorator_site(pf, node, dec)

    def _add_decorator_site(self, pf, fn_node, dec: ast.Call) -> None:
        fi = self._node_func.get(id(fn_node))
        site = TracedSite(pf.rel, dec.lineno, "partial_jit", dec,
                          fns=[fi] if fi else [],
                          fn_name=fn_node.name, owner=None,
                          cls=self.enclosing_class(pf.rel, fn_node),
                          store=("decorator", fn_node.name))
        for kw in dec.keywords:
            if kw.arg == "donate_argnums":
                site.donate = _int_tuple(kw.value)
            elif kw.arg == "static_argnums":
                site.static_nums = _int_tuple(kw.value)
            elif kw.arg == "static_argnames":
                site.static_names = _str_tuple(kw.value)
        self.sites.append(site)
        self._name_sites[(pf.rel, fn_node.name)] = site

    def _add_site(self, pf, call: ast.Call, form: str) -> None:
        owner = self.enclosing_function(pf.rel, call)
        cls = self.enclosing_class(pf.rel, call)
        site = TracedSite(pf.rel, call.lineno, form, call, owner=owner,
                          cls=cls)
        if call.args:
            site.fns, site.fn_name = self._unwrap_traced(
                call.args[0], pf.rel, owner)
        for kw in call.keywords:
            if kw.arg == "donate_argnums":
                site.donate = _int_tuple(kw.value)
            elif kw.arg == "static_argnums":
                site.static_nums = _int_tuple(kw.value)
            elif kw.arg == "static_argnames":
                site.static_names = _str_tuple(kw.value)
        site.store = self._store_of(pf.rel, call, owner)
        self.sites.append(site)
        kind, name = site.store
        if kind == "attr":
            self._attr_sites[(pf.rel, name)] = site
        elif kind in ("global", "local"):
            self._name_sites[(pf.rel, name)] = site
        if owner is not None and kind in ("dict", "return", "local"):
            # the enclosing function acts as a program factory/getter
            self._factory_sites[owner.key] = site

    def _store_of(self, rel: str, call: ast.Call, owner) -> tuple:
        """Where the constructed program lands: walk up to the
        statement and classify its target."""
        pm = self._parent_map(rel)
        cur: ast.AST = call
        stmt = None
        while cur is not None:
            if isinstance(cur, (ast.Assign, ast.AnnAssign, ast.Return)):
                stmt = cur
                break
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef, ast.Module)):
                break
            cur = pm.get(id(cur))
        if isinstance(stmt, ast.Return):
            return ("return", None)
        targets = []
        if isinstance(stmt, ast.Assign):
            targets = stmt.targets
        elif isinstance(stmt, ast.AnnAssign) and stmt.target is not None:
            targets = [stmt.target]
        # prefer attr/dict stores over tuple-assign locals
        for t in targets:
            if isinstance(t, ast.Subscript):
                base = t.value
                name = base.attr if isinstance(base, ast.Attribute) \
                    else (base.id if isinstance(base, ast.Name)
                          else None)
                return ("dict", name)
        for t in targets:
            if isinstance(t, ast.Attribute) and \
                    isinstance(t.value, ast.Name) and \
                    t.value.id == "self":
                return ("attr", t.attr)
        for t in targets:
            if isinstance(t, ast.Name):
                kind = "global" if owner is None else "local"
                return (kind, t.id)
        return ("anon", None)

    # -- dispatch resolution -------------------------------------------------

    def _find_dispatches(self) -> list[DispatchSite]:
        out: list[DispatchSite] = []
        for pf in self.forest:
            for node in pf.nodes:
                if not isinstance(node, ast.Call):
                    continue
                d = self._classify_dispatch(pf.rel, node)
                if d is not None:
                    out.append(d)
        return out

    def _classify_dispatch(self, rel: str,
                           call: ast.Call) -> DispatchSite | None:
        fn = call.func
        fi = None
        # self._jit(...) / self._jitd(...) — attr stores, matched by
        # attribute name within the module (base-class dispatch methods
        # run with subclass instances)
        if isinstance(fn, ast.Attribute) and \
                isinstance(fn.value, ast.Name) and fn.value.id == "self":
            site = self._attr_sites.get((rel, fn.attr))
            if site is not None:
                fi = self.enclosing_function(rel, call)
                return DispatchSite(rel, call.lineno, call, site, fi)
            return None
        # _jit_sort(...) — module/local name stores
        if isinstance(fn, ast.Name):
            site = self._name_sites.get((rel, fn.id))
            if site is not None and site.call is not call:
                fi = self.enclosing_function(rel, call)
                # the local name may be bound to a factory result:
                # find its binding call for bucket-key checking
                via = None
                if fi is not None:
                    via = self._binding_factory_call(fi, fn.id)
                return DispatchSite(rel, call.lineno, call, site, fi,
                                    via_factory=via)
            # local name assigned from a factory call
            fi = self.enclosing_function(rel, call)
            if fi is not None:
                bound = self._binding_factory_call(fi, fn.id)
                if bound is not None:
                    hits = self._resolve_callable(bound.func, rel, fi)
                    for h in hits:
                        site = self._factory_sites.get(h.key)
                        if site is not None:
                            return DispatchSite(rel, call.lineno, call,
                                                site, fi,
                                                via_factory=bound)
            return None
        # _matcher_program(cap)(args) / self._get_stage2(bkt)(args)
        if isinstance(fn, ast.Call):
            fi = self.enclosing_function(rel, call)
            hits = self._resolve_callable(fn.func, rel, fi)
            for h in hits:
                site = self._factory_sites.get(h.key)
                if site is not None:
                    return DispatchSite(rel, call.lineno, call, site,
                                        fi, via_factory=fn)
        return None

    def _binding_factory_call(self, fi, name: str) -> ast.Call | None:
        """The call expression a local `name` is bound from in `fi`
        (prog = self._program(*key) / _PROGRAMS.get(cap) / ...)."""
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call) and \
                    any(isinstance(t, ast.Name) and t.id == name
                        for t in node.targets):
                return node.value
        return None

    # -- reachability --------------------------------------------------------

    def reachable(self, fi) -> list:
        """FuncInfos reachable from `fi` through the call graph
        (bounded BFS; the traced closure is small)."""
        memo = self._reachable_memo.get(fi.key)
        if memo is not None:
            return memo
        seen = {fi.key}
        out = [fi]
        queue = [fi]
        while queue and len(out) < 120:
            cur = queue.pop()
            for node in ast.walk(cur.node):
                if not isinstance(node, ast.Call):
                    continue
                hit = self.graph.resolve_call(node, cur.rel, cur)
                if hit is not None and hit.key not in seen:
                    seen.add(hit.key)
                    out.append(hit)
                    queue.append(hit)
        self._reachable_memo[fi.key] = out
        return out

    def traced_bodies(self, site: TracedSite) -> list:
        seen: set = set()
        out: list = []
        for fn in site.fns:
            for body in self.reachable(fn):
                if body.key not in seen:
                    seen.add(body.key)
                    out.append(body)
        return out

    # -- compile prediction --------------------------------------------------

    def compile_predictions(self) -> dict:
        """Static per-family compile model for `bench.py lintcheck`:
        every construction site sits behind a fingerprint cache or a
        bounded program memo, so (a) warm re-runs compile nothing and
        (b) fingerprint-cached families construct at most once per
        profile row. The profiler plane falsifies this if a seam
        regresses (and the lint rules falsify the profiler if a cache
        stops keying what the kernel reads)."""
        families: list[str] = []
        for pf in self.forest:
            if not pf.rel.endswith("profiler.py"):
                continue
            for node in pf.tree.body:
                if isinstance(node, ast.Assign) and any(
                        isinstance(t, ast.Name) and t.id == "FAMILIES"
                        for t in node.targets) and \
                        isinstance(node.value, ast.Tuple):
                    families = [e.value for e in node.value.elts
                                if isinstance(e, ast.Constant)]
        # modules mentioning the family string own its construction
        # sites ("hashagg"/"scalaragg" are picked via a variable, so
        # the literal — not the profile() call arg — is the anchor)
        fam_rels: dict[str, set] = {f: set() for f in families}
        for pf in self.forest:
            for node in pf.nodes:
                if isinstance(node, ast.Constant) and \
                        node.value in fam_rels:
                    fam_rels[node.value].add(pf.rel)
        preds: dict[str, dict] = {}
        for fam in families:
            if fam == "plane":
                # plane rows key on the wrapped fn name; bucketed
                # program memos construct one unit per pow2 bucket and
                # kernel instance, so only warm stability is predicted
                preds[fam] = {"sites": sum(
                    1 for s in self.sites if s.form == "plane_jit"),
                    "per_row_bound": None, "warm_growth": 0}
            else:
                n_sites = sum(1 for s in self.sites
                              if s.rel in fam_rels[fam])
                preds[fam] = {"sites": n_sites, "per_row_bound": 1,
                              "warm_growth": 0}
        return preds


def device_flow_of(forest) -> DeviceFlow:
    """The forest's device-plane analysis, computed once and memoized
    on the forest instance (all three device rules and the bench
    cross-check share the same facts)."""
    df = getattr(forest, "_device_flow", None)
    if df is None:
        df = DeviceFlow(forest)
        forest._device_flow = df
    return df
