"""tidb_tpu.lint.flow — whole-program concurrency analysis over the
lint forest.

The single-parse engine (tidb_tpu/lint/engine.py) gives every rule a
shared AST forest; this package builds the interprocedural layer the
three flow rules share, computed ONCE per forest and memoized on it:

* `callgraph`  — a cross-module call graph (imports resolved, methods
  keyed by class, nested defs keyed by their enclosing function);
* `lockreg`    — the auto-discovered lock registry: every
  `threading.Lock/RLock/Condition` construction site, named
  `<module>:<Class.>attr`;
* `analysis`   — the flow facts: lock-acquisition edges (intra- plus
  interprocedural through the call graph), per-write-site held-lock
  sets with caller-held propagation, `# guarded-by:` annotations, and
  the lock-order DAG the runtime sanitizer (util/lockorder.py)
  validates against.

Rules consuming this live in tidb_tpu/lint/rules/ (lock-order,
guarded-by, paired-resource); `flow_of(forest)` is the one entry
point — calling it from three rules costs one analysis, preserving the
engine's parse-once/walk-cheaply contract.
"""

from tidb_tpu.lint.flow.analysis import FlowAnalysis


def flow_of(forest) -> FlowAnalysis:
    """The forest's flow analysis, computed once and memoized on the
    forest instance (all three flow rules, and the runtime sanitizer's
    DAG export, share the same facts)."""
    fl = getattr(forest, "_flow_analysis", None)
    if fl is None:
        fl = FlowAnalysis(forest)
        forest._flow_analysis = fl
    return fl


__all__ = ["FlowAnalysis", "flow_of"]
