"""Cross-module call graph over the lint forest.

Resolution is name-based and deliberately conservative (Python has no
static types to lean on): a call that cannot be resolved with
confidence resolves to NOTHING rather than fanning out to every
same-named method — for the flow rules an under-approximate graph
means missed edges, never false deadlock reports.

Functions are keyed `(rel, qualname)`:

    tidb_tpu/store/copr.py : cop_handler
    tidb_tpu/store/copr.py : CopClient._run_task
    tidb_tpu/store/stream.py : region_stream.<locals>.emit

Resolution policy, in order:
  * bare `f()`       -> a nested def of the lexically enclosing
                        function chain, else this module's top-level
                        `f`, else an `from x import f` target, else a
                        class constructor (`C()` -> `C.__init__`);
  * `self.m()`       -> this class's method `m` (no MRO walk);
  * `mod.f()`        -> module-level `f` of the imported module `mod`;
  * `<expr>.m()`     -> the UNIQUE function named `m` across the whole
                        forest, unless `m` is on the ambiguity deny
                        list (names shared with builtin containers /
                        stdlib objects, e.g. `get`, `put`, `release`).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

__all__ = ["FuncInfo", "CallGraph"]

# attribute names too generic to resolve by global uniqueness: they
# collide with dict/list/queue/lock/file/executor methods, so a unique
# in-forest homonym would hijack stdlib calls
_AMBIGUOUS = frozenset({
    "get", "put", "set", "add", "pop", "clear", "update", "remove",
    "append", "extend", "insert", "discard", "release", "acquire",
    "wait", "notify", "notify_all", "close", "open", "read", "write",
    "send", "recv", "join", "start", "run", "submit", "result", "copy",
    "items", "keys", "values", "encode", "decode", "flush", "next",
    "sort", "index", "count", "split", "strip", "format", "popleft",
    "appendleft", "popitem", "setdefault", "move_to_end", "shutdown",
    "cancel", "total", "snapshot", "name", "is_set",
})


@dataclass
class FuncInfo:
    rel: str
    qualname: str
    cls: str | None                 # innermost enclosing class
    node: ast.AST
    nested: dict[str, "FuncInfo"] = field(default_factory=dict)
    parent: "FuncInfo | None" = None

    @property
    def key(self) -> tuple[str, str]:
        return (self.rel, self.qualname)


def _module_rel(dotted: str) -> str:
    """'tidb_tpu.store.copr' -> 'tidb_tpu/store/copr.py' (packages map
    to their __init__)."""
    return dotted.replace(".", "/") + ".py"


class CallGraph:
    def __init__(self, forest):
        self.forest = forest
        self.funcs: dict[tuple, FuncInfo] = {}
        # per-module lookup tables
        self._top: dict[tuple, FuncInfo] = {}       # (rel, name)
        self._method: dict[tuple, FuncInfo] = {}    # (rel, cls, name)
        self._classes: dict[tuple, str] = {}        # (rel, Class) -> rel
        self._by_name: dict[str, list[FuncInfo]] = {}
        self._imports: dict[str, dict[str, tuple]] = {}
        rels = {pf.rel for pf in forest}
        for pf in forest:
            self._index_module(pf, rels)

    # -- indexing ------------------------------------------------------------

    def _index_module(self, pf, rels: set[str]) -> None:
        imports: dict[str, tuple] = {}   # local name -> ("mod", rel) |
        #                                  ("func", rel, name)
        for node in pf.nodes:
            if isinstance(node, ast.Import):
                for alias in node.names:
                    rel = _module_rel(alias.name)
                    pkg = alias.name.replace(".", "/") + "/__init__.py"
                    target = rel if rel in rels else \
                        (pkg if pkg in rels else None)
                    if target:
                        imports[alias.asname or
                                alias.name.split(".")[0]] = \
                            ("mod", target)
            elif isinstance(node, ast.ImportFrom) and node.module and \
                    not node.level:
                base = node.module
                for alias in node.names:
                    local = alias.asname or alias.name
                    sub = _module_rel(f"{base}.{alias.name}")
                    subpkg = f"{base}.{alias.name}".replace(".", "/") + \
                        "/__init__.py"
                    modrel = _module_rel(base)
                    modpkg = base.replace(".", "/") + "/__init__.py"
                    if sub in rels:
                        imports[local] = ("mod", sub)
                    elif subpkg in rels:
                        imports[local] = ("mod", subpkg)
                    elif modrel in rels:
                        imports[local] = ("func", modrel, alias.name)
                    elif modpkg in rels:
                        imports[local] = ("func", modpkg, alias.name)
        self._imports[pf.rel] = imports

        def visit(node, qual: str, cls: str | None,
                  parent: FuncInfo | None):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    q = f"{qual}.{child.name}" if qual else child.name
                    self._classes[(pf.rel, child.name)] = pf.rel
                    visit(child, q, child.name, parent)
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    if parent is not None:
                        q = f"{parent.qualname}.<locals>.{child.name}"
                    else:
                        q = f"{qual}.{child.name}" if qual else child.name
                    fi = FuncInfo(pf.rel, q, cls, child, parent=parent)
                    self.funcs[fi.key] = fi
                    self._by_name.setdefault(child.name, []).append(fi)
                    if parent is not None:
                        parent.nested[child.name] = fi
                    elif cls is not None:
                        self._method[(pf.rel, cls, child.name)] = fi
                    else:
                        self._top[(pf.rel, child.name)] = fi
                    visit(child, q, cls, fi)
                else:
                    visit(child, qual, cls, parent)

        visit(pf.tree, "", None, None)

    # -- resolution ----------------------------------------------------------

    def resolve_call(self, call: ast.Call, rel: str,
                     enclosing: FuncInfo | None) -> FuncInfo | None:
        fn = call.func
        if isinstance(fn, ast.Name):
            f = enclosing
            while f is not None:            # lexical closure chain
                hit = f.nested.get(fn.id)
                if hit is not None:
                    return hit
                f = f.parent
            hit = self._top.get((rel, fn.id))
            if hit is not None:
                return hit
            imp = self._imports.get(rel, {}).get(fn.id)
            if imp and imp[0] == "func":
                return self._top.get((imp[1], imp[2])) or \
                    self._method.get((imp[1], imp[2], "__init__"))
            if (rel, fn.id) in self._classes:
                return self._method.get((rel, fn.id, "__init__"))
            return None
        if isinstance(fn, ast.Attribute):
            base = fn.value
            if isinstance(base, ast.Name):
                if base.id == "self" and enclosing is not None and \
                        enclosing.cls is not None:
                    hit = self._method.get((rel, enclosing.cls, fn.attr))
                    if hit is not None:
                        return hit
                imp = self._imports.get(rel, {}).get(base.id)
                if imp and imp[0] == "mod":
                    return self._top.get((imp[1], fn.attr))
            if fn.attr in _AMBIGUOUS or fn.attr.startswith("__"):
                return None
            cands = self._by_name.get(fn.attr, [])
            # nested defs are only callable from their closure; exclude
            # them from the global-uniqueness fallback
            cands = [c for c in cands if c.parent is None]
            return cands[0] if len(cands) == 1 else None
        return None

    def enclosing(self, rel: str, qualname: str) -> FuncInfo | None:
        return self.funcs.get((rel, qualname))
