"""Auto-discovered lock registry: every `threading.Lock()/RLock()/
Condition()` construction site in the forest, named by module + owning
attribute.

Names are static identities, not runtime objects: every instance of
`MemTracker` shares the one name `tidb_tpu/memtrack.py:MemTracker._mu`.
That is exactly the granularity a lock-ORDER discipline needs — the
ordering contract is written per construction site, and the runtime
sanitizer (util/lockorder.py) maps live locks back to these names by
their construction (file, line).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass

__all__ = ["LockSite", "LockRegistry", "discover"]

_FACTORIES = {"Lock": "Lock", "RLock": "RLock", "Condition": "Condition",
              "Semaphore": "Semaphore",
              "BoundedSemaphore": "Semaphore"}


@dataclass(frozen=True)
class LockSite:
    rel: str            # module path, repo-relative
    lineno: int         # construction line
    cls: str | None     # owning class (None: module-level)
    attr: str           # attribute / global name the lock is bound to
    kind: str           # Lock | RLock | Condition | Semaphore

    @property
    def name(self) -> str:
        owner = f"{self.cls}.{self.attr}" if self.cls else self.attr
        return f"{self.rel}:{owner}"


def _factory_kind(call: ast.Call) -> str | None:
    """'threading.Lock(...)' / 'Lock(...)' -> 'Lock' (etc.), else None."""
    fn = call.func
    if isinstance(fn, ast.Attribute) and \
            isinstance(fn.value, ast.Name) and fn.value.id == "threading":
        return _FACTORIES.get(fn.attr)
    if isinstance(fn, ast.Name):
        return _FACTORIES.get(fn.id)
    return None


class LockRegistry:
    """Lock sites indexed for the resolution policy the analysis uses."""

    def __init__(self, sites: list[LockSite]):
        self.sites = sites
        self.by_name: dict[str, LockSite] = {s.name: s for s in sites}
        self.kinds: dict[str, str] = {s.name: s.kind for s in sites}
        # (rel, cls, attr) -> site  and  (rel, attr) -> module-level site
        self._scoped: dict[tuple, LockSite] = {}
        # (rel, attr) -> class-scoped sites in that module (for
        # receiver-typeless `obj.attr` resolution)
        self._mod_attr: dict[tuple, list[LockSite]] = {}
        for s in sites:
            self._scoped[(s.rel, s.cls, s.attr)] = s
            if s.cls is not None:
                self._mod_attr.setdefault((s.rel, s.attr), []).append(s)

    def module_level(self, rel: str, name: str) -> LockSite | None:
        return self._scoped.get((rel, None, name))

    def class_attr(self, rel: str, cls: str | None,
                   attr: str) -> LockSite | None:
        return self._scoped.get((rel, cls, attr))

    def unique_in_module(self, rel: str, attr: str) -> LockSite | None:
        cands = self._mod_attr.get((rel, attr), [])
        return cands[0] if len(cands) == 1 else None

    def resolve(self, rel: str, cls: str | None,
                expr: ast.expr) -> LockSite | None:
        """Resolve a lock-valued expression at a `with`/acquire site.

        Deliberately under-approximate — an unresolvable expression adds
        no edge and checks no guard, it never guesses:
          * bare name        -> this module's global of that name;
          * `self.X` in C    -> this module's C.X;
          * `<anything>.X`   -> the UNIQUE class-scoped X in this module
                                (e.g. `node._mu` inside memtrack.py);
          * ambiguous / cross-module receivers -> None.
        """
        if isinstance(expr, ast.Name):
            return self.module_level(rel, expr.id) or \
                self.class_attr(rel, cls, expr.id)
        if isinstance(expr, ast.Attribute):
            if isinstance(expr.value, ast.Name) and \
                    expr.value.id == "self" and cls is not None:
                hit = self.class_attr(rel, cls, expr.attr)
                if hit is not None:
                    return hit
            return self.unique_in_module(rel, expr.attr)
        return None


def discover(forest) -> LockRegistry:
    """Walk every module for lock constructions bound to an attribute
    (`self.X = threading.Lock()` in a class, `X = threading.Lock()` at
    module or class scope)."""
    sites: list[LockSite] = []

    def visit(pf, node, cls: str | None, in_func: bool):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                visit(pf, child, child.name, in_func)
                continue
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                visit(pf, child, cls, True)
                continue
            if isinstance(child, (ast.Assign, ast.AnnAssign)):
                value = child.value
                if not isinstance(value, ast.Call):
                    continue
                kind = _factory_kind(value)
                if kind is None:
                    continue
                targets = child.targets if isinstance(child, ast.Assign) \
                    else [child.target]
                for t in targets:
                    if isinstance(t, ast.Name) and not in_func:
                        # module/class scope only: a function-local
                        # lock has no stable cross-call identity
                        sites.append(LockSite(pf.rel, child.lineno,
                                              cls, t.id, kind))
                    elif isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self":
                        sites.append(LockSite(pf.rel, child.lineno,
                                              cls, t.attr, kind))
            else:
                visit(pf, child, cls, in_func)

    for pf in forest:
        visit(pf, pf.tree, None, False)
    return LockRegistry(sites)
