"""Single-parse static-analysis engine (ref: TiDB's `make check` — gofmt
plus govet plus project-specific vet rules — rebuilt for this package).

The package's correctness invariants used to live in four copy-pasted
AST-walking test files, each re-parsing all ~100 package modules with
its own ad-hoc suppression convention. This engine parses every module ONCE
into a shared forest (`Forest`), runs every registered `Rule` over it,
and owns the one suppression syntax:

    # lint: exempt[rule-name] reason why this site is sanctioned

* Placed on (or directly above) an offending line, the tag suppresses
  that rule's findings on the tag line and the line below it.
* Placed directly above a `def` (or its decorators), it suppresses the
  rule for the whole function body — the successor of the old
  `memtrack.AUDITED_HELPERS` function registry.
* `exempt[a,b]` exempts several rules at once; the reason is required
  (a reasonless tag is itself a finding — no blanket exemptions).
* Rules may declare legacy `aliases` (e.g. ``# memtrack: exempt``) so
  historic tags keep working while call sites migrate.

Two guards keep the suite honest:

* unused-suppression: a tag that suppressed nothing is reported — a
  stale exemption would silently sanction future regressions.
* vacuity guard: every rule declares a positive `fixture` snippet that
  must produce a finding when linted in isolation, and a `min_sites`
  floor of real in-tree sites it must have examined. A refactor that
  moves the code a rule watches out of its scope fails loudly instead
  of hollowing the rule out.

Front ends: ``python -m tidb_tpu.lint`` (CLI, see __main__.py) and the
parametrized pytest shim tests/test_lint.py.
"""

from __future__ import annotations

import ast
import io
import os
import re
import time
import tokenize
from dataclasses import dataclass, field

__all__ = ["Finding", "Suppression", "ParsedFile", "Forest", "Rule",
           "register_rule", "REGISTRY", "Report", "run", "selfcheck",
           "parse_count", "REPO", "PKG_REL"]

# repo root: tidb_tpu/lint/engine.py -> repo
REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
PKG_REL = "tidb_tpu"

# pseudo-rules emitted by the engine itself (suppression hygiene)
UNUSED_RULE = "unused-suppression"
BAD_RULE = "bad-suppression"

# every ast.parse the engine ever performs, process-wide: the
# single-parse guarantee is asserted on THIS counter (tests/test_lint.py
# pins `run()` to exactly one parse per package module, however many
# rules run), not on wall time — wall time flakes under concurrent CPU
# load inside the tier-1 budget, parse counts cannot
_PARSE_CALLS = 0


def parse_count() -> int:
    return _PARSE_CALLS

_TAG_RE = re.compile(r"#\s*lint:\s*exempt\[([A-Za-z0-9_,-]*)\]\s*(.*)")


@dataclass(frozen=True)
class Finding:
    """One structured lint result."""
    file: str          # repo-relative path
    line: int
    rule: str
    message: str

    def __str__(self):
        return f"{self.file}:{self.line}: [{self.rule}] {self.message}"


@dataclass
class Suppression:
    rule: str
    reason: str
    line: int          # line the tag sits on (1-based)
    start: int         # first line it covers
    end: int           # last line it covers (inclusive)
    alias: bool = False
    used: bool = False


class ParsedFile:
    """One module of the forest: AST + source lines + suppressions."""

    def __init__(self, rel: str, source: str,
                 aliases: dict[str, str] | None = None):
        global _PARSE_CALLS
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        _PARSE_CALLS += 1
        self.tree = ast.parse(source, filename=rel)
        self.bad_tags: list[Finding] = []
        self._def_spans = self._collect_def_spans()
        self.suppressions: list[Suppression] = []
        self._parse_tags(aliases or {})
        self._nodes: list | None = None

    @property
    def nodes(self) -> list:
        """Flat ast.walk order, computed once and shared by every rule
        (a list scan is much cheaper than a fresh tree walk per rule)."""
        if self._nodes is None:
            self._nodes = list(ast.walk(self.tree))
        return self._nodes

    def _collect_def_spans(self) -> dict[int, tuple[int, int]]:
        """first source line of a def (decorator included) -> body span.
        Functions only: a tag above a `class` would blanket-exempt
        every method under one reason, defeating the per-site audit."""
        spans: dict[int, tuple[int, int]] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                first = min([node.lineno] +
                            [d.lineno for d in node.decorator_list])
                span = (first, node.end_lineno or node.lineno)
                spans[first] = span
                spans[node.lineno] = span   # tag trailing a decorated def
        return spans

    def _scope_for_tag(self, lineno: int) -> tuple[int, int]:
        """A STANDALONE comment tag directly above a def (comment runs
        allowed) covers the def's whole span; a tag trailing the def
        line itself does too. A standalone comment anywhere else covers
        the next line; a tag trailing an ordinary statement covers that
        statement ONLY — never the line (or def) below it."""
        if lineno in self._def_spans:        # tag trailing the def line
            return self._def_spans[lineno]
        if not self.lines[lineno - 1].lstrip().startswith("#"):
            return (lineno, lineno)          # trailing a code line
        ln = lineno + 1
        while ln <= len(self.lines) and \
                self.lines[ln - 1].lstrip().startswith("#"):
            ln += 1
        if ln in self._def_spans:
            start, end = self._def_spans[ln]
            return (min(lineno, start), end)
        # standalone tag: cover the comment run down to the next code
        # line, so stacked per-rule tags above one site all apply
        return (lineno, ln)

    def _comments(self) -> dict[int, str]:
        """line -> comment text, via tokenize — so a string literal
        that merely QUOTES the tag syntax can neither suppress an
        adjacent finding nor trip the unused-suppression check."""
        out: dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(self.source).readline):
                if tok.type == tokenize.COMMENT:
                    out[tok.start[0]] = tok.string
        except (tokenize.TokenError, IndentationError, SyntaxError):
            # already ast-parsed, so this is unreachable in practice;
            # degrade to the raw-line scan rather than dropping tags
            for i, text in enumerate(self.lines, start=1):
                if "#" in text:
                    out[i] = text[text.index("#"):]
        return out

    def _parse_tags(self, aliases: dict[str, str]) -> None:
        needles = ["lint:"] + [t.lstrip("# ") for t in aliases]
        if not any(n in self.source for n in needles):
            return              # fast path: no candidate tags at all
        for i, text in sorted(self._comments().items()):
            m = _TAG_RE.search(text)
            if m:
                names = [n.strip() for n in m.group(1).split(",")]
                reason = m.group(2).strip()
                start, end = self._scope_for_tag(i)
                if not reason:
                    self.bad_tags.append(Finding(
                        self.rel, i, BAD_RULE,
                        "exempt tag without a reason — every exemption "
                        "must justify itself"))
                for name in names:
                    if not name:
                        self.bad_tags.append(Finding(
                            self.rel, i, BAD_RULE,
                            "exempt tag with empty rule name"))
                        continue
                    self.suppressions.append(
                        Suppression(name, reason, i, start, end))
                continue
            for tag, rule_name in aliases.items():
                if tag in text:
                    start, end = self._scope_for_tag(i)
                    reason = text.split(tag, 1)[1].lstrip(" -:").strip()
                    if not reason:
                        self.bad_tags.append(Finding(
                            self.rel, i, BAD_RULE,
                            f"legacy exempt tag {tag!r} without a "
                            f"reason — every exemption must justify "
                            f"itself"))
                    self.suppressions.append(Suppression(
                        rule_name, reason, i, start, end, alias=True))

    def suppressed(self, rule: str, lineno: int) -> bool:
        hit = False
        for s in self.suppressions:
            if s.rule == rule and s.start <= lineno <= s.end:
                s.used = True
                hit = True
        return hit


class Forest:
    """Every package module, parsed exactly once."""

    def __init__(self, files: dict[str, ParsedFile], root: str | None):
        self.files = files
        self.root = root        # None => synthetic forest (no docs leg)

    @classmethod
    def load(cls, root: str = REPO) -> "Forest":
        aliases = _alias_map()
        files: dict[str, ParsedFile] = {}
        pkg = os.path.join(root, PKG_REL)
        for dirpath, dirnames, filenames in os.walk(pkg):
            # the linter does not scan itself (rule fixtures contain
            # violations by design) — but only the package-root lint/,
            # not any future directory that happens to share the name
            dirnames[:] = sorted(
                d for d in dirnames
                if d != "__pycache__" and not (d == "lint"
                                               and dirpath == pkg))
            for f in sorted(filenames):
                if not f.endswith(".py"):
                    continue
                path = os.path.join(dirpath, f)
                rel = os.path.relpath(path, root)
                with open(path, encoding="utf-8") as fh:
                    files[rel] = ParsedFile(rel, fh.read(), aliases)
        return cls(files, root)

    @classmethod
    def from_sources(cls, sources: dict[str, str],
                     root: str | None = None) -> "Forest":
        aliases = _alias_map()
        return cls({rel: ParsedFile(rel, src, aliases)
                    for rel, src in sources.items()}, root)

    def __iter__(self):
        return iter(self.files.values())

    def get(self, rel: str) -> ParsedFile | None:
        return self.files.get(rel)


class Rule:
    """Base class: subclass, decorate with @register_rule("name"), and
    implement check(). Findings are yielded raw — the engine applies
    suppressions afterwards. check() must tally every candidate site it
    examined into self.sites (matched or not), feeding the vacuity
    guard; `fixture` is a snippet that must yield at least one finding
    when linted in isolation as `fixture_rel` (+ fixture_support)."""

    name: str = ""
    aliases: tuple[str, ...] = ()
    min_sites: int = 1
    fixture: str = ""
    fixture_rel: str = "tidb_tpu/__lint_fixture__.py"
    fixture_support: dict[str, str] = {}

    def __init__(self):
        self.sites = 0

    @classmethod
    def doc(cls) -> str:
        return (cls.__doc__ or "").strip().splitlines()[0]

    def check(self, forest: Forest):
        raise NotImplementedError


REGISTRY: dict[str, type[Rule]] = {}


def register_rule(name: str):
    def deco(cls: type[Rule]) -> type[Rule]:
        if name in REGISTRY:
            raise ValueError(f"duplicate rule {name!r}")
        cls.name = name
        REGISTRY[name] = cls
        return cls
    return deco


def _alias_map() -> dict[str, str]:
    return {tag: cls.name
            for cls in REGISTRY.values() for tag in cls.aliases}


def selfcheck(cls: type[Rule]) -> list[Finding]:
    """Vacuity guard, fixture leg: the rule's positive fixture must
    produce at least one finding when linted in isolation. Returns the
    problems (empty list == healthy rule)."""
    if not cls.fixture:
        return [Finding("tidb_tpu/lint", 0, cls.name,
                        "vacuity guard: rule declares no positive fixture")]
    sources = dict(cls.fixture_support)
    sources[cls.fixture_rel] = cls.fixture
    try:
        forest = Forest.from_sources(sources)
    except SyntaxError as e:
        return [Finding("tidb_tpu/lint", 0, cls.name,
                        f"vacuity guard: fixture does not parse: {e}")]
    rule = cls()
    hits = [f for f in rule.check(forest) if f.file == cls.fixture_rel]
    if not hits:
        return [Finding("tidb_tpu/lint", 0, cls.name,
                        "vacuity guard: positive fixture produced no "
                        "finding — the rule no longer matches the "
                        "pattern it documents")]
    return []


@dataclass
class Report:
    findings: list[Finding] = field(default_factory=list)
    rule_times: dict[str, float] = field(default_factory=dict)
    parse_time: float = 0.0
    total_time: float = 0.0
    files: int = 0
    rules_run: list[str] = field(default_factory=list)
    parse_calls: int = 0     # ast.parse calls Forest.load spent (one
    #                          per module; rules add ZERO)

    @property
    def clean(self) -> bool:
        return not self.findings


def run(rules: list[str] | None = None, forest: Forest | None = None,
        root: str = REPO, with_selfcheck: bool = True,
        with_vacuity: bool = True) -> Report:
    """Run `rules` (default: all registered, in registration order) over
    one shared parse of the package. Returns a Report; report.clean is
    the CI contract. with_vacuity=False skips the min_sites floor (for
    synthetic forests in the framework's own tests)."""
    t0 = time.perf_counter()
    names = list(REGISTRY) if rules is None else list(rules)
    unknown = [n for n in names if n not in REGISTRY]
    if unknown:
        raise KeyError(f"unknown rule(s): {', '.join(unknown)} "
                       f"(see --list-rules)")
    report = Report()
    if forest is None:
        p0 = _PARSE_CALLS
        forest = Forest.load(root)
        report.parse_time = time.perf_counter() - t0
        report.parse_calls = _PARSE_CALLS - p0
    report.files = len(forest.files)
    report.rules_run = names

    for f in forest:
        report.findings.extend(f.bad_tags)

    for name in names:
        cls = REGISTRY[name]
        t1 = time.perf_counter()
        rule = cls()
        for finding in rule.check(forest):
            pf = forest.get(finding.file)
            if pf is not None and pf.suppressed(name, finding.line):
                continue
            report.findings.append(finding)
        if with_vacuity and rule.sites < cls.min_sites:
            report.findings.append(Finding(
                "tidb_tpu/lint", 0, name,
                f"vacuity guard: rule examined {rule.sites} in-tree "
                f"site(s), expected >= {cls.min_sites} — its scope no "
                f"longer matches the code it was written to watch"))
        if with_selfcheck:
            report.findings.extend(selfcheck(cls))
        report.rule_times[name] = time.perf_counter() - t1

    ran = set(names)
    for f in forest:
        for s in f.suppressions:
            if s.rule in ran and not s.used:
                report.findings.append(Finding(
                    f.rel, s.line, UNUSED_RULE,
                    f"exempt[{s.rule}] suppressed nothing — stale tags "
                    f"sanction future regressions; delete it"))
            elif s.rule not in REGISTRY:
                report.findings.append(Finding(
                    f.rel, s.line, BAD_RULE,
                    f"exempt tag names unknown rule {s.rule!r}"))

    report.findings.sort(key=lambda x: (x.file, x.line, x.rule))
    report.total_time = time.perf_counter() - t0
    return report
