"""tidb_tpu.lint — the package's static-analysis subsystem.

One engine, one parse, one suppression syntax (see engine.py). Run it:

    python -m tidb_tpu.lint              # CI front end, exit 1 on findings
    python -m tidb_tpu.lint --list-rules
    python -m tidb_tpu.lint --rule lock-discipline

or through the pytest shim tests/test_lint.py (one shared parse for the
whole rule set). Rules live in tidb_tpu/lint/rules/; docs/LINTS.md has
the catalog, the suppression syntax and the how-to-add-a-rule recipe.
"""

from tidb_tpu.lint import rules as _rules  # noqa: F401  (registers rules)
from tidb_tpu.lint.engine import (Finding, Forest, REGISTRY, Report,
                                  Rule, register_rule, run, selfcheck)

__all__ = ["Finding", "Forest", "REGISTRY", "Report", "Rule",
           "register_rule", "run", "selfcheck"]
