"""Small shared AST helpers for lint rules (one place, not re-grown
per rule the way the four original test walkers each did)."""

from __future__ import annotations

import ast

__all__ = ["enclosing_map", "root_name", "call_name"]


def enclosing_map(tree):
    """lineno -> innermost enclosing function qualname (span-based)."""
    spans = []

    def visit(node, qual):
        for child in ast.iter_child_nodes(node):
            q = qual
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                q = f"{qual}.{child.name}" if qual else child.name
                if not isinstance(child, ast.ClassDef):
                    spans.append((child.lineno, child.end_lineno, q))
            visit(child, q)

    visit(tree, "")

    def lookup(lineno):
        best = None
        for a, b, q in spans:
            if a <= lineno <= (b or a):
                if best is None or a >= best[0]:
                    best = (a, q)
        return best[1] if best else ""

    return lookup


def root_name(expr) -> str | None:
    """Leftmost Name a value/call chain hangs off: jnp.max(x).item()
    -> 'jnp'; np.asarray(v) -> 'np'; foo -> 'foo'."""
    while True:
        if isinstance(expr, ast.Name):
            return expr.id
        if isinstance(expr, ast.Attribute):
            expr = expr.value
        elif isinstance(expr, ast.Call):
            expr = expr.func
        elif isinstance(expr, ast.Subscript):
            expr = expr.value
        else:
            return None


def call_name(call: ast.Call) -> str | None:
    """Terminal name of the callee: SQLError(...) / errors.SQLError(...)
    both -> 'SQLError'."""
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None
