"""Rule catalog: importing this package registers every rule, in the
order CI reports them. Four ported from the original standalone test
walkers, ten project-specific additions, three whole-program flow
rules built on tidb_tpu/lint/flow (call graph + lock registry over
the same shared parse), and three device-plane dataflow rules built
on tidb_tpu/lint/flow/device (traced-program discovery over that
same parse)."""

from tidb_tpu.lint.rules import (  # noqa: F401  (import == register)
    wire,        # wire-discipline   (ported: tests/test_lint_wire.py)
    sync,        # hot-path-sync     (ported: tests/test_lint_sync.py)
    metrics,     # metric-names      (ported: tests/test_lint_metrics.py)
    memtrack,    # memtrack-alloc    (ported: tests/test_lint_memtrack.py)
    locks,       # lock-discipline
    sysvars,     # sysvar-registry
    errcodes,    # errcode-discipline
    dtypes,      # dtype-discipline
    excepts,     # bare-except
    devcache,    # device-cache
    decode,      # decode-discipline (encoded execution stays encoded)
    failpoints,  # failpoint-discipline (fault-injection registry)
    planeimports,  # no-parallel-import (unified device plane only)
    tracenames,  # trace-names       (statement-trace span vocabulary)
    lockorder,   # lock-order        (flow: acquisition-order cycles)
    guardedby,   # guarded-by        (flow: annotated shared state)
    pairres,     # paired-resource   (flow: consume/release, dispatch/
    #              finalize balance)
    device,      # donation-safety / cache-key / retrace-hazard
)                #                    (flow: device-plane dataflow)
