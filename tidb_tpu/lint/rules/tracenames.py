"""Trace span-name discipline: the declared SPAN_NAMES vocabulary and
the trace.begin/trace.span call sites track each other (same registry
shape as metric-names and failpoint-discipline)."""

from __future__ import annotations

import ast

from tidb_tpu.lint.engine import Finding, Rule, register_rule

_TRACE = "tidb_tpu/trace.py"


def declared_span_names(pf) -> dict[str, int]:
    """String keys of trace.py's module-level SPAN_NAMES dict
    -> lineno."""
    out = {}
    for node in pf.tree.body:
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        else:
            continue
        if len(targets) == 1 and isinstance(targets[0], ast.Name) and \
                targets[0].id == "SPAN_NAMES" and \
                isinstance(node.value, ast.Dict):
            for key in node.value.keys:
                if isinstance(key, ast.Constant) and \
                        isinstance(key.value, str):
                    out[key.value] = key.lineno
    return out


def _span_calls(pf):
    """trace.begin(...) / trace.span(...) / trace.Span(...) where the
    receiver is the trace module (incl. the `_trace` local-import
    alias). Span() construction counts: session builds its pre-closed
    parse span that way, and a constructed span enters the same trees
    the registry documents."""
    for node in pf.nodes:
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if isinstance(fn, ast.Attribute) and \
                fn.attr in ("begin", "span", "Span") and \
                isinstance(fn.value, ast.Name) and \
                fn.value.id in ("trace", "_trace"):
            yield node, fn.attr


@register_rule("trace-names")
class TraceNamesRule(Rule):
    """Every trace.begin()/trace.span()/trace.Span() call site names a
    span declared in trace.SPAN_NAMES, as a string literal; and every
    declared name is opened by at least one in-tree site.

    The registry is the operator-facing span vocabulary (the docs, the
    Chrome export lanes and the bench latency attribution all read
    these names): a span opened under an undeclared name is a timeline
    lane no attribution bucket or doc explains, and a declared name no
    site opens is catalog fiction.
    """

    min_sites = 20      # lifecycle + device plane + storage seams
    fixture = (
        "from tidb_tpu import trace\n"
        "def f():\n"
        "    with trace.span('not/declared'):\n"
        "        pass\n"
    )
    fixture_support = {
        _TRACE: 'SPAN_NAMES = {"plan": "planning"}\n',
    }

    def check(self, forest):
        decl_pf = forest.get(_TRACE)
        if decl_pf is None:
            yield Finding(_TRACE, 1, self.name,
                          "trace.py missing from the forest — the span "
                          "registry is gone")
            return
        declared = declared_span_names(decl_pf)
        if not declared:
            yield Finding(_TRACE, 1, self.name,
                          "trace.py lost its SPAN_NAMES table")
            return
        used: set[str] = set()
        for pf in forest:
            if pf.rel == _TRACE:
                continue    # the registry module's own helpers
            for call, kind in _span_calls(pf):
                self.sites += 1
                arg = call.args[0] if call.args else None
                if not (isinstance(arg, ast.Constant) and
                        isinstance(arg.value, str)):
                    yield Finding(
                        pf.rel, call.lineno, self.name,
                        f"trace.{kind} must name its span with a "
                        f"string literal from trace.SPAN_NAMES "
                        f"(computed names defeat the vocabulary audit)")
                    continue
                if arg.value not in declared:
                    yield Finding(
                        pf.rel, call.lineno, self.name,
                        f"trace.{kind}({arg.value!r}) opens a span not "
                        f"declared in trace.SPAN_NAMES — declare it "
                        f"(one vocabulary: docs, Chrome export, bench "
                        f"attribution)")
                    continue
                used.add(arg.value)
        for name, lineno in sorted(declared.items()):
            if name not in used:
                yield Finding(
                    _TRACE, lineno, self.name,
                    f"span name {name!r} is declared but no in-tree "
                    f"site opens it — dead vocabulary entry")
