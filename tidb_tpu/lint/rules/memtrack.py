"""Memory-accounting discipline (port of tests/test_lint_memtrack.py).

The old walker consulted the `memtrack.AUDITED_HELPERS` function
registry plus an ad-hoc ``# memtrack: exempt`` tag; both conventions now
ride the uniform suppression syntax — a ``# lint: exempt[memtrack-alloc]
reason`` directly above a `def` covers the whole helper (the registry's
successor, kept honest by the engine's unused-suppression check), and
the legacy tag spelling keeps working as a registered alias.
"""

from __future__ import annotations

import ast

from tidb_tpu.lint.astutil import enclosing_map
from tidb_tpu.lint.engine import Finding, Rule, register_rule

SCAN_DIRS = ("tidb_tpu/executor/", "tidb_tpu/ops/")
ALLOC_FNS = ("empty", "zeros", "concatenate")
CONST_MAX = 4096


def _const_size(arg):
    """Statically-known element count of a size argument, else None."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, int):
        return arg.value
    if isinstance(arg, (ast.Tuple, ast.List)):
        prod = 1
        for el in arg.elts:
            if not (isinstance(el, ast.Constant) and
                    isinstance(el.value, int)):
                return None
            prod *= el.value
        return prod
    return None


def _is_bool_dtype(call) -> bool:
    cands = [kw.value for kw in call.keywords if kw.arg == "dtype"]
    if len(call.args) > 1:
        cands.append(call.args[1])
    return any(isinstance(c, ast.Name) and c.id == "bool" for c in cands)


def _below_threshold(call) -> bool:
    if not call.args:
        return True                     # no size: nothing to bound
    size = _const_size(call.args[0])
    if size is not None and size <= CONST_MAX:
        return True
    return _is_bool_dtype(call)


@register_rule("memtrack-alloc")
class MemtrackAllocRule(Rule):
    """Every data-sized numpy allocation in executor/ and ops/ is
    covered by memtrack accounting or carries an explicit exemption.

    np.empty / np.zeros / np.concatenate whose size scales with input
    data must either live inside an exempted helper (its bytes are
    billed by the function's owner, directly or through its caller) or
    carry a per-line exempt tag — a new operator buffering rows without
    billing a tracker fails this rule instead of silently bypassing
    per-query accounting. Auto-exempt below-threshold sites: constant
    sizes <= 4096 elements, and bool masks (1 byte/row, an order of
    magnitude below the column payloads the trackers bound).
    """

    aliases = ("# memtrack: exempt",)
    min_sites = 30      # the scan must actually see the alloc sites
    fixture_rel = "tidb_tpu/executor/__lint_fixture__.py"
    fixture = (
        "import numpy as np\n"
        "def buffer_rows(n):\n"
        "    return np.empty(n, dtype=np.int64)\n"
    )

    def check(self, forest):
        for pf in forest:
            if not pf.rel.startswith(SCAN_DIRS):
                continue
            enclosing = None    # built on first finding only
            for node in pf.nodes:
                if not (isinstance(node, ast.Call) and
                        isinstance(node.func, ast.Attribute) and
                        node.func.attr in ALLOC_FNS and
                        isinstance(node.func.value, ast.Name) and
                        node.func.value.id == "np"):
                    continue
                self.sites += 1
                if _below_threshold(node):
                    continue
                if enclosing is None:
                    enclosing = enclosing_map(pf.tree)
                qual = enclosing(node.lineno) or "<module>"
                yield Finding(
                    pf.rel, node.lineno, self.name,
                    f"data-sized np.{node.func.attr} in {qual} without "
                    f"memtrack accounting — bill a tracker node or tag "
                    f"'# lint: exempt[memtrack-alloc] <reason>'")
