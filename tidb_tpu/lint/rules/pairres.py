"""Paired-resource dataflow: memtrack consume/release balance and
kernel dispatch/finalize pairing."""

from __future__ import annotations

import ast

from tidb_tpu.lint.engine import Finding, Rule, register_rule
from tidb_tpu.lint.flow import flow_of
from tidb_tpu.lint.rules._shape import TRIVIAL_STMTS, release_try_follows

# the tracker implementation itself (its wrappers ARE the pairing) is
# out of scope; everything that CALLS it is in scope
_IMPL = "tidb_tpu/memtrack.py"

# between a consume and its settling try, plain expression statements
# (logging, metrics bumps) are also tolerated — unlike a lock permit,
# a ledger charge outliving one of those by a raise is reclaimed by
# the statement root's detach, so the floor is deliberately softer
_SIMPLE = TRIVIAL_STMTS + (ast.Expr,)


def _terminal(call: ast.Call) -> str | None:
    fn = call.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def _kw(call: ast.Call) -> set:
    return {k.arg for k in call.keywords if k.arg}


def _is_consume(n) -> bool:
    return isinstance(n, ast.Call) and _terminal(n) == "consume" and \
        (_kw(n) & {"host", "device"})


def _is_release(n) -> bool:
    return isinstance(n, ast.Call) and _terminal(n) == "release" and \
        (_kw(n) & {"host", "device"})


def _releases_mem(stmts) -> bool:
    for s in stmts:
        for n in ast.walk(s):
            if _is_release(n):
                return True
    return False


@register_rule("paired-resource")
class PairedResourceRule(Rule):
    """memtrack consume must release on all paths (exceptions included);
    kernel dispatch() results must reach a finalize().

    A `consume(host=/device=)` charge that a raised exception can strand
    inflates the statement ledger until detach-on-close papers over it —
    and under per-query quotas an inflated ledger cancels INNOCENT
    statements. The sanctioned shapes, checked per top-level function
    (nested closures included):

      * the consume sits under a `try` whose `finally` releases (or is
        immediately followed by one, bar trivial assignments);
      * the consume lives in a nested closure of a pipeline whose
        driver releases in a `finally` (dispatch/finalize pairs split
        across closures — ops/runtime.pipeline_map's shape);
      * `memtrack.device_scope(...)` — balanced by construction.

    Deliberate cross-function ownership transfers (cache residency
    released on eviction, sorter buffers released on spill/drain) are
    audited drops: tag them `# lint: exempt[paired-resource] reason`.

    The dispatch leg: a function that calls `<kernel>.dispatch(` must
    also finalize — a dispatched future that never reaches
    `finalize()` silently drops its result AND its device-ledger
    release (every kernel's finalize path credits dispatch_nbytes
    back).
    """

    min_sites = 15

    fixture = (
        "from tidb_tpu import memtrack\n"
        "def leak(plan, rows):\n"
        "    memtrack.consume(plan, host=64)\n"
        "    return rows\n"
        "def drop(kernel, chunk):\n"
        "    tok = kernel.dispatch(chunk)\n"
        "    return tok\n"
        "def drop_partition_loop(kernel, parts):\n"
        "    # the hybrid-join partition staging shape, abandoned:\n"
        "    # per-partition dispatches that never reach a finalize\n"
        "    toks = []\n"
        "    for p in parts:\n"
        "        toks.append(kernel.dispatch(p))\n"
        "    return toks\n"
        "class StagedStore:\n"
        "    # the delta store's stage->merge->release shape, UNtagged:\n"
        "    # bytes staged in one method and released in another are a\n"
        "    # cross-function ownership transfer the rule must flag\n"
        "    # unless the consume site carries the exempt tag\n"
        "    def stage(self, plan, part):\n"
        "        memtrack.consume(plan, host=32)\n"
        "        self.parts.append(part)\n"
        "    def merge(self, plan):\n"
        "        self.parts.clear()\n"
        "        memtrack.release(plan, host=32)\n"
    )

    def check(self, forest):
        fl = flow_of(forest)
        for fi in fl.graph.funcs.values():
            if fi.parent is not None or fi.rel == _IMPL:
                continue
            yield from self._check_toplevel(fi)

    def _check_toplevel(self, fi):
        subtree = list(ast.walk(fi.node))
        cross_release = any(
            isinstance(n, ast.Try) and _releases_mem(n.finalbody)
            for n in subtree)
        has_finalize = any(
            isinstance(n, ast.Call) and _terminal(n) == "finalize"
            for n in subtree)
        for n in subtree:
            if isinstance(n, ast.Call) and isinstance(n.func,
                                                      ast.Attribute) \
                    and n.func.attr == "dispatch":
                self.sites += 1
                if not has_finalize:
                    yield Finding(
                        fi.rel, n.lineno, self.name,
                        f"dispatch() result in {fi.qualname} never "
                        f"reaches a finalize() — the async future (and "
                        f"its device-ledger release) is dropped")
        yield from self._scan(fi, fi.node.body, False, False,
                              cross_release)

    def _scan(self, fi, stmts, protected, nested, cross_release):
        for i, stmt in enumerate(stmts):
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # a closure's body runs at CALL time: the enclosing
                # try/finally protects its definition, not its charges
                yield from self._scan(fi, stmt.body, False, True,
                                      cross_release)
                continue
            if isinstance(stmt, ast.ClassDef):
                yield from self._scan(fi, stmt.body, False, nested,
                                      cross_release)
                continue
            if isinstance(stmt, ast.Try):
                prot = protected or _releases_mem(stmt.finalbody)
                yield from self._scan(fi, stmt.body, prot, nested,
                                      cross_release)
                for h in stmt.handlers:
                    yield from self._scan(fi, h.body, prot, nested,
                                          cross_release)
                yield from self._scan(fi, stmt.orelse, prot, nested,
                                      cross_release)
                yield from self._scan(fi, stmt.finalbody, protected,
                                      nested, cross_release)
                continue
            for block in ("body", "orelse", "finalbody"):
                if hasattr(stmt, block):
                    yield from self._scan(fi, getattr(stmt, block),
                                          protected, nested,
                                          cross_release)
            if isinstance(stmt, ast.Match):
                for case in stmt.cases:
                    yield from self._scan(fi, case.body, protected,
                                          nested, cross_release)
            for n in self._stmt_calls(stmt):
                if not _is_consume(n):
                    continue
                self.sites += 1
                if protected:
                    continue
                if self._release_try_follows(stmts, i + 1):
                    continue
                if nested and cross_release:
                    # pipeline shape: the charge is released by the
                    # driver's finally in this same top-level function
                    continue
                yield Finding(
                    fi.rel, n.lineno, self.name,
                    f"consume() in {fi.qualname} has no matching "
                    f"release on the exception path — wrap in "
                    f"try/finally (or memtrack.device_scope), or tag "
                    f"the deliberate ownership transfer")

    @staticmethod
    def _stmt_calls(stmt):
        """Calls in this statement's expression parts, not descending
        into sub-blocks (they are scanned as statements) or nested
        defs (they are scanned with nested=True)."""
        header: list = []
        if isinstance(stmt, (ast.If, ast.While)):
            header = [stmt.test]
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            header = [stmt.iter]
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            header = [it.context_expr for it in stmt.items]
        elif isinstance(stmt, ast.Match):
            header = [stmt.subject]
        elif isinstance(stmt, ast.Try):
            header = []
        else:
            header = [stmt]
        for e in header:
            for n in ast.walk(e):
                if isinstance(n, ast.Call):
                    yield n

    @staticmethod
    def _release_try_follows(stmts, j) -> bool:
        return release_try_follows(stmts, j, _releases_mem,
                                   trivial=_SIMPLE)
