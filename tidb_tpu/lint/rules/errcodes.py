"""MySQL error-code discipline: codes come from errcode.py, never from
integer literals at raise sites."""

from __future__ import annotations

import ast

from tidb_tpu.lint.astutil import call_name
from tidb_tpu.lint.engine import Finding, Rule, register_rule

# call shapes that put an error code on the client-visible wire
_SINKS = ("SQLError", "add_warning")
_CODE_LO, _CODE_HI = 1000, 9999


@register_rule("errcode-discipline")
class ErrcodeDisciplineRule(Rule):
    """SQLError / add_warning never take an integer-literal error code —
    use the named constants of errcode.py.

    errcode.py is the single catalog mapping the framework's errors
    onto the MySQL wire codes drivers switch on (1062 duplicate key,
    8175 mem quota, 9xxx retryable storage). A literal `1051` at a
    raise site is invisible to that catalog: it can't be audited for
    retryability classification, and a typo ships a wrong code straight
    to clients.
    """

    fixture = (
        "from tidb_tpu.session import SQLError\n"
        "def f():\n"
        "    raise SQLError(1064, 'syntax error')\n"
    )

    def check(self, forest):
        for pf in forest:
            for node in pf.nodes:
                if not (isinstance(node, ast.Call) and
                        call_name(node) in _SINKS):
                    continue
                self.sites += 1
                args = list(node.args) + [kw.value for kw in node.keywords]
                for arg in args:
                    if isinstance(arg, ast.Constant) and \
                            isinstance(arg.value, int) and \
                            not isinstance(arg.value, bool) and \
                            _CODE_LO <= arg.value <= _CODE_HI:
                        yield Finding(
                            pf.rel, node.lineno, self.name,
                            f"{call_name(node)} with integer-literal "
                            f"code {arg.value} — use the named constant "
                            f"from errcode.py so the catalog stays the "
                            f"single source of wire codes")
