"""Guarded-by checking: writes to annotated shared state must hold the
owning lock."""

from __future__ import annotations

import ast

from tidb_tpu.lint.engine import Finding, Rule, register_rule
from tidb_tpu.lint.flow import flow_of
from tidb_tpu.lint.flow.analysis import MUTATORS


@register_rule("guarded-by")
class GuardedByRule(Rule):
    """Writes to a `# guarded-by: <lock>`-annotated attribute must hold
    the owning lock.

    The annotation sits on (or directly above) the attribute's
    initialization line:

        self.host = 0          # guarded-by: _mu
        _STATS = _fresh()      # guarded-by: _stats_lock

    and declares, in the module that owns the state, which lock
    protects it. Any write to that attribute elsewhere in the module —
    assignment, augmented assignment, `del`, or a container mutation
    (`.append`/`.pop`/`.update`/...) — must happen with the lock held:
    lexically inside `with lock:`, or in a helper whose every in-tree
    call site holds it (`DeviceCache._drop_locked` is the canonical
    case). `__init__` bodies and module import time are construction —
    single-threaded by definition — and exempt. Reads are out of
    scope: the seeded modules' read paths are either locked already or
    deliberately racy-by-design snapshots, and a read-barrier lint
    would drown the write findings that actually corrupt state.

    An annotation naming a lock the registry cannot resolve is itself
    a finding — a typo'd guard is a silently unchecked one.
    """

    min_sites = 30      # annotations + writes examined in-tree

    fixture = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._mu = threading.Lock()\n"
        "        self.n = 0   # guarded-by: _mu\n"
        "    def bump(self):\n"
        "        self.n += 1\n"
    )

    def check(self, forest):
        fl = flow_of(forest)
        # (rel, attr) -> annotation, split by base kind
        attr_owned: dict[tuple, object] = {}
        name_owned: dict[tuple, object] = {}
        for ann in fl.annotations:
            self.sites += 1
            if not ann.attr:
                yield Finding(
                    ann.rel, ann.lineno, self.name,
                    "guarded-by tag is not attached to an attribute or "
                    "module-global initialization line")
                continue
            if ann.lock is None:
                yield Finding(
                    ann.rel, ann.lineno, self.name,
                    f"guarded-by names {ann.lock_text!r}, which resolves "
                    f"to no registered lock in this module — a typo'd "
                    f"guard checks nothing")
                continue
            if ann.cls is not None:
                attr_owned[(ann.rel, ann.attr)] = ann
            else:
                name_owned[(ann.rel, ann.attr)] = ann
        if not attr_owned and not name_owned:
            return
        for pf in forest:
            yield from self._check_module(fl, pf, attr_owned, name_owned)

    def _check_module(self, fl, pf, attr_owned, name_owned):
        rel = pf.rel
        facts = [(key, f) for key, f in fl.facts.items()
                 if key[0] == rel]
        for _key, f in facts:
            for w in f.writes:
                ann = attr_owned.get((rel, w.name)) if w.base == "attr" \
                    else name_owned.get((rel, w.name))
                if ann is None:
                    continue
                self.sites += 1
                if self._allowed(fl, w, ann):
                    continue
                yield Finding(
                    rel, w.lineno, self.name,
                    f"write to {w.name!r} without holding {ann.lock} "
                    f"(declared guarded-by at {ann.rel}:{ann.lineno}) — "
                    f"a concurrent reader/writer sees torn state")
            for cs in f.calls:
                fn = cs.call.func
                if not (isinstance(fn, ast.Attribute) and
                        fn.attr in MUTATORS):
                    continue
                base = fn.value
                ann = None
                if isinstance(base, ast.Attribute):
                    ann = attr_owned.get((rel, base.attr))
                    wname = base.attr
                elif isinstance(base, ast.Name):
                    ann = name_owned.get((rel, base.id))
                    wname = base.id
                if ann is None:
                    continue
                self.sites += 1
                held = frozenset(cs.held) | fl.caller_held.get(
                    cs.func.key, frozenset())
                if ann.lock in held or cs.func.node.name == "__init__":
                    continue
                yield Finding(
                    rel, cs.lineno, self.name,
                    f"mutation of {wname!r} (.{fn.attr}) without holding "
                    f"{ann.lock} (declared guarded-by at "
                    f"{ann.rel}:{ann.lineno})")

    @staticmethod
    def _allowed(fl, w, ann) -> bool:
        if w.func.node.name == "__init__":
            return True         # construction is single-threaded
        return ann.lock in fl.held_at(w)
