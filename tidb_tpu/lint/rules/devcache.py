"""Device-cache upload discipline: region columns reach HBM only through
the audited upload helper."""

from __future__ import annotations

import ast

from tidb_tpu.lint.engine import Finding, Rule, register_rule

_SCOPES = ("tidb_tpu/store/", "tidb_tpu/executor/")
_AUDITED = "tidb_tpu/store/device_cache.py"
_UPLOADS = ("device_put", "device_put_chunk")


@register_rule("device-cache")
class DeviceCacheRule(Rule):
    """In store/ and executor/, jax.device_put / runtime.device_put_chunk
    calls live ONLY in store/device_cache.py (the audited upload helper).

    The HBM region-block cache is the single owner of device residency
    for region columns: its ledger (memtrack `hbm-cache` node) is exact
    only if every upload of storage-side columns flows through
    `upload_block`. A stray device_put in a handler or executor creates
    untracked, unbudgeted HBM residency that the eviction/OOM machinery
    can neither see nor reclaim — the exact failure mode the old
    per-chunk transfer memos had. Kernel-internal transfers (ops/,
    parallel/) are out of scope: they are transient dispatch staging,
    billed per-dispatch via dispatch_nbytes.
    """

    min_sites = 1       # the audited upload_block site must still exist
    fixture_rel = "tidb_tpu/store/__lint_fixture__.py"
    fixture = (
        "import jax\n"
        "def serve_block(cols):\n"
        "    return jax.device_put(cols)\n"
    )

    def check(self, forest):
        for pf in forest:
            if not pf.rel.startswith(_SCOPES):
                continue
            for node in pf.nodes:
                kind = self._upload_kind(node)
                if kind is None:
                    continue
                self.sites += 1
                if pf.rel == _AUDITED:
                    continue        # sanctioned: the audited helper
                yield Finding(
                    pf.rel, node.lineno, self.name,
                    f"direct {kind} of region columns outside the "
                    f"audited upload helper — route the transfer "
                    f"through store/device_cache.upload_block so HBM "
                    f"residency stays tracked and evictable")

    @staticmethod
    def _upload_kind(node) -> str | None:
        if not isinstance(node, ast.Call):
            return None
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr in _UPLOADS:
            return fn.attr
        if isinstance(fn, ast.Name) and fn.id in _UPLOADS:
            return fn.id
        return None
