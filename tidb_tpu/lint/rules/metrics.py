"""Metric-name registry discipline (port of tests/test_lint_metrics.py)."""

from __future__ import annotations

import ast

from tidb_tpu.lint.engine import Finding, Rule, register_rule

_METRICS = "tidb_tpu/metrics.py"


def declared_constants(pf) -> dict[str, tuple[str, int]]:
    """UPPERCASE module-level string constants of metrics.py:
    NAME -> (value, lineno)."""
    out = {}
    for node in pf.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id.isupper() and \
                isinstance(node.value, ast.Constant) and \
                isinstance(node.value.value, str):
            out[node.targets[0].id] = (node.value.value, node.lineno)
    return out


def _metric_calls(pf):
    """<anything>.counter/.histogram/.gauge(...) where the receiver is
    the metrics module (imported as `metrics`)."""
    for node in pf.nodes:
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if isinstance(fn, ast.Attribute) and \
                fn.attr in ("counter", "histogram", "gauge") and \
                isinstance(fn.value, ast.Name) and \
                fn.value.id == "metrics":
            yield node


def _name_arg(call):
    if call.args:
        return call.args[0]
    for kw in call.keywords:
        if kw.arg == "name":
            return kw.value
    return None


@register_rule("metric-names")
class MetricNamesRule(Rule):
    """Every metrics.counter/histogram/gauge call site passes a name
    CONSTANT declared in metrics.py — never a string literal.

    A typo'd stringly family name would silently fork a metric family;
    the registry of names in metrics.py is the single place scrape
    dashboards are built against. Declared names must also follow the
    Prometheus conventions (tidb_tpu_ prefix, lowercase, unit suffix
    _total/_seconds/_bytes — or the unitless gauge-level suffixes
    _current/_depth/_ratio for instantaneous counts and proportions
    like open connections, queue depths and device utilization, which
    carry no unit to name).
    """

    min_sites = 10      # the session + coprocessor layers really emit
    fixture = (
        "from tidb_tpu import metrics\n"
        "def f():\n"
        "    metrics.counter('tidb_tpu_oops_total')\n"
    )
    fixture_support = {
        _METRICS: 'QUERIES_TOTAL = "tidb_tpu_queries_total"\n',
    }

    def check(self, forest):
        decl_pf = forest.get(_METRICS)
        if decl_pf is None:
            yield Finding(_METRICS, 1, self.name,
                          "metrics.py missing from the forest — the "
                          "metric-name registry is gone")
            return
        consts = declared_constants(decl_pf)
        if not consts:
            yield Finding(_METRICS, 1, self.name,
                          "metrics.py lost its name constants")
        for const, (value, lineno) in consts.items():
            ok = (value.startswith("tidb_tpu_") and value == value.lower()
                  and value.endswith(("_total", "_seconds", "_bytes",
                                      "_current", "_depth", "_ratio")))
            if not ok:
                yield Finding(
                    decl_pf.rel, lineno, self.name,
                    f"{const} = {value!r} breaks Prometheus naming: "
                    f"tidb_tpu_ prefix, lowercase, unit suffix "
                    f"_total/_seconds/_bytes (or gauge-level "
                    f"_current/_depth/_ratio)")
        for pf in forest:
            for call in _metric_calls(pf):
                self.sites += 1
                arg = _name_arg(call)
                if arg is None:
                    yield Finding(pf.rel, call.lineno, self.name,
                                  "metric call without a name argument")
                    continue
                if isinstance(arg, ast.Attribute) and \
                        isinstance(arg.value, ast.Name) and \
                        arg.value.id == "metrics" and arg.attr in consts:
                    continue
                yield Finding(
                    pf.rel, call.lineno, self.name,
                    f"metric name must be a metrics.<CONSTANT> declared "
                    f"in metrics.py, got {ast.dump(arg)[:60]}")


def _labels_arg(call):
    """The labels argument of a metrics.counter/histogram/gauge call
    (positional position differs: counter(name, labels), histogram/
    gauge(name, value, labels))."""
    idx = 1 if call.func.attr == "counter" else 2
    if len(call.args) > idx:
        return call.args[idx]
    for kw in call.keywords:
        if kw.arg == "labels":
            return kw.value
    return None


# label keys that ARE a per-tenant / per-statement dimension: one series
# per session or statement is unbounded cardinality by construction
_FORBIDDEN_LABEL_KEYS = frozenset({
    "session", "session_id", "sid", "conn", "conn_id", "connection",
    "user", "username", "tenant", "digest", "digest_text", "stmt",
    "stmt_id", "statement", "trace_id", "sql", "query",
})

# identifiers whose VALUE is per-session/per-statement state: binding
# one as a label value mints a series per tenant even under an innocent
# key name
_FORBIDDEN_VALUE_IDENTS = frozenset({
    "session_id", "sid", "conn_id", "digest", "trace_id", "sql",
    "current_sql", "user", "username",
})


@register_rule("metric-cardinality")
class MetricCardinalityRule(Rule):
    """Prometheus label sets stay bounded: no per-session, per-user,
    per-statement or per-trace label values at metrics.* call sites.

    The metrics registry is process-cumulative and every labeled series
    lives forever in the exposition — a label keyed by session id or
    SQL digest grows one series per tenant/statement shape and
    eventually dominates scrape cost and registry memory. That
    attribution belongs in the resource meter and its memtables
    (tidb_tpu/meter.py: information_schema.resource_usage, GET /top),
    which are bounded and evictable. Three checks per call site:

      * the labels argument is an inline dict literal (reviewable
        cardinality — a dict built elsewhere hides its keys);
      * no label KEY names a tenant/statement dimension
        (session/user/digest/sql/trace_id/...);
      * no label VALUE is an f-string, string concatenation, call, or
        a name/attribute bound to per-session state (session_id, sql,
        digest, ...) — computed values are how unbounded series get
        minted by accident.

    Constants and bounded enums (outcome/reason/op/worker names) pass.
    """

    min_sites = 10      # every labeled family in the tree goes through
    fixture = (
        "from tidb_tpu import metrics\n"
        "Q = 'x'\n"
        "def f(session_id, digest):\n"
        "    metrics.counter(metrics.Q, {'session': session_id})\n"
        "    metrics.counter(metrics.Q, {'op': digest})\n"
        "    metrics.counter(metrics.Q, {'op': f'q-{session_id}'})\n"
    )
    fixture_support = {
        _METRICS: 'Q = "tidb_tpu_queries_total"\n',
    }

    def _value_ident(self, node):
        """Terminal identifier of a Name/Attribute value expression."""
        if isinstance(node, ast.Name):
            return node.id
        if isinstance(node, ast.Attribute):
            return node.attr
        return None

    def check(self, forest):
        for pf in forest:
            for call in _metric_calls(pf):
                labels = _labels_arg(call)
                if labels is None or (
                        isinstance(labels, ast.Constant)
                        and labels.value is None):
                    continue
                self.sites += 1
                if not isinstance(labels, ast.Dict):
                    yield Finding(
                        pf.rel, call.lineno, self.name,
                        "metric labels must be an inline dict literal "
                        "so the label cardinality is reviewable at the "
                        "call site")
                    continue
                for key, val in zip(labels.keys, labels.values):
                    if isinstance(key, ast.Constant) and \
                            isinstance(key.value, str) and \
                            key.value.lower() in _FORBIDDEN_LABEL_KEYS:
                        yield Finding(
                            pf.rel, call.lineno, self.name,
                            f"label key {key.value!r} is a per-tenant/"
                            f"per-statement dimension — unbounded "
                            f"series cardinality; attribute this in "
                            f"the resource meter (tidb_tpu/meter.py), "
                            f"not Prometheus")
                    if isinstance(val, ast.Constant):
                        continue
                    if isinstance(val, (ast.JoinedStr, ast.BinOp,
                                        ast.Call, ast.Subscript)):
                        yield Finding(
                            pf.rel, call.lineno, self.name,
                            "computed label value (f-string/concat/"
                            "call/index) can mint unbounded series — "
                            "use a bounded enum name, or move the "
                            "attribution into the resource meter")
                        continue
                    ident = self._value_ident(val)
                    if ident is not None and \
                            ident.lower() in _FORBIDDEN_VALUE_IDENTS:
                        yield Finding(
                            pf.rel, call.lineno, self.name,
                            f"label value {ident!r} is per-session/"
                            f"per-statement state — one series per "
                            f"tenant; attribute this in the resource "
                            f"meter (tidb_tpu/meter.py) instead")
