"""Metric-name registry discipline (port of tests/test_lint_metrics.py)."""

from __future__ import annotations

import ast

from tidb_tpu.lint.engine import Finding, Rule, register_rule

_METRICS = "tidb_tpu/metrics.py"


def declared_constants(pf) -> dict[str, tuple[str, int]]:
    """UPPERCASE module-level string constants of metrics.py:
    NAME -> (value, lineno)."""
    out = {}
    for node in pf.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id.isupper() and \
                isinstance(node.value, ast.Constant) and \
                isinstance(node.value.value, str):
            out[node.targets[0].id] = (node.value.value, node.lineno)
    return out


def _metric_calls(pf):
    """<anything>.counter/.histogram/.gauge(...) where the receiver is
    the metrics module (imported as `metrics`)."""
    for node in pf.nodes:
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if isinstance(fn, ast.Attribute) and \
                fn.attr in ("counter", "histogram", "gauge") and \
                isinstance(fn.value, ast.Name) and \
                fn.value.id == "metrics":
            yield node


def _name_arg(call):
    if call.args:
        return call.args[0]
    for kw in call.keywords:
        if kw.arg == "name":
            return kw.value
    return None


@register_rule("metric-names")
class MetricNamesRule(Rule):
    """Every metrics.counter/histogram/gauge call site passes a name
    CONSTANT declared in metrics.py — never a string literal.

    A typo'd stringly family name would silently fork a metric family;
    the registry of names in metrics.py is the single place scrape
    dashboards are built against. Declared names must also follow the
    Prometheus conventions (tidb_tpu_ prefix, lowercase, unit suffix
    _total/_seconds/_bytes — or the unitless gauge-level suffixes
    _current/_depth for instantaneous counts like open connections and
    queue depths, which carry no unit to name).
    """

    min_sites = 10      # the session + coprocessor layers really emit
    fixture = (
        "from tidb_tpu import metrics\n"
        "def f():\n"
        "    metrics.counter('tidb_tpu_oops_total')\n"
    )
    fixture_support = {
        _METRICS: 'QUERIES_TOTAL = "tidb_tpu_queries_total"\n',
    }

    def check(self, forest):
        decl_pf = forest.get(_METRICS)
        if decl_pf is None:
            yield Finding(_METRICS, 1, self.name,
                          "metrics.py missing from the forest — the "
                          "metric-name registry is gone")
            return
        consts = declared_constants(decl_pf)
        if not consts:
            yield Finding(_METRICS, 1, self.name,
                          "metrics.py lost its name constants")
        for const, (value, lineno) in consts.items():
            ok = (value.startswith("tidb_tpu_") and value == value.lower()
                  and value.endswith(("_total", "_seconds", "_bytes",
                                      "_current", "_depth")))
            if not ok:
                yield Finding(
                    decl_pf.rel, lineno, self.name,
                    f"{const} = {value!r} breaks Prometheus naming: "
                    f"tidb_tpu_ prefix, lowercase, unit suffix "
                    f"_total/_seconds/_bytes (or gauge-level "
                    f"_current/_depth)")
        for pf in forest:
            for call in _metric_calls(pf):
                self.sites += 1
                arg = _name_arg(call)
                if arg is None:
                    yield Finding(pf.rel, call.lineno, self.name,
                                  "metric call without a name argument")
                    continue
                if isinstance(arg, ast.Attribute) and \
                        isinstance(arg.value, ast.Name) and \
                        arg.value.id == "metrics" and arg.attr in consts:
                    continue
                yield Finding(
                    pf.rel, call.lineno, self.name,
                    f"metric name must be a metrics.<CONSTANT> declared "
                    f"in metrics.py, got {ast.dump(arg)[:60]}")
