"""Exception-swallowing discipline for the execution and storage
layers."""

from __future__ import annotations

import ast

from tidb_tpu.lint.engine import Finding, Rule, register_rule

_SCAN_DIRS = ("tidb_tpu/executor/", "tidb_tpu/ops/", "tidb_tpu/store/")


def _is_bare(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True
    t = handler.type
    return isinstance(t, ast.Name) and t.id == "BaseException"


def _reraises(handler: ast.ExceptHandler) -> bool:
    """True if the handler contains a raise that can actually
    propagate: not one swallowed by a nested try, and not one inside a
    nested def that merely defines (doesn't run) it."""

    def scan(stmts) -> bool:
        for s in stmts:
            if isinstance(s, ast.Raise):
                return True
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                continue
            if isinstance(s, ast.Try):
                # raises in the inner body may be caught there — unless
                # the try has no except clauses (pure try/finally, the
                # canonical cleanup-then-raise shape); raises in its
                # handlers / orelse / finally escape the handler
                if not s.handlers and scan(s.body):
                    return True
                if scan(s.orelse) or scan(s.finalbody) or \
                        any(scan(h.body) for h in s.handlers):
                    return True
            elif isinstance(s, (ast.If, ast.While, ast.For,
                                ast.AsyncFor)):
                if scan(s.body) or scan(s.orelse):
                    return True
            elif isinstance(s, (ast.With, ast.AsyncWith)):
                if scan(s.body):
                    return True
            elif isinstance(s, ast.Match):
                if any(scan(c.body) for c in s.cases):
                    return True
        return False

    return scan(handler.body)


@register_rule("bare-except")
class BareExceptRule(Rule):
    """No `except:` / `except BaseException:` that swallows in
    executor/, ops/ and store/.

    A blanket handler in these layers eats KeyboardInterrupt, the
    cooperative-kill QuotaExceededError, and the typed storage errors
    the retry machinery classifies — turning a cancelled query into
    silently-wrong results. Catching BaseException is sanctioned only
    as a cleanup-then-`raise` shape (release a ledger, then re-raise);
    a handler with no raise must name the exceptions it really means.
    """

    fixture_rel = "tidb_tpu/store/__lint_fixture__.py"
    fixture = (
        "def f(work):\n"
        "    try:\n"
        "        work()\n"
        "    except BaseException:\n"
        "        return None\n"
    )

    def check(self, forest):
        for pf in forest:
            if not pf.rel.startswith(_SCAN_DIRS):
                continue
            for node in pf.nodes:
                if not isinstance(node, ast.ExceptHandler):
                    continue
                self.sites += 1
                if _is_bare(node) and not _reraises(node):
                    what = "bare except" if node.type is None else \
                        "except BaseException"
                    yield Finding(
                        pf.rel, node.lineno, self.name,
                        f"{what} without re-raise swallows "
                        f"KeyboardInterrupt, quota cancellation and "
                        f"typed storage errors — name the exceptions, "
                        f"or clean up and `raise`")
