"""Sysvar registry discipline: the tidb_tpu_* namespace is closed, and
the docs track the registry."""

from __future__ import annotations

import ast
import glob
import os
import re

from tidb_tpu.lint.engine import Finding, Rule, register_rule
from tidb_tpu.lint.rules.metrics import declared_constants

_CONFIG = "tidb_tpu/config.py"
_METRICS = "tidb_tpu/metrics.py"
_PREFIX = "tidb_tpu_"


def declared_sysvars(pf) -> dict[str, int]:
    """Keys of the config.py _DEFS registry dict -> lineno."""
    out = {}
    for node in pf.tree.body:
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        else:
            continue
        if len(targets) == 1 and isinstance(targets[0], ast.Name) and \
                targets[0].id == "_DEFS" and \
                isinstance(node.value, ast.Dict):
            for key in node.value.keys:
                if isinstance(key, ast.Constant) and \
                        isinstance(key.value, str):
                    out[key.value] = key.lineno
    return out


@register_rule("sysvar-registry")
class SysvarRegistryRule(Rule):
    """Every tidb_tpu_* string literal in the package is a sysvar
    declared in config.py (or a metric name declared in metrics.py),
    and every declared sysvar appears in the docs.

    The namespace is the user-facing contract: `SET @@tidb_tpu_x` only
    works for registered vars, and a get_var("tidb_tpu_tpyo") raises at
    runtime on exactly the path that was never tested. Conversely a
    sysvar added without documentation is invisible to operators — the
    docs leg doubles as a drift check (docs/*.md + README.md are
    scanned for each declared name).
    """

    fixture = 'FLAG = "tidb_tpu_bogus_knob"\n'
    fixture_support = {
        _CONFIG: '_DEFS = {"tidb_tpu_device": ("bool", 1)}\n',
        _METRICS: 'Q = "tidb_tpu_queries_total"\n',
    }

    def check(self, forest):
        cfg = forest.get(_CONFIG)
        if cfg is None:
            yield Finding(_CONFIG, 1, self.name,
                          "config.py missing from the forest — the "
                          "sysvar registry is gone")
            return
        sysvars = declared_sysvars(cfg)
        if not sysvars:
            yield Finding(_CONFIG, 1, self.name,
                          "config.py lost its _DEFS sysvar registry")
            return
        metrics_pf = forest.get(_METRICS)
        metric_names = set()
        if metrics_pf is not None:
            metric_names = {v for v, _ in
                            declared_constants(metrics_pf).values()}
        known = set(sysvars) | metric_names
        self.sites += len(sysvars)
        for pf in forest:
            if pf.rel in (_CONFIG, _METRICS):
                continue        # the declaration sites themselves
            for node in pf.nodes:
                if isinstance(node, ast.Constant) and \
                        isinstance(node.value, str) and \
                        node.value.startswith(_PREFIX):
                    self.sites += 1
                    if node.value not in known:
                        yield Finding(
                            pf.rel, node.lineno, self.name,
                            f"string literal {node.value!r} is not a "
                            f"sysvar declared in config.py (nor a "
                            f"declared metric name) — register it or "
                            f"rename it out of the tidb_tpu_ namespace")
        yield from self._docs_leg(forest, sysvars)

    def _docs_leg(self, forest, sysvars):
        if forest.root is None:
            return              # synthetic forest: no docs on disk
        corpus = ""
        for path in [os.path.join(forest.root, "README.md"), *sorted(
                glob.glob(os.path.join(forest.root, "docs", "*.md")))]:
            try:
                with open(path, encoding="utf-8") as f:
                    corpus += f.read() + "\n"
            except OSError:
                continue
        for name, lineno in sorted(sysvars.items()):
            if not re.search(re.escape(name) + r"(?![a-z0-9_])", corpus):
                yield Finding(
                    _CONFIG, lineno, self.name,
                    f"sysvar {name!r} is declared but appears nowhere "
                    f"in README.md or docs/*.md — document it (operator "
                    f"surface must track the registry)")
