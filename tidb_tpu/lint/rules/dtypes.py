"""Device dtype discipline: no 64-bit device-array construction in ops/
without an explicit, justified exemption."""

from __future__ import annotations

import ast

from tidb_tpu.lint.engine import Finding, Rule, register_rule

_SCAN_DIR = "tidb_tpu/ops/"
_CONSTRUCT = ("empty", "zeros", "ones", "full", "full_like",
              "zeros_like", "ones_like", "arange", "asarray", "array",
              "astype")
_HOSTILE = ("int64", "float64")


def _hostile_dtype(call: ast.Call) -> str | None:
    """'jnp.int64'/'jnp.float64' if any argument pins a 64-bit device
    dtype, else None. Only jnp-rooted dtypes count: host-side numpy
    int64 lanes are the SQL-exactness representation and never land in
    HBM unconverted."""
    for arg in list(call.args) + [kw.value for kw in call.keywords]:
        for n in ast.walk(arg):
            if isinstance(n, ast.Attribute) and n.attr in _HOSTILE and \
                    isinstance(n.value, ast.Name) and n.value.id == "jnp":
                return f"jnp.{n.attr}"
    return None


@register_rule("dtype-discipline")
class DtypeDisciplineRule(Rule):
    """No jnp.int64 / jnp.float64 array construction in ops/ without an
    exempt tag naming why the 64-bit lanes are required.

    TPUs have no native 64-bit ALU path: int64 lowers to dual-word
    emulation and float64 is software-emulated — both silently multiply
    HBM footprint and kill the vector unit. The kernels that genuinely
    need exactness (scaled-decimal sums, memcomparable key codes,
    bitcast hashing) declare it with a per-site or per-function
    `# lint: exempt[dtype-discipline] reason` so every 64-bit device
    buffer in ops/ is a documented decision, not an accident.
    """

    fixture_rel = "tidb_tpu/ops/__lint_fixture__.py"
    fixture = (
        "import jax.numpy as jnp\n"
        "def slots(n):\n"
        "    return jnp.zeros(n, dtype=jnp.int64)\n"
    )

    def check(self, forest):
        for pf in forest:
            if not pf.rel.startswith(_SCAN_DIR):
                continue
            for node in pf.nodes:
                if not (isinstance(node, ast.Call) and
                        isinstance(node.func, ast.Attribute) and
                        node.func.attr in _CONSTRUCT):
                    continue
                self.sites += 1
                hostile = _hostile_dtype(node)
                if hostile is None:
                    continue
                yield Finding(
                    pf.rel, node.lineno, self.name,
                    f"{node.func.attr} with {hostile}: TPU-hostile "
                    f"64-bit device dtype — downcast/bitcast at the "
                    f"device boundary, or justify it with "
                    f"'# lint: exempt[dtype-discipline] <reason>'")
