"""Wire-path codec discipline (port of tests/test_lint_wire.py)."""

from __future__ import annotations

import ast

from tidb_tpu.lint.engine import Finding, Rule, register_rule

# every module that builds, parses, or routes frames
WIRE_PATH_FILES = (
    "tidb_tpu/store/wire.py",
    "tidb_tpu/store/remote.py",
    "tidb_tpu/store/stream.py",
    "tidb_tpu/store/copr.py",
    "tidb_tpu/store/region_cache.py",
    "tidb_tpu/mockstore/rpc.py",
)

_CODE_LOADERS = ("pickle", "cPickle", "dill", "shelve", "marshal")

# the only functions allowed to call socket .recv(); each must be a
# bounded loop over an explicit byte count
RECV_HELPERS = {"_recv_exact"}

_RECV_HOME = "tidb_tpu/store/remote.py"


def _functions_calling_recv(tree):
    out = {}

    class V(ast.NodeVisitor):
        def __init__(self):
            self.stack = []

        def _visit_func(self, node):
            self.stack.append(node.name)
            self.generic_visit(node)
            self.stack.pop()

        visit_FunctionDef = _visit_func
        visit_AsyncFunctionDef = _visit_func

        def visit_Call(self, node):
            fn = node.func
            if isinstance(fn, ast.Attribute) and fn.attr == "recv":
                name = self.stack[-1] if self.stack else "<module>"
                out.setdefault(name, []).append(node)
            self.generic_visit(node)

    V().visit(tree)
    return out


@register_rule("wire-discipline")
class WireRule(Rule):
    """Wire path stays pickle-free and every socket recv is the bounded
    length-prefixed helper.

    1. No wire-path module imports a code-executing deserializer
       (pickle family): decoding must never execute code. Trusted
       local-disk snapshots live in store/snapshot.py, deliberately OFF
       the wire list.
    2. Every socket `recv` happens inside `_recv_exact`, which loops on
       an explicit remaining-byte count and raises on EOF; ad-hoc
       `sock.recv(65536)` loops are how partial reads become frame
       desync.
    3. store/wire.py (the codec) calls no eval/exec/__import__/compile:
       decode() only constructs registry types.
    """

    min_sites = 1       # at least the _recv_exact recv itself
    fixture_rel = "tidb_tpu/store/wire.py"
    fixture = (
        "import pickle\n"
        "def read_frame(sock, n):\n"
        "    return sock.recv(65536)\n"
    )

    def check(self, forest):
        for rel in WIRE_PATH_FILES:
            pf = forest.get(rel)
            if pf is None:
                # the old walker failed loudly (FileNotFoundError) when
                # a wire module moved; a silent skip would un-enforce
                # the invariants exactly when a refactor renames a file
                yield Finding(
                    rel, 1, self.name,
                    "wire-path module missing from the forest — moved/"
                    "renamed files must update WIRE_PATH_FILES in "
                    "tidb_tpu/lint/rules/wire.py")
                continue
            yield from self._check_imports(pf)
            yield from self._check_recv(pf)
        yield from self._check_helper(forest)
        yield from self._check_codec_closed(forest)

    def _check_imports(self, pf):
        for node in pf.nodes:
            mods = []
            if isinstance(node, ast.Import):
                mods = [a.name for a in node.names]
            elif isinstance(node, ast.ImportFrom) and node.module:
                mods = [node.module]
            for mod in mods:
                self.sites += 1
                if mod.split(".")[0] in _CODE_LOADERS:
                    yield Finding(
                        pf.rel, node.lineno, self.name,
                        f"imports {mod}: wire-path modules must stay "
                        f"pickle-free (trusted on-disk snapshots belong "
                        f"in store/snapshot.py)")

    def _check_recv(self, pf):
        for fname, calls in _functions_calling_recv(pf.tree).items():
            for call in calls:
                self.sites += 1
                if fname not in RECV_HELPERS:
                    yield Finding(
                        pf.rel, call.lineno, self.name,
                        f"socket recv in {fname!r}, outside the bounded "
                        f"helper(s) {sorted(RECV_HELPERS)} — all frame "
                        f"reads go through the length-prefixed "
                        f"_recv_exact loop")
                elif not call.args or isinstance(call.args[0],
                                                 ast.Constant):
                    yield Finding(
                        pf.rel, call.lineno, self.name,
                        "recv must take the exact remaining byte count, "
                        "never no-arg / constant-buffer style")

    def _check_helper(self, forest):
        pf = forest.get(_RECV_HOME)
        if pf is None:
            return
        helper = None
        for node in ast.walk(pf.tree):
            if isinstance(node, ast.FunctionDef) and \
                    node.name == "_recv_exact":
                helper = node
                break
        if helper is None:
            yield Finding(pf.rel, 1, self.name,
                          "store/remote.py lost _recv_exact")
            return
        self.sites += 1
        has_loop = any(isinstance(n, ast.While) for n in ast.walk(helper))
        raises = any(isinstance(n, ast.Raise) for n in ast.walk(helper))
        if not (has_loop and raises):
            yield Finding(pf.rel, helper.lineno, self.name,
                          "_recv_exact must loop to the requested count "
                          "and raise on EOF (no silent short read)")

    def _check_codec_closed(self, forest):
        pf = forest.get("tidb_tpu/store/wire.py")
        if pf is None:
            return
        for node in ast.walk(pf.tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Name) and \
                    node.func.id in ("eval", "exec", "__import__",
                                     "compile"):
                yield Finding(pf.rel, node.lineno, self.name,
                              f"codec calls {node.func.id} — decode() "
                              f"only constructs registry types")
