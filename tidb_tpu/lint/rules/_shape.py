"""Statement-shape recognizers shared by more than one rule — one
implementation so pairing semantics cannot drift between rules."""

from __future__ import annotations

import ast

# statements allowed between a resource charge and the try/finally
# that settles it: bindings that cannot re-enter the resource
TRIVIAL_STMTS = (ast.Assign, ast.AnnAssign, ast.AugAssign)


def release_try_follows(stmts, j, releases,
                        trivial=TRIVIAL_STMTS) -> bool:
    """The sanctioned sequence shape: after skipping `trivial`
    statements from stmts[j], the next statement is a `try` whose
    finalbody satisfies `releases` (a predicate over the statement
    list — lock-discipline looks for `.release()`, paired-resource for
    ledger `release(host=/device=)` calls)."""
    while j < len(stmts) and isinstance(stmts[j], trivial):
        j += 1
    return j < len(stmts) and isinstance(stmts[j], ast.Try) and \
        releases(stmts[j].finalbody)
