"""Failpoint registry discipline: the declared table and the eval sites
track each other (same shape as the metric-names rule)."""

from __future__ import annotations

import ast

from tidb_tpu.lint.engine import Finding, Rule, register_rule

_FAILPOINT = "tidb_tpu/util/failpoint.py"


def declared_points(pf) -> dict[str, int]:
    """String keys of failpoint.py's module-level REGISTRY dict
    -> lineno."""
    out = {}
    for node in pf.tree.body:
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        else:
            continue
        if len(targets) == 1 and isinstance(targets[0], ast.Name) and \
                targets[0].id == "REGISTRY" and \
                isinstance(node.value, ast.Dict):
            for key in node.value.keys:
                if isinstance(key, ast.Constant) and \
                        isinstance(key.value, str):
                    out[key.value] = key.lineno
    return out


def _eval_calls(pf):
    """failpoint.eval(...) / failpoint.enable(...) / .disable(...)
    where the receiver is the failpoint module. enable/disable sites
    matter too: arming a typo'd name in package code would raise only
    on the path that was never tested."""
    for node in pf.nodes:
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if isinstance(fn, ast.Attribute) and \
                fn.attr in ("eval", "enable", "disable") and \
                isinstance(fn.value, ast.Name) and \
                fn.value.id == "failpoint":
            yield node, fn.attr


@register_rule("failpoint-discipline")
class FailpointDisciplineRule(Rule):
    """Every failpoint.eval()/enable()/disable() call site names a
    point declared in failpoint.REGISTRY, as a string literal; and
    every declared point is evaluated by at least one in-tree seam.

    The registry table is the operator-facing fault catalog
    (docs/ROBUSTNESS.md, GET /failpoint): an eval of an undeclared
    name is a seam chaos tooling can never arm (it silently never
    fires), and a declared name with no eval site is catalog fiction —
    an operator arming it would believe a fault was injected when
    nothing can fire it.
    """

    min_sites = 8       # the instrumented seams across the device plane
    fixture = (
        "from tidb_tpu.util import failpoint\n"
        "def f():\n"
        "    failpoint.eval('not/declared')\n"
    )
    fixture_support = {
        _FAILPOINT: 'REGISTRY = {"hbm/fill": "device cache upload"}\n',
    }

    def check(self, forest):
        decl_pf = forest.get(_FAILPOINT)
        if decl_pf is None:
            yield Finding(_FAILPOINT, 1, self.name,
                          "util/failpoint.py missing from the forest — "
                          "the failpoint registry is gone")
            return
        declared = declared_points(decl_pf)
        if not declared:
            yield Finding(_FAILPOINT, 1, self.name,
                          "failpoint.py lost its REGISTRY table")
            return
        evaluated: set[str] = set()
        for pf in forest:
            if pf.rel == _FAILPOINT:
                continue    # the registry module's own helpers
            for call, kind in _eval_calls(pf):
                self.sites += 1
                arg = call.args[0] if call.args else None
                if not (isinstance(arg, ast.Constant) and
                        isinstance(arg.value, str)):
                    yield Finding(
                        pf.rel, call.lineno, self.name,
                        f"failpoint.{kind} must name its point with a "
                        f"string literal (computed names defeat the "
                        f"registry audit)")
                    continue
                if arg.value not in declared:
                    yield Finding(
                        pf.rel, call.lineno, self.name,
                        f"failpoint.{kind}({arg.value!r}) names a point "
                        f"not declared in failpoint.REGISTRY — declare "
                        f"it (one table, docs/ROBUSTNESS.md catalog)")
                    continue
                if kind == "eval":
                    evaluated.add(arg.value)
        for name, lineno in sorted(declared.items()):
            if name not in evaluated:
                yield Finding(
                    _FAILPOINT, lineno, self.name,
                    f"failpoint {name!r} is declared but no in-tree "
                    f"seam evaluates it — dead catalog entry (arming "
                    f"it can never fire)")
