"""Device synchronization discipline: the ported block_until_ready ban
(tests/test_lint_sync.py) plus its generalization to every other way of
forcing a device value onto the host."""

from __future__ import annotations

import ast

from tidb_tpu.lint.astutil import enclosing_map, root_name
from tidb_tpu.lint.engine import Finding, Rule, register_rule

_PROFILER = "tidb_tpu/runtime_stats.py"


@register_rule("hot-path-sync")
class HotPathSyncRule(Rule):
    """block_until_ready appears nowhere in the package except
    runtime_stats.py (the gated profiling path).

    The dispatch-ahead pipeline's whole win is that superchunk k+1
    transfers while k executes; ONE accidental block_until_ready on the
    hot path serializes every dispatch and silently erases the overlap.
    Syncs at operator output boundaries use jax.device_get, which is
    visible in review precisely because it returns the data. Matched as
    Name, Attribute, or string constant, so aliased imports and
    getattr(jax, "block_until_ready") are all caught.
    """

    min_sites = 1       # the sanctioned profiling site must still exist
    fixture = "def f(arr):\n    return arr.block_until_ready()\n"

    def check(self, forest):
        for pf in forest:
            for node in pf.nodes:
                hit = (isinstance(node, ast.Attribute) and
                       node.attr == "block_until_ready") or \
                      (isinstance(node, ast.Name) and
                       node.id == "block_until_ready") or \
                      (isinstance(node, ast.Constant) and
                       node.value == "block_until_ready")
                if not hit:
                    continue
                if pf.rel == _PROFILER:
                    self.sites += 1     # sanctioned: profiling owns it
                    continue
                yield Finding(
                    pf.rel, node.lineno, self.name,
                    "block_until_ready on the hot path (use "
                    "jax.device_get at an output boundary, or "
                    "runtime_stats.device_call for gated profiling)")


@register_rule("device-sync")
class DeviceSyncRule(Rule):
    """Device values are materialized on the host only in finalize()
    helpers (or the gated profiler): no stray jax.device_get / .item()
    / np.asarray on device arrays mid-pipeline.

    Every kernel is split into async dispatch() and blocking finalize()
    so transfers overlap execution; a device_get (or an .item() /
    np.asarray over a jnp value, which device-transfers implicitly)
    anywhere else reintroduces a serialization point invisible to the
    pipeline. Matched: any spelling of device_get, plus .item()/
    np.asarray/np.array whose receiver/argument is syntactically rooted
    at jnp or jax. Sanctioned: functions named finalize, and
    runtime_stats.py.
    """

    min_sites = 1       # the finalize() device_gets must still exist
    fixture = (
        "import jax\n"
        "def step(pending):\n"
        "    return jax.device_get(pending)\n"
    )

    def check(self, forest):
        for pf in forest:
            if pf.rel == _PROFILER:
                continue
            enclosing = None    # built on first hit: most files have none
            for node in pf.nodes:
                site = self._sync_kind(node)
                if site is None:
                    continue
                self.sites += 1
                if enclosing is None:
                    enclosing = enclosing_map(pf.tree)
                func = enclosing(node.lineno)
                if func.split(".")[-1] == "finalize":
                    continue            # sanctioned output boundary
                yield Finding(
                    pf.rel, node.lineno, self.name,
                    f"{site} outside a finalize() helper forces a "
                    f"device->host sync mid-pipeline — move it to the "
                    f"kernel's finalize() output boundary")

    @staticmethod
    def _sync_kind(node) -> str | None:
        if isinstance(node, ast.Attribute) and node.attr == "device_get":
            return "device_get"
        if isinstance(node, ast.Name) and node.id == "device_get":
            return "device_get"
        if isinstance(node, ast.Constant) and node.value == "device_get":
            return "device_get"
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Attribute) and fn.attr == "item" and \
                    root_name(fn.value) in ("jnp", "jax"):
                return ".item() on a device value"
            if isinstance(fn, ast.Attribute) and \
                    fn.attr in ("asarray", "array") and \
                    isinstance(fn.value, ast.Name) and \
                    fn.value.id == "np" and node.args and \
                    root_name(node.args[0]) in ("jnp", "jax"):
                return "np.asarray on a device value"
        return None
