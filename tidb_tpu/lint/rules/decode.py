"""Decode discipline: full-column dictionary decodes live only in
registered late-materialize helpers."""

from __future__ import annotations

import ast

from tidb_tpu.lint.astutil import call_name, enclosing_map
from tidb_tpu.lint.engine import Finding, Rule, register_rule

# the hot operator layer the encoded path flows through; decode-shaped
# gathers anywhere here silently rot encoded execution back to wide
# vectors (store/device_cache.py's per-delta encode loops are the
# ENCODE direction and out of scope)
_SCOPES = ("tidb_tpu/ops/",)
_EXTRA_FILES = ("tidb_tpu/store/copr.py",)

_DECODER = "decode_codes"


def _registry() -> set[tuple[str, str]]:
    """(file, function) pairs sanctioned to decode whole columns —
    read from the live module so the registry and the rule cannot
    drift (a stale entry is itself a finding)."""
    from tidb_tpu.ops.encoded import LATE_MATERIALIZE
    return set(LATE_MATERIALIZE)


@register_rule("decode-discipline")
class DecodeDisciplineRule(Rule):
    """In ops/ and store/copr.py, full-column dictionary decode —
    calling decode_codes, or gathering a dictionary by a codes array —
    happens only inside helpers registered in
    ops/encoded.LATE_MATERIALIZE (or behind a justified tag).

    Encoded execution (`tidb_tpu_encoded_exec`) only pays off while the
    operator layer stays in code space end-to-end: one convenience
    decode in a kernel wrapper quietly re-materializes the wide vectors
    the whole path exists to avoid, and nothing fails — queries just
    get slower. Matched shapes: (a) any call to decode_codes (THE
    audited decoder) outside a registered late-materialize helper;
    (b) a comprehension gathering `values[c] for c in codes` where the
    container or iterable name is dictionary-shaped (contains 'values'
    or 'dict') — the hand-rolled form of the same decode. Registered
    helpers that stop existing are reported (registry staleness).
    """

    min_sites = 1       # decode_codes' own registered body must exist
    fixture_rel = "tidb_tpu/ops/__lint_fixture__.py"
    fixture = (
        "def serve(values, codes):\n"
        "    return [values[c] for c in codes]\n"
    )

    def check(self, forest):
        registry = _registry()
        seen_funcs: set[tuple[str, str]] = set()
        seen_files: set[str] = set()
        for pf in forest:
            seen_files.add(pf.rel)
            if not (pf.rel.startswith(_SCOPES) or
                    pf.rel in _EXTRA_FILES):
                continue
            enclosing = enclosing_map(pf.tree)
            for node in pf.nodes:
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    seen_funcs.add((pf.rel, node.name))
                    if (pf.rel, node.name) in registry:
                        # the audited decoder itself: the site the
                        # min_sites floor guards (scope drift that
                        # loses it must fail loudly)
                        self.sites += 1
                kind = self._decode_kind(node)
                if kind is None:
                    continue
                self.sites += 1
                fn = (enclosing(node.lineno) or "").split(".")[-1]
                if (pf.rel, fn) in registry:
                    continue        # sanctioned late-materialize helper
                yield Finding(
                    pf.rel, node.lineno, self.name,
                    f"full-column dictionary decode ({kind}) outside a "
                    f"registered late-materialize helper — decode at "
                    f"the operator-output finalize boundary "
                    f"(ops/encoded.decode_codes) or register the "
                    f"helper in ops/encoded.LATE_MATERIALIZE")
        # registry staleness: a sanctioned helper that stopped existing
        # must not silently exempt future code at its old name. Only
        # judged for files this forest actually parsed — fixture
        # forests see a handful of synthetic files
        for rel, fn in sorted(registry):
            if rel in seen_files and (rel, fn) not in seen_funcs:
                yield Finding(
                    rel, 0, self.name,
                    f"LATE_MATERIALIZE registers {fn}() which no longer "
                    f"exists in {rel} — prune the registry entry")

    @staticmethod
    def _decode_kind(node) -> str | None:
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name and name.split(".")[-1] == _DECODER:
                return f"{_DECODER} call"
            return None
        if isinstance(node, (ast.ListComp, ast.GeneratorExp)):
            if len(node.generators) != 1:
                return None
            gen = node.generators[0]
            if not isinstance(gen.target, ast.Name):
                return None
            target = gen.target.id
            for sub in ast.walk(node.elt):
                if (isinstance(sub, ast.Subscript) and
                        isinstance(sub.value, ast.Name) and
                        isinstance(sub.slice, ast.Name) and
                        sub.slice.id == target and
                        any(k in sub.value.id.lower()
                            for k in ("values", "dict"))):
                    return "dictionary gather comprehension"
        return None
