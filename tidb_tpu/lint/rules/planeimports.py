"""Device-plane import discipline: package code addresses the unified
plane directly; tidb_tpu.parallel exists only as compatibility shims."""

from __future__ import annotations

import ast

from tidb_tpu.lint.engine import Finding, Rule, register_rule

_SHIM_PKG = "tidb_tpu/parallel/"
_LEGACY = "tidb_tpu.parallel"
# the unified plane modules package code imports instead; counting
# their in-tree import sites is the vacuity floor — a refactor that
# renames the plane out from under this rule fails loudly instead of
# hollowing it out
_PLANE = ("tidb_tpu.devplane", "tidb_tpu.ops.meshagg",
          "tidb_tpu.ops.meshjoin", "tidb_tpu.ops.meshshuffle")


@register_rule("no-parallel-import")
class NoParallelImportRule(Rule):
    """Package code (outside the tidb_tpu/parallel/ shims themselves)
    never imports tidb_tpu.parallel.

    The unified device plane — tidb_tpu/devplane.py plus
    ops/meshagg.py / ops/meshjoin.py / ops/meshshuffle.py — is the real
    module set; the parallel package is a frozen compatibility surface
    kept for historical import paths (tests, external callers). A
    package-internal import of a shim re-couples new code to the
    retired layer, hides the true dependency graph, and quietly
    resurrects the split-world execution paths this refactor removed.
    """

    min_sites = 4   # the plane modules really are imported in-package
    fixture = (
        "from tidb_tpu.parallel import MeshAggKernel\n"
        "def run(mesh, ch):\n"
        "    return MeshAggKernel(mesh, None, [], [])(ch)\n"
    )

    def check(self, forest):
        for pf in forest:
            in_shim = pf.rel.startswith(_SHIM_PKG)
            for node in pf.nodes:
                cands = self._candidates(node)
                if not cands:
                    continue
                legacy = [c for c in cands
                          if c == _LEGACY or
                          c.startswith(_LEGACY + ".")]
                if legacy:
                    self.sites += 1
                    if in_shim:
                        continue    # the shims may reference themselves
                    yield Finding(
                        pf.rel, node.lineno, self.name,
                        f"import of the legacy {_LEGACY} shim package "
                        f"from package code — import the unified device "
                        f"plane (tidb_tpu.devplane, or tidb_tpu.ops."
                        f"meshagg / meshjoin / meshshuffle) directly")
                elif any(c in _PLANE for c in cands):
                    self.sites += 1     # vacuity floor: plane imports

    @staticmethod
    def _candidates(node) -> list:
        """Dotted module paths an import statement could bind: for
        ``from a.b import c`` both ``a.b`` and ``a.b.c`` (the latter
        catches ``from tidb_tpu import parallel``)."""
        if isinstance(node, ast.Import):
            return [a.name for a in node.names]
        if isinstance(node, ast.ImportFrom) and node.module:
            return [node.module] + \
                [node.module + "." + a.name for a in node.names]
        return []
