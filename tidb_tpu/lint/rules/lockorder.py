"""Whole-program lock-order analysis: no cycles in the acquisition
order graph (the static half of a lock-order sanitizer)."""

from __future__ import annotations

from tidb_tpu.lint.engine import Finding, Rule, register_rule
from tidb_tpu.lint.flow import flow_of

_DOC = "docs/CONCURRENCY.md"


@register_rule("lock-order")
class LockOrderRule(Rule):
    """No cycle in the whole-program lock acquisition order graph.

    Every `threading.Lock/RLock/Condition` construction site is
    auto-registered under a static name (module + attribute). Nested
    `with lock:` blocks and acquire/release sequences contribute order
    edges, propagated interprocedurally: a call made while holding L
    adds L -> every lock the callee may transitively acquire. A cycle
    in the resulting graph is a potential deadlock the moment two
    threads walk it from different entry points — exactly what
    concurrent serving (ROADMAP item 1) will do to today's ~40
    independently-invented locks. Self-edges on non-reentrant locks
    (a plain Lock re-acquired on the same thread) deadlock without any
    second thread and are reported too; RLock/Condition self-edges are
    reentrancy, not bugs. The runtime half is util/lockorder.py, which
    replays observed acquisition orders against this DAG under
    tests/test_race_harness.py.

    The docs leg keeps docs/CONCURRENCY.md's lock inventory in sync
    with the registry: every discovered lock must be listed there.
    """

    min_sites = 40      # in-tree acquisition sites the walk must visit

    fixture = (
        "import threading\n"
        "_a = threading.Lock()\n"
        "_b = threading.Lock()\n"
        "def f():\n"
        "    with _a:\n"
        "        with _b:\n"
        "            pass\n"
        "def g():\n"
        "    with _b:\n"
        "        with _a:\n"
        "            pass\n"
    )

    def check(self, forest):
        fl = flow_of(forest)
        for facts in fl.facts.values():
            self.sites += len(facts.acquisitions)
        for locks, proof in fl.cycles():
            a, b, rel, lineno, note = proof[0]
            if len(locks) == 1:
                msg = (f"lock {locks[0]} may be re-acquired while "
                       f"already held ({note}) — a non-reentrant lock "
                       f"self-deadlocks here; use an RLock or restructure")
            else:
                chain = " -> ".join(locks + [locks[0]])
                sites = "; ".join(
                    f"{s}->{d} at {r}:{ln} ({n})"
                    for s, d, r, ln, n in proof[:4])
                msg = (f"lock-order cycle {chain}: two threads entering "
                       f"from different edges deadlock. Edges: {sites}")
            yield Finding(rel, lineno, self.name, msg)
        yield from self._docs_leg(forest, fl)

    def _docs_leg(self, forest, fl):
        if forest.root is None:
            return              # synthetic forest: no docs on disk
        import os
        path = os.path.join(forest.root, _DOC)
        try:
            with open(path, encoding="utf-8") as f:
                corpus = f.read()
        except OSError:
            corpus = ""
        for site in fl.registry.sites:
            if site.name not in corpus:
                yield Finding(
                    site.rel, site.lineno, self.name,
                    f"lock {site.name} ({site.kind}) is missing from "
                    f"{_DOC}'s inventory table — the registry and the "
                    f"doc must not drift")
