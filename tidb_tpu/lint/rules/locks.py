"""Lock acquisition discipline for the concurrency-bearing layers."""

from __future__ import annotations

import ast

from tidb_tpu.lint.engine import Finding, Rule, register_rule

SCAN = ("tidb_tpu/memtrack.py", "tidb_tpu/metrics.py",
        "tidb_tpu/session/", "tidb_tpu/store/")

_SIMPLE = (ast.Assign, ast.AnnAssign, ast.AugAssign)


def _releases(stmts) -> bool:
    for s in stmts:
        for n in ast.walk(s):
            if isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute) and \
                    n.func.attr == "release":
                return True
    return False


def _acquires(expr):
    for n in ast.walk(expr):
        if isinstance(n, ast.Call) and \
                isinstance(n.func, ast.Attribute) and \
                n.func.attr == "acquire":
            yield n


@register_rule("lock-discipline")
class LockDisciplineRule(Rule):
    """No bare .acquire() outside `with` / try-finally in memtrack.py,
    metrics.py, session/ and store/.

    A lock or semaphore acquired without an immediately-following
    try/finally release leaks on the first exception between acquire
    and release — and in these layers (the memory-tracker tree, the
    metrics registry, session statement lifecycle, the connection-pool
    semaphores) a leaked permit deadlocks the process quietly. The
    sanctioned shape is `with lock:` or `x.acquire()` followed (bar
    trivial assignments) by `try: ... finally: x.release()`; an acquire
    already inside a try whose finally releases also passes.
    """

    fixture_rel = "tidb_tpu/store/__lint_fixture__.py"
    fixture = (
        "import threading\n"
        "_lock = threading.Lock()\n"
        "def f(work):\n"
        "    _lock.acquire()\n"
        "    work()\n"
        "    _lock.release()\n"
    )

    def check(self, forest):
        for pf in forest:
            if not (pf.rel in SCAN[:2] or pf.rel.startswith(SCAN[2:])):
                continue
            yield from self._block(pf, pf.tree.body, False)

    def _finding(self, pf, node):
        return Finding(
            pf.rel, node.lineno, self.name,
            "bare .acquire() outside with/try-finally — a raise before "
            "the matching release leaks the permit; acquire, then "
            "`try: ... finally: release()` (or use `with`)")

    def _header(self, pf, exprs, protected):
        for expr in exprs:
            if expr is None:
                continue
            for call in _acquires(expr):
                self.sites += 1
                if not protected:
                    yield self._finding(pf, call)

    def _block(self, pf, stmts, protected):
        for i, stmt in enumerate(stmts):
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                yield from self._block(pf, stmt.body, False)
            elif isinstance(stmt, ast.Try):
                prot = protected or _releases(stmt.finalbody)
                yield from self._block(pf, stmt.body, prot)
                for h in stmt.handlers:
                    yield from self._block(pf, h.body, prot)
                yield from self._block(pf, stmt.orelse, prot)
                yield from self._block(pf, stmt.finalbody, protected)
            elif isinstance(stmt, (ast.If, ast.While)):
                yield from self._header(pf, [stmt.test], protected)
                yield from self._block(pf, stmt.body, protected)
                yield from self._block(pf, stmt.orelse, protected)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                yield from self._header(pf, [stmt.iter], protected)
                yield from self._block(pf, stmt.body, protected)
                yield from self._block(pf, stmt.orelse, protected)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                yield from self._header(
                    pf, [it.context_expr for it in stmt.items], protected)
                yield from self._block(pf, stmt.body, protected)
            elif isinstance(stmt, ast.Match):
                yield from self._header(pf, [stmt.subject], protected)
                for case in stmt.cases:
                    yield from self._block(pf, case.body, protected)
            elif isinstance(stmt, (ast.Expr, ast.Assign, ast.AnnAssign)) \
                    and isinstance(getattr(stmt, "value", None),
                                   ast.Call) and \
                    isinstance(stmt.value.func, ast.Attribute) and \
                    stmt.value.func.attr == "acquire":
                # canonical statement forms: `x.acquire()` and
                # `got = x.acquire(timeout=...)` ahead of try/finally
                self.sites += 1
                if not (protected or
                        self._release_try_follows(stmts, i + 1)):
                    yield self._finding(pf, stmt.value)
            else:
                yield from self._header(pf, [stmt], protected)

    @staticmethod
    def _release_try_follows(stmts, j) -> bool:
        """Skip trivial assignments, then require try/finally-release."""
        while j < len(stmts) and isinstance(stmts[j], _SIMPLE):
            j += 1
        return j < len(stmts) and isinstance(stmts[j], ast.Try) and \
            _releases(stmts[j].finalbody)
