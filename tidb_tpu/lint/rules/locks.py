"""Lock acquisition discipline for the concurrency-bearing layers."""

from __future__ import annotations

import ast

from tidb_tpu.lint.engine import Finding, Rule, register_rule
from tidb_tpu.lint.rules._shape import release_try_follows

SCAN = ("tidb_tpu/memtrack.py", "tidb_tpu/metrics.py",
        "tidb_tpu/session/", "tidb_tpu/store/")


def _releases(stmts) -> bool:
    for s in stmts:
        for n in ast.walk(s):
            if isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute) and \
                    n.func.attr == "release":
                return True
    return False


def _acquires(expr):
    for n in ast.walk(expr):
        if isinstance(n, ast.Call) and \
                isinstance(n.func, ast.Attribute) and \
                n.func.attr == "acquire":
            yield n


_WAITERS = ("wait", "wait_for", "notify", "notify_all")


def _condition_names(pf) -> set:
    """Attributes / globals assigned `threading.Condition(...)` in this
    file — the receivers whose wait/notify calls the rule checks (an
    Event.wait or Thread.join must not false-positive)."""
    out = set()
    for n in pf.nodes:
        if isinstance(n, (ast.Assign, ast.AnnAssign)) and \
                isinstance(getattr(n, "value", None), ast.Call):
            fn = n.value.func
            name = fn.attr if isinstance(fn, ast.Attribute) else \
                fn.id if isinstance(fn, ast.Name) else None
            if name != "Condition":
                continue
            targets = n.targets if isinstance(n, ast.Assign) \
                else [n.target]
            for t in targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
                elif isinstance(t, ast.Attribute):
                    out.add(t.attr)
    return out


def _receiver_name(expr) -> str | None:
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return None


@register_rule("lock-discipline")
class LockDisciplineRule(Rule):
    """No bare .acquire() outside `with` / try-finally, and no
    Condition wait/notify outside `with cond:`, in memtrack.py,
    metrics.py, session/ and store/.

    A lock or semaphore acquired without an immediately-following
    try/finally release leaks on the first exception between acquire
    and release — and in these layers (the memory-tracker tree, the
    metrics registry, session statement lifecycle, the connection-pool
    semaphores) a leaked permit deadlocks the process quietly. The
    sanctioned shape is `with lock:` or `x.acquire()` followed (bar
    trivial assignments) by `try: ... finally: x.release()` — the
    assign form `got = x.acquire(timeout=...)` ahead of the try/finally
    included; an acquire already inside a try whose finally releases
    also passes. RLocks are held to the same shape: reentrancy forgives
    double-acquire, not a leak on the exception path.

    The Condition leg: `cond.wait()` / `cond.notify()` /
    `cond.notify_all()` on a `threading.Condition` constructed in the
    same file must sit lexically inside `with cond:` — calling either
    without the underlying lock raises RuntimeError at the worst
    possible time (under load, on the signaling path).
    """

    fixture_rel = "tidb_tpu/store/__lint_fixture__.py"
    fixture = (
        "import threading\n"
        "_lock = threading.Lock()\n"
        "def f(work):\n"
        "    _lock.acquire()\n"
        "    work()\n"
        "    _lock.release()\n"
    )

    def check(self, forest):
        for pf in forest:
            if not (pf.rel in SCAN[:2] or pf.rel.startswith(SCAN[2:])):
                continue
            self._conds = _condition_names(pf)
            yield from self._block(pf, pf.tree.body, False, ())

    def _finding(self, pf, node):
        return Finding(
            pf.rel, node.lineno, self.name,
            "bare .acquire() outside with/try-finally — a raise before "
            "the matching release leaks the permit; acquire, then "
            "`try: ... finally: release()` (or use `with`)")

    def _wait_finding(self, pf, call):
        return Finding(
            pf.rel, call.lineno, self.name,
            f"Condition.{call.func.attr}() outside `with` of its "
            f"condition — raises RuntimeError('cannot ... un-acquired "
            f"lock') on the signaling path; wrap in `with cond:`")

    def _header(self, pf, exprs, protected, withs):
        for expr in exprs:
            if expr is None:
                continue
            for call in _acquires(expr):
                self.sites += 1
                if not protected:
                    yield self._finding(pf, call)
            for n in ast.walk(expr):
                if isinstance(n, ast.Call) and \
                        isinstance(n.func, ast.Attribute) and \
                        n.func.attr in _WAITERS and \
                        _receiver_name(n.func.value) in self._conds:
                    self.sites += 1
                    if ast.dump(n.func.value) not in withs:
                        yield self._wait_finding(pf, n)

    def _block(self, pf, stmts, protected, withs):
        for i, stmt in enumerate(stmts):
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                yield from self._block(pf, stmt.body, False, ())
            elif isinstance(stmt, ast.Try):
                prot = protected or _releases(stmt.finalbody)
                yield from self._block(pf, stmt.body, prot, withs)
                for h in stmt.handlers:
                    yield from self._block(pf, h.body, prot, withs)
                yield from self._block(pf, stmt.orelse, prot, withs)
                yield from self._block(pf, stmt.finalbody, protected,
                                       withs)
            elif isinstance(stmt, (ast.If, ast.While)):
                yield from self._header(pf, [stmt.test], protected, withs)
                yield from self._block(pf, stmt.body, protected, withs)
                yield from self._block(pf, stmt.orelse, protected, withs)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                yield from self._header(pf, [stmt.iter], protected, withs)
                yield from self._block(pf, stmt.body, protected, withs)
                yield from self._block(pf, stmt.orelse, protected, withs)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                yield from self._header(
                    pf, [it.context_expr for it in stmt.items],
                    protected, withs)
                inner = withs + tuple(
                    ast.dump(it.context_expr) for it in stmt.items)
                yield from self._block(pf, stmt.body, protected, inner)
            elif isinstance(stmt, ast.Match):
                yield from self._header(pf, [stmt.subject], protected,
                                        withs)
                for case in stmt.cases:
                    yield from self._block(pf, case.body, protected,
                                           withs)
            elif isinstance(stmt, (ast.Expr, ast.Assign, ast.AnnAssign)) \
                    and isinstance(getattr(stmt, "value", None),
                                   ast.Call) and \
                    isinstance(stmt.value.func, ast.Attribute) and \
                    stmt.value.func.attr == "acquire":
                # canonical statement forms: `x.acquire()` and
                # `got = x.acquire(timeout=...)` ahead of try/finally
                self.sites += 1
                if not (protected or
                        self._release_try_follows(stmts, i + 1)):
                    yield self._finding(pf, stmt.value)
            else:
                yield from self._header(pf, [stmt], protected, withs)

    @staticmethod
    def _release_try_follows(stmts, j) -> bool:
        """Skip trivial assignments, then require try/finally-release
        (the shared sequence-shape recognizer, rules/_shape.py)."""
        return release_try_follows(stmts, j, _releases)
