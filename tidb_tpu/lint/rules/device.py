"""Device-plane discipline rules: donation-safety, cache-key
completeness, and retrace-hazard — the three checks of the
tidb_tpu/lint/flow/device.py dataflow pass (see that module's
docstring for the hazard classes; docs/PERF.md "Device-plane
discipline" for the contracts they enforce)."""

from __future__ import annotations

import ast

from tidb_tpu.lint.engine import Finding, Rule, register_rule
from tidb_tpu.lint.flow.device import (COERCIONS, SHAPERS, _MESH_ROOT,
                                       _call_name, _is_const,
                                       _is_mesh_fp, _root_names,
                                       device_flow_of)

_BUILTINS = frozenset({
    "self", "len", "max", "min", "sorted", "sum", "range", "zip",
    "enumerate", "list", "tuple", "dict", "set", "frozenset", "id",
    "getattr", "setattr", "hasattr", "isinstance", "print", "abs",
    "any", "all", "repr", "str", "type", "iter", "next", "map",
    "filter", "reversed", "slice", "None", "True", "False",
    "int", "float", "bool", "bytes", "object", "Exception",
    "ValueError", "RuntimeError", "KeyError",
})


def _mod_info(df, rel: str) -> tuple:
    """(scope_names, mutable_globals) for a module. scope_names are
    code references — imports (external ones included; the callgraph
    only indexes in-forest targets), function/class defs, builtins.
    mutable_globals are lowercase module-level assignment targets:
    reads of THOSE from a traced body are trace-time state."""
    cache = getattr(df, "_mod_info_cache", None)
    if cache is None:
        cache = df._mod_info_cache = {}
    hit = cache.get(rel)
    if hit is not None:
        return hit
    g = df.graph
    scope = set(g._imports.get(rel, {}))
    scope |= {n for (r, n) in g._top if r == rel}
    scope |= {c for (r, c) in g._classes if r == rel}
    scope |= _BUILTINS
    mutable: set = set()
    pf = next((p for p in df.forest if p.rel == rel), None)
    if pf is not None:
        for node in pf.nodes:
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                for alias in node.names:
                    scope.add(alias.asname or
                              alias.name.split(".")[0])
        for stmt in pf.tree.body:
            targets = []
            if isinstance(stmt, ast.Assign):
                targets = stmt.targets
            elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
                targets = [stmt.target]
            for t in targets:
                if isinstance(t, ast.Name) and not _is_const(t.id):
                    mutable.add(t.id)
        mutable -= scope
    out = (scope, mutable)
    cache[rel] = out
    return out


def _bound_names(fi) -> set:
    """Names bound in `fi` or its lexical closure chain: params,
    assignment targets, loop/with/comprehension targets."""
    out: set = set()
    cur = fi
    while cur is not None:
        a = cur.node.args
        for arg in (a.posonlyargs + a.args + a.kwonlyargs):
            out.add(arg.arg)
        if a.vararg:
            out.add(a.vararg.arg)
        if a.kwarg:
            out.add(a.kwarg.arg)
        for node in ast.walk(cur.node):
            if isinstance(node, ast.Name) and \
                    isinstance(node.ctx, (ast.Store, ast.Del)):
                out.add(node.id)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef, ast.ClassDef)):
                out.add(node.name)
        cur = cur.parent
    return out


# ---------------------------------------------------------------------------
# donation-safety
# ---------------------------------------------------------------------------

@register_rule("donation-safety")
class DonationSafetyRule(Rule):
    """A buffer donated to a traced program must have no live use after
    the dispatch on any path.

    `donate_argnums` hands the operand's device memory to XLA for
    reuse as program scratch/output: any later read — directly,
    through an alias, through a closure capture, or by an enclosing
    retry loop re-dispatching the same binding — is a read-after-free
    that silently corrupts on TPU while passing every CPU test. The
    donated operand must also skip the per-chunk device memo
    (`device_put_chunk(..., memo=False)`): a memoized donated buffer
    is a dangling cache entry. The PR 8 overflow-retry shape (re-
    dispatching *non-donated* device-resident lanes off the pending
    token) is recognized as sanctioned — donation tracking applies
    only to donating programs."""

    min_sites = 3
    fixture = (
        "import jax\n"
        "from tidb_tpu.ops import runtime\n"
        "\n"
        "class K:\n"
        "    def __init__(self):\n"
        "        self._jitd = None\n"
        "\n"
        "    def _kernel(self, cols, n):\n"
        "        return cols\n"
        "\n"
        "    def dispatch(self, chunk):\n"
        "        cols, _d = runtime.device_put_chunk(chunk)\n"
        "        if self._jitd is None:\n"
        "            self._jitd = jax.jit(self._kernel,\n"
        "                                 donate_argnums=(0,))\n"
        "        pending = self._jitd(cols, 4)\n"
        "        total = cols[0].sum()\n"
        "        return pending, total\n"
    )

    def check(self, forest):
        df = device_flow_of(forest)
        for d in df.dispatches:
            if not d.site.donating:
                continue
            for pos in d.site.donate:
                if pos >= len(d.call.args):
                    continue
                self.sites += 1
                yield from self._check_donated(df, d, d.call.args[pos])

    def _check_donated(self, df, d, arg):
        if not isinstance(arg, ast.Name):
            yield Finding(
                d.rel, d.line, self.name,
                f"donated operand `{ast.unparse(arg)}` is not a "
                f"locally-owned name — donation requires exclusive "
                f"ownership the analysis can see")
            return
        fi = d.func
        if fi is None:
            return
        names = {arg.id} | self._aliases(fi, arg.id)
        yield from self._check_memo(d, fi, arg.id)
        if self._returns_dispatch(df, d, fi):
            # `return jitd(cols, ...)`: the function exits at the
            # dispatch, so reads on sibling branches (the non-donating
            # twin the line after) can never see the donated buffer —
            # the sanctioned ops/hashagg dispatch shape.
            return
        end = d.call.end_lineno or d.line
        call_nodes = {id(n) for n in ast.walk(d.call)}
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Name) and node.id in names and \
                    isinstance(node.ctx, ast.Load) and \
                    id(node) not in call_nodes and node.lineno > end:
                yield Finding(
                    d.rel, node.lineno, self.name,
                    f"`{node.id}` read after its buffer was donated to "
                    f"`{d.site.fn_name}` at line {d.line} — "
                    f"read-after-free on hardware that honors donation")
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)) and \
                    node is not fi.node:
                if any(isinstance(n, ast.Name) and n.id in names and
                       isinstance(n.ctx, ast.Load)
                       for n in ast.walk(node)):
                    yield Finding(
                        d.rel, node.lineno, self.name,
                        f"closure `{node.name}` captures donated "
                        f"buffer `{arg.id}` — it may outlive the "
                        f"dispatch at line {d.line}")
        yield from self._check_loop(df, d, fi, names)

    def _returns_dispatch(self, df, d, fi) -> bool:
        pm = df._parent_map(d.rel)
        cur = pm.get(id(d.call))
        while cur is not None and cur is not fi.node:
            if isinstance(cur, ast.Return):
                return True
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return False
            cur = pm.get(id(cur))
        return False

    def _aliases(self, fi, name: str) -> set:
        out: set = set()
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id == name:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
        return out

    def _check_loop(self, df, d, fi, names: set):
        """An enclosing loop whose next iteration re-dispatches a
        binding created OUTSIDE the loop re-reads freed memory."""
        pm = df._parent_map(d.rel)
        cur = pm.get(id(d.call))
        loop = None
        while cur is not None and cur is not fi.node:
            if isinstance(cur, (ast.While, ast.For)):
                loop = cur
                break
            cur = pm.get(id(cur))
        if loop is None:
            return
        for node in ast.walk(loop):
            if isinstance(node, ast.Name) and node.id in names and \
                    isinstance(node.ctx, ast.Store):
                return      # rebound every iteration: each trip owns
                #             a fresh buffer
            if isinstance(node, ast.For) and \
                    isinstance(node.target, ast.Name) and \
                    node.target.id in names:
                return
        yield Finding(
            d.rel, d.line, self.name,
            f"retry loop re-dispatches donated buffer bound outside "
            f"the loop — the second iteration reads memory freed by "
            f"the first (donate only per-iteration bindings, or reuse "
            f"non-donated lanes like ops/join.py's pending token)")

    def _check_memo(self, d, fi, name: str):
        """The donated transfer must opt out of the chunk device memo."""
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Assign) or \
                    not isinstance(node.value, ast.Call):
                continue
            if _call_name(node.value) != "device_put_chunk":
                continue
            binds = any(
                (isinstance(t, ast.Name) and t.id == name) or
                (isinstance(t, ast.Tuple) and
                 any(isinstance(e, ast.Name) and e.id == name
                     for e in t.elts))
                for t in node.targets)
            if binds and not any(kw.arg == "memo"
                                 for kw in node.value.keywords):
                yield Finding(
                    d.rel, node.value.lineno, self.name,
                    "donated transfer uses the default memoizing "
                    "device_put_chunk — a memoized donated buffer is "
                    "read-after-free; pass memo=not donate")


# ---------------------------------------------------------------------------
# cache-key
# ---------------------------------------------------------------------------

@register_rule("cache-key")
class CacheKeyRule(Rule):
    """Everything a traced kernel body reads must be an operand or be
    folded into the executable's cache key.

    A kernel object whose traced body closes over `self` state, a
    config/sysvar, or a mutable module global is specialized on that
    value at trace time; if the value is not part of the
    `FingerprintCache`/program-memo key, a later call with different
    state silently reuses the stale executable. Checks: (a) every ctor
    argument feeding traced-read `self` attributes appears in the
    cache key (via `plan_fingerprint` args, the key tuple, or the
    executor/mesh cache-put helper); (b) every key includes
    `devplane.mesh_fingerprint` (the PR 18 plane-identity contract);
    (c) traced bodies read no config vars or mutable module globals;
    (d) kernel classes owning instance-bound programs are constructed
    only under a kernel cache; (e) profiler registrations
    distinguish the same components the cache key does."""

    min_sites = 15
    fixture = (
        "import jax\n"
        "from tidb_tpu.ops import runtime\n"
        "from tidb_tpu import config, devplane\n"
        "\n"
        "class K:\n"
        "    def __init__(self, exprs, width):\n"
        "        self.exprs = exprs\n"
        "        self.width = width\n"
        "        self._jit = jax.jit(self._kernel)\n"
        "\n"
        "    def _kernel(self, cols, n):\n"
        "        lim = config.direct_agg_slots()\n"
        "        return (cols, self.width, lim)\n"
        "\n"
        "_KERNELS = runtime.FingerprintCache(8)\n"
        "\n"
        "def kernel_for(exprs, width):\n"
        "    fp = runtime.plan_fingerprint(None, exprs, [])\n"
        "    key = (fp, devplane.mesh_fingerprint(process=True))\n"
        "    def make():\n"
        "        return K(exprs, width)\n"
        "    return _KERNELS.get_or_create(key, make)\n"
    )

    def check(self, forest):
        df = device_flow_of(forest)
        classes = self._kernel_classes(df)
        caching = self._caching_functions(df, classes)
        cached_ctors = set()
        for F, info in caching.values():
            yield from self._check_caching_fn(df, F, info, classes,
                                              cached_ctors)
        yield from self._check_uncached_ctors(df, classes, cached_ctors,
                                              caching)
        yield from self._check_traced_state(df, classes)

    # -- kernel classes ------------------------------------------------------

    def _kernel_classes(self, df) -> dict:
        """(rel, cls) -> {"attrs": {attr: read line}, "init": {attr:
        set of ctor param roots}, "params": [ctor params],
        "instance_bound": bool}."""
        out: dict = {}
        for site in df.sites:
            for fn in site.fns:
                if fn.cls is None:
                    continue
                key = (fn.rel, fn.cls)
                info = out.setdefault(
                    key, {"attrs": {}, "init": {}, "params": [],
                          "instance_bound": False, "fns": {}})
                info["fns"][fn.key] = fn
                if site.cls == fn.cls and site.store[0] in (
                        "attr", "dict", "return"):
                    info["instance_bound"] = True
        for (rel, cls), info in out.items():
            seen_bodies = set()
            for fn in list(info["fns"].values()):
                for body in df.reachable(fn):
                    if body.cls == cls and body.rel == rel and \
                            body.key not in seen_bodies:
                        seen_bodies.add(body.key)
                        self._attr_reads(body, info["attrs"])
            init = df.graph._method.get((rel, cls, "__init__"))
            if init is not None:
                a = init.node.args
                params = [p.arg for p in a.args[1:]]
                info["params"] = params
                env = {p: {p} for p in params}
                self._init_closure(df, init, env, info["init"], 0)
        return out

    def _attr_reads(self, fi, attrs: dict) -> None:
        callee_ids = set()
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute):
                callee_ids.add(id(node.func))
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Attribute) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id == "self" and \
                    isinstance(node.ctx, ast.Load) and \
                    id(node) not in callee_ids and \
                    not node.attr.startswith("__"):
                attrs.setdefault(node.attr, node.lineno)

    def _init_closure(self, df, fi, env: dict, out: dict,
                      depth: int) -> None:
        """self.X assignments of __init__ (helpers inlined to depth 3):
        X -> ctor-param roots of its value."""
        if depth > 3:
            return

        def roots(expr) -> set:
            r: set = set()
            for n in _root_names(expr):
                r |= env.get(n, set())
            for node in ast.walk(expr):
                if isinstance(node, ast.Call):
                    cn = _call_name(node)
                    if _is_mesh_fp(node) or (
                            isinstance(node.func, ast.Attribute) and
                            isinstance(node.func.value, ast.Name) and
                            node.func.value.id == "devplane"):
                        r.add(_MESH_ROOT)
                    _ = cn
            return r

        for node in ast.walk(fi.node):
            if isinstance(node, ast.Assign):
                val_roots = roots(node.value)
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self":
                        out.setdefault(t.attr, set()).update(val_roots)
                    elif isinstance(t, ast.Name):
                        env.setdefault(t.id, set()).update(val_roots)
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id == "self":
                helper = df.graph._method.get(
                    (fi.rel, fi.cls, node.func.attr))
                if helper is not None and \
                        node.func.attr != "__init__":
                    a = helper.node.args
                    hparams = [p.arg for p in a.args[1:]]
                    henv = {}
                    for i, arg in enumerate(node.args):
                        if i < len(hparams):
                            henv[hparams[i]] = roots(arg)
                    for kw in node.keywords:
                        if kw.arg in hparams:
                            henv[kw.arg] = roots(kw.value)
                    self._init_closure(df, helper, henv, out, depth + 1)

    # -- caching functions ---------------------------------------------------

    def _caching_functions(self, df, classes) -> dict:
        """Functions that own a kernel cache: they call
        `.get_or_create(key, ...)`, a cache helper whose body both
        fingerprints the mesh and stores into a module dict, or — when
        they construct a kernel class themselves — memoize inline into
        a keyed dict (the executor shuffle-kernel shape). Scoped to
        modules that hold traced sites or functions that construct a
        kernel class; unrelated registries (the profiler's own row
        cache) are not kernel caches."""
        site_rels = {s.rel for s in df.sites}
        out: dict = {}
        for fi in df.graph.funcs.values():
            if fi.parent is not None:
                continue
            makes_kernel = any(
                isinstance(n, ast.Call) and
                self._ctor_class(df, fi, n, classes) is not None
                for n in ast.walk(fi.node))
            if fi.rel not in site_rels and not makes_kernel:
                continue
            entries = []        # (kind, node)
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                if isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "get_or_create" and node.args:
                    entries.append(("get_or_create", node))
                else:
                    hit = df.graph.resolve_call(node, fi.rel, fi)
                    if hit is not None and hit.rel == fi.rel and \
                            hit is not fi and \
                            self._is_cache_helper(hit):
                        entries.append(("helper", node))
            if makes_kernel and not entries:
                for node in ast.walk(fi.node):
                    if isinstance(node, ast.Assign):
                        for t in node.targets:
                            if isinstance(t, ast.Subscript):
                                entries.append(("inline", t))
            if entries:
                out[fi.key] = (fi, entries)
        return out

    def _is_cache_helper(self, fi) -> bool:
        has_mesh = any(isinstance(n, ast.Call) and _is_mesh_fp(n)
                       for n in ast.walk(fi.node))
        if not has_mesh:
            return False
        for node in ast.walk(fi.node):
            if isinstance(node, (ast.Assign,)):
                for t in node.targets:
                    if isinstance(t, ast.Subscript):
                        return True
            if isinstance(node, ast.Subscript) and \
                    isinstance(node.ctx, ast.Store):
                return True
        return False

    def _resolve_roots(self, fi, expr, memo=None, depth=0) -> set:
        """Transitive bare-name roots of `expr` within `fi`'s body
        (locals resolved through their bindings; mesh-fingerprint
        calls contribute the mesh pseudo-root)."""
        if memo is None:
            memo = {}
        out: set = set()
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                if _is_mesh_fp(node) or (
                        isinstance(node.func, ast.Attribute) and
                        node.func.attr == "active_mesh"):
                    out.add(_MESH_ROOT)
        if depth > 4:
            return out | _root_names(expr)
        for name in _root_names(expr):
            if name in memo:
                out |= memo[name]
                continue
            memo[name] = {name}
            binding = None
            for node in ast.walk(fi.node):
                if isinstance(node, ast.Assign) and any(
                        isinstance(t, ast.Name) and t.id == name
                        for t in node.targets):
                    binding = node.value
                    break
            if binding is not None:
                # a local is an alias: its roots are the underlying
                # sources, not the alias name itself
                resolved = self._resolve_roots(fi, binding, memo,
                                               depth + 1)
                memo[name] = resolved or {name}
            out |= memo[name]
        return out

    def _check_caching_fn(self, df, F, entries, classes, cached_ctors):
        covered: set = set()
        mesh_ok = False
        for kind, call in entries:
            self.sites += 1
            if kind == "get_or_create":
                key_roots = self._resolve_roots(F, call.args[0])
                covered |= key_roots
                if _MESH_ROOT in key_roots:
                    mesh_ok = True
            elif kind == "inline":
                key_roots = self._resolve_roots(F, call.slice)
                covered |= key_roots
                if _MESH_ROOT in key_roots:
                    mesh_ok = True
            else:
                for arg in call.args:
                    covered |= self._resolve_roots(F, arg)
                mesh_ok = True      # helper bodies fingerprint the mesh
        if not mesh_ok:
            yield Finding(
                F.rel, entries[0][1].lineno, self.name,
                f"kernel cache in {F.qualname}() does not fold "
                f"devplane.mesh_fingerprint into its key — a mesh "
                f"reshape would reuse executables compiled for another "
                f"plane")
        mod_names, _ = _mod_info(df, F.rel)
        # constructions of kernel classes inside F (and its closures)
        for node in ast.walk(F.node):
            if not isinstance(node, ast.Call):
                continue
            cls_key = self._ctor_class(df, F, node, classes)
            if cls_key is None:
                continue
            cached_ctors.add(id(node))
            info = classes[cls_key]
            bindings = self._ctor_bindings(info["params"], node)
            for attr, line in sorted(info["attrs"].items()):
                self.sites += 1
                roots = info["init"].get(attr)
                if roots is None:
                    continue        # attr not ctor-derived: flagged by
                    #                 _check_traced_state if stateful
                for param in sorted(roots):
                    if param == _MESH_ROOT:
                        if not mesh_ok:
                            yield Finding(
                                F.rel, node.lineno, self.name,
                                f"{cls_key[1]}.{attr} derives from the "
                                f"device plane but the cache key has "
                                f"no mesh fingerprint")
                        continue
                    arg_expr = bindings.get(param)
                    if arg_expr is None:
                        continue    # default value: constant
                    need = {n for n in self._resolve_roots(F, arg_expr)
                            if n not in mod_names and not _is_const(n)
                            and n != _MESH_ROOT}
                    missing = need - covered
                    if missing:
                        yield Finding(
                            F.rel, node.lineno, self.name,
                            f"traced body of {cls_key[1]} reads "
                            f"self.{attr} (line {line}) but ctor arg "
                            f"{param!r} <- {', '.join(sorted(missing))} "
                            f"is not folded into the cache key")
        # profiler registrations must key on covered components
        var_covered = {n for n in covered
                       if n != _MESH_ROOT and not _is_const(n)}
        for node in ast.walk(F.node):
            if isinstance(node, ast.Call) and \
                    _call_name(node) == "profile" and \
                    len(node.args) >= 2 and \
                    isinstance(node.args[0], ast.Constant):
                if isinstance(node.args[1], ast.Constant):
                    continue    # explicit unfingerprinted row ("~")
                self.sites += 1
                fp_roots = self._resolve_roots(F, node.args[1])
                if var_covered and not (fp_roots & (
                        var_covered | {_MESH_ROOT})):
                    yield Finding(
                        F.rel, node.lineno, self.name,
                        "profiler registration does not distinguish "
                        "the cache key's components — profile rows "
                        "from different executables would merge")

    def _ctor_class(self, df, F, call, classes):
        if isinstance(call.func, ast.Name):
            rel = df.graph._classes.get((F.rel, call.func.id))
            if rel is not None and (rel, call.func.id) in classes:
                return (rel, call.func.id)
        hit = df.graph.resolve_call(call, F.rel, F)
        if hit is not None and hit.cls is not None and \
                hit.node.name == "__init__" and \
                (hit.rel, hit.cls) in classes:
            return (hit.rel, hit.cls)
        return None

    def _ctor_bindings(self, params, call) -> dict:
        out = {}
        for i, arg in enumerate(call.args):
            if i < len(params):
                out[params[i]] = arg
        for kw in call.keywords:
            if kw.arg in params:
                out[kw.arg] = kw.value
        return out

    def _check_uncached_ctors(self, df, classes, cached_ctors, caching):
        """Instance-bound traced programs must be built under a kernel
        cache — a per-statement construction recompiles per query."""
        bound = {k for k, v in classes.items() if v["instance_bound"]}
        if not bound:
            return
        names = {cls: key for key, v in classes.items()
                 for (rel, cls) in [key] if key in bound}
        for pf in df.forest:
            for node in pf.nodes:
                if not isinstance(node, ast.Call) or \
                        id(node) in cached_ctors:
                    continue
                if not isinstance(node.func, ast.Name) or \
                        node.func.id not in names:
                    continue
                key = names[node.func.id]
                if df.graph._classes.get(
                        (pf.rel, node.func.id)) != key[0] and \
                        pf.rel != key[0]:
                    # name does not resolve to the kernel class here
                    if node.func.id not in df.graph._imports.get(
                            pf.rel, {}):
                        continue
                self.sites += 1
                fi = df.enclosing_function(pf.rel, node)
                if fi is not None and (fi.key in caching or (
                        fi.parent is not None and
                        fi.parent.key in caching)):
                    continue
                if fi is not None and fi.cls == key[1]:
                    continue        # class's own plumbing
                yield Finding(
                    pf.rel, node.lineno, self.name,
                    f"{node.func.id} owns instance-bound traced "
                    f"programs but is constructed outside a kernel "
                    f"cache — every construction recompiles")

    def _check_traced_state(self, df, classes):
        """Config reads and mutable module globals inside traced
        bodies."""
        seen: set = set()
        scanned: set = set()
        for site in df.sites:
            for fn in site.fns:
                if fn.key in scanned:
                    continue
                scanned.add(fn.key)
                self.sites += 1
                _, mod_mutable = _mod_info(df, fn.rel)
                bound = _bound_names(fn)
                for node in ast.walk(fn.node):
                    if isinstance(node, ast.Call) and \
                            isinstance(node.func, ast.Attribute) and \
                            isinstance(node.func.value, ast.Name) and \
                            node.func.value.id == "config":
                        k = (fn.rel, node.lineno)
                        if k not in seen:
                            seen.add(k)
                            yield Finding(
                                fn.rel, node.lineno, self.name,
                                f"config.{node.func.attr}() read "
                                f"inside traced body "
                                f"{fn.qualname} — the executable "
                                f"snapshots the value at trace time; "
                                f"pass it as a ctor arg folded into "
                                f"the cache key")
                    elif isinstance(node, ast.Name) and \
                            isinstance(node.ctx, ast.Load) and \
                            node.id in mod_mutable and \
                            node.id not in bound:
                        k = (fn.rel, node.lineno, node.id)
                        if k not in seen:
                            seen.add(k)
                            yield Finding(
                                fn.rel, node.lineno, self.name,
                                f"traced body {fn.qualname} reads "
                                f"module global `{node.id}` — trace-"
                                f"time state the cache key cannot "
                                f"see")


# ---------------------------------------------------------------------------
# retrace-hazard
# ---------------------------------------------------------------------------

@register_rule("retrace-hazard")
class RetraceHazardRule(Rule):
    """Dispatch shapes and static arguments must be bounded, and traced
    bodies must not coerce traced values to Python.

    jit caches one executable per (shapes, dtypes, static args): raw
    data-sized operands compile per input length (~300ms stalls the
    profiler plane measures after the fact), so operands must flow
    through the pow2 superchunk bucketing (`runtime.bucket_size` /
    `pad_column` / `device_put_chunk`) and program-memo keys must be
    bucketed (the `meshjoin._stage2_jits[bucket]` bounded-dict shape
    is sanctioned). Static arguments must be hashable. Inside a traced
    body, `float()`/`int()`/`bool()`/`.item()`/`np.asarray` force a
    trace-time sync or constant-fold — host coercions belong in
    finalize, after `jax.device_get`."""

    min_sites = 10
    fixture = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "\n"
        "_sort = jax.jit(jnp.sort)\n"
        "\n"
        "def device_sort(data):\n"
        "    return np.asarray(_sort(data))\n"
        "\n"
        "def kernel_body(cols, n):\n"
        "    return bool(cols[0].sum())\n"
        "\n"
        "_K = jax.jit(kernel_body)\n"
    )

    def check(self, forest):
        df = device_flow_of(forest)
        inline = set()          # fns reachable from traced bodies:
        for site in df.sites:   # calls there are inlined traces, not
            for fn in site.fns:  # dispatch boundaries
                inline |= {f.key for f in df.reachable(fn)}
        for d in df.dispatches:
            self.sites += 1
            if d.func is not None and d.func.key in inline:
                continue
            yield from self._check_shapes(d)
            yield from self._check_memo_key(df, d)
            yield from self._check_static_args(d)
        yield from self._check_coercions(df)

    def _check_shapes(self, d):
        fi = d.func
        if fi is None:
            return
        if any(isinstance(n, ast.Call) and _call_name(n) in SHAPERS
               for n in ast.walk(fi.node)):
            return
        a = fi.node.args
        params = {p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)}
        for arg in d.call.args:
            if isinstance(arg, ast.Name) and arg.id in params:
                yield Finding(
                    d.rel, d.line, self.name,
                    f"`{arg.id}` dispatched to `{d.site.fn_name}` at "
                    f"its raw size — one executable per input shape; "
                    f"route it through runtime.bucket_size pow2 "
                    f"padding")

    def _check_memo_key(self, df, d):
        if d.via_factory is None:
            return
        fi = d.func
        for arg in d.via_factory.args:
            self.sites += 1
            if not self._bounded_key(fi, arg, 0):
                yield Finding(
                    d.rel, d.line, self.name,
                    f"program-memo key `{ast.unparse(arg)}` is not "
                    f"bucketed — an unbounded key set compiles (and "
                    f"pins) one program per distinct value")

    def _bounded_key(self, fi, expr, depth: int) -> bool:
        if isinstance(expr, ast.Starred):
            expr = expr.value
        if isinstance(expr, ast.Constant):
            return True
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                cn = _call_name(node) or ""
                if cn in SHAPERS or "bucket" in cn:
                    return True
        if isinstance(expr, ast.Attribute):
            return "cap" in expr.attr or "bucket" in expr.attr
        if isinstance(expr, (ast.Tuple, ast.List)):
            return all(self._bounded_key(fi, e, depth + 1)
                       for e in expr.elts)
        if isinstance(expr, ast.Name) and fi is not None:
            a = fi.node.args
            if expr.id in {p.arg for p in
                           (a.posonlyargs + a.args + a.kwonlyargs)}:
                return True     # caller's discipline, checked there
            if depth > 3:
                return False
            for node in ast.walk(fi.node):
                if isinstance(node, ast.Assign) and any(
                        isinstance(t, ast.Name) and t.id == expr.id
                        for t in node.targets):
                    return self._bounded_key(fi, node.value, depth + 1)
        return False

    def _check_static_args(self, d):
        names = d.site.static_names
        nums = d.site.static_nums
        if not names and not nums:
            return
        exprs = [a for i, a in enumerate(d.call.args) if i in nums]
        exprs += [kw.value for kw in d.call.keywords if kw.arg in names]
        for e in exprs:
            self.sites += 1
            if isinstance(e, (ast.List, ast.Set, ast.Dict)):
                yield Finding(
                    d.rel, d.line, self.name,
                    f"unhashable {type(e).__name__.lower()} literal "
                    f"passed at a static position of "
                    f"`{d.site.fn_name}` — jit's cache key requires "
                    f"hashable statics")

    def _check_coercions(self, df):
        seen: set = set()
        for site in df.sites:
            for body in df.traced_bodies(site):
                if body.key in seen:
                    continue
                seen.add(body.key)
                self.sites += 1
                yield from self._scan_body(body)

    def _scan_body(self, fi):
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if isinstance(fn, ast.Name) and fn.id in COERCIONS and \
                    node.args and not all(
                        isinstance(a, ast.Constant) or
                        all(_is_const(r) for r in _root_names(a))
                        for a in node.args):
                yield Finding(
                    fi.rel, node.lineno, self.name,
                    f"{fn.id}() on a traced value inside "
                    f"{fi.qualname} forces a trace-time sync — host "
                    f"coercions belong in finalize")
            elif isinstance(fn, ast.Attribute) and fn.attr == "item" \
                    and not node.args:
                yield Finding(
                    fi.rel, node.lineno, self.name,
                    f".item() inside traced body {fi.qualname} — "
                    f"device sync per element")
            elif isinstance(fn, ast.Attribute) and \
                    isinstance(fn.value, ast.Name) and \
                    (fn.value.id, fn.attr) in (
                        ("np", "asarray"), ("np", "array"),
                        ("numpy", "asarray"), ("numpy", "array"),
                        ("jax", "device_get")) and \
                    not (node.args and
                         isinstance(node.args[0], ast.Constant)):
                yield Finding(
                    fi.rel, node.lineno, self.name,
                    f"{fn.value.id}.{fn.attr}() inside traced body "
                    f"{fi.qualname} materializes on host mid-trace")
