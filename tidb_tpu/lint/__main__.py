"""CLI front end: ``python -m tidb_tpu.lint``.

Exit-code contract (CI / pre-commit, scripts/lint.sh):
    0  every selected rule ran clean
    1  findings (printed one per line: file:line: [rule] message)
    2  usage error (unknown rule, bad flags)

``--json`` swaps the human lines for one machine-readable document
(stable schema, pinned by tests/test_lint.py::test_cli_json_smoke):

    {"version": 1, "clean": bool, "files": N, "rules": [...],
     "findings": [{"file", "line", "rule", "message"}, ...],
     "timing": {"parse_ms", "total_ms", "parse_calls",
                "rule_ms": {rule: ms}}}

The exit-code contract is identical in both modes.
"""

from __future__ import annotations

import argparse
import json
import sys

from tidb_tpu.lint import REGISTRY, run


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tidb_tpu.lint",
        description="Project static analysis: every rule over one "
                    "shared parse of the tidb_tpu package.")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    parser.add_argument("--rule", action="append", metavar="NAME",
                        help="run only this rule (repeatable)")
    parser.add_argument("-q", "--quiet", action="store_true",
                        help="findings only, no timing report")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable report on stdout "
                             "(same exit codes)")
    args = parser.parse_args(argv)

    if args.list_rules:
        width = max(len(n) for n in REGISTRY)
        for name, cls in REGISTRY.items():
            print(f"{name:<{width}}  {cls.doc()}")
        return 0

    try:
        report = run(rules=args.rule)
    except KeyError as e:
        print(e.args[0], file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps({
            "version": 1,
            "clean": report.clean,
            "files": report.files,
            "rules": report.rules_run,
            "findings": [
                {"file": f.file, "line": f.line, "rule": f.rule,
                 "message": f.message} for f in report.findings],
            "timing": {
                "parse_ms": round(report.parse_time * 1e3, 1),
                "total_ms": round(report.total_time * 1e3, 1),
                "parse_calls": report.parse_calls,
                "rule_ms": {n: round(t * 1e3, 1)
                            for n, t in report.rule_times.items()},
            },
        }, indent=1))
        return 1 if report.findings else 0

    for finding in report.findings:
        print(finding)
    if not args.quiet:
        slowest = sorted(report.rule_times.items(),
                         key=lambda kv: -kv[1])[:3]
        print(f"{len(report.rules_run)} rule(s) over "
              f"{report.files} files: {len(report.findings)} finding(s) "
              f"in {report.total_time * 1e3:.0f} ms "
              f"(parse {report.parse_time * 1e3:.0f} ms; slowest "
              + ", ".join(f"{n} {t * 1e3:.0f} ms" for n, t in slowest)
              + ")")
    return 1 if report.findings else 0


if __name__ == "__main__":
    sys.exit(main())
