"""CLI front end: ``python -m tidb_tpu.lint``.

Exit-code contract (CI / pre-commit):
    0  every selected rule ran clean
    1  findings (printed one per line: file:line: [rule] message)
    2  usage error (unknown rule, bad flags)
"""

from __future__ import annotations

import argparse
import sys

from tidb_tpu.lint import REGISTRY, run


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tidb_tpu.lint",
        description="Project static analysis: every rule over one "
                    "shared parse of the tidb_tpu package.")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    parser.add_argument("--rule", action="append", metavar="NAME",
                        help="run only this rule (repeatable)")
    parser.add_argument("-q", "--quiet", action="store_true",
                        help="findings only, no timing report")
    args = parser.parse_args(argv)

    if args.list_rules:
        width = max(len(n) for n in REGISTRY)
        for name, cls in REGISTRY.items():
            print(f"{name:<{width}}  {cls.doc()}")
        return 0

    try:
        report = run(rules=args.rule)
    except KeyError as e:
        print(e.args[0], file=sys.stderr)
        return 2

    for finding in report.findings:
        print(finding)
    if not args.quiet:
        slowest = sorted(report.rule_times.items(),
                         key=lambda kv: -kv[1])[:3]
        print(f"{len(report.rules_run)} rule(s) over "
              f"{report.files} files: {len(report.findings)} finding(s) "
              f"in {report.total_time * 1e3:.0f} ms "
              f"(parse {report.parse_time * 1e3:.0f} ms; slowest "
              + ", ".join(f"{n} {t * 1e3:.0f} ms" for n, t in slowest)
              + ")")
    return 1 if report.findings else 0


if __name__ == "__main__":
    sys.exit(main())
