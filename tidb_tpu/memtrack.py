"""Hierarchical per-query memory tracking: the util/memory.Tracker analogue.

Reference: the reference's util/memory — every byte a statement holds is
attributed to a tree of Trackers rooted at the session, `mem-quota-query`
bounds the per-statement total, and OOM actions (spill, then cancel) fire
when the root crosses it.

Here every tracker keeps TWO ledgers — host bytes (chunk buffers, hash
builds, agg state, sort runs, superchunk staging) and device bytes
(padded superchunk uploads, donated kernel buffers, device-resident join
builds) — because on a TPU serving stack HBM is the scarcer resource and
the two must not launder into one number. Consumption rolls up the
parent chain:

    operator node  ->  statement root  ->  session root  ->  SERVER

The statement root carries the `tidb_tpu_mem_quota_query` quota and the
ordered OOM-action chain: spill actions registered by operators that can
shed memory (executor/extsort.SpillSorter) fire first; when none remain
(or none helped) the query cancels — `on_cancel` flips the session's
cooperative-kill flag so concurrent coprocessor workers stop too, and
QuotaExceededError surfaces as ER_MEM_EXCEED_QUOTA.

Lock discipline: consume/release take one per-node lock at a time while
walking up (never nested), and OOM actions fire AFTER every lock is
dropped, so a spill action may itself consume/release re-entrantly.
Cost is a few lock/unlock pairs per *batch* (not per row) — noise next
to the 64k-row chunk work it accounts.

The thread-local `tracking()` context installs a statement root exactly
like runtime_stats.collecting installs the stats collector; the
coprocessor fan-out re-installs it inside pool workers so storage-side
allocations credit the issuing reader.
"""

from __future__ import annotations

import contextlib
import threading

from tidb_tpu import metrics

__all__ = ["MemTracker", "QuotaExceededError", "SERVER", "tracking",
           "suspended", "current", "session_root", "statement_root",
           "server_node", "op_node", "consume", "release", "device_scope",
           "track_to", "register_spill",
           "chunk_bytes", "result_bytes", "device_put_bytes",
           "sessions_snapshot"]


class QuotaExceededError(Exception):
    """Statement memory over tidb_tpu_mem_quota_query with no spill
    action left — surfaced to clients as ER_MEM_EXCEED_QUOTA."""


class MemTracker:
    """One node of the tracking tree. host/device are the two ledgers;
    peaks are monotone high-water marks. quota (statement roots only,
    0 = unlimited) bounds host+device."""

    __slots__ = ("label", "parent", "quota", "on_cancel", "_mu",
                 "host", "device", "host_peak", "device_peak",
                 "_actions", "_firing", "_cancel_msg", "_nodes",
                 "children", "fault_degraded")

    def __init__(self, label: str, parent: "MemTracker | None" = None,
                 quota: int = 0, on_cancel=None):
        self.label = label
        self.parent = parent            # guarded-by: _mu
        self.quota = quota
        self.on_cancel = on_cancel
        self._mu = threading.Lock()
        self.host = 0                   # guarded-by: _mu
        self.device = 0                 # guarded-by: _mu
        self.host_peak = 0              # guarded-by: _mu
        self.device_peak = 0            # guarded-by: _mu
        self._actions: list = []        # guarded-by: _mu  (OOM spills)
        self._firing = False            # guarded-by: _mu
        self._cancel_msg: str | None = None   # guarded-by: _mu
        # statement roots only: sched.degrade_statement latched this
        # statement onto the host path after a retried device fault
        self.fault_degraded = False
        # id(plan) -> (plan, tracker)
        self._nodes: dict[int, tuple] = {}    # guarded-by: _mu
        self.children: dict[int, "MemTracker"] = {}   # guarded-by: _mu

    # -- the two ledgers -----------------------------------------------------

    def consume(self, host: int = 0, device: int = 0) -> None:
        """Charge bytes to this node and every ancestor; fires the
        OOM-action chain of the nearest quota-carrying ancestor AFTER all
        locks are released (actions may consume/release re-entrantly).

        The next-parent pointer is read UNDER the node's lock: detach()
        snapshots the counters and severs the parent link in one locked
        region, so a walker that charged a node before the detach also
        reaches the old parent (whose release then cancels out), and one
        that charged after stops at the severed link — either way the
        ancestor ledgers stay exact under races with straggling
        coprocessor workers."""
        node = self
        fire = None
        while node is not None:
            with node._mu:
                node.host += host
                node.device += device
                if node.host > node.host_peak:
                    node.host_peak = node.host
                if node.device > node.device_peak:
                    node.device_peak = node.device
                if fire is None and node.quota and \
                        node.host + node.device > node.quota:
                    fire = node
                nxt = node.parent
            node = nxt
        if fire is not None:
            fire._over_quota()

    def release(self, host: int = 0, device: int = 0) -> None:
        node = self
        while node is not None:
            with node._mu:
                node.host -= host
                node.device -= device
                nxt = node.parent
            node = nxt

    def total(self) -> int:
        return self.host + self.device

    def peak_total(self) -> int:
        return self.host_peak + self.device_peak

    # -- OOM action chain ----------------------------------------------------

    def add_spill_action(self, fn) -> None:
        """Register a memory-shedding callback (fires in quota order,
        re-armed: a spiller that frees bytes may fire again on a later
        episode). The callback must be safe to invoke from ANY thread
        that consumes into this tree."""
        with self._mu:
            self._actions.append(fn)

    def remove_spill_action(self, fn) -> None:
        with self._mu:
            try:
                self._actions.remove(fn)
            except ValueError:
                pass

    def _over_quota(self) -> None:
        with self._mu:
            if self._cancel_msg is not None:
                # cancel already latched: stragglers (cop workers still
                # draining) re-raise WITHOUT re-counting the event or
                # re-running the spill chain — one cancelled statement is
                # one cancel, however many threads hit the wall
                msg = self._cancel_msg
            elif self._firing:     # an action on another frame is already
                return             # shedding; let it finish
            else:
                msg = None
                self._firing = True
                actions = list(self._actions)
        if msg is not None:
            raise QuotaExceededError(msg)
        try:
            for act in actions:
                with self._mu:
                    before = self.host + self.device
                    if before <= self.quota:
                        return
                try:
                    act()
                except Exception:  # noqa: BLE001 - a broken spiller must
                    pass           # not mask the cancel below
                with self._mu:
                    freed = before - (self.host + self.device)
                if freed > 0:
                    # count only spills that actually shed bytes: an
                    # already-drained sorter invoked in vain is not an
                    # OOM-action event
                    metrics.counter(metrics.MEM_QUOTA_EXCEEDED,
                                    {"action": "spill"})
            with self._mu:
                total = self.host + self.device
                if total <= self.quota:
                    return
                msg = (f"Out Of Memory Quota! query tracked {total} "
                       f"bytes > tidb_tpu_mem_quota_query {self.quota}")
                self._cancel_msg = msg
            metrics.counter(metrics.MEM_QUOTA_EXCEEDED,
                            {"action": "cancel"})
            if self.on_cancel is not None:
                # on_cancel(msg) runs BEFORE the raise so the session can
                # remember why it was killed: when this fires on a pool
                # worker, the session thread usually trips the
                # cooperative-kill check before the worker's exception
                # drains, and must still surface the quota error
                try:
                    self.on_cancel(msg)
                except Exception:  # noqa: BLE001
                    pass
            raise QuotaExceededError(msg)
        finally:
            with self._mu:
                self._firing = False

    def cancel(self, msg: str) -> bool:
        """Latch a statement cancel from OUTSIDE the quota chain — the
        dispatch watchdog's door (tidb_tpu/sched.py): the message
        latches exactly like a quota cancel (stragglers that later trip
        the quota re-raise it, never re-count), and the on_cancel hook
        fires so the session's cooperative-kill flag flips. Unlike
        _over_quota this never raises — the caller is a monitor thread,
        not the consuming thread. -> False when a cancel was already
        latched."""
        with self._mu:
            if self._cancel_msg is not None:
                return False
            self._cancel_msg = msg
        if self.on_cancel is not None:
            try:
                self.on_cancel(msg)
            except Exception:  # noqa: BLE001 - monitor must survive
                pass
        return True

    def run_spill_actions(self, target: int = 0,
                          recurse: bool = False) -> int:
        """Administratively drive registered spill actions until this
        node's total() is at/below `target` bytes; -> bytes freed.
        Unlike the quota chain (_over_quota) this NEVER cancels and
        needs no quota armed — it is the door the admission controller
        and the status port's /shed hook use to fire the shed chain the
        HBM cache (and, with recurse=True, running statements' spill
        actions: hybrid-join cold partitions, sort buffers) registered.
        Actions fire with every tracker lock dropped, exactly like the
        quota chain, so they may consume/release re-entrantly."""
        with self._mu:
            before = self.host + self.device
        if before <= target:
            return 0
        actions: list = []
        nodes = [self]
        seen: set[int] = set()
        while nodes:
            node = nodes.pop()
            if id(node) in seen:
                continue
            seen.add(id(node))
            with node._mu:
                actions.extend(node._actions)
                if recurse:
                    nodes.extend(node.children.values())
        for act in actions:
            with self._mu:
                cur = self.host + self.device
            if cur <= target:
                break
            try:
                act()
            except Exception:  # noqa: BLE001 - one broken spiller must
                pass           # not stop the rest of the chain
        with self._mu:
            after = self.host + self.device
        return max(before - after, 0)

    # -- per-plan-node children (statement roots) ----------------------------

    def node(self, plan, name: str | None = None) -> "MemTracker":
        """Child tracker for one plan node; the entry pins the plan so
        ids cannot recycle while this root lives (cleared on detach)."""
        with self._mu:
            ent = self._nodes.get(id(plan))
        if ent is not None:
            return ent[1]
        if name is None:
            name = type(plan).__name__.removeprefix("Phys")
        child = MemTracker(name, parent=self)
        with self._mu:
            ent = self._nodes.setdefault(id(plan), (plan, child))
        return ent[1]

    def link(self, alias_plan, node: "MemTracker") -> None:
        """Route charges made against `alias_plan` (a reader's CopPlan,
        executed storage-side) onto the owning node's tracker."""
        with self._mu:
            self._nodes[id(alias_plan)] = (alias_plan, node)

    def get(self, plan) -> "MemTracker | None":
        with self._mu:
            ent = self._nodes.get(id(plan))
        return ent[1] if ent is not None else None

    # -- lifecycle -----------------------------------------------------------

    def detach(self) -> None:
        """Unhook from the parent, crediting back everything still held:
        release-on-close is what leaves the session root at zero after
        each statement even when an abandoned generator never ran its
        finally. Peaks (and residual current counters) survive for
        post-mortem readers (bench, slow log)."""
        with self._mu:
            p = self.parent
            if p is None:
                return
            # counters snapshot + parent sever in ONE locked region:
            # see consume() for why this keeps ancestor ledgers exact
            # under racing walkers
            h, d = self.host, self.device
            self.parent = None
            self._nodes = {}       # drop plan pins
            self._actions = []
        with p._mu:
            p.children.pop(id(self), None)
        if h or d:
            p.release(host=h, device=d)

    def snapshot(self) -> dict:
        with self._mu:
            return {"label": self.label, "host": self.host,
                    "device": self.device, "host_peak": self.host_peak,
                    "device_peak": self.device_peak}


# process root: every session tracker hangs off it, so its ledgers are
# the server totals information_schema.memory_usage reports
SERVER = MemTracker("server")


def session_root(session_id: int) -> MemTracker:
    t = MemTracker(f"session-{session_id}", parent=SERVER)
    with SERVER._mu:
        SERVER.children[id(t)] = t
    return t


def server_node(label: str) -> MemTracker:
    """A long-lived server-scope tracker (shared caches, pools): a child
    of SERVER whose ledgers roll up into the server totals that
    information_schema.memory_usage reports, without belonging to any
    session or statement. The HBM region-block cache charges its
    resident bytes here (store/device_cache.py) — budget enforcement is
    the cache's LRU, visibility is this ledger. The MVCC delta store
    bills its staged commit journal to a sibling `delta-store` node
    (store/delta.py), with a registered spill action that forces an
    early merge — so /shed and admission-driven shedding reclaim
    staged delta bytes like any other server-scope residency."""
    t = MemTracker(label, parent=SERVER)
    with SERVER._mu:
        SERVER.children[id(t)] = t
    return t


def statement_root(parent: MemTracker | None, quota: int = 0,
                   on_cancel=None, label: str = "stmt") -> MemTracker:
    t = MemTracker(label, parent=parent, quota=quota, on_cancel=on_cancel)
    if parent is not None:
        with parent._mu:
            parent.children[id(t)] = t
    return t


def sessions_snapshot() -> list[dict]:
    """Per-session tracker snapshots, session creation order."""
    with SERVER._mu:
        kids = list(SERVER.children.values())
    return [t.snapshot() for t in kids]


# -- thread-local installation (mirrors runtime_stats.collecting) -----------

_tl = threading.local()


@contextlib.contextmanager
def tracking(root: MemTracker | None):
    """Install `root` as this thread's active statement tracker. Passing
    None nests transparently (keeps the outer tracker)."""
    prev = getattr(_tl, "root", None)
    _tl.root = root if root is not None else prev
    try:
        yield _tl.root
    finally:
        _tl.root = prev


@contextlib.contextmanager
def suspended():
    """Hide the active tracker (internal bookkeeping sessions run inside
    a client statement but must not bill it — the memory twin of
    runtime_stats.suspended)."""
    prev = getattr(_tl, "root", None)
    _tl.root = None
    try:
        yield
    finally:
        _tl.root = prev


def current() -> MemTracker | None:
    return getattr(_tl, "root", None)


def op_node(plan) -> MemTracker | None:
    """The active statement's tracker node for `plan` (None when no
    tracker is installed — internal sessions, library use)."""
    root = getattr(_tl, "root", None)
    if root is None:
        return None
    return root.node(plan)


def consume(plan, host: int = 0, device: int = 0) -> None:
    """Charge bytes against the active statement's node for `plan`
    (no-op without a tracker) — the call-site form for executors and the
    coprocessor handler."""
    root = getattr(_tl, "root", None)
    if root is not None and (host or device):
        root.node(plan).consume(host=host, device=device)


def release(plan, host: int = 0, device: int = 0) -> None:
    root = getattr(_tl, "root", None)
    if root is not None and (host or device):
        root.node(plan).release(host=host, device=device)


@contextlib.contextmanager
def device_scope(plan, nbytes: int):
    """Hold `nbytes` on `plan`'s device ledger for the duration of a
    synchronous kernel call — the leak-proof form of the
    consume/try/finally-release pattern at dispatch sites. Split
    dispatch/finalize pairs (pipelines) still pair the calls manually
    because the release happens in a different closure."""
    consume(plan, device=nbytes)
    try:
        yield
    finally:
        release(plan, device=nbytes)


def track_to(plan, nbytes: int, prev: int = 0, kind: str = "host") -> int:
    """Move `plan`'s tracked bytes (one ledger) to an absolute value:
    the pattern for accumulators that grow or shrink (hash builds, TopN
    windows, agg state). Returns nbytes for the caller to carry."""
    delta = nbytes - prev
    if delta > 0:
        consume(plan, **{kind: delta})
    elif delta < 0:
        release(plan, **{kind: -delta})
    return nbytes


def register_spill(fn):
    """Hook a spill action onto the active statement root; returns an
    unregister callable (a no-op pair when no tracker is active)."""
    root = getattr(_tl, "root", None)
    if root is None:
        return lambda: None
    root.add_spill_action(fn)
    return lambda: root.remove_spill_action(fn)


# -- size estimators --------------------------------------------------------


def chunk_bytes(chunk) -> int:
    """Host footprint of a chunk: numpy buffers at their real size,
    object (string) columns at pointer + payload length. Memoized on
    the (immutable) chunk — string columns make this an O(rows) scan,
    and hot cached chunks are re-sized on every dispatch."""
    hit = getattr(chunk, "_bytes_memo", None)
    if hit is not None:
        return hit
    total = 0
    for c in chunk.columns:
        data = c.data
        if getattr(data, "dtype", None) is not None and \
                data.dtype != object:
            total += data.nbytes
        else:
            total += 8 * len(data)
            total += sum(len(x) for x in data
                         if isinstance(x, (str, bytes)))
        total += len(c.valid)          # bool mask
    try:
        chunk._bytes_memo = total
    except AttributeError:
        pass        # duck-typed chunk without the memo slot
    return total


def result_bytes(res) -> int:
    """Host footprint of a coprocessor response payload: a decoded
    Chunk (chunk_bytes), or an agg partial shaped like
    ops.hashagg.GroupResult (keys / per-agg lane arrays / counts).
    Anything else — scalar partials are a handful of lanes — rounds to
    its lane arrays alone."""
    if getattr(res, "columns", None) is not None:
        return chunk_bytes(res)
    total = 0
    for lanes in getattr(res, "partials", None) or []:
        for arr in lanes:
            nb = getattr(arr, "nbytes", None)
            total += nb if nb is not None else 8 * len(arr)
    counts = getattr(res, "counts", None)
    if counts is not None:
        total += counts.nbytes
    for key in getattr(res, "keys", None) or []:
        total += 8 * max(len(key), 1)
        total += sum(len(x) for x in key if isinstance(x, (str, bytes)))
    return total


_MIN_BUCKET = 1024     # mirrors ops/runtime.MIN_BUCKET (no jax import here)


def _bucket(n: int) -> int:
    b = _MIN_BUCKET
    while b < n:
        b <<= 1
    return b


def device_put_bytes(chunk, size: int | None = None) -> int:
    """HBM bytes one device_put_chunk transfer stages, from shapes alone:
    each column pads to the bucket size; varlen columns ship as int64
    dict codes; every column carries a bool validity lane."""
    n = size or _bucket(max(chunk.num_rows, 1))
    total = 0
    for c in chunk.columns:
        itemsize = 8 if c.data.dtype == object else c.data.dtype.itemsize
        total += n * (itemsize + 1)
    return total


# The allocation lint that used to consult an AUDITED_HELPERS function
# registry here now lives in tidb_tpu/lint (rule `memtrack-alloc`):
# helpers whose data-sized numpy allocations are covered by tracker
# accounting carry a lint-exempt tag (rule memtrack-alloc, with reason)
# on their def, and the engine's unused-suppression check reports any
# tag that stops matching (the old registry-staleness guard).
