"""In-process metrics history: a bounded time-series ring + sampler.

metrics.py is deliberately instantaneous — counters accumulate, gauges
are last-write-wins, and the /metrics endpoint assumes an EXTERNAL
scraper keeps the history. Nothing in-process could answer "how busy
was the device over the last minute" or "is the HBM hit rate decaying",
which is exactly what the adaptive-runtime items (ROADMAP 2 and 3, per
the hash-vs-sort study arxiv 2411.13245) and the serve bench's
utilization audit need. This module keeps that history in-process:

* a background sampler — supervised per util/supervisor.py, so a
  crashing beat restarts counted instead of dying silently — snapshots
  every registered gauge plus DERIVED series each
  `tidb_tpu_metrics_history_interval_ms`:
    - `tidb_tpu_device_utilization_ratio`: the resource meter's SERVER
      device busy-ns delta over the wall interval (tidb_tpu/meter.py);
      also published as a live gauge,
    - `tidb_tpu_hbm_occupancy_ratio`: HBM cache resident bytes over
      budget (live gauge too),
    - `hbm_hit_ratio`: cache hits over lookups within the interval,
    - memtrack SERVER host/device ledger bytes;
* each tick also calls `meter.roll_interval()`, so the per-tenant
  "current interval" numbers in information_schema.resource_usage and
  GET /top describe the same wall window as the history point;
* the ring is bounded by `tidb_tpu_metrics_history_points` and billed
  to a `metrics-history` memtrack SERVER node with a registered shed
  action — admission shedding and GET /shed reclaim retained points
  like any other server-scope residency (trace-ring discipline).

`sample_now()` is the deterministic door: tests and bench call it to
record a point (and roll the meter intervals) without waiting out the
cadence. Served as JSON on `GET /metrics/history` (server/status.py).
"""

from __future__ import annotations

import threading
import time

from tidb_tpu import config, memtrack, meter, metrics

__all__ = ["ensure_started", "sample_now", "series", "points",
           "stats", "shed", "reset_for_tests"]

# fixed supervisor tick: each beat checks whether a sample is due
# against the (live-settable) interval sysvar, so SET takes effect
# without restarting the worker thread
_TICK_S = 0.25

# rough per-point retention cost billed to the memtrack node: a dict of
# ~a-few-dozen float series plus the key strings
_POINT_EST_BYTES = 96


class _Ring:
    """Sampled points, oldest first, bounded by the points sysvar and
    billed to the `metrics-history` memtrack SERVER node. The shed
    action clears it (GET /shed, admission shedding)."""

    def __init__(self):
        self._mu = threading.Lock()
        # (t_unix, point, billed_cost), oldest first
        self._points: list[tuple[float, dict, int]] = []  # guarded-by: _mu
        self._bytes = 0                               # guarded-by: _mu
        self._node = None                             # guarded-by: _mu

    def _tracker(self):
        with self._mu:
            if self._node is None:
                self._node = memtrack.server_node("metrics-history")
                self._node.add_spill_action(self.shed)
            return self._node

    def append(self, t: float, point: dict) -> None:
        cost = _POINT_EST_BYTES * max(len(point), 1)
        node = self._tracker()
        # lint: exempt[paired-resource] ownership transfer: point bytes release on evict (below) / shed / reset
        node.consume(host=cost)
        cap = config.metrics_history_points()
        evicted = 0
        with self._mu:
            self._points.append((t, point, cost))
            self._bytes += cost
            while len(self._points) > cap:
                _t, _p, old_cost = self._points.pop(0)
                self._bytes -= old_cost
                evicted += old_cost
        if evicted:
            node.release(host=evicted)

    def shed(self) -> int:
        """Drop every retained point (the memtrack shed action).
        -> bytes freed."""
        with self._mu:
            freed = self._bytes
            self._points.clear()
            self._bytes = 0
            node = self._node
        if node is not None and freed:
            node.release(host=freed)
        return freed

    def points(self) -> list[tuple[float, dict]]:
        with self._mu:
            return [(t, p) for t, p, _c in self._points]

    def stats(self) -> dict:
        with self._mu:
            return {"points": len(self._points), "bytes": self._bytes}


_RING = _Ring()

_state_mu = threading.Lock()
_started = False                 # guarded-by: _state_mu
_stop: threading.Event | None = None   # guarded-by: _state_mu
# previous-tick baselines for the derived rate series
_prev_mu = threading.Lock()
_prev: dict = {}                 # guarded-by: _prev_mu


def _hbm_counter_totals() -> tuple[int, int]:
    snap = metrics.snapshot()
    return (int(snap.get(metrics.HBM_CACHE_HITS, 0)),
            int(snap.get(metrics.HBM_CACHE_MISSES, 0)))


def sample_now() -> dict:
    """Record one history point NOW (and roll the per-tenant meter
    interval baselines): derived utilization/occupancy/hit-rate series
    plus a copy of every registered gauge. Returns the point."""
    now_wall = time.time()
    now_ns = time.perf_counter_ns()
    server_device_ns = meter.SERVER.totals()["device_ns"]
    hits, misses = _hbm_counter_totals()
    chip_busy = _chip_busy_ns()
    with _prev_mu:
        prev = dict(_prev)
        _prev.update(t_ns=now_ns, device_ns=server_device_ns,
                     hbm_hits=hits, hbm_misses=misses,
                     chip_ns=chip_busy)
    point: dict = {}
    wall_ns = now_ns - prev.get("t_ns", now_ns)
    if wall_ns > 0:
        util = (server_device_ns - prev.get("device_ns", 0)) / wall_ns
        point["tidb_tpu_device_utilization_ratio"] = round(max(util, 0.0), 6)
        lookups = (hits - prev.get("hbm_hits", 0)) + \
            (misses - prev.get("hbm_misses", 0))
        point["hbm_hit_ratio"] = round(
            (hits - prev.get("hbm_hits", 0)) / lookups, 6) \
            if lookups > 0 else 0.0
        metrics.gauge(metrics.DEVICE_UTILIZATION,
                      point["tidb_tpu_device_utilization_ratio"])
        # per-chip slot busy-time ratios (the scheduler's placement
        # signal as a series; label cardinality = the plane's device
        # count). The gauges ride into the point via gauges_snapshot.
        prev_chip = prev.get("chip_ns", {})
        for c, ns in sorted(chip_busy.items()):
            ratio = max(ns - prev_chip.get(c, 0), 0) / wall_ns
            metrics.gauge(metrics.CHIP_UTILIZATION, round(ratio, 6),
                          {"chip": c})
    budget = config.device_cache_bytes()
    resident = _hbm_resident_bytes()
    point["tidb_tpu_hbm_occupancy_ratio"] = \
        round(resident / budget, 6) if budget > 0 else 0.0
    metrics.gauge(metrics.HBM_OCCUPANCY,
                  point["tidb_tpu_hbm_occupancy_ratio"])
    point["server_host_bytes"] = memtrack.SERVER.host
    point["server_device_bytes"] = memtrack.SERVER.device
    # every registered gauge rides along (cardinality is bounded by the
    # metric-cardinality lint, so this stays a few dozen series)
    point.update(metrics.gauges_snapshot())
    meter.roll_interval()
    _RING.append(now_wall, point)
    return point


def _hbm_resident_bytes() -> int:
    from tidb_tpu.store import device_cache
    return device_cache.tracker().device


def _chip_busy_ns() -> dict:
    from tidb_tpu import sched
    return sched.device_scheduler().chip_busy_ns()


_last_sample_ns = 0.0
_beat_mu = threading.Lock()


def _beat() -> None:
    """One supervisor tick: sample when the cadence sysvar says a point
    is due; an interval of 0 idles the sampler without stopping the
    (cheap) tick."""
    global _last_sample_ns
    interval_ms = config.metrics_history_interval_ms()
    if interval_ms <= 0:
        return
    with _beat_mu:
        now = time.perf_counter_ns()
        if now - _last_sample_ns < interval_ms * 1e6:
            return
        _last_sample_ns = now
    sample_now()


def ensure_started() -> None:
    """Start the supervised sampler thread once per process (idempotent;
    Server.start / StatusServer.start / the bench legs call it)."""
    global _started, _stop
    with _state_mu:
        if _started:
            return
        _started = True
        _stop = threading.Event()
        from tidb_tpu.util import supervisor
        supervisor.supervise("metrics-history", _beat, _stop, _TICK_S)


def series(names: list[str] | None = None) -> dict:
    """{series_name: [[unix_seconds, value], ...]} over the retained
    window (the GET /metrics/history payload). A point that lacks a
    series (gauge not yet written at that tick) skips that timestamp."""
    out: dict[str, list] = {}
    for t, point in _RING.points():
        for name, v in point.items():
            if names is not None and name not in names:
                continue
            out.setdefault(name, []).append([round(t, 3), v])
    return out


def points() -> list[tuple[float, dict]]:
    return _RING.points()


def stats() -> dict:
    st = _RING.stats()
    st["interval_ms"] = config.metrics_history_interval_ms()
    return st


def shed() -> int:
    return _RING.shed()


def reset_for_tests() -> None:
    """Clear the ring and the rate baselines (test isolation); the
    sampler thread, if started, keeps running — it is allowlisted
    long-lived infrastructure (util/testleak.py)."""
    _RING.shed()
    with _prev_mu:
        _prev.clear()
