"""Columnar batch format (Arrow layout), numpy-backed, device-transferable.

Reference: /root/reference/util/chunk/chunk.go:27-97 — per-column null bitmap
plus fixed-width data buffer, or offsets + varlen buffer. Here:

* Fixed-width columns are a single numpy array (int64 / float64) plus a
  boolean validity array (True = valid, Arrow convention). These views are
  exactly what `jax.device_put` ships to HBM — host<->device DMA is a memcpy.
* Varlen (string/bytes) columns are numpy object arrays on the host;
  `dict_encode` produces int64 codes + a dictionary so group-by/join keys
  can ride the device path (SURVEY.md §7 "Variable-length strings on device").

Unlike the reference's append-row-at-a-time builder, the fast path is
columnar construction from numpy; append_row exists for the control plane.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, Sequence

import numpy as np

from tidb_tpu.sqltypes import (EvalType, FieldType, TypeCode, decimal_to_scaled,
                               np_dtype_for, scaled_to_decimal)

__all__ = ["Column", "Chunk", "dict_encode", "MAX_CHUNK_SIZE"]

# Default row cap per chunk; ref: sessionctx/variable/session.go:244 (1024).
# We default larger because TPU kernels amortize better on big batches.
MAX_CHUNK_SIZE = 32768


class Column:
    """One column: numpy data + validity mask."""

    # _enc memoizes dict_encode's (codes, values) — columns are
    # immutable once built, so the dictionary pass runs once per column
    # no matter how many consumers (device transfer, join key encoding,
    # encoded filters) ask for codes. The values list may be EXTENDED in
    # place by the HBM cache's incremental dict growth (store/
    # device_cache.py): appends only, existing codes stay stable.
    __slots__ = ("ft", "data", "valid", "_enc")

    def __init__(self, ft: FieldType, data: np.ndarray, valid: np.ndarray | None = None):
        self.ft = ft
        self.data = data
        if valid is None:
            valid = np.ones(len(data), dtype=bool)
        self.valid = valid

    # -- construction -------------------------------------------------------

    @staticmethod
    def empty(ft: FieldType) -> "Column":
        return Column(ft, np.empty(0, dtype=np_dtype_for(ft.tp)), np.empty(0, dtype=bool))

    @staticmethod
    def from_values(ft: FieldType, values: Iterable) -> "Column":
        """Build from python values (None = NULL). Converts decimals/datetimes
        to their int64 device representation per sqltypes conventions."""
        vals = list(values)
        n = len(vals)
        dtype = np_dtype_for(ft.tp)
        valid = np.array([v is not None for v in vals], dtype=bool)
        if dtype == np.dtype(object):
            data = np.empty(n, dtype=object)
            for i, v in enumerate(vals):
                data[i] = v if v is not None else ""
        else:
            data = np.zeros(n, dtype=dtype)
            et = ft.eval_type
            for i, v in enumerate(vals):
                if v is None:
                    continue
                if et == EvalType.DECIMAL:
                    data[i] = decimal_to_scaled(v, ft.frac)
                else:
                    data[i] = v
        return Column(ft, data, valid)

    # -- access --------------------------------------------------------------

    def __len__(self) -> int:
        return len(self.data)

    def is_null(self, i: int) -> bool:
        return not self.valid[i]

    def get(self, i: int):
        """Python value at row i (host path; decimals decoded exactly)."""
        if not self.valid[i]:
            return None
        v = self.data[i]
        if self.ft.tp == TypeCode.NEWDECIMAL:
            return scaled_to_decimal(int(v), self.ft.frac)
        if isinstance(v, np.generic):
            return v.item()
        return v

    def take(self, idx: np.ndarray) -> "Column":
        return Column(self.ft, self.data[idx], self.valid[idx])

    def slice(self, start: int, stop: int) -> "Column":
        return Column(self.ft, self.data[start:stop], self.valid[start:stop])

    def concat(self, other: "Column") -> "Column":
        return Column(self.ft, np.concatenate([self.data, other.data]),
                      np.concatenate([self.valid, other.valid]))

    @property
    def fixed_width(self) -> bool:
        return self.data.dtype != np.dtype(object)


class Chunk:
    """A batch of rows in columnar layout. Ref: util/chunk/chunk.go NewChunk."""

    # _dev_cache: memoized device-resident columns (ops/runtime.py
    # device_put_chunk) — chunks are treated as immutable once built.
    # _scan_handles/_delta_memo ride cached base chunks only
    # (store/delta.py): the row handles of a cached record scan, and
    # the memoized base-plus-delta merges computed from them.
    # _bytes_memo caches memtrack's O(columns-payload) byte sizing —
    # hot cached chunks are re-sized on every dispatch otherwise
    __slots__ = ("columns", "_dev_cache", "_cop_filter_memo",
                 "_scan_handles", "_delta_memo", "_bytes_memo")

    def __getstate__(self):
        # device memos and filter memos are process-local accelerators;
        # they must never ride a pickle across the storage RPC
        return {"columns": self.columns}

    def __setstate__(self, state):
        self.columns = state["columns"]

    def __init__(self, columns: Sequence[Column]):
        self.columns = list(columns)
        if self.columns:
            n = len(self.columns[0])
            for c in self.columns:
                assert len(c) == n, "ragged chunk"

    # -- construction -------------------------------------------------------

    @staticmethod
    def empty(fts: Sequence[FieldType]) -> "Chunk":
        return Chunk([Column.empty(ft) for ft in fts])

    @staticmethod
    def from_rows(fts: Sequence[FieldType], rows: Iterable[Sequence]) -> "Chunk":
        rows = list(rows)
        cols = []
        for j, ft in enumerate(fts):
            cols.append(Column.from_values(ft, [r[j] for r in rows]))
        return Chunk(cols)

    @staticmethod
    def from_arrays(fts: Sequence[FieldType], arrays: Sequence[np.ndarray],
                    valids: Sequence[np.ndarray] | None = None) -> "Chunk":
        cols = []
        for j, ft in enumerate(fts):
            v = valids[j] if valids is not None else None
            cols.append(Column(ft, np.asarray(arrays[j]), v))
        return Chunk(cols)

    # -- access --------------------------------------------------------------

    @property
    def num_rows(self) -> int:
        return len(self.columns[0]) if self.columns else 0

    @property
    def num_cols(self) -> int:
        return len(self.columns)

    def __len__(self) -> int:
        return self.num_rows

    def col(self, j: int) -> Column:
        return self.columns[j]

    def row(self, i: int) -> tuple:
        return tuple(c.get(i) for c in self.columns)

    def iter_rows(self):
        for i in range(self.num_rows):
            yield self.row(i)

    def to_pylist(self) -> list[tuple]:
        return list(self.iter_rows())

    def take(self, idx: np.ndarray) -> "Chunk":
        return Chunk([c.take(idx) for c in self.columns])

    def filter(self, mask: np.ndarray) -> "Chunk":
        return self.take(np.flatnonzero(mask))

    def slice(self, start: int, stop: int) -> "Chunk":
        return Chunk([c.slice(start, stop) for c in self.columns])

    def concat(self, other: "Chunk") -> "Chunk":
        if not self.columns:
            return other
        return Chunk([a.concat(b) for a, b in zip(self.columns, other.columns)])

    @staticmethod
    def concat_all(chunks: list["Chunk"]) -> "Chunk | None":
        """One-pass concatenation (pairwise .concat in a loop re-copies the
        accumulated prefix per chunk — O(C^2) in chunk count)."""
        chunks = [c for c in chunks if c.columns]
        if not chunks:
            return None
        if len(chunks) == 1:
            return chunks[0]
        cols = []
        for j, c0 in enumerate(chunks[0].columns):
            cols.append(Column(
                c0.ft,
                np.concatenate([c.columns[j].data for c in chunks]),
                np.concatenate([c.columns[j].valid for c in chunks])))
        return Chunk(cols)

    def field_types(self) -> list[FieldType]:
        return [c.ft for c in self.columns]


def dict_encode(col: Column) -> tuple[np.ndarray, list]:
    """Dictionary-encode a varlen column: returns (int64 codes, dictionary).

    NULLs get code -1. The codes array rides the device path for group-by /
    join keys; the dictionary stays host-side for final decode. Columns
    with a _ci collation encode by CASEFOLDED value — case variants share
    one code, so device group-by/compare over codes follows the collation
    (the dictionary keeps the first-seen variant for decode, matching the
    host path's representative-row semantics).

    The result is memoized on the column (columns are immutable): hot
    cached chunks pay the Python encode pass once, and every consumer
    (device transfer, join key encoder, encoded filter translation)
    shares ONE (codes, values) pair — the identity that makes
    shared-dictionary detection possible (ops/encoded.py).
    """
    hit = getattr(col, "_enc", None)
    if hit is not None:
        return hit
    codes = np.empty(len(col), dtype=np.int64)
    mapping: dict = {}
    values: list = []
    data, valid = col.data, col.valid
    ci = col.ft.is_ci
    if ci:
        from tidb_tpu.sqltypes import collation_key
    for i in range(len(col)):
        if not valid[i]:
            codes[i] = -1
            continue
        v = data[i]
        k = collation_key(v) if ci else v
        c = mapping.get(k)
        if c is None:
            c = len(values)
            mapping[k] = c
            values.append(v)
        codes[i] = c
    col._enc = (codes, values)
    return codes, values
