"""First-run bootstrap + versioned upgrades of the `mysql` catalog.

Reference: /root/reference/bootstrap.go:40-180 — DDL+DML creating
mysql.user / db / tables_priv / GLOBAL_VARIABLES / tidb / help_topic,
with a persisted bootstrap version and an `upgradeToVerN` chain so a
store written by version N opens under version N+1 code (bootstrap.go
upgrade() dispatching upgradeToVer2...). Grant rows here use a BIGINT
privilege bitmask (see tidb_tpu/privilege.py) instead of per-priv enum
columns.

Adding a migration: bump BOOTSTRAP_VERSION, append `_upgrade_to_verN`
to _UPGRADES. Each step must be idempotent — a crash between a step and
the version-row update replays the step on next open.
"""

from __future__ import annotations

import threading

from tidb_tpu.privilege import ALL_PRIVS

__all__ = ["bootstrap", "load_global_variables", "BOOTSTRAP_VERSION"]

BOOTSTRAP_VERSION = 3

_DDL = [
    "CREATE DATABASE IF NOT EXISTS mysql",
    # id handles are implicit (no int pk): account rows are small
    """CREATE TABLE IF NOT EXISTS mysql.user (
        host VARCHAR(255), user VARCHAR(32),
        authentication_string VARCHAR(64), privs BIGINT)""",
    """CREATE TABLE IF NOT EXISTS mysql.db (
        host VARCHAR(255), user VARCHAR(32), db VARCHAR(64),
        privs BIGINT)""",
    """CREATE TABLE IF NOT EXISTS mysql.tables_priv (
        host VARCHAR(255), user VARCHAR(32), db VARCHAR(64),
        table_name VARCHAR(64), privs BIGINT)""",
    """CREATE TABLE IF NOT EXISTS mysql.global_variables (
        variable_name VARCHAR(64), variable_value VARCHAR(1024))""",
    """CREATE TABLE IF NOT EXISTS mysql.tidb (
        variable_name VARCHAR(64), variable_value VARCHAR(1024),
        comment VARCHAR(1024))""",
]

_lock = threading.Lock()


def _bootstrapped_version(session) -> int:
    if not session.domain.info_schema().has_db("mysql"):
        return 0
    try:
        rows = session.query(
            "SELECT variable_value FROM mysql.tidb "
            "WHERE variable_name = 'bootstrapped'").rows
    except Exception:  # noqa: BLE001 - partial earlier bootstrap
        return 0
    return int(rows[0][0]) if rows else 0


def load_global_variables(storage) -> None:
    """Apply persisted SET GLOBAL values to the process config registry
    (ref: session.go:1166 loading GLOBAL_VARIABLES at session start)."""
    from tidb_tpu import config
    from tidb_tpu.session import Session

    s = Session(storage, internal=True)
    try:
        if not s.domain.info_schema().has_db("mysql"):
            return
        for name, value in s.query(
                "SELECT variable_name, variable_value "
                "FROM mysql.global_variables").rows:
            if config.is_known(name):
                try:
                    config.set_var(name, value)
                except (TypeError, ValueError):
                    pass   # stale row with an invalid value: ignore
    finally:
        s.close()


_HELP_TOPIC_DDL = """CREATE TABLE IF NOT EXISTS mysql.help_topic (
    help_topic_id BIGINT PRIMARY KEY, name VARCHAR(64),
    help_category_id BIGINT, description VARCHAR(1024),
    example VARCHAR(1024), url VARCHAR(128))"""


def _upgrade_to_ver2(session) -> None:
    """SUPER joined ALL_PRIVS — re-grant root (ref: bootstrap.go's
    upgradeToVer2 re-granting new privileges)."""
    session.execute(
        f"UPDATE mysql.user SET privs = {ALL_PRIVS} "
        "WHERE user = 'root' AND host = '%'")


def _upgrade_to_ver3(session) -> None:
    """mysql.help_topic, bootstrapped by the reference since its first
    version (ref: bootstrap.go:100 tableHelpTopic) — created on upgrade
    for stores bootstrapped before round 5."""
    session.execute(_HELP_TOPIC_DDL)


_UPGRADES = {2: _upgrade_to_ver2, 3: _upgrade_to_ver3}
assert set(_UPGRADES) == set(range(2, BOOTSTRAP_VERSION + 1))


def _write_version(session, ver: int, fresh: bool) -> None:
    if fresh:
        session.execute(
            f"INSERT INTO mysql.tidb VALUES ('bootstrapped', '{ver}', "
            "'Bootstrap version. Do not delete.')")
    else:
        session.execute(
            f"UPDATE mysql.tidb SET variable_value = '{ver}' "
            "WHERE variable_name = 'bootstrapped'")


def bootstrap(storage) -> None:
    """Idempotent: fresh stores get the full current catalog; stores
    bootstrapped by older code run the upgrade chain one version at a
    time, persisting the version after each step (ref: bootstrap.go
    runInBootstrapSession / doDDLWorks / doDMLWorks / upgrade)."""
    from tidb_tpu.session import Session

    with _lock:
        session = Session(storage, internal=True)
        try:
            ver = _bootstrapped_version(session)
            if ver >= BOOTSTRAP_VERSION:
                return
            if ver == 0:
                for ddl in _DDL + [_HELP_TOPIC_DDL]:
                    session.execute(ddl)
                if not session.query("SELECT user FROM mysql.user "
                                     "WHERE user = 'root'").rows:
                    session.execute(
                        "INSERT INTO mysql.user VALUES "
                        f"('%', 'root', '', {ALL_PRIVS})")
                _write_version(session, BOOTSTRAP_VERSION, fresh=True)
                return
            for v in range(ver + 1, BOOTSTRAP_VERSION + 1):
                _UPGRADES[v](session)
                _write_version(session, v, fresh=False)
        finally:
            session.close()
