"""First-run bootstrap: the `mysql` system catalog + root account.

Reference: /root/reference/bootstrap.go:40-180 — DDL+DML creating
mysql.user / db / tables_priv / GLOBAL_VARIABLES / tidb, versioned so
upgrades can run incremental steps, executed once per store under a
bootstrap guard. Grant rows here use a BIGINT privilege bitmask (see
tidb_tpu/privilege.py) instead of per-priv enum columns.
"""

from __future__ import annotations

import threading

from tidb_tpu.privilege import ALL_PRIVS

__all__ = ["bootstrap", "load_global_variables", "BOOTSTRAP_VERSION"]

BOOTSTRAP_VERSION = 2   # v2: SUPER added to ALL_PRIVS (root re-granted)

_DDL = [
    "CREATE DATABASE IF NOT EXISTS mysql",
    # id handles are implicit (no int pk): account rows are small
    """CREATE TABLE IF NOT EXISTS mysql.user (
        host VARCHAR(255), user VARCHAR(32),
        authentication_string VARCHAR(64), privs BIGINT)""",
    """CREATE TABLE IF NOT EXISTS mysql.db (
        host VARCHAR(255), user VARCHAR(32), db VARCHAR(64),
        privs BIGINT)""",
    """CREATE TABLE IF NOT EXISTS mysql.tables_priv (
        host VARCHAR(255), user VARCHAR(32), db VARCHAR(64),
        table_name VARCHAR(64), privs BIGINT)""",
    """CREATE TABLE IF NOT EXISTS mysql.global_variables (
        variable_name VARCHAR(64), variable_value VARCHAR(1024))""",
    """CREATE TABLE IF NOT EXISTS mysql.tidb (
        variable_name VARCHAR(64), variable_value VARCHAR(1024),
        comment VARCHAR(1024))""",
]

_lock = threading.Lock()


def _bootstrapped_version(session) -> int:
    if not session.domain.info_schema().has_db("mysql"):
        return 0
    try:
        rows = session.query(
            "SELECT variable_value FROM mysql.tidb "
            "WHERE variable_name = 'bootstrapped'").rows
    except Exception:  # noqa: BLE001 - partial earlier bootstrap
        return 0
    return int(rows[0][0]) if rows else 0


def load_global_variables(storage) -> None:
    """Apply persisted SET GLOBAL values to the process config registry
    (ref: session.go:1166 loading GLOBAL_VARIABLES at session start)."""
    from tidb_tpu import config
    from tidb_tpu.session import Session

    s = Session(storage, internal=True)
    try:
        if not s.domain.info_schema().has_db("mysql"):
            return
        for name, value in s.query(
                "SELECT variable_name, variable_value "
                "FROM mysql.global_variables").rows:
            if config.is_known(name):
                try:
                    config.set_var(name, value)
                except (TypeError, ValueError):
                    pass   # stale row with an invalid value: ignore
    finally:
        s.close()


def bootstrap(storage) -> None:
    """Idempotent: creates system tables + root@% superuser on first run
    (ref: bootstrap.go runInBootstrapSession / doDDLWorks / doDMLWorks)."""
    from tidb_tpu.session import Session

    with _lock:
        session = Session(storage, internal=True)
        try:
            ver = _bootstrapped_version(session)
            if ver >= BOOTSTRAP_VERSION:
                return
            for ddl in _DDL:
                session.execute(ddl)
            if not session.query(
                    "SELECT user FROM mysql.user WHERE user = 'root'").rows:
                session.execute(
                    "INSERT INTO mysql.user VALUES "
                    f"('%', 'root', '', {ALL_PRIVS})")
            elif ver < 2:
                # upgradeToVer2: SUPER joined ALL_PRIVS — re-grant root
                # (ref: bootstrap.go's versioned upgradeToVerN steps)
                session.execute(
                    f"UPDATE mysql.user SET privs = {ALL_PRIVS} "
                    "WHERE user = 'root' AND host = '%'")
            if ver == 0:
                session.execute(
                    "INSERT INTO mysql.tidb VALUES ('bootstrapped', "
                    f"'{BOOTSTRAP_VERSION}', 'Bootstrap version. Do not "
                    "delete.')")
            else:
                session.execute(
                    "UPDATE mysql.tidb SET variable_value = "
                    f"'{BOOTSTRAP_VERSION}' WHERE variable_name = "
                    "'bootstrapped'")
        finally:
            session.close()
