"""SQL workload driver — the benchdb equivalent.

Reference: /root/reference/cmd/benchdb/main.go — a job string
("create|truncate|insert:0_10000|update-random:0_10000:100000|
select:0_10000:10|gc") run against a live store, each job timed.
Here jobs run through a Session over the in-process mock storage by
default, or over the out-of-process storage with --addr host:port
(store/remote.py), mirroring the reference's mocktikv-vs-tikv split.

Usage: python -m tidb_tpu.benchmarks.benchdb \
    [--run JOBS] [--table NAME] [--batch N] [--blob N] [--addr H:P]
"""

from __future__ import annotations

import argparse
import random
import time

__all__ = ["run_jobs", "main"]

DEFAULT_JOBS = ("create|truncate|insert:0_10000|"
                "update-random:0_10000:30000|select:0_10000:10|"
                "update-range:5000_5100:1000|select:0_10000:10|gc|"
                "select:0_10000:10")


def _span(spec: str):
    a, _, b = spec.partition("_")
    return int(a), int(b)


class _BenchDB:
    def __init__(self, session, table: str, batch: int, blob: int):
        self.s = session
        self.table = table
        self.batch = batch
        self.blob = blob
        self.rng = random.Random(42)

    def create(self, _spec):
        self.s.execute(
            f"CREATE TABLE IF NOT EXISTS {self.table} "
            "(id BIGINT PRIMARY KEY, k BIGINT, data VARCHAR(4096))")

    def truncate(self, _spec):
        self.s.execute(f"TRUNCATE TABLE {self.table}")

    def _blob(self) -> str:
        return "A" * self.blob

    def insert(self, spec):
        lo, hi = _span(spec)
        ids = list(range(lo, hi))
        for i in range(0, len(ids), self.batch):
            chunk = ids[i:i + self.batch]
            vals = ",".join(f"({j},{j},'{self._blob()}')" for j in chunk)
            self.s.execute(f"INSERT INTO {self.table} VALUES {vals}")

    def update_random(self, spec):
        span, _, count = spec.partition(":")
        lo, hi = _span(span)
        n = int(count)
        for i in range(0, n, self.batch):
            self.s.execute("BEGIN")
            for _ in range(min(self.batch, n - i)):
                j = self.rng.randrange(lo, hi)
                self.s.execute(
                    f"UPDATE {self.table} SET k = k + 1 WHERE id = {j}")
            self.s.execute("COMMIT")

    def update_range(self, spec):
        span, _, count = spec.partition(":")
        lo, hi = _span(span)
        for _ in range(int(count) // max(hi - lo, 1) or 1):
            self.s.execute(f"UPDATE {self.table} SET k = k + 1 "
                           f"WHERE id >= {lo} AND id < {hi}")

    def select(self, spec):
        span, _, count = spec.partition(":")
        lo, hi = _span(span)
        for _ in range(int(count or 1)):
            self.s.query(f"SELECT id, k FROM {self.table} "
                         f"WHERE id >= {lo} AND id < {hi}")

    def query(self, spec):
        sql, _, count = spec.rpartition(":")
        for _ in range(int(count or 1)):
            self.s.query(sql)

    def gc(self, _spec):
        from tidb_tpu.store.gcworker import GCWorker
        w = GCWorker(self.s.storage, gc_life_time_ms=0)
        w.run_once()


_JOBS = {"create": _BenchDB.create, "truncate": _BenchDB.truncate,
         "insert": _BenchDB.insert, "update-random": _BenchDB.update_random,
         "update_random": _BenchDB.update_random,
         "update-range": _BenchDB.update_range,
         "update_range": _BenchDB.update_range,
         "select": _BenchDB.select, "query": _BenchDB.query,
         "gc": _BenchDB.gc}


def run_jobs(session, jobs: str, table: str = "benchdb",
             batch: int = 100, blob: int = 1000) -> list[tuple]:
    """-> [(job, seconds)]; each job timed like the reference's runJobs."""
    db = _BenchDB(session, table, batch, blob)
    out = []
    for work in jobs.split("|"):
        work = work.strip()
        name, _, spec = work.partition(":")
        name = name.lower()      # job names only: query: SQL keeps case
        fn = _JOBS.get(name)
        if fn is None:
            raise ValueError(f"unknown job {name!r}")
        t0 = time.perf_counter()
        fn(db, spec)
        dt = time.perf_counter() - t0
        out.append((work, dt))
        print(f"{work}: {dt:.3f}s", flush=True)
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tidb_tpu.benchmarks.benchdb")
    p.add_argument("--run", default=DEFAULT_JOBS)
    p.add_argument("--table", default="benchdb")
    p.add_argument("--batch", type=int, default=100)
    p.add_argument("--blob", type=int, default=1000)
    p.add_argument("--addr", default=None,
                   help="host:port of an out-of-process storage node")
    args = p.parse_args(argv)
    from tidb_tpu.session import Session
    if args.addr:
        from tidb_tpu.store.remote import connect
        host, _, port = args.addr.rpartition(":")
        storage = connect(host or "127.0.0.1", int(port))
    else:
        from tidb_tpu.store.storage import new_mock_storage
        storage = new_mock_storage()
    s = Session(storage)
    s.execute("CREATE DATABASE IF NOT EXISTS bench")
    s.execute("USE bench")
    run_jobs(s, args.run, args.table, args.batch, args.blob)
    s.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
