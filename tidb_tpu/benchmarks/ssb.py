"""SSB-shaped streaming wide-scan benchmark — BASELINE config 5.

Reference target (BASELINE.json configs[5]): "SSB wide scan: concurrent
distsql regions streaming into TPU Selection+HashAgg with host->HBM
overlap" (ref paths: store/tikv/coprocessor.go:342 region worker pool,
distsql/distsql.go:92 producer/consumer channel). Here: a Star-Schema-
Benchmark lineorder-shaped wide fact table (13 numeric columns), split
into N regions, aggregated by an SSB Q1.1-shaped query

    SELECT SUM(lo_extendedprice * lo_discount) FROM lineorder
    WHERE lo_discount BETWEEN 1 AND 3 AND lo_quantity < 25

plus a grouped variant, with `tidb_tpu_stream_rows` forced BELOW the
table size so the mesh path streams double-buffered super-batches
(launch batch k+1 while batch k drains — the host->HBM overlap).

Usage: python -m tidb_tpu.benchmarks.ssb [--sf F] [--regions N]
       [--stream-rows N]
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np

__all__ = ["run", "main"]

DDL = """CREATE TABLE lineorder (
    lo_orderkey BIGINT PRIMARY KEY, lo_linenumber BIGINT,
    lo_custkey BIGINT, lo_partkey BIGINT, lo_suppkey BIGINT,
    lo_orderdate BIGINT, lo_quantity BIGINT, lo_extendedprice BIGINT,
    lo_ordtotalprice BIGINT, lo_discount BIGINT, lo_revenue BIGINT,
    lo_supplycost BIGINT, lo_tax BIGINT)"""

Q11 = ("SELECT SUM(lo_extendedprice * lo_discount) FROM lineorder "
       "WHERE lo_discount >= 1 AND lo_discount <= 3 "
       "AND lo_quantity < 25")
QGRP = ("SELECT lo_discount, COUNT(*), SUM(lo_revenue) FROM lineorder "
        "WHERE lo_quantity < 30 GROUP BY lo_discount")


def run(sf: float = 0.1, regions: int = 16,
        stream_rows: int | None = None) -> dict:
    from tidb_tpu import config
    from tidb_tpu import devplane as mesh_config
    from tidb_tpu.schema.model import TableInfo  # noqa: F401 (import check)
    from tidb_tpu.session import Session
    from tidb_tpu.store.storage import new_mock_storage
    from tidb_tpu.table import Table, bulkload

    n = int(6_000_000 * sf)
    rng = np.random.default_rng(7)
    storage = new_mock_storage()
    s = Session(storage)
    s.execute("CREATE DATABASE ssb; USE ssb")
    s.execute(DDL)
    info = s.domain.info_schema().table("ssb", "lineorder")
    t0 = time.perf_counter()
    bulkload.bulk_load(storage, Table(info, storage), {
        "lo_orderkey": np.arange(n, dtype=np.int64),
        "lo_linenumber": rng.integers(1, 8, n),
        "lo_custkey": rng.integers(0, 30_000, n),
        "lo_partkey": rng.integers(0, 200_000, n),
        "lo_suppkey": rng.integers(0, 2_000, n),
        "lo_orderdate": rng.integers(0, 2556, n),
        "lo_quantity": rng.integers(1, 51, n),
        "lo_extendedprice": rng.integers(90_000, 10_000_000, n),
        "lo_ordtotalprice": rng.integers(100_000, 38_000_000, n),
        "lo_discount": rng.integers(0, 11, n),
        "lo_revenue": rng.integers(80_000, 9_000_000, n),
        "lo_supplycost": rng.integers(50_000, 120_000, n),
        "lo_tax": rng.integers(0, 9, n)})
    s.execute(f"SPLIT TABLE lineorder REGIONS {regions}")
    load_secs = time.perf_counter() - t0

    # force the streaming mesh path: batches well below the table size
    if stream_rows is None:
        stream_rows = max(1 << 17, n // 8)
    prev_stream = config.get_var("tidb_tpu_stream_rows")
    prev_device = config.get_var("tidb_tpu_device")
    prev_mesh = mesh_config.active_mesh() is not None
    config.set_var("tidb_tpu_stream_rows", int(stream_rows))

    out = {"rows": n, "regions": regions, "stream_rows": int(stream_rows),
           "load_secs": round(load_secs, 2)}
    try:
        for name, sql in (("q11", Q11), ("qgrp", QGRP)):
            config.set_var("tidb_tpu_device", 1)
            mesh_config.enable_mesh()
            s.query(sql)                  # compile + warm
            t0 = time.perf_counter()
            dev_rows = s.query(sql).rows
            d = time.perf_counter() - t0
            config.set_var("tidb_tpu_device", 0)
            mesh_config.disable_mesh()
            t0 = time.perf_counter()
            host_rows = s.query(sql).rows
            h = time.perf_counter() - t0
            assert sorted(map(str, dev_rows)) == \
                sorted(map(str, host_rows))
            out[name] = {"device_secs": round(d, 4),
                         "host_secs": round(h, 4),
                         "rows_per_sec": round(n / d, 1),
                         "speedup": round(h / d, 2)}
            print(f"{name}: device {d:.3f}s host {h:.3f}s "
                  f"({n / d:.0f} rows/s, {h / d:.2f}x)", flush=True)
    finally:
        # restore process-global knobs: library callers (and the test
        # suite) must not inherit this harness's device/stream state
        config.set_var("tidb_tpu_stream_rows", prev_stream)
        config.set_var("tidb_tpu_device", prev_device)
        if prev_mesh:
            mesh_config.enable_mesh()
        else:
            mesh_config.disable_mesh()
        s.close()
    print(json.dumps(out), flush=True)
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tidb_tpu.benchmarks.ssb")
    p.add_argument("--sf", type=float, default=0.1)
    p.add_argument("--regions", type=int, default=16)
    p.add_argument("--stream-rows", type=int, default=None)
    args = p.parse_args(argv)
    run(args.sf, args.regions, args.stream_rows)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
