"""External-sort throughput — the benchfilesort equivalent.

Reference: /root/reference/cmd/benchfilesort — times util/filesort
building sorted on-disk runs and merging them. Here the subject is
executor/extsort.SpillSorter (the same role: spill-to-disk sort with
bounded memory), timed end-to-end: feed N random rows in chunks, force
runs of `run_rows`, drain the globally sorted stream.

Usage: python -m tidb_tpu.benchmarks.benchfilesort \
    [--rows N] [--run-rows N] [--chunk-rows N] [--key-cols N]
"""

from __future__ import annotations

import argparse
import time

import numpy as np

__all__ = ["run", "main"]


def run(rows: int = 200_000, run_rows: int = 50_000,
        chunk_rows: int = 8192, key_cols: int = 1) -> dict:
    from tidb_tpu.chunk import Chunk, Column
    from tidb_tpu.executor.extsort import SpillSorter
    from tidb_tpu.expression import col
    from tidb_tpu.sqltypes import new_int_field, new_string_field

    rng = np.random.default_rng(42)
    fts = [new_int_field() for _ in range(key_cols)] + [new_string_field()]
    by = [(col(i, fts[i]), i % 2 == 1) for i in range(key_cols)]

    t0 = time.perf_counter()
    sorter = SpillSorter(by, run_rows=run_rows)
    fed = 0
    payload = np.array([f"row-payload-{i % 97}" for i in range(chunk_rows)],
                       dtype=object)
    while fed < rows:
        n = min(chunk_rows, rows - fed)
        cols = [Column(fts[i], rng.integers(0, rows, n),
                       np.ones(n, dtype=bool))
                for i in range(key_cols)]
        cols.append(Column(fts[-1], payload[:n], np.ones(n, dtype=bool)))
        sorter.add(Chunk(cols))
        fed += n
    build_secs = time.perf_counter() - t0

    t0 = time.perf_counter()
    out_rows = 0
    prev = None
    for ch in sorter.sorted_chunks():
        out_rows += ch.num_rows
        first = int(ch.columns[0].data[0])
        if prev is not None and key_cols == 1:
            assert first >= prev, "sort order violated"
        prev = int(ch.columns[0].data[-1])
    drain_secs = time.perf_counter() - t0
    sorter.close()
    assert out_rows == rows

    total = build_secs + drain_secs
    print(f"rows={rows} runs_of={run_rows} build={build_secs:.3f}s "
          f"drain={drain_secs:.3f}s total={total:.3f}s "
          f"({rows / total:.0f} rows/s)", flush=True)
    return {"rows": rows, "build_secs": build_secs,
            "drain_secs": drain_secs, "rows_per_sec": rows / total}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tidb_tpu.benchmarks.benchfilesort")
    p.add_argument("--rows", type=int, default=200_000)
    p.add_argument("--run-rows", type=int, default=50_000)
    p.add_argument("--chunk-rows", type=int, default=8192)
    p.add_argument("--key-cols", type=int, default=1)
    args = p.parse_args(argv)
    run(args.rows, args.run_rows, args.chunk_rows, args.key_cols)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
