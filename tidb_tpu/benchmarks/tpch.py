"""Scaled TPC-H generator + offline loader for the end-to-end benchmark.

Reference: BASELINE.md configs 2-4 (TPC-H Q1/Q3/Q5 through the server) and
/root/reference/cmd/benchdb (the SQL workload driver role). Row counts
scale with `sf` following the TPC-H spec's cardinalities; value
distributions match tests/tpch.py so the tiny SQL-loaded corpus and the
bulk-loaded benchmark corpus exercise identical query selectivities.

Everything is generated as numpy columns and ingested through
table.bulkload (the offline-import path) — the SQL INSERT path is
exercised separately by the test suite.
"""

from __future__ import annotations

import datetime

import numpy as np

from tidb_tpu.table import Table, bulkload

REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
NATIONS = [  # (name, region_idx) — the 25 spec nations
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]
SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY", "HOUSEHOLD"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
FLAGS = ["A", "N", "R"]
STATUSES = ["F", "O"]

_EPOCH_DATE = datetime.date(1992, 1, 1)
_DAY_US = 86_400_000_000


def _epoch_us() -> int:
    # match sqltypes.parse_datetime's epoch convention exactly
    from tidb_tpu.sqltypes import parse_datetime
    return parse_datetime("1992-01-01")

DDL = """
CREATE TABLE region (r_regionkey BIGINT PRIMARY KEY, r_name VARCHAR(25));
CREATE TABLE nation (n_nationkey BIGINT PRIMARY KEY, n_name VARCHAR(25),
                     n_regionkey BIGINT);
CREATE TABLE customer (c_custkey BIGINT PRIMARY KEY,
                       c_nationkey BIGINT, c_mktsegment VARCHAR(10));
CREATE TABLE supplier (s_suppkey BIGINT PRIMARY KEY, s_nationkey BIGINT);
CREATE TABLE orders (o_orderkey BIGINT PRIMARY KEY, o_custkey BIGINT,
                     o_orderdate DATE, o_shippriority BIGINT,
                     o_orderpriority VARCHAR(15));
CREATE TABLE lineitem (l_id BIGINT PRIMARY KEY, l_orderkey BIGINT,
                       l_suppkey BIGINT,
                       l_quantity DECIMAL(15,2),
                       l_extendedprice DECIMAL(15,2),
                       l_discount DECIMAL(15,2), l_tax DECIMAL(15,2),
                       l_returnflag CHAR(1), l_linestatus CHAR(1),
                       l_shipdate DATE, l_commitdate DATE,
                       l_receiptdate DATE);
"""


def _days_us(days: np.ndarray) -> np.ndarray:
    """TPC-H day offsets -> epoch-microsecond DATE datums."""
    return _epoch_us() + days.astype(np.int64) * _DAY_US


class ScaledTpch:
    """Numpy TPC-H tables at scale factor `sf` (sf=1 ~ 6M lineitem)."""

    def __init__(self, sf: float = 1.0, seed: int = 42):
        rng = np.random.default_rng(seed)
        self.sf = sf
        customers = max(int(150_000 * sf), 50)
        orders = max(int(1_500_000 * sf), 200)
        lineitems = max(int(6_001_215 * sf), 800)
        suppliers = max(int(10_000 * sf), 20)
        self.counts = {"region": len(REGIONS), "nation": len(NATIONS),
                       "customer": customers, "supplier": suppliers,
                       "orders": orders, "lineitem": lineitems}
        n_nation = len(NATIONS)
        self.c_custkey = np.arange(customers, dtype=np.int64)
        self.c_nationkey = rng.integers(0, n_nation, customers)
        self.c_mktsegment = rng.integers(0, len(SEGMENTS), customers)
        self.s_suppkey = np.arange(suppliers, dtype=np.int64)
        self.s_nationkey = rng.integers(0, n_nation, suppliers)
        self.o_orderkey = np.arange(orders, dtype=np.int64)
        self.o_custkey = rng.integers(0, customers, orders)
        self.o_orderdate = rng.integers(0, 2405, orders)  # days since epoch
        self.o_shippriority = np.zeros(orders, dtype=np.int64)
        self.o_orderpriority = rng.integers(0, len(PRIORITIES), orders)
        self.l_orderkey = rng.integers(0, orders, lineitems)
        self.l_suppkey = rng.integers(0, suppliers, lineitems)
        self.l_quantity = rng.integers(1, 51, lineitems)       # whole units
        self.l_extendedprice = rng.integers(90000, 10500000, lineitems)
        self.l_discount = rng.integers(0, 11, lineitems)       # percent
        self.l_tax = rng.integers(0, 9, lineitems)             # percent
        self.l_returnflag = rng.integers(0, 3, lineitems)
        self.l_linestatus = rng.integers(0, 2, lineitems)
        base = self.o_orderdate[self.l_orderkey]
        self.l_shipdate = base + rng.integers(1, 122, lineitems)
        self.l_commitdate = base + rng.integers(30, 92, lineitems)
        self.l_receiptdate = self.l_shipdate + rng.integers(1, 31, lineitems)


def load(session, storage, d: ScaledTpch, regions_per_table: int = 4) -> int:
    """DDL + bulk ingest + region pre-split. -> total rows loaded."""
    for stmt in DDL.strip().split(";"):
        if stmt.strip():
            session.execute(stmt)
    ischema = session.domain.info_schema()
    db = session.current_db

    def tbl(name):
        return Table(ischema.table(db, name), storage)

    def strs(values, idx):
        return np.array(values, dtype=object)[idx]

    total = 0
    total += bulkload.bulk_load(storage, tbl("region"), {
        "r_regionkey": np.arange(len(REGIONS), dtype=np.int64),
        "r_name": np.array(REGIONS, dtype=object)})
    total += bulkload.bulk_load(storage, tbl("nation"), {
        "n_nationkey": np.arange(len(NATIONS), dtype=np.int64),
        "n_name": np.array([n for n, _r in NATIONS], dtype=object),
        "n_regionkey": np.array([r for _n, r in NATIONS], dtype=np.int64)})
    total += bulkload.bulk_load(storage, tbl("customer"), {
        "c_custkey": d.c_custkey,
        "c_nationkey": d.c_nationkey,
        "c_mktsegment": strs(SEGMENTS, d.c_mktsegment)})
    total += bulkload.bulk_load(storage, tbl("supplier"), {
        "s_suppkey": d.s_suppkey, "s_nationkey": d.s_nationkey})
    total += bulkload.bulk_load(storage, tbl("orders"), {
        "o_orderkey": d.o_orderkey, "o_custkey": d.o_custkey,
        "o_orderdate": _days_us(d.o_orderdate),
        "o_shippriority": d.o_shippriority,
        "o_orderpriority": strs(PRIORITIES, d.o_orderpriority)})
    nl = d.counts["lineitem"]
    total += bulkload.bulk_load(storage, tbl("lineitem"), {
        "l_id": np.arange(nl, dtype=np.int64),
        "l_orderkey": d.l_orderkey, "l_suppkey": d.l_suppkey,
        "l_quantity": d.l_quantity * 100,          # DECIMAL(15,2) scaled
        "l_extendedprice": d.l_extendedprice,      # cents == scaled frac 2
        "l_discount": d.l_discount,                # 0.0p -> p at frac 2
        "l_tax": d.l_tax,
        "l_returnflag": strs(FLAGS, d.l_returnflag),
        "l_linestatus": strs(STATUSES, d.l_linestatus),
        "l_shipdate": _days_us(d.l_shipdate),
        "l_commitdate": _days_us(d.l_commitdate),
        "l_receiptdate": _days_us(d.l_receiptdate)})
    # pre-split the big tables so reads exercise the region fan-out
    # (ref: cluster.go SplitTable; BASELINE config 5's multi-region scan)
    cluster = storage.cluster
    for name, count in (("lineitem", nl), ("orders", d.counts["orders"])):
        cluster.split_table(ischema.table(db, name).id, regions_per_table,
                            max_handle=count)
    return total


Q1 = """
SELECT l_returnflag, l_linestatus,
       SUM(l_quantity) AS sum_qty,
       SUM(l_extendedprice) AS sum_base_price,
       SUM(l_extendedprice * (1 - l_discount)) AS sum_disc_price,
       SUM(l_extendedprice * (1 - l_discount) * (1 + l_tax)) AS sum_charge,
       AVG(l_quantity) AS avg_qty,
       AVG(l_extendedprice) AS avg_price,
       AVG(l_discount) AS avg_disc,
       COUNT(*) AS count_order
FROM lineitem
WHERE l_shipdate <= DATE '1998-12-01' - INTERVAL '90' DAY
GROUP BY l_returnflag, l_linestatus
ORDER BY l_returnflag, l_linestatus
"""

Q3 = """
SELECT l_orderkey,
       SUM(l_extendedprice * (1 - l_discount)) AS revenue,
       o_orderdate, o_shippriority
FROM customer, orders, lineitem
WHERE c_mktsegment = 'BUILDING'
  AND c_custkey = o_custkey
  AND l_orderkey = o_orderkey
  AND o_orderdate < DATE '1995-03-15'
  AND l_shipdate > DATE '1995-03-15'
GROUP BY l_orderkey, o_orderdate, o_shippriority
ORDER BY revenue DESC, o_orderdate
LIMIT 10
"""

Q5 = """
SELECT n_name,
       SUM(l_extendedprice * (1 - l_discount)) AS revenue
FROM customer, orders, lineitem, supplier, nation, region
WHERE c_custkey = o_custkey
  AND l_orderkey = o_orderkey
  AND l_suppkey = s_suppkey
  AND c_nationkey = s_nationkey
  AND s_nationkey = n_nationkey
  AND n_regionkey = r_regionkey
  AND r_name = 'ASIA'
  AND o_orderdate >= DATE '1994-01-01'
  AND o_orderdate < DATE '1994-01-01' + INTERVAL '1' YEAR
GROUP BY n_name
ORDER BY revenue DESC
"""

# per-query input-row accounting (tables each query scans)
QUERY_TABLES = {
    "q1": ["lineitem"],
    "q3": ["lineitem", "orders", "customer"],
    "q5": ["lineitem", "orders", "customer", "supplier", "nation",
           "region"],
}
QUERIES = {"q1": Q1, "q3": Q3, "q5": Q5}
