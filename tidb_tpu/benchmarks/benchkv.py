"""Txn / raw KV throughput tool.

Reference: /root/reference/cmd/benchkv/main.go:122-140 (batchRW
measuring transactional set+get round trips against a live cluster) and
cmd/benchraw (the raw-KV variant). Drives the same code paths a SQL
workload uses — 2PC with region batching for txn mode, region-routed
raw ops for raw mode — against the in-process store or an
out-of-process storage server (--addr host:port).

    python -m tidb_tpu.benchmarks.benchkv --keys 20000 --batch 200
    python -m tidb_tpu.benchmarks.benchkv --mode raw --workers 8
"""

from __future__ import annotations

import argparse
import json
import threading
import time


def _run_txn(storage, keys: int, batch: int, worker_id: int) -> None:
    for lo in range(0, keys, batch):
        txn = storage.begin()
        for i in range(lo, min(lo + batch, keys)):
            txn.set(b"bench_w%d_k%08d" % (worker_id, i), b"v%d" % i)
        txn.commit()
    for lo in range(0, keys, batch):
        txn = storage.begin()
        for i in range(lo, min(lo + batch, keys)):
            assert txn.get(b"bench_w%d_k%08d" % (worker_id, i)) is not None
        txn.rollback()


def _run_raw(storage, keys: int, batch: int, worker_id: int) -> None:
    from tidb_tpu.store.rawkv import RawKVClient
    c = RawKVClient(storage)
    for lo in range(0, keys, batch):
        c.batch_put([(b"bench_w%d_k%08d" % (worker_id, i), b"v%d" % i)
                     for i in range(lo, min(lo + batch, keys))])
    for lo in range(0, keys, batch):
        got = c.batch_get([b"bench_w%d_k%08d" % (worker_id, i)
                           for i in range(lo, min(lo + batch, keys))])
        assert len(got) == min(lo + batch, keys) - lo


def run(storage, mode: str = "txn", keys: int = 10000, batch: int = 100,
        workers: int = 1) -> dict:
    fn = _run_txn if mode == "txn" else _run_raw
    t0 = time.perf_counter()
    if workers == 1:
        fn(storage, keys, batch, 0)
    else:
        errors: list[BaseException] = []

        def guarded(w: int) -> None:
            try:
                fn(storage, keys, batch, w)
            except BaseException as e:  # noqa: BLE001 - re-raised below
                errors.append(e)

        ts = [threading.Thread(target=guarded, args=(w,))
              for w in range(workers)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        if errors:       # a failed worker must fail the benchmark
            raise errors[0]
    dt = time.perf_counter() - t0
    total_ops = keys * workers * 2          # one write + one read per key
    return {"metric": f"benchkv_{mode}_ops_per_sec",
            "value": round(total_ops / dt, 1), "unit": "ops/s",
            "keys": keys, "batch": batch, "workers": workers,
            "elapsed_s": round(dt, 3)}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--mode", choices=("txn", "raw"), default="txn")
    p.add_argument("--keys", type=int, default=10000)
    p.add_argument("--batch", type=int, default=100)
    p.add_argument("--workers", type=int, default=1)
    p.add_argument("--addr", help="host:port of an out-of-process "
                                  "storage server (default: in-process)")
    p.add_argument("--regions", type=int, default=4,
                   help="pre-split the keyspace (in-process only)")
    args = p.parse_args(argv)
    if args.addr:
        from tidb_tpu.store.remote import connect
        host, port = args.addr.rsplit(":", 1)
        storage = connect(host, int(port))
    else:
        from tidb_tpu.store.storage import new_mock_storage
        storage = new_mock_storage()
        for w in range(args.workers):
            for i in range(1, args.regions):
                try:
                    storage.cluster.split(
                        b"bench_w%d_k%08d" %
                        (w, i * args.keys // args.regions))
                except ValueError:
                    pass
    print(json.dumps(run(storage, args.mode, args.keys, args.batch,
                         args.workers)))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
