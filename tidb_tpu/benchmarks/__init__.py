"""Benchmark harnesses (ref: /root/reference/cmd/benchdb — SQL workloads
against a store — and BASELINE.md's measurement configs)."""
