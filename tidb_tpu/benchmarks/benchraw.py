"""Raw KV throughput — the benchraw equivalent.

Reference: /root/reference/cmd/benchraw/main.go — parallel batch puts/
gets/deletes against the raw KV API, reporting elapsed time. Runs
against the in-process mock storage by default or an out-of-process
node with --addr (the reference's live-TiKV mode).

Usage: python -m tidb_tpu.benchmarks.benchraw \
    [--num N] [--batch N] [--value-size N] [--workers N] [--addr H:P]
"""

from __future__ import annotations

import argparse
import time
from concurrent.futures import ThreadPoolExecutor

__all__ = ["run", "main"]


def run(storage, num: int = 10000, batch: int = 128,
        value_size: int = 64, workers: int = 4) -> dict:
    from tidb_tpu.store.rawkv import RawKVClient
    client = RawKVClient(storage)
    val = b"v" * value_size
    keys = [b"raw_%010d" % i for i in range(num)]
    batches = [keys[i:i + batch] for i in range(0, num, batch)]

    def timed(name, fn):
        t0 = time.perf_counter()
        with ThreadPoolExecutor(max_workers=workers) as ex:
            list(ex.map(fn, batches))
        dt = time.perf_counter() - t0
        print(f"{name}: {num} keys in {dt:.3f}s "
              f"({num / dt:.0f} ops/s)", flush=True)
        return dt

    out = {
        "put_secs": timed("batch_put", lambda ks: client.batch_put(
            [(k, val) for k in ks])),
        "get_secs": timed("batch_get", client.batch_get),
        "delete_secs": timed(
            "delete", lambda ks: [client.delete(k) for k in ks]),
    }
    out["num"] = num
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tidb_tpu.benchmarks.benchraw")
    p.add_argument("--num", type=int, default=10000)
    p.add_argument("--batch", type=int, default=128)
    p.add_argument("--value-size", type=int, default=64)
    p.add_argument("--workers", type=int, default=4)
    p.add_argument("--addr", default=None)
    args = p.parse_args(argv)
    if args.addr:
        from tidb_tpu.store.remote import connect
        host, _, port = args.addr.rpartition(":")
        storage = connect(host or "127.0.0.1", int(port))
    else:
        from tidb_tpu.store.storage import new_mock_storage
        storage = new_mock_storage()
    run(storage, args.num, args.batch, args.value_size, args.workers)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
