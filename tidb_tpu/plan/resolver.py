"""Name resolution: AST expressions -> columnar expression trees.

Reference: /root/reference/plan/expression_rewriter.go (AST -> Expression
with column resolution against the child plan's schema) and
plan/resolver.go name checks.
"""

from __future__ import annotations

import datetime as _dt
import decimal as _decimal
from dataclasses import dataclass, field

from tidb_tpu import sqltypes as st
from tidb_tpu.expression import (AggDesc, AggFunc, ColumnRef, Constant,
                                 Expression, Op, col, const, func)
from tidb_tpu.parser import ast

__all__ = ["PlanSchema", "SchemaCol", "Resolver", "ResolveError"]


class ResolveError(Exception):
    pass


class ColumnAmbiguousError(ResolveError):
    """Ambiguity is a hard error even when an outer scope could resolve
    the name — never silently correlate an ambiguous column."""


# ---------------------------------------------------------------------------
# Outer-scope stack for correlated subqueries. While a subquery's plan is
# being built, the outer plan's schema sits on this stack; any name that
# fails to resolve locally is looked up outward and becomes a shared
# CorrelatedCol cell the apply executor binds per outer row (ref:
# expression_rewriter.go b.outerSchemas). Thread-local: each server
# connection plans on its own thread.


@dataclass
class OuterScope:
    schema: PlanSchema
    cells: dict = field(default_factory=dict)   # outer_idx -> CorrelatedCol


import threading as _threading

_scopes_tls = _threading.local()


def _outer_scopes() -> list:
    stack = getattr(_scopes_tls, "stack", None)
    if stack is None:
        stack = _scopes_tls.stack = []
    return stack


def reset_volatile() -> None:
    """Planner calls this before building; volatile folds (NOW(), ...)
    mark the flag so the resulting plan is never cached."""
    _scopes_tls.volatile = False


def mark_volatile() -> None:
    _scopes_tls.volatile = True


def was_volatile() -> bool:
    return getattr(_scopes_tls, "volatile", False)


class push_outer:
    """Context manager exposing an outer schema to subquery resolution."""

    def __init__(self, schema: PlanSchema):
        self.scope = OuterScope(schema)

    def __enter__(self) -> OuterScope:
        _outer_scopes().append(self.scope)
        return self.scope

    def __exit__(self, *exc):
        _outer_scopes().pop()
        return False


@dataclass
class SchemaCol:
    name: str                 # lower column/alias name
    table: str = ""           # lower table alias
    ft: st.FieldType = None
    col_id: int = 0           # ColumnInfo.id for datasource columns


@dataclass
class PlanSchema:
    cols: list[SchemaCol] = field(default_factory=list)

    def find(self, name: str, table: str = "") -> int:
        name = name.lower()
        table = table.lower()
        hits = [i for i, c in enumerate(self.cols)
                if c.name == name and (not table or c.table == table)]
        if not hits:
            raise ResolveError(f"Unknown column '{name}'")
        if len(hits) > 1:
            raise ColumnAmbiguousError(f"Column '{name}' is ambiguous")
        return hits[0]

    def merge(self, other: "PlanSchema") -> "PlanSchema":
        return PlanSchema(self.cols + other.cols)

    def __len__(self):
        return len(self.cols)


_FUNC_OPS = {
    "ABS": Op.ABS, "CEIL": Op.CEIL, "CEILING": Op.CEIL, "FLOOR": Op.FLOOR,
    "ROUND": Op.ROUND, "POW": Op.POW, "POWER": Op.POW, "SQRT": Op.SQRT,
    "EXP": Op.EXP, "LN": Op.LN, "LOG2": Op.LOG2, "SIGN": Op.SIGN,
    "CONCAT": Op.CONCAT, "LENGTH": Op.LENGTH, "UPPER": Op.UPPER,
    "UCASE": Op.UPPER, "LOWER": Op.LOWER, "LCASE": Op.LOWER,
    "TRIM": Op.TRIM, "LEFT": Op.LEFT, "RIGHT": Op.RIGHT,
    "SUBSTRING": Op.SUBSTRING, "SUBSTR": Op.SUBSTRING, "REPLACE": Op.REPLACE,
    "INSTR": Op.INSTR, "ASCII": Op.ASCII,
    "YEAR": Op.YEAR, "MONTH": Op.MONTH, "DAY": Op.DAY,
    "DAYOFMONTH": Op.DAY, "HOUR": Op.HOUR, "MINUTE": Op.MINUTE,
    "SECOND": Op.SECOND, "DATEDIFF": Op.DATEDIFF,
    "IF": Op.IF, "IFNULL": Op.IFNULL, "COALESCE": Op.COALESCE,
    "MID": Op.SUBSTRING,
}

_AGG_MAP = {"COUNT": AggFunc.COUNT, "SUM": AggFunc.SUM, "AVG": AggFunc.AVG,
            "MIN": AggFunc.MIN, "MAX": AggFunc.MAX,
            "BIT_AND": AggFunc.BIT_AND, "BIT_OR": AggFunc.BIT_OR,
            "BIT_XOR": AggFunc.BIT_XOR,
            "GROUP_CONCAT": AggFunc.GROUP_CONCAT}

def _row_eq(le: "ast.RowExpr", ri: "ast.RowExpr") -> ast.ExprNode:
    """(a,b) = (c,d)  ->  a=c AND b=d."""
    out = None
    for x, y in zip(le.items, ri.items):
        c = ast.BinaryOp("=", x, y)
        out = c if out is None else ast.BinaryOp("AND", out, c)
    return out


def _row_ord(op: str, le, ri, i: int) -> ast.ExprNode:
    """Lexicographic row ordering: (a1,a2) < (b1,b2) is
    a1<b1 OR (a1=b1 AND a2<b2); <=/>= stay weak only at the tail."""
    x, y = le.items[i], ri.items[i]
    if i == len(le.items) - 1:
        return ast.BinaryOp(op, x, y)
    strict = {"<=": "<", ">=": ">"}.get(op, op)
    return ast.BinaryOp(
        "OR", ast.BinaryOp(strict, x, y),
        ast.BinaryOp("AND", ast.BinaryOp("=", x, y),
                     _row_ord(op, le, ri, i + 1)))


def _has_correlated(x) -> bool:
    from tidb_tpu.expression.core import CorrelatedCol
    if isinstance(x, CorrelatedCol):
        return True
    return any(_has_correlated(a) for a in getattr(x, "args", ()))


_BIN_OPS = {"+": Op.PLUS, "-": Op.MINUS, "*": Op.MUL, "/": Op.DIV,
            "DIV": Op.INTDIV, "%": Op.MOD, "MOD": Op.MOD,
            "=": Op.EQ, "<": Op.LT, "<=": Op.LE, ">": Op.GT, ">=": Op.GE,
            "<>": Op.NE, "!=": Op.NE, "<=>": Op.NULLEQ,
            "AND": Op.AND, "OR": Op.OR, "XOR": Op.XOR,
            "&": Op.BIT_AND, "|": Op.BIT_OR, "^": Op.BIT_XOR,
            "<<": Op.SHL, ">>": Op.SHR}


def _expr_key(e):
    """Structural identity of a resolved expression: column INDEXES
    (names are display-only and can collide across tables)."""
    if e is None:
        return None
    if isinstance(e, ColumnRef):
        return ("col", e.idx)
    if isinstance(e, Constant):
        return ("const", repr(e.value))
    args = getattr(e, "args", None)
    if args is not None:
        return (type(e).__name__, getattr(e, "op", None),
                tuple(_expr_key(a) for a in args))
    return repr(e)


class Resolver:
    """Resolves AST exprs against a PlanSchema. When `agg_collector` is set,
    AggregateCall nodes are collected as AggDescs and replaced by refs into
    the aggregation's output schema."""

    def __init__(self, schema: PlanSchema,
                 agg_collector: list[AggDesc] | None = None,
                 agg_base: int = 0):
        self.schema = schema
        self.aggs = agg_collector
        self.agg_base = agg_base  # index offset of agg outputs in out schema

    def resolve(self, e: ast.ExprNode) -> Expression:
        m = getattr(self, "_r_" + type(e).__name__, None)
        if m is None:
            raise ResolveError(f"unsupported expression {type(e).__name__}")
        return m(e)

    # -- leaves --------------------------------------------------------------

    def _r_Literal(self, e: ast.Literal) -> Expression:
        v = e.value
        if isinstance(v, str):
            # date-ish literals stay strings until compared with a time
            # column; the comparison coercion below handles it
            return const(v)
        return const(v)

    def _r_ColName(self, e: ast.ColName) -> Expression:
        try:
            idx = self.schema.find(e.name, e.table)
        except ColumnAmbiguousError:
            raise
        except ResolveError:
            for scope in reversed(_outer_scopes()):
                try:
                    oi = scope.schema.find(e.name, e.table)
                except ColumnAmbiguousError:
                    raise   # ambiguity is a hard error at EVERY scope
                except ResolveError:
                    continue
                cc = scope.cells.get(oi)
                if cc is None:
                    from tidb_tpu.expression.core import CorrelatedCol
                    sc = scope.schema.cols[oi]
                    cc = CorrelatedCol(sc.ft, name=sc.name)
                    scope.cells[oi] = cc
                return cc
            raise
        sc = self.schema.cols[idx]
        return ColumnRef(idx, sc.ft, name=sc.name)

    def _r_VariableExpr(self, e: ast.VariableExpr) -> Expression:
        raise ResolveError("variables resolve in the session layer")

    # -- operators -----------------------------------------------------------

    def _coerce_time(self, a: Expression, b: Expression):
        """'2024-01-01' literals compared to DATETIME columns become
        epoch-micros constants (MySQL implicit date coercion)."""
        for x, y in ((a, b), (b, a)):
            if x.ft.eval_type == st.EvalType.DATETIME and \
                    isinstance(y, Constant) and isinstance(y.value, str):
                try:
                    micros = st.parse_datetime(y.value)
                except ValueError:
                    raise ResolveError(f"invalid date literal {y.value!r}")
                new = Constant(micros, x.ft)
                if y is b:
                    return a, new
                return new, b
        return a, b

    def _r_BinaryOp(self, e: ast.BinaryOp) -> Expression:
        if isinstance(e.left, ast.RowExpr) or \
                isinstance(e.right, ast.RowExpr):
            # (a,b) <cmp> (c,d): desugar to scalar logic (ref:
            # expression/expression.go row-expression handling); NULLs
            # propagate correctly through the Kleene AND/OR ops
            return self.resolve(self._desugar_row_cmp(e))
        op = _BIN_OPS.get(e.op)
        if op is None:
            raise ResolveError(f"unsupported operator {e.op}")
        a = self.resolve(e.left)
        b = self.resolve(e.right)
        a, b = self._coerce_time(a, b)
        a, b = self._coerce_enum_set(a, b)
        return func(op, a, b)

    @staticmethod
    def _normalize_enum_const(col_ft, value):
        """-> normalized member spelling, or the value unchanged."""
        from tidb_tpu.sqltypes import TypeCode
        if col_ft.tp in (TypeCode.ENUM, TypeCode.SET) and \
                isinstance(value, str):
            from tidb_tpu.table import _normalize_enum_set
            try:
                return _normalize_enum_set(value, col_ft)
            except Exception:   # noqa: BLE001 - unknown member
                return value
        return value

    @staticmethod
    def _coerce_enum_set(a: Expression, b: Expression):
        """A string constant compared against an ENUM/SET column
        normalizes to the member's stored spelling (writes accept
        members case-insensitively, so reads must too; an unknown
        member stays as-is and simply matches nothing)."""
        from tidb_tpu.sqltypes import TypeCode

        def fix(col, const):
            if isinstance(const, Constant) and \
                    isinstance(const.value, str):
                norm = Resolver._normalize_enum_const(col.ft, const.value)
                if norm != const.value:
                    return Constant(norm, const.ft)
            return const

        return fix(b, a), fix(a, b)

    def _r_UnaryOp(self, e: ast.UnaryOp) -> Expression:
        a = self.resolve(e.operand)
        if e.op == "-":
            # fold over numeric literals: INTERVAL -1 MONTH and range
            # pruning both want a plain Constant, not a ScalarFunc
            if isinstance(a, Constant) and not isinstance(a.value, bool) \
                    and isinstance(a.value, (int, float, _decimal.Decimal)):
                return Constant(-a.value, a.ft)
            return func(Op.UNARY_MINUS, a)
        if e.op == "NOT":
            return func(Op.NOT, a)
        if e.op == "~":
            return func(Op.BIT_NEG, a)
        raise ResolveError(f"unsupported unary {e.op}")

    def _r_IsNullExpr(self, e: ast.IsNullExpr) -> Expression:
        f = func(Op.IS_NOT_NULL if e.negated else Op.IS_NULL,
                 self.resolve(e.expr))
        return f

    def _r_InExpr(self, e: ast.InExpr) -> Expression:
        if isinstance(e.items, ast.SubqueryExpr):
            raise ResolveError("IN (subquery) not yet supported")
        if isinstance(e.expr, ast.RowExpr):
            # (a,b) IN ((1,2),(3,4)): OR over per-row equality chains
            want = len(e.expr.items)
            ors = None
            for item in e.items:
                if not isinstance(item, ast.RowExpr) or \
                        len(item.items) != want:
                    raise ResolveError(
                        f"Operand should contain {want} column(s)")
                c = _row_eq(e.expr, item)
                ors = c if ors is None else ast.BinaryOp("OR", ors, c)
            if ors is None:
                raise ResolveError("IN list must not be empty")
            out = self.resolve(ors)
            return func(Op.NOT, out) if e.negated else out
        target = self.resolve(e.expr)
        vals = []
        for item in e.items:
            r = self.resolve(item)
            if not isinstance(r, Constant):
                # fall back to OR chain for non-constant items
                ors = None
                for item2 in e.items:
                    t2, r2 = self._coerce_time(target, self.resolve(item2))
                    _, r2 = self._coerce_enum_set(t2, r2)
                    cmp_ = func(Op.EQ, t2, r2)
                    ors = cmp_ if ors is None else func(Op.OR, ors, cmp_)
                return func(Op.NOT, ors) if e.negated else ors
            _, r = self._coerce_time(target, r)
            vals.append(self._normalize_enum_const(target.ft, r.value))
        out = func(Op.IN, target, extra=vals)
        return func(Op.NOT, out) if e.negated else out

    def _r_BetweenExpr(self, e: ast.BetweenExpr) -> Expression:
        x = self.resolve(e.expr)
        lo = self.resolve(e.low)
        hi = self.resolve(e.high)
        x1, lo = self._coerce_time(x, lo)
        x2, hi = self._coerce_time(x, hi)
        _, lo = self._coerce_enum_set(x1, lo)
        _, hi = self._coerce_enum_set(x2, hi)
        r = func(Op.AND, func(Op.GE, x1, lo), func(Op.LE, x2, hi))
        return func(Op.NOT, r) if e.negated else r

    def _r_LikeExpr(self, e: ast.LikeExpr) -> Expression:
        pat = self.resolve(e.pattern)
        if not isinstance(pat, Constant) or not isinstance(pat.value, str):
            raise ResolveError("LIKE pattern must be a string literal")
        out = func(Op.LIKE, self.resolve(e.expr),
                   extra=(pat.value, e.escape))
        return func(Op.NOT, out) if e.negated else out

    def _r_CaseExpr(self, e: ast.CaseExpr) -> Expression:
        args = []
        if e.operand is not None:
            op_expr = self.resolve(e.operand)
            for c, v in e.when_clauses:
                cc, rc = self._coerce_time(op_expr, self.resolve(c))
                args.append(func(Op.EQ, cc, rc))
                args.append(self.resolve(v))
        else:
            for c, v in e.when_clauses:
                args.append(self.resolve(c))
                args.append(self.resolve(v))
        if e.else_clause is not None:
            args.append(self.resolve(e.else_clause))
        return func(Op.CASE, *args)

    def _r_CastExpr(self, e: ast.CastExpr) -> Expression:
        a = self.resolve(e.expr)
        et = e.ft.eval_type
        if et == st.EvalType.INT:
            return func(Op.CAST_INT, a)
        if et == st.EvalType.REAL:
            return func(Op.CAST_REAL, a)
        if et == st.EvalType.DECIMAL:
            return func(Op.CAST_DECIMAL, a, extra=e.ft)
        if et == st.EvalType.DATETIME:
            if isinstance(a, Constant) and isinstance(a.value, str):
                return Constant(st.parse_datetime(a.value), e.ft)
            return a  # already micros
        return func(Op.CAST_STRING, a)

    def _r_FuncCall(self, e: ast.FuncCall) -> Expression:
        name = e.name.upper()
        if name in ("DATE_ADD", "DATE_SUB", "ADDDATE", "SUBDATE"):
            return self._date_arith(e, sub=name in ("DATE_SUB", "SUBDATE"))
        if name == "DATE":
            a = self.resolve(e.args[0])
            if isinstance(a, Constant) and isinstance(a.value, str):
                return Constant(st.parse_datetime(a.value),
                                st.new_date_field())
            return a
        if name == "NOW" or name == "CURRENT_TIMESTAMP":
            mark_volatile()   # folded at plan time: such plans never cache
            return Constant(st.datetime_to_micros(_dt.datetime.now()),
                            st.new_datetime_field())
        if name == "DATABASE":
            raise ResolveError("DATABASE() resolves in the session layer")
        if name == "ISNULL":
            if len(e.args) != 1:
                raise ResolveError("Incorrect parameter count for ISNULL")
            return func(Op.IS_NULL, self.resolve(e.args[0]))
        if name == "NULLIF":
            if len(e.args) != 2:
                raise ResolveError("Incorrect parameter count for NULLIF")
            # NULLIF(a,b) == CASE WHEN a=b THEN NULL ELSE a END
            a = self.resolve(e.args[0])
            b = self.resolve(e.args[1])
            return func(Op.CASE, func(Op.EQ, a, b),
                        Constant(None, a.ft), a)
        op = _FUNC_OPS.get(name)
        if op is None:
            from tidb_tpu.expression.builtins import lookup
            spec = lookup(name)
            if spec is None:
                raise ResolveError(f"unsupported function {name}")
            if not (spec.min_args <= len(e.args) <= spec.max_args):
                raise ResolveError(
                    f"Incorrect parameter count for {name}")
            args = [self.resolve(a) for a in e.args]
            return func(Op.GENERIC, *args, extra=spec)
        args = [self.resolve(a) for a in e.args]
        return func(op, *args)

    def _date_arith(self, e: ast.FuncCall, sub: bool) -> Expression:
        base = self.resolve(e.args[0])
        if isinstance(base, Constant) and isinstance(base.value, str):
            base = Constant(st.parse_datetime(base.value),
                            st.new_datetime_field())
        iv = e.args[1]
        if isinstance(iv, ast.FuncCall) and iv.name == "INTERVAL":
            n = self.resolve(iv.args[0])
            unit = iv.args[1].value
        else:
            n = self.resolve(iv)
            unit = "DAY"
        if not isinstance(n, Constant) and not n.columns_used() and \
                not _has_correlated(n):
            # fold computed amounts (INTERVAL 1+1 DAY)
            import numpy as _np
            d, v = n.eval_xp(_np, [], 1)
            val = None if not v[0] else (
                d[0].item() if hasattr(d[0], "item") else d[0])
            if val is not None and \
                    n.ft.eval_type == st.EvalType.DECIMAL:
                # eval_xp yields the scaled int representation
                val = st.scaled_to_decimal(int(val), max(n.ft.frac, 0))
            n = Constant(val, n.ft)
        if not isinstance(n, Constant):
            raise ResolveError("INTERVAL amount must be constant")
        if n.value is None:
            return Constant(None, base.ft)   # NULL interval -> NULL
        v = n.value
        if isinstance(v, str):
            try:
                v = _decimal.Decimal(v.strip())
            except _decimal.InvalidOperation:
                raise ResolveError(f"incorrect INTERVAL amount {v!r}")
        if isinstance(v, (float, _decimal.Decimal)):
            dv = _decimal.Decimal(str(v))
            if not dv.is_finite() or abs(dv) > 10 ** 12:
                raise ResolveError(
                    f"incorrect INTERVAL amount {str(n.value)!r}")
            if unit == "SECOND" and dv != dv.to_integral_value():
                # MySQL: a fractional SECOND amount is seconds.micros
                total = int((dv * 1_000_000).quantize(
                    0, rounding=_decimal.ROUND_HALF_UP))
                total *= -1 if sub else 1
                if isinstance(base, Constant):
                    return Constant(None if base.value is None
                                    else base.value + total, base.ft)
                return func(Op.DATE_ADD_US, base, const(total))
            # other integer units round half-up
            v = dv.quantize(0, rounding=_decimal.ROUND_HALF_UP)
        amount = int(v) * (-1 if sub else 1)
        us_per = {"MICROSECOND": 1, "SECOND": 1_000_000,
                  "MINUTE": 60_000_000, "HOUR": 3_600_000_000,
                  "DAY": 86_400_000_000, "WEEK": 7 * 86_400_000_000}
        months_per = {"MONTH": 1, "QUARTER": 3, "YEAR": 12}
        if unit in us_per:
            total = amount * us_per[unit]
            if isinstance(base, Constant):
                return Constant(None if base.value is None
                                else base.value + total, base.ft)
            return func(Op.DATE_ADD_US, base, const(total))
        if unit not in months_per:
            raise ResolveError(f"unsupported INTERVAL unit {unit}")
        months = months_per[unit] * amount
        if isinstance(base, Constant):
            # fold for constants so index range pruning still sees a
            # plain comparison constant (the common TPC-H case)
            dt = st.micros_to_datetime(base.value)
            y = dt.year + (dt.month - 1 + months) // 12
            m = (dt.month - 1 + months) % 12 + 1
            try:
                nd = dt.replace(year=y, month=m)
            except ValueError:  # day beyond target month: clamp
                nxt_y, nxt_m = (y, m + 1) if m < 12 else (y + 1, 1)
                last = (_dt.date(nxt_y, nxt_m, 1) -
                        _dt.timedelta(days=1)).day
                nd = dt.replace(year=y, month=m, day=last)
            return Constant(st.datetime_to_micros(nd), base.ft)
        return func(Op.ADD_MONTHS, base, const(months))

    def _r_AggregateCall(self, e: ast.AggregateCall) -> Expression:
        if self.aggs is None:
            raise ResolveError(
                f"aggregate {e.name} not allowed in this clause")
        name = e.name.upper()
        fn = _AGG_MAP.get(name)
        if fn is None:
            raise ResolveError(f"unsupported aggregate {name}")
        arg = None
        if not e.star:
            if len(e.args) != 1:
                raise ResolveError(f"{name} takes one argument")
            arg = self.resolve(e.args[0])
        desc = AggDesc(fn, arg, distinct=e.distinct,
                       sep=getattr(e, "sep", ","))

        # reuse identical aggs — compared STRUCTURALLY (column indexes,
        # not display names: max(a.b) and max(b.b) both repr as max(b))
        def key(d):
            return (d.fn, d.distinct, d.sep, _expr_key(d.arg))
        for i, d in enumerate(self.aggs):
            if key(d) == key(desc):
                return ColumnRef(self.agg_base + i, d.result_ft)
        self.aggs.append(desc)
        return ColumnRef(self.agg_base + len(self.aggs) - 1, desc.result_ft)

    def _r_SubqueryExpr(self, e):
        raise ResolveError("scalar subqueries not yet supported")

    def _r_ExistsSubquery(self, e):
        raise ResolveError("EXISTS subqueries not yet supported")

    def _r_RowExpr(self, e):
        raise ResolveError(
            "row expression only valid in comparisons and IN")

    def _desugar_row_cmp(self, e: ast.BinaryOp) -> ast.ExprNode:
        le, ri = e.left, e.right
        if not (isinstance(le, ast.RowExpr) and
                isinstance(ri, ast.RowExpr)):
            n = len((le if isinstance(le, ast.RowExpr) else ri).items)
            raise ResolveError(f"Operand should contain {n} column(s)")
        if len(le.items) != len(ri.items):
            raise ResolveError(
                f"Operand should contain {len(le.items)} column(s)")
        if e.op == "=":
            return _row_eq(le, ri)
        if e.op in ("<>", "!="):
            return ast.UnaryOp("NOT", _row_eq(le, ri))
        if e.op in ("<", ">", "<=", ">="):
            return _row_ord(e.op, le, ri, 0)
        raise ResolveError(f"unsupported row operator {e.op}")

    def _r_DefaultExpr(self, e):
        raise ResolveError("DEFAULT only valid in INSERT values")

    def _r_ParamMarker(self, e):
        if not e.bound:
            raise ResolveError("unbound parameter marker (use EXECUTE "
                               "with USING, or the binary protocol)")
        return const(e.value)

    def _r_Star(self, e):
        raise ResolveError("* only valid in select list")
