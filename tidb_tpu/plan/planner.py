"""Rule-based planner: AST -> physical plan with storage pushdown.

Reference: /root/reference/plan/ — logical build (logical_plan_builder.go),
rule-based optimization {columnPruner, ppdSolver, aggregationOptimizer,
pushDownTopNOptimizer} (plan/optimizer.go:42-50), and the copTask/rootTask
split (plan/task.go:116-499). Rules here run during construction:

* predicate pushdown: WHERE/ON conjuncts sink into table readers (split
  into device-safe vs host-only parts), equi-conds become hash-join keys
* column pruning: readers scan only referenced columns
* aggregation pushdown: single-reader group-by ships as a storage-side
  partial agg (CopPlan.aggs) merged by a root PhysFinalAgg
* TopN pushdown: ORDER BY + LIMIT over a bare reader pushes the limit
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass

from tidb_tpu import sqltypes as st
from tidb_tpu.expression import (AggDesc, AggFunc, ColumnRef, Constant,
                                 Expression, Op, ScalarFunc, and_all, func)
from tidb_tpu.parser import ast
from tidb_tpu.plan import physical as ph
from tidb_tpu.plan.resolver import (ColumnAmbiguousError, PlanSchema,
                                    Resolver, ResolveError, SchemaCol)
from tidb_tpu.schema.infoschema import InfoSchema, SchemaError

__all__ = ["Planner", "PlanError"]


class PlanError(Exception):
    pass


def split_conjuncts(e: ast.ExprNode | None) -> list[ast.ExprNode]:
    if e is None:
        return []
    if isinstance(e, ast.BinaryOp) and e.op == "AND":
        return split_conjuncts(e.left) + split_conjuncts(e.right)
    return [e]


def flatten_and(e: Expression | None) -> list[Expression]:
    if e is None:
        return []
    if isinstance(e, ScalarFunc) and e.op == Op.AND:
        return flatten_and(e.args[0]) + flatten_and(e.args[1])
    return [e]


def split_device_host(cond: Expression | None):
    """Partition a resolved conjunction into (device_safe, host_only)."""
    if cond is None:
        return None, None
    dev, host = [], []

    def walk(c: Expression):
        if isinstance(c, ScalarFunc) and c.op == Op.AND:
            walk(c.args[0])
            walk(c.args[1])
        elif c.is_device_safe():
            dev.append(c)
        else:
            host.append(c)

    walk(cond)
    return and_all(dev), and_all(host)


class _JoinGeometry:
    """Shared bookkeeping for one inner-join tree: leaf offsets in the
    concatenated schema, per-condition leaf sets, per-leaf size
    estimates (0 is a real estimate — an empty side should lead)."""

    BIG = 1 << 40      # leaves with no estimate order last

    def __init__(self, leaves, conds):
        self.leaves = leaves
        self.conds = conds
        self.offs = []
        at = 0
        for lf in leaves:
            self.offs.append(at)
            at += len(lf.schema)
        self.size = []
        for lf in leaves:
            est = getattr(lf, "est_rows", None)
            self.size.append(self.BIG if est is None else est)
        self.cond_leaves = [
            frozenset(self.leaf_of(i) for i in c.columns_used())
            for c in conds]

    def leaf_of(self, idx: int) -> int:
        for li in range(len(self.leaves)):
            if self.offs[li] <= idx < \
                    self.offs[li] + len(self.leaves[li].schema):
                return li
        raise PlanError("column outside join leaves")


class Planner:
    def __init__(self, infoschema: InfoSchema, current_db: str,
                 stats_handle=None, storage=None):
        self.stats = stats_handle
        self.ischema = infoschema
        self.db = current_db
        self.storage = storage   # membership registry for cluster_* fan-out
        self._handle_refs: set = set()   # multi-table DELETE targets
        # (level, code, message) notes the session surfaces as SHOW
        # WARNINGS — e.g. a cluster_* fan-out that degraded to partial
        # rows because a member was unreachable
        self.warnings: list[tuple[str, int, str]] = []

    def _tbl_stats(self, info):
        """TableStats for the table — pseudo when never analyzed."""
        if self.stats is None:
            from tidb_tpu.statistics import TableStats
            return TableStats(table_id=info.id)
        return self.stats.get(info.id)

    # -- entry ---------------------------------------------------------------

    def plan(self, stmt: ast.StmtNode) -> ph.PhysPlan:
        if isinstance(stmt, (ast.SelectStmt, ast.UnionStmt)):
            from tidb_tpu.plan.resolver import (mark_volatile,
                                                reset_volatile, was_volatile)
            # The volatile flag is process-global; a nested plan() (sub-
            # query, derived table) must compute ITS cacheability from a
            # clean flag, then leave "outer-so-far OR child" behind so an
            # enclosing statement keeps any NOW()-style fold it already
            # marked and inherits the child's volatility.
            outer_volatile = was_volatile()
            reset_volatile()
            from tidb_tpu.plan.mesh_route import route_mesh
            # mesh routing first: the fused mesh operators subsume the
            # algorithm choice below (and handle capacity escalation
            # themselves); the physical pass then optimizes what remains
            built = self._plan_query(stmt)
            # mesh routing first: its fused star-join pipeline matches
            # the ORIGINAL join shapes (and already orders dims itself);
            # greedy reorder then improves whatever stays on the
            # per-operator path
            p = self._opt_physical(self._reorder_joins(
                route_mesh(self._opt_access(built))))
            p.cacheable = not was_volatile()
            if outer_volatile:
                mark_volatile()
            return p
        if isinstance(stmt, ast.InsertStmt):
            p = self.plan_insert(stmt)
            if p.source is not None:
                p.source = self._opt_access(p.source)
            return p
        if isinstance(stmt, ast.UpdateStmt):
            p = self.plan_update(stmt)
            p.reader = self._opt_access(p.reader)
            return p
        if isinstance(stmt, ast.DeleteStmt):
            p = self.plan_delete(stmt)
            p.reader = self._opt_access(p.reader)
            return p
        raise PlanError(f"no plan for {type(stmt).__name__}")

    # -- FROM ----------------------------------------------------------------

    def _table_info(self, ts: ast.TableSource):
        db = ts.db or self.db
        if not db:
            raise PlanError("No database selected")
        try:
            return db, self.ischema.table(db, ts.name)
        except SchemaError as e:
            raise PlanError(str(e)) from None

    def build_reader(self, ts: ast.TableSource) -> ph.PhysPlan:
        db = (ts.db or self.db).lower()
        if db == "information_schema":
            return self._build_memtable(ts)
        if db == "performance_schema":
            return self._build_perfschema(ts)
        _db, info = self._table_info(ts)
        cols = info.public_columns()
        schema_cols = [
            SchemaCol(c.name.lower(), ts.ref_name.lower(), c.ft, c.id)
            for c in cols]
        handle_col = None
        if ts.ref_name.lower() in getattr(self, "_handle_refs", ()):
            # multi-table DELETE target: the row handle rides the join
            schema_cols.append(SchemaCol("_handle", ts.ref_name.lower(),
                                         st.new_int_field()))
            handle_col = len(cols)
        cop = ph.CopPlan(table=info, cols=list(cols),
                         handle_col=handle_col,
                         index_hints=list(ts.index_hints))
        return ph.PhysTableReader(schema=PlanSchema(schema_cols), cop=cop)

    # -- INFORMATION_SCHEMA virtual tables (ref: infoschema/tables.go) -------

    _MEMTABLES = ("schemata", "tables", "columns", "statistics",
                  "character_sets", "collations", "memory_usage",
                  "statement_traces", "resource_usage",
                  "kernel_profile", "statement_profile",
                  "cluster_members", "cluster_processlist",
                  "cluster_resource_usage", "cluster_statement_traces",
                  "cluster_kernel_profile")

    def _build_memtable(self, ts: ast.TableSource) -> ph.PhysValues:
        """Serve catalog metadata as constant rows computed from the
        current schema snapshot (the TableScanExec-over-memtable role of
        executor.go:803-912 + infoschema/tables.go)."""
        from tidb_tpu.schema.model import SchemaState
        from tidb_tpu.sqltypes import (new_int_field, new_string_field)
        name = ts.name.lower()
        alias = ts.ref_name.lower()
        if name.startswith("cluster_") and name in self._MEMTABLES:
            return self._build_cluster_table(name, alias)
        sf, intf = new_string_field(64), new_int_field()

        def mk(cols_spec, rows):
            schema = PlanSchema([SchemaCol(n, alias, ft)
                                 for n, ft in cols_spec])
            const_rows = []
            for r in rows:
                exprs = []
                for v, (_n, ft) in zip(r, cols_spec):
                    exprs.append(Constant(v, ft))
                const_rows.append(exprs)
            return ph.PhysValues(schema=schema, rows=const_rows)

        isch = self.ischema
        if name == "schemata":
            return mk([("catalog_name", sf), ("schema_name", sf)],
                      [("def", d) for d in
                       ["information_schema"] + isch.db_names()])
        if name == "tables":
            rows = []
            for d in isch.db_names():
                for t in isch.table_names(d):
                    info = isch.table(d, t)
                    rows.append(("def", d, t, "BASE TABLE", info.id))
            return mk([("table_catalog", sf), ("table_schema", sf),
                       ("table_name", sf), ("table_type", sf),
                       ("tidb_table_id", intf)], rows)
        if name == "columns":
            rows = []
            for d in isch.db_names():
                for t in isch.table_names(d):
                    info = isch.table(d, t)
                    for pos, c in enumerate(info.public_columns(), 1):
                        key = "PRI" if (info.pk_is_handle and
                                        c.name == info.pk_col_name) else ""
                        rows.append((d, t, c.name.lower(), pos,
                                     _type_word(c.ft),
                                     "NO" if c.ft.not_null else "YES",
                                     key))
            return mk([("table_schema", sf), ("table_name", sf),
                       ("column_name", sf), ("ordinal_position", intf),
                       ("data_type", sf), ("is_nullable", sf),
                       ("column_key", sf)], rows)
        if name == "statistics":
            rows = []
            for d in isch.db_names():
                for t in isch.table_names(d):
                    info = isch.table(d, t)
                    if info.pk_is_handle and info.pk_col_name:
                        rows.append((d, t, 0, "PRIMARY", 1,
                                     info.pk_col_name.lower()))
                    for idx in info.indexes:
                        if idx.state != SchemaState.PUBLIC:
                            continue
                        for seq, cn in enumerate(idx.columns, 1):
                            rows.append((d, t, 0 if idx.unique else 1,
                                         idx.name.lower(), seq,
                                         cn.lower()))
            return mk([("table_schema", sf), ("table_name", sf),
                       ("non_unique", intf), ("index_name", sf),
                       ("seq_in_index", intf), ("column_name", sf)], rows)
        if name == "character_sets":
            # the four charsets the engine actually stores (ref:
            # infoschema/tables.go charset rows / util/charset)
            rows = [("utf8mb4", "utf8mb4_bin", "UTF-8 Unicode", 4),
                    ("utf8", "utf8_bin", "UTF-8 Unicode", 3),
                    ("latin1", "latin1_bin", "cp1252 West European", 1),
                    ("binary", "binary", "Binary pseudo charset", 1)]
            return mk([("character_set_name", sf),
                       ("default_collate_name", sf),
                       ("description", sf), ("maxlen", intf)], rows)
        if name == "memory_usage":
            # hierarchical memory trackers (memtrack.py): one row per
            # live session (current + peak, host/device ledgers) plus
            # the server-root totals every session rolls up into
            from tidb_tpu import memtrack
            srv = memtrack.SERVER.snapshot()
            rows = [("server", 0, srv["host"], srv["device"],
                     srv["host_peak"], srv["device_peak"])]
            for snap in memtrack.sessions_snapshot():
                sid = snap["label"].rsplit("-", 1)[-1]
                rows.append(("session",
                             int(sid) if sid.isdigit() else 0,
                             snap["host"], snap["device"],
                             snap["host_peak"], snap["device_peak"]))
            pv = mk([("scope", sf), ("session_id", intf),
                     ("current_host_bytes", intf),
                     ("current_device_bytes", intf),
                     ("peak_host_bytes", intf),
                     ("peak_device_bytes", intf)], rows)
            # tracker state moves per statement with no schema-version
            # bump: a cached plan would serve a frozen snapshot forever
            pv.cacheable = False
            return pv
        if name == "resource_usage":
            # the continuous resource meter (meter.py): cumulative AND
            # current-interval work per tenant — device busy-time,
            # host-fallback time, sched slot / admission waits, bytes
            # dispatched, rows served — one row per user and per
            # session (live or retained-closed), plus the SERVER total
            # row the per-session sum reconciles against
            from tidb_tpu import meter
            rows = []

            def row(scope, snap):
                iv = snap["interval"]
                rows.append((scope, snap["session_id"],
                             snap["user"] or None, snap["statements"],
                             snap["device_ns"], iv["device_ns"],
                             snap["host_fallback_ns"],
                             snap["slot_wait_ns"],
                             snap["admission_wait_ns"],
                             snap["rows_sent"], snap["bytes_encoded"],
                             snap["bytes_decoded_equiv"]))

            row("server", meter.server_snapshot())
            for snap in meter.users_snapshot():
                row("user", snap)
            for snap in meter.sessions_snapshot():
                row("session", snap)
            pv = mk([("scope", sf), ("session_id", intf), ("user", sf),
                     ("statements", intf), ("device_time_ns", intf),
                     ("device_time_interval_ns", intf),
                     ("host_fallback_ns", intf),
                     ("slot_wait_ns", intf),
                     ("admission_wait_ns", intf),
                     ("rows_sent", intf), ("bytes_encoded", intf),
                     ("bytes_decoded_equiv", intf)], rows)
            # meter state moves per statement with no schema-version
            # bump: a cached plan would serve a frozen snapshot forever
            pv.cacheable = False
            return pv
        if name == "statement_traces":
            # retained statement span trees (trace.py ring): one row
            # per trace, joinable to perfschema digests via `digest`
            # (events_statements_summary_by_digest.last_trace_id points
            # back here); the full tree serves on GET /trace/<id>
            from tidb_tpu import trace as _trace
            rows = []
            for r in _trace.ring_snapshot():
                rows.append((r["trace_id"], r["digest"],
                             r["sql"][:256], int(r["start_unix"] * 1e6),
                             r["duration_ns"], r["span_count"],
                             r["reason"], r["error"]))
            pv = mk([("trace_id", intf), ("digest", sf),
                     ("sql_text", new_string_field(256)),
                     ("start_time_us", intf), ("duration_ns", intf),
                     ("span_count", intf), ("reason", sf),
                     ("error", sf)], rows)
            # the ring moves per statement with no schema-version bump
            pv.cacheable = False
            return pv
        if name == "kernel_profile":
            # the kernel profiling plane (profiler.py): one row per
            # (kernel family, plan fingerprint, mesh) — compile cost and
            # cache attribution, dispatch/byte totals, and where the
            # kernel sits against the platform's memory roofline
            from tidb_tpu import profiler
            from tidb_tpu.sqltypes import new_double_field
            df = new_double_field()
            rows = []
            for p in profiler.snapshot():
                rows.append((p["family"], p["fingerprint"], p["mesh"],
                             p["generation"], p["compiles"],
                             p["compile_ns"], p["compile_cache"],
                             p["pcache_hits"], p["pcache_misses"],
                             p["reuses"], p["dispatches"], p["busy_ns"],
                             p["bytes_in"], p["bytes_out"],
                             p["bytes_encoded"],
                             p["bytes_decoded_equiv"],
                             p["escalations"], p["fallbacks"],
                             p["achieved_gbps"],
                             p["roofline_fraction"]))
            pv = mk([("family", sf), ("fingerprint", sf), ("mesh", sf),
                     ("generation", intf), ("compiles", intf),
                     ("compile_ns", intf), ("compile_cache", sf),
                     ("pcache_hits", intf), ("pcache_misses", intf),
                     ("reuses", intf), ("dispatches", intf),
                     ("busy_ns", intf), ("bytes_in", intf),
                     ("bytes_out", intf), ("bytes_encoded", intf),
                     ("bytes_decoded_equiv", intf),
                     ("escalations", intf), ("fallbacks", intf),
                     ("achieved_gbps", df),
                     ("roofline_fraction", df)], rows)
            # profile rows move per dispatch with no schema-version
            # bump: a cached plan would serve a frozen snapshot forever
            pv.cacheable = False
            return pv
        if name == "statement_profile":
            # the per-digest mode-history memo (perfschema.py): which
            # execution mode each operator of each digest actually ran,
            # with observed group cardinality and per-mode device time —
            # the read side for feedback-driven mode selection
            from tidb_tpu import perfschema
            rows = []
            for r in perfschema.memo_snapshot():
                rows.append((r["digest"], r["op"], r["mode"], r["runs"],
                             r["device_ns"], r["rows"], r["last_mode"],
                             r["last_groups"], r["max_groups"],
                             int(r["last_seen"] * 1e6)))
            pv = mk([("digest", sf), ("op", sf), ("mode", sf),
                     ("runs", intf), ("device_ns", intf),
                     ("rows", intf), ("last_mode", sf),
                     ("last_groups", intf), ("max_groups", intf),
                     ("last_seen_us", intf)], rows)
            pv.cacheable = False
            return pv
        if name == "collations":
            rows = [("utf8mb4_bin", "utf8mb4", 46, "", "Yes", 1),
                    ("utf8mb4_general_ci", "utf8mb4", 45, "Yes", "Yes", 1),
                    ("utf8_bin", "utf8", 83, "", "Yes", 1),
                    ("utf8_general_ci", "utf8", 33, "Yes", "Yes", 1),
                    ("latin1_bin", "latin1", 47, "", "Yes", 1),
                    ("binary", "binary", 63, "Yes", "Yes", 1)]
            return mk([("collation_name", sf), ("character_set_name", sf),
                       ("id", intf), ("is_default", sf),
                       ("is_compiled", sf), ("sortlen", intf)], rows)
        raise PlanError(
            f"Unknown table 'information_schema.{ts.name}' "
            f"(available: {', '.join(self._MEMTABLES)})")

    # -- INFORMATION_SCHEMA cluster_* tables (ref: infoschema/tables.go
    # CLUSTER_* wrappers over the infosync membership) -----------------------

    def _live_members(self) -> list[dict]:
        """The membership registry, degraded to this process alone when
        there is no registry to scan (no storage bound, nothing
        heartbeating, or the store plane is unreachable — the last
        case also leaves a warning)."""
        from tidb_tpu import member
        members: list[dict] = []
        if self.storage is not None:
            try:
                members = member.live_members(self.storage)
            except Exception as e:  # noqa: BLE001 - degrade, never error
                self.warnings.append((
                    "Warning", 1105,
                    f"cluster membership scan failed ({e}); showing "
                    f"this member only"))
        return members or [member.identity()]

    def _cluster_docs(self) -> dict:
        """Every live member's /cluster/state document, keyed by member
        id — one bounded concurrent sweep (statusclient.fetch_all). An
        unreachable member contributes a SHOW WARNINGS row instead of
        rows; a registry of one local-placeholder member (no fleet) is
        served in-process without HTTP."""
        from tidb_tpu import member
        members = self._live_members()
        if len(members) == 1 and \
                members[0]["id"] == member.identity()["id"]:
            doc = member.local_state()
            return {doc["member"]["id"]: doc}
        from tidb_tpu.util import statusclient
        docs, errors = statusclient.fetch_all(members, "/cluster/state")
        for mid, err in sorted(errors.items()):
            self.warnings.append((
                "Warning", 1105,
                f"cluster fan-out: member {mid} unreachable ({err}); "
                f"results are partial"))
        return docs

    def _build_cluster_table(self, name: str, alias: str) \
            -> ph.PhysValues:
        """CLUSTER_* memtables: the fleet-wide twins of the local
        memtables, built by fanning one /cluster/state fetch out over
        every live member. Queryable from ANY member; an unreachable
        member costs at most one bounded timeout and one warning — the
        query returns the members that answered, never an error."""
        from tidb_tpu.sqltypes import new_int_field, new_string_field
        sf, intf = new_string_field(64), new_int_field()

        def mk(cols_spec, rows):
            schema = PlanSchema([SchemaCol(n, alias, ft)
                                 for n, ft in cols_spec])
            const_rows = [[Constant(v, ft)
                           for v, (_n, ft) in zip(r, cols_spec)]
                          for r in rows]
            pv = ph.PhysValues(schema=schema, rows=const_rows)
            # membership and peer state move with no schema-version
            # bump: a cached plan would serve a frozen fleet forever
            pv.cacheable = False
            return pv

        if name == "cluster_members":
            # registry-only: one snapshot range scan, no HTTP fan-out
            rows = [(m["id"], m["host"], m["status_port"], m["role"],
                     int(m["start_unix"] * 1e6), m.get("expiry", 0))
                    for m in self._live_members()]
            return mk([("member_id", sf), ("host", sf),
                       ("status_port", intf), ("role", sf),
                       ("start_time_us", intf),
                       ("lease_expiry_ms", intf)], rows)
        docs = self._cluster_docs()
        if name == "cluster_processlist":
            rows = []
            for mid, doc in sorted(docs.items()):
                for p in doc.get("processlist", ()):
                    rows.append((mid, p["id"], p["user"], p["host"],
                                 p["db"], p["command"],
                                 int(p["time_s"]), p["info"],
                                 p["mem_bytes"], int(p["device_ms"]),
                                 p["rows_sent"]))
            return mk([("member", sf), ("id", intf), ("user", sf),
                       ("host", sf), ("db", sf), ("command", sf),
                       ("time", intf), ("info", new_string_field(100)),
                       ("mem_bytes", intf), ("device_ms", intf),
                       ("rows_sent", intf)], rows)
        if name == "cluster_resource_usage":
            rows = []

            def ru_row(mid, scope, snap):
                iv = snap["interval"]
                rows.append((mid, scope, snap["session_id"],
                             snap["user"] or None, snap["statements"],
                             snap["device_ns"], iv["device_ns"],
                             snap["host_fallback_ns"],
                             snap["slot_wait_ns"],
                             snap["admission_wait_ns"],
                             snap["rows_sent"], snap["bytes_encoded"],
                             snap["bytes_decoded_equiv"]))

            for mid, doc in sorted(docs.items()):
                ru = doc.get("resource_usage") or {}
                if ru.get("server"):
                    ru_row(mid, "server", ru["server"])
                for snap in ru.get("users", ()):
                    ru_row(mid, "user", snap)
                for snap in ru.get("sessions", ()):
                    ru_row(mid, "session", snap)
            return mk([("member", sf), ("scope", sf),
                       ("session_id", intf), ("user", sf),
                       ("statements", intf), ("device_time_ns", intf),
                       ("device_time_interval_ns", intf),
                       ("host_fallback_ns", intf),
                       ("slot_wait_ns", intf),
                       ("admission_wait_ns", intf),
                       ("rows_sent", intf), ("bytes_encoded", intf),
                       ("bytes_decoded_equiv", intf)], rows)
        if name == "cluster_kernel_profile":
            # fleet-wide kernel profiles: every member's registry rows
            # with the member id prefixed — the per-mesh keying makes a
            # 1-chip member and an 8-chip member distinguishable even
            # for the same plan fingerprint
            from tidb_tpu.sqltypes import new_double_field
            df = new_double_field()
            rows = []
            for mid, doc in sorted(docs.items()):
                for p in doc.get("kernel_profile", ()):
                    rows.append((mid, p["family"], p["fingerprint"],
                                 p["mesh"], p["generation"],
                                 p["compiles"], p["compile_ns"],
                                 p["compile_cache"], p["reuses"],
                                 p["dispatches"], p["busy_ns"],
                                 p["bytes_in"], p["bytes_out"],
                                 p["escalations"], p["fallbacks"],
                                 p["achieved_gbps"],
                                 p["roofline_fraction"]))
            return mk([("member", sf), ("family", sf),
                       ("fingerprint", sf), ("mesh", sf),
                       ("generation", intf), ("compiles", intf),
                       ("compile_ns", intf), ("compile_cache", sf),
                       ("reuses", intf), ("dispatches", intf),
                       ("busy_ns", intf), ("bytes_in", intf),
                       ("bytes_out", intf), ("escalations", intf),
                       ("fallbacks", intf), ("achieved_gbps", df),
                       ("roofline_fraction", df)], rows)
        # cluster_statement_traces: every member's retained trace ring,
        # with the origin stamps that stitch a store-plane record back
        # to the fleet trace id of the SQL member that issued it
        rows = []
        for mid, doc in sorted(docs.items()):
            for r in doc.get("traces", ()):
                rows.append((mid, r["trace_id"],
                             r.get("origin_trace_id", r["trace_id"]),
                             r.get("origin_member", ""), r["digest"],
                             r["sql"][:256],
                             int(r["start_unix"] * 1e6),
                             r["duration_ns"], r["span_count"],
                             r["reason"], r["error"]))
        return mk([("member", sf), ("trace_id", intf),
                   ("origin_trace_id", intf), ("origin_member", sf),
                   ("digest", sf), ("sql_text", new_string_field(256)),
                   ("start_time_us", intf), ("duration_ns", intf),
                   ("span_count", intf), ("reason", sf),
                   ("error", sf)], rows)

    # -- PERFORMANCE_SCHEMA virtual tables (ref: perfschema/const.go:120-298
    # events_statements_current / events_statements_history) -----------------

    _PERF_TABLES = ("events_statements_current",
                    "events_statements_history",
                    "events_statements_summary_by_digest")

    def _build_perfschema(self, ts: ast.TableSource) -> ph.PhysValues:
        from tidb_tpu import perfschema
        from tidb_tpu.sqltypes import new_int_field, new_string_field
        name = ts.name.lower()
        alias = ts.ref_name.lower()
        if name not in self._PERF_TABLES:
            raise PlanError(
                f"Unknown table 'performance_schema.{ts.name}' "
                f"(available: {', '.join(self._PERF_TABLES)})")
        if name == "events_statements_summary_by_digest":
            return self._build_digest_summary(alias)
        events = perfschema.current_events() \
            if name == "events_statements_current" \
            else perfschema.history_events()
        sf, intf = new_string_field(1024), new_int_field()
        cols_spec = [("thread_id", intf), ("event_id", intf),
                     ("sql_text", sf), ("state", sf),
                     ("timer_start_us", intf), ("timer_wait_ns", intf),
                     ("parse_ns", intf), ("plan_ns", intf),
                     ("exec_ns", intf), ("commit_ns", intf),
                     ("rows_sent", intf), ("error", sf)]
        schema = PlanSchema([SchemaCol(n, alias, ft)
                             for n, ft in cols_spec])
        rows = []
        for ev in events:
            rows.append([Constant(v, ft) for v, (_n, ft) in zip(
                (ev["thread_id"], ev["event_id"], ev["sql_text"],
                 ev["state"], ev["timer_start_us"], ev["timer_wait_ns"],
                 ev["parse_ns"], ev["plan_ns"], ev["exec_ns"],
                 ev["commit_ns"], ev["rows"], ev["error"]), cols_spec)])
        pv = ph.PhysValues(schema=schema, rows=rows)
        # events change per statement with no schema-version bump: a
        # cached plan would serve a frozen snapshot forever
        pv.cacheable = False
        return pv

    def _build_digest_summary(self, alias: str) -> ph.PhysValues:
        """events_statements_summary_by_digest: the per-digest aggregate
        rows (ref: util/stmtsummary/statement_summary.go surfaced as a
        performance_schema memtable)."""
        from tidb_tpu import perfschema
        from tidb_tpu.sqltypes import new_int_field, new_string_field
        sf, intf = new_string_field(1024), new_int_field()
        cols_spec = [("digest", sf), ("digest_text", sf),
                     ("exec_count", intf), ("sum_latency_ns", intf),
                     ("max_latency_ns", intf), ("min_latency_ns", intf),
                     ("avg_latency_ns", intf), ("sum_parse_ns", intf),
                     ("sum_plan_ns", intf), ("sum_exec_ns", intf),
                     ("sum_commit_ns", intf), ("sum_rows", intf),
                     ("sum_errors", intf), ("max_mem_bytes", intf),
                     ("last_trace_id", intf), ("first_seen", intf),
                     ("last_seen", intf), ("top_operators", sf)]
        schema = PlanSchema([SchemaCol(n, alias, ft)
                             for n, ft in cols_spec])
        rows = []
        for r in perfschema.digest_summary():
            vals = (r["digest"], r["digest_text"], r["exec_count"],
                    r["sum_latency_ns"], r["max_latency_ns"],
                    r["min_latency_ns"], r["avg_latency_ns"],
                    r["sum_parse_ns"], r["sum_plan_ns"],
                    r["sum_exec_ns"], r["sum_commit_ns"], r["sum_rows"],
                    r["sum_errors"], r["max_mem_bytes"],
                    r["last_trace_id"], int(r["first_seen"]),
                    int(r["last_seen"]), r["top_operators"])
            rows.append([Constant(v, ft)
                         for v, (_n, ft) in zip(vals, cols_spec)])
        pv = ph.PhysValues(schema=schema, rows=rows)
        pv.cacheable = False     # aggregates move per statement
        return pv

    def build_from(self, node) -> ph.PhysPlan:
        if isinstance(node, ast.TableSource):
            return self.build_reader(node)
        if isinstance(node, ast.SubqueryTable):
            sub = self._plan_query(node.select)
            alias = node.alias.lower()
            schema = PlanSchema([
                SchemaCol(c.name, alias, c.ft) for c in sub.schema.cols])
            sub.schema = schema
            return sub
        if isinstance(node, ast.Join):
            left = self.build_from(node.left)
            right = self.build_from(node.right)
            tp = {ast.JoinType.INNER: "inner", ast.JoinType.CROSS: "inner",
                  ast.JoinType.LEFT: "left",
                  ast.JoinType.RIGHT: "right"}[node.tp]
            join = ph.PhysHashJoin(
                schema=left.schema.merge(right.schema),
                children=[left, right], join_type=tp)
            conds = []
            if node.on is not None:
                r = Resolver(join.schema)
                conds = [r.resolve(c) for c in split_conjuncts(node.on)]
            using = list(node.using)
            if node.natural:
                # NATURAL JOIN: equijoin on every shared column name,
                # in left-schema order (ref: MySQL natural join rules)
                rnames = {c.name for c in right.schema.cols}
                using = [c.name for c in left.schema.cols
                         if c.name in rnames]
            for u in using:
                li = left.schema.find(u)
                ri = right.schema.find(u)
                conds.append(func(
                    Op.EQ, ColumnRef(li, left.schema.cols[li].ft),
                    ColumnRef(ri + len(left.schema), right.schema.cols[ri].ft)))
            for c in conds:
                self._assign_cond(join, c, where_phase=False)
            if using:
                # USING/NATURAL coalesce the join columns: they appear
                # ONCE (from the row-preserving side), first, then the
                # remaining left then right columns — and unqualified
                # references to them are not ambiguous
                nl = len(left.schema)
                u_low = [u.lower() for u in using]
                take = []
                for u in u_low:
                    take.append(right.schema.find(u) + nl
                                if tp == "right" else left.schema.find(u))
                for i, c in enumerate(left.schema.cols):
                    if c.name.lower() not in u_low:
                        take.append(i)
                for i, c in enumerate(right.schema.cols):
                    if c.name.lower() not in u_low:
                        take.append(nl + i)
                cols = [join.schema.cols[i] for i in take]
                return ph.PhysProjection(
                    schema=PlanSchema(list(cols)), children=[join],
                    exprs=[ColumnRef(i, join.schema.cols[i].ft)
                           for i in take])
            return join
        raise PlanError(f"unsupported FROM {type(node).__name__}")

    # -- predicate assignment ------------------------------------------------

    def _assign_cond(self, plan: ph.PhysPlan, cond: Expression,
                     where_phase: bool) -> ph.PhysPlan:
        """Sink one resolved conjunct as deep as legal; returns the
        (possibly wrapped) plan."""
        if isinstance(plan, ph.PhysHashJoin):
            nl = len(plan.children[0].schema)
            used = cond.columns_used()
            left_ok = all(i < nl for i in used)
            right_ok = all(i >= nl for i in used)
            lt = plan.join_type
            if left_ok and (lt != "right" or not where_phase or
                            self._rejects_null(cond)):
                plan.children[0] = self._assign_cond(
                    plan.children[0], cond, where_phase)
                return plan
            if right_ok and (lt != "left" or not where_phase or
                             self._rejects_null(cond)):
                remap = {i: i - nl for i in used}
                plan.children[1] = self._assign_cond(
                    plan.children[1], cond.map_columns(remap), where_phase)
                return plan
            # equi-join key? EQ(left col expr, right col expr)
            if isinstance(cond, ScalarFunc) and cond.op == Op.EQ and \
                    lt in ("inner", "left", "right"):
                a, b = cond.args
                ua, ub = a.columns_used(), b.columns_used()
                if ua and ub:
                    if all(i < nl for i in ua) and all(i >= nl for i in ub):
                        plan.left_keys.append(a)
                        plan.right_keys.append(
                            b.map_columns({i: i - nl for i in ub}))
                        return plan
                    if all(i < nl for i in ub) and all(i >= nl for i in ua):
                        plan.left_keys.append(b)
                        plan.right_keys.append(
                            a.map_columns({i: i - nl for i in ua}))
                        return plan
            if lt == "inner":
                plan.other_cond = cond if plan.other_cond is None else \
                    func(Op.AND, plan.other_cond, cond)
                return plan
            # outer join + unpushable WHERE cond: filter above the join
            return ph.PhysSelection(schema=plan.schema, children=[plan],
                                    cond=cond)
        if isinstance(plan, ph.PhysTableReader) and not plan.cop.is_agg:
            dev, host = split_device_host(cond)
            if dev is not None:
                plan.cop.filter = dev if plan.cop.filter is None else \
                    func(Op.AND, plan.cop.filter, dev)
            if host is not None:
                plan.cop.host_filter = host if plan.cop.host_filter is None \
                    else func(Op.AND, plan.cop.host_filter, host)
            return plan
        if isinstance(plan, ph.PhysSelection):
            plan.cond = func(Op.AND, plan.cond, cond)
            return plan
        if isinstance(plan, ph.PhysApply):
            if plan.mode == "scalar" and any(
                    i >= len(plan.children[0].schema)
                    for i in cond.columns_used()):
                # the predicate reads the appended scalar column: it
                # cannot sink below the apply that produces it
                return ph.PhysSelection(schema=plan.schema,
                                        children=[plan], cond=cond)
            # sink plain predicates below the apply (same outer schema,
            # scalar appends at the end so base indices are stable):
            # the correlated inner then runs only for surviving rows
            plan.children[0] = self._assign_cond(plan.children[0], cond,
                                                 where_phase)
            return plan
        return ph.PhysSelection(schema=plan.schema, children=[plan],
                                cond=cond)

    # -- access path selection ----------------------------------------------

    def _opt_access(self, plan: ph.PhysPlan) -> ph.PhysPlan:
        """Post-pass (ref: plan/physical_plan_builder.go:203-516 access-path
        choice, rule-based until stats land): walk the tree; for every
        table reader, extract pk-handle ranges (always, also under agg
        pushdown) and consider unique-point gets / secondary-index paths
        for non-agg readers. All original conjuncts stay as residual
        filters, so range extraction can never change results."""
        for i, c in enumerate(plan.children):
            plan.children[i] = self._opt_access(c)
        if isinstance(plan, ph.PhysTableReader):
            return self._choose_access_path(plan)
        return plan

    # Cost factors (ref: the copTask/rootTask cost charges, plan/task.go:213
    # netWorkFactor and the double-read penalty of IndexLookUp).
    _COVER_FACTOR = 1.2    # covering index: scan + net per row
    _LOOKUP_FACTOR = 4.0   # index lookup: scan + net + random row fetch

    def _choose_access_path(self, reader: ph.PhysTableReader) -> ph.PhysPlan:
        from tidb_tpu import ranger as rg
        cop = reader.cop
        info = cop.table
        conj = flatten_and(cop.filter) + flatten_and(cop.host_filter)
        st = self._tbl_stats(info)
        use_cbo = not st.pseudo
        if use_cbo:
            from tidb_tpu.statistics import selectivity
            reader.est_rows = max(1, st.count) * (selectivity(
                st, conj, reader.schema.cols, info) if conj else 1.0)
        if not conj or cop.ranges is not None:
            return reader
        off_by_name: dict[str, int] = {}
        for i, sc in enumerate(reader.schema.cols):
            off_by_name.setdefault(sc.name, i)

        # 1. pk-is-handle ranges (narrow the record scan in place)
        if info.pk_is_handle and info.pk_col_name:
            pk_off = off_by_name.get(info.pk_col_name.lower())
            if pk_off is not None:
                path = rg.detach_handle_conditions(conj, pk_off)
                if path.useful and path.ranges is not None:
                    kvr = rg.handle_ranges_to_kv(info.id, path.ranges)
                    if kvr is not None:
                        if not cop.is_agg and len(path.ranges) == 1 and \
                                path.eq_count == 1 and \
                                isinstance(path.ranges[0].low[0], int) and \
                                path.ranges[0].low == path.ranges[0].high:
                            return self._point_get(reader,
                                                   path.ranges[0].low[0],
                                                   None, None)
                        cop.ranges = kvr
                        # when the ranges encode EVERY conjunct, the scan's
                        # actual row count is exactly the range count ->
                        # feed it back to the pk histogram
                        if len(path.consumed) == len(conj) and \
                                not cop.is_agg and use_cbo:
                            pk_col = info.col_by_name(info.pk_col_name)
                            cop.feedback = (pk_col.id, path.ranges)
                        return reader

        # 2. secondary-index paths (non-agg readers only: agg pushdown to
        # the TPU kernel beats an index lookup unless stats say otherwise)
        if cop.is_agg or cop.limit is not None:
            return reader
        # index columns are covering iff every output column is indexed
        idx_cover_base = set()
        if info.pk_is_handle and info.pk_col_name:
            idx_cover_base.add(info.pk_col_name.lower())
        # USE/IGNORE/FORCE INDEX hints (ref: planbuilder.go
        # getPossibleAccessPaths): IGNORE removes candidates, USE/FORCE
        # restrict to the named set, FORCE additionally disfavors the
        # full table scan
        ignored = {n.lower() for k, ns in cop.index_hints
                   if k == "IGNORE" for n in ns}
        restrict = {n.lower() for k, ns in cop.index_hints
                    if k in ("USE", "FORCE") for n in ns}
        forced = any(k == "FORCE" and ns for k, ns in cop.index_hints)
        candidates = []
        for idx in info.indexes:
            from tidb_tpu.schema.model import SchemaState
            if idx.state != SchemaState.PUBLIC:
                continue
            if idx.name.lower() in ignored:
                continue
            if restrict and idx.name.lower() not in restrict:
                continue
            offsets, fts = [], []
            ok = True
            for cname in idx.columns:
                o = off_by_name.get(cname.lower())
                if o is None:
                    ok = False
                    break
                offsets.append(o)
                fts.append(reader.schema.cols[o].ft)
            if not ok:
                continue
            path = rg.detach_index_conditions(conj, offsets, fts)
            if path.useful and path.ranges:
                indexed = idx_cover_base | {cn.lower() for cn in idx.columns}
                covering = all(c.name.lower() in indexed for c in cop.cols)
                # _ci index columns store casefolded keys, not original
                # values: such indexes can route but never cover
                if covering and any(
                        info.col_by_name(cn).ft.is_ci
                        for cn in idx.columns):
                    covering = False
                candidates.append((idx, path, covering))
        if not candidates:
            return reader
        if use_cbo:
            # cost = rows read x per-row factor; full scan reads count rows
            scan_cost = float(max(1, st.count))
            best = None
            for idx, path, cov in candidates:
                rows = st.index_ranges_row_count(idx, path.ranges)
                factor = self._COVER_FACTOR if cov else self._LOOKUP_FACTOR
                cost = rows * factor
                if best is None or cost < best[3]:
                    best = (idx, path, cov, cost)
            if best[3] >= scan_cost and not forced:
                return reader            # table scan wins
            idx, path, covering, _cost = best
        else:
            idx, path, covering = max(candidates, key=lambda c: c[1].score)
        # unique full point -> PointGet
        if idx.unique and path.eq_count == len(idx.columns) and \
                len(path.ranges) == 1 and not path.has_interval:
            r = path.ranges[0]
            if r.low == r.high and all(v is not None for v in r.low):
                return self._point_get(reader, None, idx, list(r.low))
        kv_ranges = rg.index_ranges_to_kv(info.id, idx.id, path.ranges)
        # covering index: every output column is an index column -> decode
        # straight from index entries, skip the row fetch entirely
        if covering:
            cov = ph.CopPlan(
                table=info, cols=cop.cols, handle_col=cop.handle_col,
                ranges=kv_ranges, index=idx, filter=cop.filter,
                host_filter=cop.host_filter)
            out = ph.PhysIndexReader(schema=reader.schema, cop=cov)
            out.est_rows = reader.est_rows
            return out
        index_cols = [info.col_by_name(c) for c in idx.columns]
        index_cop = ph.CopPlan(
            table=info, cols=index_cols, handle_col=len(index_cols),
            ranges=kv_ranges, index=idx)
        out = ph.PhysIndexLookUp(schema=reader.schema, index_cop=index_cop,
                                 table_cop=cop)
        out.est_rows = reader.est_rows
        return out

    # -- physical algorithm selection ----------------------------------------
    # (ref: plan/gen_physical_plans.go:114-417 join enumeration +
    # plan/task.go:116-499 costing — collapsed to targeted rewrites costed
    # with the same stats the access-path pass uses)

    # beyond this many estimated groups, the sort-based StreamAgg beats
    # the hash kernel's capacity-escalation / collision-fallback protocol
    _STREAM_AGG_NDV = 1 << 16

    # -- join reordering (ref: plan/join_reorder.go greedy solver over
    # estimated cardinalities; runs after access-path optimization so
    # leaf est_rows reflect pushed filters) ----------------------------------

    def _reorder_joins(self, plan: ph.PhysPlan) -> ph.PhysPlan:
        """Greedy reorder of MAXIMAL inner-join trees: seed with the
        smallest leaf that participates in a join condition, repeatedly
        attach the smallest connected leaf (cross joins last). The
        rebuilt tree is left-deep with the smaller input of every join
        as the hash build side, and a column projection restores the
        original output order so nothing downstream notices."""
        if not (isinstance(plan, ph.PhysHashJoin) and
                plan.join_type == "inner"):
            for i, c in enumerate(plan.children):
                plan.children[i] = self._reorder_joins(c)
            if isinstance(plan, ph.PhysApply) and plan.inner is not None:
                plan.inner = self._reorder_joins(plan.inner)
            return plan
        leaves, conds = self._collect_inner_tree(plan)
        new_leaves = [self._reorder_joins(lf) for lf in leaves]
        geo = _JoinGeometry(new_leaves, conds)
        order = self._greedy_order(geo) if len(new_leaves) > 2 else None
        if (order is None or order == list(range(len(new_leaves)))) and \
                all(a is b for a, b in zip(new_leaves, leaves)):
            return plan
        return self._rebuild_join_tree(
            plan, geo, order or list(range(len(new_leaves))))

    def _collect_inner_tree(self, p: ph.PhysPlan):
        """-> (leaves, conds) with every condition expressed over the
        concatenated leaf schema in ORIGINAL leaf order. Compound
        other_conds split into conjuncts so each applies (and can become
        a join key) at the earliest join covering its leaves."""
        if isinstance(p, ph.PhysHashJoin) and p.join_type == "inner":
            lleaves, lconds = self._collect_inner_tree(p.children[0])
            rleaves, rconds = self._collect_inner_tree(p.children[1])
            lw = sum(len(x.schema) for x in lleaves)
            conds = list(lconds)
            for c in rconds:
                conds.append(c.map_columns(
                    {i: i + lw for i in c.columns_used()}))
            for lk, rk in zip(p.left_keys, p.right_keys):
                rk2 = rk.map_columns(
                    {i: i + lw for i in rk.columns_used()})
                conds.append(func(Op.EQ, lk, rk2))
            conds.extend(flatten_and(p.other_cond))
            return lleaves + rleaves, conds
        return [p], []

    def _greedy_order(self, geo: "_JoinGeometry") -> list[int] | None:
        n = len(geo.leaves)
        # seed must participate in a join condition — seeding with a
        # disconnected (cross-joined) leaf would multiply every later
        # join by its cardinality
        in_conds = set().union(*geo.cond_leaves) if geo.cond_leaves \
            else set()
        if not in_conds:
            return None             # pure cross product: keep as written
        placed = [min(in_conds, key=lambda i: geo.size[i])]
        remaining = set(range(n)) - set(placed)
        while remaining:
            connected = [i for i in remaining
                         if any(i in cl and cl - {i} <= set(placed)
                                for cl in geo.cond_leaves)]
            pool = connected or sorted(remaining)
            nxt = min(pool, key=lambda i: geo.size[i])
            placed.append(nxt)
            remaining.discard(nxt)
        return placed

    def _rebuild_join_tree(self, orig: ph.PhysHashJoin,
                           geo: "_JoinGeometry",
                           order: list[int]) -> ph.PhysPlan:
        leaves, offs = geo.leaves, geo.offs
        n = len(leaves)
        width = sum(len(lf.schema) for lf in leaves)
        pending = list(zip(geo.conds, geo.cond_leaves))
        # cur_pos: original global index -> index in acc's CURRENT schema
        # (child orientation varies per join, so positions are tracked
        # dynamically rather than precomputed)
        first = order[0]
        acc = leaves[first]
        acc_set = {first}
        acc_est = geo.size[first]
        cur_pos = {offs[first] + k: k
                   for k in range(len(leaves[first].schema))}
        for pos in range(1, n):
            li = order[pos]
            leaf = leaves[li]
            leaf_w = len(leaf.schema)
            leaf_est = geo.size[li]
            # the smaller input becomes the hash BUILD side (right);
            # the bigger streams as the probe (left)
            leaf_right = acc_est >= leaf_est
            acc_w = len(acc.schema)
            if leaf_right:
                children = [acc, leaf]
                schema = acc.schema.merge(leaf.schema)
                leaf_base, nw = acc_w, acc_w
            else:
                children = [leaf, acc]
                schema = leaf.schema.merge(acc.schema)
                cur_pos = {g: p + leaf_w for g, p in cur_pos.items()}
                leaf_base, nw = 0, leaf_w
            for k in range(leaf_w):
                cur_pos[offs[li] + k] = leaf_base + k
            join = ph.PhysHashJoin(schema=schema, children=children,
                                   join_type="inner")
            here = acc_set | {li}
            rest = []
            for c, cl in pending:
                if not (cl <= here and (li in cl or pos == n - 1)):
                    rest.append((c, cl))
                    continue
                c2 = c.map_columns({i: cur_pos[i]
                                    for i in c.columns_used()})
                if isinstance(c2, ScalarFunc) and c2.op == Op.EQ:
                    a, b = c2.args
                    ua, ub = a.columns_used(), b.columns_used()
                    if ua and ub and all(i < nw for i in ua) and \
                            all(i >= nw for i in ub):
                        join.left_keys.append(a)
                        join.right_keys.append(b.map_columns(
                            {i: i - nw for i in ub}))
                        continue
                    if ua and ub and all(i < nw for i in ub) and \
                            all(i >= nw for i in ua):
                        join.left_keys.append(b)
                        join.right_keys.append(a.map_columns(
                            {i: i - nw for i in ua}))
                        continue
                join.other_cond = c2 if join.other_cond is None else \
                    func(Op.AND, join.other_cond, c2)
            pending = rest
            acc = join
            acc_set = here
            # FK-join heuristic: the fact side dominates the intermediate
            acc_est = max(acc_est, leaf_est)
            join.est_rows = acc_est if acc_est < _JoinGeometry.BIG \
                else None
        # restore the original column order for everything above
        exprs = [ColumnRef(cur_pos[i], orig.schema.cols[i].ft,
                           name=orig.schema.cols[i].name)
                 for i in range(width)]
        out = ph.PhysProjection(schema=orig.schema, children=[acc],
                                exprs=exprs)
        out.est_rows = getattr(orig, "est_rows", None)
        return out

    def _opt_physical(self, plan: ph.PhysPlan) -> ph.PhysPlan:
        """Post-pass choosing among physically-equivalent operators:
        HashJoin vs MergeJoin vs IndexJoin, HashAgg vs StreamAgg."""
        for i, c in enumerate(plan.children):
            plan.children[i] = self._opt_physical(c)
        if isinstance(plan, ph.PhysApply) and plan.inner is not None:
            plan.inner = self._opt_physical(plan.inner)
        if isinstance(plan, ph.PhysHashJoin):
            return self._choose_join_algorithm(plan)
        if isinstance(plan, ph.PhysHashAgg):
            return self._choose_agg_algorithm(plan)
        if isinstance(plan, ph.PhysFinalAgg):
            return self._choose_final_agg(plan)
        return plan

    def _choose_join_algorithm(self, join: ph.PhysHashJoin) -> ph.PhysPlan:
        """Cost the physically-equivalent algorithms and keep the cheapest:

          index join: outer_rows x lookup factor (reads ONLY matching
                      inner rows, point fetches pay the double-read tax)
          merge join: outer_scan + inner_scan (both streams, no build)
          hash join:  outer_scan + inner_scan + inner build

        Rows come from the access pass's stats estimates; with pseudo
        stats only the stats-free merge-vs-hash preference applies."""
        self._attach_probe_cms(join)
        if len(join.left_keys) != 1 or join.join_type not in (
                "inner", "left"):
            return join
        left, right = join.children
        outer_est = getattr(left, "est_rows", None)
        inner_count = None
        if isinstance(right, ph.PhysTableReader):
            st = self._tbl_stats(right.cop.table)
            if not st.pseudo:
                inner_count = float(st.count)

        merge_ok = (self._pk_ordered_reader(left, join.left_keys[0]) and
                    self._pk_ordered_reader(right, join.right_keys[0]))
        inner_idx = self._index_join_path(right, join.right_keys[0])
        index_ok = (inner_idx is not False and outer_est is not None and
                    inner_count is not None)

        if index_ok:
            index_cost = outer_est * self._LOOKUP_FACTOR
            scan_cost = (outer_est or 0) + inner_count
            if index_cost < scan_cost:
                return ph.PhysIndexJoin(
                    schema=join.schema, children=[left, right],
                    left_keys=join.left_keys, right_keys=join.right_keys,
                    inner_index=inner_idx, join_type=join.join_type,
                    other_cond=join.other_cond)
        if merge_ok:
            # same scan volume as hash, minus the build materialization
            left.keep_order = True
            right.keep_order = True
            return ph.PhysMergeJoin(
                schema=join.schema, children=join.children,
                left_keys=join.left_keys, right_keys=join.right_keys,
                join_type=join.join_type, other_cond=join.other_cond)
        return join

    def _attach_probe_cms(self, join: ph.PhysHashJoin) -> None:
        """Hand the executor the probe-side key column's ANALYZE-time
        CMSketch (when the single probe key traces to a base column):
        the hybrid hash join seeds its heavy-hitter lane from it, so a
        known-skewed key routes to the broadcast lane from the very
        first probe batch instead of after streaming detection."""
        if len(join.left_keys) != 1 or \
                not isinstance(join.left_keys[0], ColumnRef):
            return
        cs = self._trace_col_stats(join.children[0],
                                   join.left_keys[0].idx)
        if cs is not None and cs.cms is not None:
            join.probe_cms = cs.cms

    @staticmethod
    def _pk_ordered_reader(plan, key: Expression) -> bool:
        """Is `plan` a record scan whose rows arrive ordered by `key`
        (= the pk-is-handle column)?"""
        if not isinstance(plan, ph.PhysTableReader) or plan.cop.is_agg or \
                plan.cop.limit is not None or plan.cop.index is not None:
            return False
        if not isinstance(key, ColumnRef):
            return False
        info = plan.cop.table
        if not info.pk_is_handle or not info.pk_col_name:
            return False
        sc = plan.schema.cols[key.idx]
        return sc.name == info.pk_col_name.lower()

    @staticmethod
    def _index_join_path(plan, right_key: Expression):
        """Index (or None = pk handle) usable to point-fetch inner rows by
        the join key; False when the inner side is not lookup-able."""
        from tidb_tpu.schema.model import SchemaState
        if not isinstance(plan, ph.PhysTableReader) or plan.cop.is_agg or \
                plan.cop.limit is not None or plan.cop.index is not None or \
                plan.cop.ranges is not None:
            return False
        if not isinstance(right_key, ColumnRef):
            return False
        info = plan.cop.table
        name = plan.schema.cols[right_key.idx].name
        if info.pk_is_handle and info.pk_col_name and \
                name == info.pk_col_name.lower():
            return None                      # pk-handle point lookups
        for idx in info.indexes:
            if idx.state == SchemaState.PUBLIC and \
                    idx.columns[0].lower() == name:
                return idx
        return False

    def _choose_agg_algorithm(self, agg: ph.PhysHashAgg) -> ph.PhysPlan:
        if not agg.group_exprs or any(a.distinct for a in agg.aggs):
            return agg
        ndv = self._group_ndv_estimate(agg.children[0], agg.group_exprs)
        if ndv is not None and ndv > self._STREAM_AGG_NDV:
            return ph.PhysStreamAgg(
                schema=agg.schema, children=agg.children,
                group_exprs=agg.group_exprs, aggs=agg.aggs,
                sorted_input=False)
        return agg

    def _choose_final_agg(self, fin: ph.PhysFinalAgg) -> ph.PhysPlan:
        """A pushed-down partial agg with very many groups overflows the
        storage-side hash kernel per chunk AND ships huge partial tables;
        beyond the NDV threshold, scan raw and segment-reduce at the root
        instead (StreamAgg has no capacity limit)."""
        reader = fin.children[0]
        if not isinstance(reader, ph.PhysTableReader) or \
                not reader.cop.is_agg:
            return fin
        cop = reader.cop
        if not cop.group_exprs or any(a.distinct for a in cop.aggs):
            return fin
        ndv = self._group_ndv_estimate(reader, cop.group_exprs)
        if ndv is None or ndv <= self._STREAM_AGG_NDV:
            return fin
        from dataclasses import replace as _replace
        raw = ph.PhysTableReader(
            schema=reader.schema,
            cop=_replace(cop, group_exprs=None, aggs=None))
        raw.est_rows = reader.est_rows
        return ph.PhysStreamAgg(schema=fin.schema, children=[raw],
                                group_exprs=list(cop.group_exprs),
                                aggs=list(cop.aggs), sorted_input=False)

    def _group_ndv_estimate(self, child: ph.PhysPlan, group_exprs):
        """Max per-column NDV of bare group columns, traced through the
        child tree to base-table statistics; None when untraceable or
        stats are pseudo (the decision then defaults to hash agg, whose
        runtime escalation still protects correctness)."""
        best = None
        for g in group_exprs:
            if not isinstance(g, ColumnRef):
                continue
            ndv = self._trace_col_ndv(child, g.idx)
            if ndv is not None:
                best = ndv if best is None else max(best, ndv)
        return best

    def _trace_col_ndv(self, plan: ph.PhysPlan, idx: int):
        cs = self._trace_col_stats(plan, idx)
        return cs.hist.ndv if cs is not None else None

    def _trace_col_stats(self, plan: ph.PhysPlan, idx: int):
        """ColumnStats of a bare column, traced through the child tree
        to base-table statistics; None when untraceable or pseudo."""
        if isinstance(plan, (ph.PhysSelection, ph.PhysLimit, ph.PhysSort,
                             ph.PhysTopN)):
            return self._trace_col_stats(plan.children[0], idx)
        if isinstance(plan, (ph.PhysHashJoin, ph.PhysMergeJoin,
                             ph.PhysIndexJoin)):
            nl = len(plan.children[0].schema)
            if idx < nl:
                return self._trace_col_stats(plan.children[0], idx)
            return self._trace_col_stats(plan.children[1], idx - nl)
        if isinstance(plan, ph.PhysProjection):
            e = plan.exprs[idx]
            if isinstance(e, ColumnRef):
                return self._trace_col_stats(plan.children[0], e.idx)
            return None
        if isinstance(plan, (ph.PhysTableReader, ph.PhysIndexReader)):
            sc = plan.schema.cols[idx]
            if not sc.col_id:
                return None
            stats = self._tbl_stats(plan.cop.table)
            if stats.pseudo:
                return None
            return stats.columns.get(sc.col_id)
        return None

    def _point_get(self, reader: ph.PhysTableReader, handle, idx, values
                   ) -> ph.PhysPointGet:
        cop = reader.cop
        filt = and_all([e for e in (cop.filter, cop.host_filter)
                        if e is not None])
        return ph.PhysPointGet(schema=reader.schema, table=cop.table,
                               cols=cop.cols, handle_col=cop.handle_col,
                               handle=handle, index=idx, index_values=values,
                               filter=filt)

    @staticmethod
    def _rejects_null(cond: Expression) -> bool:
        """True if the cond is false for NULL inputs (so pushing below an
        outer join's null-supplying side is sound). Conservative: plain
        comparisons reject NULL; IS NULL / IFNULL-style do not."""
        if isinstance(cond, ScalarFunc) and cond.op in (
                Op.EQ, Op.NE, Op.LT, Op.LE, Op.GT, Op.GE, Op.LIKE, Op.IN):
            return True
        return False

    # -- SELECT --------------------------------------------------------------

    def plan_select(self, stmt: ast.SelectStmt) -> ph.PhysPlan:
        if stmt.from_clause is None:
            return self._plan_select_no_from(stmt)
        plan = self.build_from(stmt.from_clause)
        # WHERE
        for c_ast in split_conjuncts(stmt.where):
            applied = self._try_subquery_conjunct(plan, c_ast)
            if applied is not None:
                plan = applied
                continue
            if _contains_scalar_subquery(c_ast):
                # subquery in a general expression position, e.g.
                # v > (SELECT ...) + 1: lift it to an applied column
                plan, c_ast = self._lift_scalars_in_expr(plan, c_ast)
                plan = ph.PhysSelection(
                    schema=plan.schema, children=[plan],
                    cond=Resolver(plan.schema).resolve(c_ast))
                continue
            plan = self._assign_cond(plan,
                                     Resolver(plan.schema).resolve(c_ast),
                                     where_phase=True)

        # scalar subqueries in select/having/order project as applied
        # columns before anything reads those expressions
        plan, stmt = self._lift_scalar_subqueries(plan, stmt)

        has_agg = bool(stmt.group_by) or _contains_agg(stmt)
        if has_agg:
            plan, out_schema, proj_exprs, proj_names, order_keys = \
                self._plan_agg_select(stmt, plan)
        else:
            proj_exprs, proj_names = self._resolve_fields(stmt, plan.schema)
            out_schema = PlanSchema([
                SchemaCol(n, "", e.ft) for n, e in
                zip(proj_names, proj_exprs)])
            order_keys = None
            if stmt.having is not None:
                # HAVING without aggregates acts as a filter; MySQL
                # resolves bare names against select aliases first
                # (ref: executor tests, aggregate HAVING family)
                def _subst(n):
                    if isinstance(n, ast.ColName) and not n.table and \
                            not self._column_shadows(plan.schema, n.name):
                        # FROM-clause-first: a real column shadows the
                        # alias (same rule as the agg HAVING path)
                        for f in stmt.fields:
                            if not isinstance(f.expr, ast.Star) and \
                                    f.alias and \
                                    f.alias.lower() == n.name.lower():
                                return f.expr
                    return n
                h_ast = self._rewrite_ast(stmt.having, _subst)
                plan = ph.PhysSelection(
                    schema=plan.schema, children=[plan],
                    cond=Resolver(plan.schema).resolve(h_ast))

        if stmt.distinct:
            # SQL order: projection -> DISTINCT -> ORDER BY -> LIMIT
            plan = ph.PhysProjection(schema=out_schema, children=[plan],
                                     exprs=proj_exprs)
            gexprs = [ColumnRef(i, c.ft) for i, c in
                      enumerate(out_schema.cols)]
            plan = ph.PhysHashAgg(schema=out_schema, children=[plan],
                                  group_exprs=gexprs, aggs=[])
            if stmt.order_by:
                by = []
                for bi in stmt.order_by:
                    target = self._maybe_alias_target(bi.expr, stmt)
                    if not isinstance(target, ast.ColName):
                        raise PlanError("ORDER BY with DISTINCT must name "
                                        "select-list columns")
                    oi = out_schema.find(target.name, target.table)
                    by.append((ColumnRef(oi, out_schema.cols[oi].ft),
                               bi.desc))
                plan = ph.PhysSort(schema=out_schema, children=[plan], by=by)
            if stmt.limit is not None:
                plan = ph.PhysLimit(schema=out_schema, children=[plan],
                                    count=stmt.limit, offset=stmt.offset)
            return plan

        # ORDER BY
        by = []
        if stmt.order_by:
            by = self._resolve_order(stmt, plan.schema, out_schema,
                                     proj_exprs, order_keys)
        # TopN pushdown / sort / limit assembly
        if by:
            if stmt.limit is not None:
                plan = ph.PhysTopN(schema=plan.schema, children=[plan],
                                   by=by, count=stmt.limit,
                                   offset=stmt.offset)
            else:
                plan = ph.PhysSort(schema=plan.schema, children=[plan],
                                   by=by)
        elif stmt.limit is not None:
            if isinstance(plan, ph.PhysTableReader) and not plan.cop.is_agg \
                    and stmt.offset == 0:
                plan.cop.limit = stmt.limit
            plan = ph.PhysLimit(schema=plan.schema, children=[plan],
                                count=stmt.limit, offset=stmt.offset)
        return ph.PhysProjection(schema=out_schema, children=[plan],
                                 exprs=proj_exprs)

    # -- UNION ---------------------------------------------------------------

    def _plan_query(self, stmt) -> ph.PhysPlan:
        """SELECT or UNION — every seam that accepts a query body."""
        return self.plan_union(stmt) if isinstance(stmt, ast.UnionStmt) \
            else self.plan_select(stmt)

    def plan_union(self, stmt: ast.UnionStmt) -> ph.PhysPlan:
        """UNION as a real operator tree (ref: builder.go UnionExec):
        branches stream through PhysUnion; MySQL's mixed ALL/DISTINCT
        rule applies — a DISTINCT union dedups everything to its left —
        via one HashAgg grouped on every output column."""
        sels = [self._plan_query(s) for s in stmt.selects]
        width = len(sels[0].schema)
        for s in sels[1:]:
            if len(s.schema) != width:
                raise PlanError(
                    "The used SELECT statements have a different number "
                    "of columns")
        out_cols = []
        for i in range(width):
            fts = [s.schema.cols[i].ft for s in sels]
            out_cols.append(SchemaCol(sels[0].schema.cols[i].name, "",
                                      _union_ft(fts)))
        out_schema = PlanSchema(out_cols)

        def union_of(children):
            return ph.PhysUnion(schema=out_schema, children=list(children))

        distinct_idx = [i for i, a in enumerate(stmt.alls) if not a]
        if distinct_idx:
            k = distinct_idx[-1] + 2     # branches covered by the dedup
            head = union_of(sels[:k])
            gexprs = [ColumnRef(i, c.ft) for i, c in enumerate(out_cols)]
            dedup = ph.PhysHashAgg(schema=out_schema, children=[head],
                                   group_exprs=gexprs, aggs=[])
            plan = union_of([dedup] + sels[k:]) if k < len(sels) else dedup
        else:
            plan = union_of(sels)

        if stmt.order_by:
            by = []
            for bi in stmt.order_by:
                target = bi.expr
                if isinstance(target, ast.Literal) and \
                        isinstance(target.value, int) and \
                        1 <= target.value <= width:
                    oi = target.value - 1
                elif isinstance(target, ast.ColName) and not target.table:
                    oi = out_schema.find(target.name.lower())
                else:
                    raise PlanError("UNION ORDER BY must name output "
                                    "columns")
                by.append((ColumnRef(oi, out_cols[oi].ft), bi.desc))
            if stmt.limit is not None:
                return ph.PhysTopN(schema=out_schema, children=[plan],
                                   by=by, count=stmt.limit,
                                   offset=stmt.offset)
            plan = ph.PhysSort(schema=out_schema, children=[plan], by=by)
        elif stmt.limit is not None:
            plan = ph.PhysLimit(schema=out_schema, children=[plan],
                                count=stmt.limit, offset=stmt.offset)
        return plan

    def _plan_select_no_from(self, stmt: ast.SelectStmt) -> ph.PhysPlan:
        plan = None
        if _contains_agg(stmt):
            # SELECT SUM(1.2e2) * 0.1 — aggregate over the one-row dual
            # (MySQL: no-FROM behaves as a single-row table); reuse the
            # regular agg path so expressions over aggregates work
            from tidb_tpu.sqltypes import new_int_field
            ift = new_int_field()
            plan = ph.PhysValues(
                schema=PlanSchema([SchemaCol("__dual", "", ift)]),
                rows=[[Constant(1, ift)]])
            plan, stmt = self._lift_scalar_subqueries(plan, stmt)
            plan, out_schema, proj_exprs, _names, _ok = \
                self._plan_agg_select(stmt, plan)
            plan = ph.PhysProjection(schema=out_schema, children=[plan],
                                     exprs=proj_exprs)
            # the dual input yields at most one group, so ORDER BY and
            # DISTINCT are no-ops here — but LIMIT/OFFSET still apply
            # (SELECT COUNT(*) LIMIT 0 is empty)
            if stmt.limit is not None:
                plan = ph.PhysLimit(schema=out_schema, children=[plan],
                                    count=stmt.limit, offset=stmt.offset)
            return plan
        if any(_contains_scalar_subquery(f.expr) for f in stmt.fields
               if not isinstance(f.expr, ast.Star)):
            # subqueries over a one-row dual input: the lift appends
            # their values as apply columns as usual (a zero-column
            # chunk would report zero rows)
            from tidb_tpu.sqltypes import new_int_field
            ift = new_int_field()
            plan = ph.PhysValues(
                schema=PlanSchema([SchemaCol("__dual", "", ift)]),
                rows=[[Constant(1, ift)]])
            plan, stmt = self._lift_scalar_subqueries(plan, stmt)
        r = Resolver(plan.schema if plan is not None else PlanSchema([]))
        exprs, names = [], []
        for f in stmt.fields:
            if isinstance(f.expr, ast.Star):
                raise PlanError("SELECT * requires FROM")
            e = r.resolve(f.expr)
            exprs.append(e)
            names.append(f.alias or _field_name(f.expr))
        schema = PlanSchema([SchemaCol(n, "", e.ft)
                             for n, e in zip(names, exprs)])
        if plan is not None:
            return ph.PhysProjection(schema=schema, children=[plan],
                                     exprs=exprs)
        return ph.PhysValues(schema=schema, rows=[exprs])

    # -- subquery conjuncts (ref: plan/expression_rewriter.go subquery
    # handling + decorrelateSolver; here: apply-style, uncorrelated inner
    # plans run once in the executor) -----------------------------------------

    _CMP_OPS = {"=": Op.EQ, "<": Op.LT, "<=": Op.LE, ">": Op.GT,
                ">=": Op.GE, "<>": Op.NE, "!=": Op.NE}

    def _try_subquery_conjunct(self, plan: ph.PhysPlan, c_ast
                               ) -> ph.PhysApply | None:
        """Recognize EXISTS / IN (SELECT) / <cmp> (SELECT) conjuncts and
        rewrite them to a PhysApply over `plan`. Returns None when the
        conjunct contains no subquery (normal resolution proceeds)."""
        negate = False
        node = c_ast
        while isinstance(node, ast.UnaryOp) and node.op == "NOT":
            negate = not negate
            node = node.operand

        if isinstance(node, ast.ExistsSubquery):
            anti = negate != node.negated
            dec = self._try_decorrelate(plan, node.select, anti,
                                        in_expr=None)
            if dec is not None:
                return dec
            inner, corr = self._plan_subquery(plan.schema, node.select)
            return ph.PhysApply(schema=plan.schema, children=[plan],
                                inner=inner, mode="exists",
                                negated=anti, corr=corr)

        if isinstance(node, ast.InExpr) and \
                isinstance(node.items, ast.SubqueryExpr):
            neg = negate != node.negated
            if not neg:
                # positive IN only: NOT IN has three-valued NULL
                # semantics an anti join would get wrong
                dec = self._try_decorrelate(plan, node.items.select,
                                            anti=False, in_expr=node.expr)
                if dec is not None:
                    return dec
            inner, corr = self._plan_subquery(plan.schema,
                                              node.items.select)
            if len(inner.schema.cols) != 1:
                raise PlanError("subquery must return 1 column for IN")
            left = Resolver(plan.schema).resolve(node.expr)
            return ph.PhysApply(schema=plan.schema, children=[plan],
                                inner=inner, mode="in",
                                negated=neg,
                                left=left, corr=corr)

        if isinstance(node, ast.QuantSubquery):
            # expr <cmp> ANY/ALL (SELECT ...): apply with quantifier
            # (ref: plan/expression_rewriter.go handleCompareSubquery)
            inner, corr = self._plan_subquery(plan.schema, node.select)
            if len(inner.schema.cols) != 1:
                raise PlanError("subquery must return 1 column")
            left = Resolver(plan.schema).resolve(node.expr)
            return ph.PhysApply(schema=plan.schema, children=[plan],
                                inner=inner, mode="cmp", negated=negate,
                                left=left, cmp_op=self._CMP_OPS[node.op],
                                quant=node.quant, corr=corr)

        if isinstance(node, ast.BinaryOp) and node.op in self._CMP_OPS:
            lhs_sub = isinstance(node.left, ast.SubqueryExpr)
            rhs_sub = isinstance(node.right, ast.SubqueryExpr)
            if lhs_sub == rhs_sub:          # neither (or both: unsupported)
                if lhs_sub:
                    raise PlanError("subquery on both comparison sides")
                return None
            sub = node.left if lhs_sub else node.right
            other = node.right if lhs_sub else node.left
            op = self._CMP_OPS[node.op]
            if lhs_sub:                     # flip: keep subquery on the right
                op = {Op.LT: Op.GT, Op.LE: Op.GE, Op.GT: Op.LT,
                      Op.GE: Op.LE}.get(op, op)
            inner, corr = self._plan_subquery(plan.schema, sub.select)
            if len(inner.schema.cols) != 1:
                raise PlanError("scalar subquery must return 1 column")
            left = Resolver(plan.schema).resolve(other)
            return ph.PhysApply(schema=plan.schema, children=[plan],
                                inner=inner, mode="cmp", negated=negate,
                                left=left, cmp_op=op, corr=corr)
        return None

    def _lift_scalars_in_expr(self, plan: ph.PhysPlan, e):
        """Replace every scalar (SELECT ...) inside `e` with a reference
        to a column appended by a PhysApply mode="scalar" wrapped around
        `plan` (ref: plan/expression_rewriter.go handleScalarSubquery).
        Returns the (possibly wrapped) plan and the rewritten AST."""
        import dataclasses
        holder = [plan]

        def lift(node):
            outer = holder[0]
            inner, corr = self._plan_subquery(outer.schema, node.select)
            if len(inner.schema.cols) != 1:
                raise PlanError("scalar subquery must return 1 column")
            name = f"__sq{len(outer.schema.cols)}"
            sc = SchemaCol(name, "", inner.schema.cols[0].ft)
            holder[0] = ph.PhysApply(
                schema=PlanSchema(outer.schema.cols + [sc]),
                children=[outer], inner=inner, mode="scalar", corr=corr)
            return ast.ColName(name=name)

        def walk(node):
            if isinstance(node, ast.SubqueryExpr):
                return lift(node)
            if isinstance(node, ast.InExpr) and \
                    isinstance(node.items, ast.SubqueryExpr):
                # IN's row set in expression position: desugar to a
                # three-valued scalar aggregate over a derived table,
                # then lift that (ref: expression_rewriter.go
                # handleInSubquery non-conjunct case)
                if self._contains_agg(node.expr):
                    # embedding SUM(b) in the generated subquery would
                    # read outer agg state that does not exist there
                    raise PlanError(
                        "aggregate as IN-subquery operand in expression "
                        "position is not supported")
                colref = lift(_in_as_scalar(walk(node.expr),
                                            node.items.select))
                return ast.UnaryOp("NOT", colref) if node.negated \
                    else colref
            if isinstance(node, ast.ExistsSubquery):
                # EXISTS in expression position -> COUNT(*) > 0 over a
                # LIMIT 1 inner: the executor stops at the first row
                inner_sel = node.select
                if getattr(inner_sel, "limit", None) is None:
                    inner_sel = dataclasses.replace(inner_sel, limit=1)
                cnt = ast.SubqueryExpr(select=ast.SelectStmt(
                    fields=[ast.SelectField(
                        expr=ast.AggregateCall(name="COUNT", star=True))],
                    from_clause=ast.SubqueryTable(
                        select=inner_sel, alias="__ex")))
                out = ast.BinaryOp(">", lift(cnt), ast.Literal(0))
                return ast.UnaryOp("NOT", out) if node.negated else out
            return self._rewrite_ast_shallow(node, walk)

        ne = walk(e)        # mutates holder: must run before the read
        return holder[0], ne

    def _rewrite_ast_shallow(self, e, walk):
        """One dataclass-rebuild level: recurse via `walk` (which owns
        the node-type decisions), no fn applied to `e` itself."""
        import dataclasses
        if dataclasses.is_dataclass(e) and isinstance(e, ast.ExprNode) \
                and not isinstance(e, (ast.SubqueryExpr,
                                       ast.ExistsSubquery,
                                       ast.QuantSubquery)):
            updates = {}
            for fld in dataclasses.fields(e):
                v = getattr(e, fld.name)
                if isinstance(v, ast.ExprNode):
                    nv = walk(v)
                    if nv is not v:
                        updates[fld.name] = nv
                elif isinstance(v, list):
                    nl = [self._walk_item(x, walk) for x in v]
                    if any(a is not b for a, b in zip(nl, v)):
                        updates[fld.name] = nl
            if updates:
                return dataclasses.replace(e, **updates)
        return e

    @staticmethod
    def _walk_item(x, walk):
        if isinstance(x, ast.ExprNode):
            return walk(x)
        if isinstance(x, tuple) and any(
                isinstance(y, ast.ExprNode) for y in x):
            nt = tuple(walk(y) if isinstance(y, ast.ExprNode) else y
                       for y in x)
            return x if all(a is b for a, b in zip(nt, x)) else nt
        return x

    def _lift_scalar_subqueries(self, plan: ph.PhysPlan,
                                stmt: ast.SelectStmt):
        import dataclasses
        exprs = [f.expr for f in stmt.fields]
        if stmt.having is not None:
            exprs.append(stmt.having)
        exprs.extend(b.expr for b in stmt.order_by or [])
        if not any(_contains_scalar_subquery(x) for x in exprs):
            return plan, stmt
        changed = {}
        fields = []
        for f in stmt.fields:
            plan, ne = self._lift_scalars_in_expr(plan, f.expr)
            if ne is not f.expr:
                # keep the pre-lift display name: clients must not see
                # the internal __sqN / desugared-node names
                f = dataclasses.replace(
                    f, expr=ne, alias=f.alias or _field_name(f.expr))
            fields.append(f)
        changed["fields"] = fields
        if stmt.having is not None:
            plan, nh = self._lift_scalars_in_expr(plan, stmt.having)
            changed["having"] = nh
        if stmt.order_by:
            order = []
            for b in stmt.order_by:
                plan, ne = self._lift_scalars_in_expr(plan, b.expr)
                order.append(dataclasses.replace(b, expr=ne)
                             if ne is not b.expr else b)
            changed["order_by"] = order
        return plan, dataclasses.replace(stmt, **changed)

    def _try_decorrelate(self, plan: ph.PhysPlan, sub_select,
                         anti: bool, in_expr) -> ph.PhysPlan | None:
        """Rewrite a correlated EXISTS / positive IN subquery into a
        (anti-)semi hash join (ref: decorrelateSolver, plan/optimizer.go:
        42-50): correlated equalities in the subquery WHERE become join
        keys, the remainder stays as the inner filter. Returns None when
        the shape doesn't qualify — the caller falls back to PhysApply.
        """
        if not isinstance(sub_select, ast.SelectStmt) or \
                sub_select.from_clause is None or sub_select.group_by or \
                sub_select.having is not None or \
                sub_select.limit is not None or _contains_agg(sub_select):
            # scalar aggregates change EXISTS/IN cardinality (one row
            # ALWAYS exists; IN compares against a per-group value): the
            # join rewrite cannot express them
            return None
        conjs = split_conjuncts(sub_select.where)
        if not any(isinstance(c, ast.BinaryOp) and c.op == "="
                   for c in conjs):
            return None   # no equality: nothing can become a join key
        # classify WHERE conjuncts: outer_expr = inner_expr pairs peel
        # off as join keys
        try:
            inner_from = Planner(self.ischema, self.db,
                                 stats_handle=self.stats).build_from(
                sub_select.from_clause)
        except (PlanError, ResolveError):
            return None
        corr_pairs: list[tuple] = []    # (outer ast, inner ast)
        residual: list = []

        def resolves(schema, e_ast) -> bool:
            try:
                Resolver(schema).resolve(e_ast)
                return True
            except (ResolveError, PlanError):
                return False

        for c in conjs:
            if isinstance(c, ast.BinaryOp) and c.op == "=":
                li = resolves(inner_from.schema, c.left)
                ri = resolves(inner_from.schema, c.right)
                lo = resolves(plan.schema, c.left)
                ro = resolves(plan.schema, c.right)
                if not li and lo and ri:
                    corr_pairs.append((c.left, c.right))
                    continue
                if not ri and ro and li:
                    corr_pairs.append((c.right, c.left))
                    continue
            residual.append(c)
        if not corr_pairs:
            return None

        # rebuilt subquery: the IN value column (the subquery's own select
        # item) plus the inner join-key columns become the select list;
        # the correlated equalities are gone
        fields = []
        if in_expr is not None:
            if len(sub_select.fields) != 1 or \
                    isinstance(sub_select.fields[0].expr, ast.Star):
                return None
            fields.append(sub_select.fields[0])
        for i, (_o, inner_ast) in enumerate(corr_pairs):
            fields.append(ast.SelectField(expr=inner_ast, alias=f"_k{i}"))
        where = None
        for c in residual:
            where = c if where is None else \
                ast.BinaryOp(op="AND", left=where, right=c)
        mod = ast.SelectStmt(fields=fields,
                             from_clause=sub_select.from_clause,
                             where=where)
        try:
            # no outer scope: any REMAINING correlation fails resolution
            # here and we fall back to the apply path
            inner_plan = Planner(self.ischema, self.db,
                                 stats_handle=self.stats).plan(mod)
        except (PlanError, ResolveError):
            return None
        r = Resolver(plan.schema)
        try:
            left_keys = ([r.resolve(in_expr)] if in_expr is not None
                         else [])
            left_keys += [r.resolve(o) for o, _i in corr_pairs]
        except (ResolveError, PlanError):
            return None
        right_keys = [ColumnRef(i, c.ft)
                      for i, c in enumerate(inner_plan.schema.cols)]
        if len(left_keys) != len(right_keys):
            return None
        return ph.PhysHashJoin(schema=plan.schema,
                               children=[plan, inner_plan],
                               left_keys=left_keys, right_keys=right_keys,
                               join_type="anti" if anti else "semi")

    def _plan_subquery(self, outer_schema: PlanSchema, sub_select):
        """Plan an inner SELECT with the outer schema visible for
        correlated column resolution."""
        from tidb_tpu.plan.resolver import push_outer
        with push_outer(outer_schema) as scope:
            inner = Planner(self.ischema, self.db,
                            stats_handle=self.stats).plan(sub_select)
        corr = sorted(scope.cells.items())
        return inner, corr

    # -- fields / projection -------------------------------------------------

    def _expand_fields(self, stmt: ast.SelectStmt, schema: PlanSchema):
        """Expand * / t.* into per-column fields."""
        out = []
        for f in stmt.fields:
            if isinstance(f.expr, ast.Star):
                tbl = f.expr.table.lower()
                for i, c in enumerate(schema.cols):
                    if not c.table and c.name.startswith("__sq"):
                        continue   # lifted scalar-subquery helper column
                    if not tbl or c.table == tbl:
                        out.append((ast.ColName(name=c.name, table=c.table),
                                    c.name))
                if not out:
                    raise PlanError(f"unknown table '{tbl}' in {tbl}.*")
            else:
                out.append((f.expr, f.alias or _field_name(f.expr)))
        return out

    def _resolve_fields(self, stmt, schema: PlanSchema):
        r = Resolver(schema)
        exprs, names = [], []
        for e_ast, name in self._expand_fields(stmt, schema):
            exprs.append(r.resolve(e_ast))
            names.append(name)
        return exprs, names

    # -- aggregation ---------------------------------------------------------

    def _plan_agg_select(self, stmt: ast.SelectStmt, plan: ph.PhysPlan):
        in_schema = plan.schema
        base_r = Resolver(in_schema)
        # 1. group exprs over input schema
        group_asts = [bi.expr for bi in stmt.group_by]
        group_exprs = []
        group_targets = [self._maybe_alias_target(ga, stmt, in_schema)
                         for ga in group_asts]   # GROUP BY alias/position
        group_exprs = [base_r.resolve(ga2) for ga2 in group_targets]
        group_ast_reprs = [repr(ga2) for ga2 in group_targets]

        aggs: list[AggDesc] = []
        num_g = len(group_exprs)

        def agg_schema():
            cols = []
            for i, (ge, gr) in enumerate(zip(group_exprs, group_asts)):
                nm = gr.name.lower() if isinstance(gr, ast.ColName) else \
                    f"_g{i}"
                tb = gr.table.lower() if isinstance(gr, ast.ColName) else ""
                cols.append(SchemaCol(nm, tb, ge.ft))
            for j, a in enumerate(aggs):
                cols.append(SchemaCol(f"_a{j}", "", a.result_ft))
            return PlanSchema(cols)

        resolver = _AggResolver(in_schema, aggs, num_g, group_ast_reprs,
                                group_exprs)
        # 2. select fields over (group cols + aggs)
        proj_exprs, proj_names = [], []
        for e_ast, name in self._expand_fields(stmt, in_schema):
            proj_exprs.append(resolver.resolve_over_agg(e_ast))
            proj_names.append(name)
        # 3. having
        having_expr = None
        if stmt.having is not None:
            having_expr = resolver.resolve_over_agg(
                self._substitute_aliases(stmt.having, stmt,
                                         resolver.in_schema))
        # 4. order by may reference aggs too — resolve now, carry through
        order_keys = []
        if stmt.order_by:
            for bi in stmt.order_by:
                target = self._maybe_alias_target(bi.expr, stmt)
                try:
                    order_keys.append(
                        (resolver.resolve_over_agg(target), bi.desc))
                except ResolveError:
                    order_keys.append(None)  # resolved later vs aliases

        # decide pushdown: single bare reader + no distinct aggs
        reader_ok = isinstance(plan, ph.PhysTableReader) and \
            not plan.cop.is_agg and plan.cop.limit is None
        no_distinct = all(not a.distinct for a in aggs)
        if reader_ok and no_distinct:
            plan.cop.group_exprs = group_exprs
            plan.cop.aggs = aggs
            agg_plan = ph.PhysFinalAgg(schema=agg_schema(), children=[plan],
                                       aggs=aggs, num_group_cols=num_g)
        else:
            agg_plan = ph.PhysHashAgg(schema=agg_schema(), children=[plan],
                                      group_exprs=group_exprs, aggs=aggs)
        out = agg_plan
        if having_expr is not None:
            out = ph.PhysSelection(schema=agg_plan.schema, children=[out],
                                   cond=having_expr)
        out_schema = PlanSchema([SchemaCol(n, "", e.ft)
                                 for n, e in zip(proj_names, proj_exprs)])
        return out, out_schema, proj_exprs, proj_names, order_keys

    def _substitute_aliases(self, e, stmt: ast.SelectStmt,
                            schema: PlanSchema | None = None,
                            in_agg: bool = False):
        """Replace select-list aliases ANYWHERE inside an expression
        (HAVING may combine aliases with other predicates, e.g.
        HAVING s > 40 AND g < 5 — MySQL resolves those against the
        select list). A real FROM-clause column of the same name wins
        over the alias (MySQL's HAVING resolution order); an alias
        whose expression holds an aggregate may not land inside
        another aggregate (ER_INVALID_GROUP_FUNC_USE)."""
        import dataclasses
        if isinstance(e, ast.ColName) and not e.table:
            if self._column_shadows(schema, e.name):
                return e
            for f in stmt.fields:
                if f.alias and f.alias.lower() == e.name.lower():
                    if in_agg and self._contains_agg(f.expr):
                        raise ResolveError(
                            "Invalid use of group function")
                    return f.expr
            return e
        if dataclasses.is_dataclass(e) and isinstance(e, ast.ExprNode) \
                and not isinstance(e, (ast.SubqueryExpr,
                                       ast.ExistsSubquery)):
            inner_agg = in_agg or isinstance(e, ast.AggregateCall)
            updates = {}
            for fld in dataclasses.fields(e):
                v = getattr(e, fld.name)
                if isinstance(v, ast.ExprNode):
                    nv = self._substitute_aliases(v, stmt, schema,
                                                  inner_agg)
                    if nv is not v:
                        updates[fld.name] = nv
                elif isinstance(v, list):
                    nl = [self._substitute_aliases(x, stmt, schema,
                                                   inner_agg)
                          if isinstance(x, ast.ExprNode) else x
                          for x in v]
                    if any(a is not b for a, b in zip(nl, v)):
                        updates[fld.name] = nl
            if updates:
                return dataclasses.replace(e, **updates)
        return e

    def _contains_agg(self, e) -> bool:
        import dataclasses
        if isinstance(e, ast.AggregateCall):
            return True
        if dataclasses.is_dataclass(e) and isinstance(e, ast.ExprNode):
            for fld in dataclasses.fields(e):
                v = getattr(e, fld.name)
                if isinstance(v, ast.ExprNode) and self._contains_agg(v):
                    return True
                if isinstance(v, list) and any(
                        isinstance(x, ast.ExprNode) and
                        self._contains_agg(x) for x in v):
                    return True
        return False

    def _maybe_alias_target(self, e: ast.ExprNode, stmt: ast.SelectStmt,
                            schema: PlanSchema | None = None):
        """GROUP BY / ORDER BY may name a select alias or 1-based
        position. Pass `schema` for GROUP BY: MySQL resolves GROUP
        BY/HAVING names FROM-clause-first (a real column shadows the
        alias), but ORDER BY select-list-first."""
        if isinstance(e, ast.Literal) and isinstance(e.value, int) and \
                1 <= e.value <= len(stmt.fields):
            f = stmt.fields[e.value - 1]
            if not isinstance(f.expr, ast.Star):
                return f.expr
        if isinstance(e, ast.ColName) and not e.table:
            if self._column_shadows(schema, e.name):
                return e
            for f in stmt.fields:
                if f.alias and f.alias.lower() == e.name.lower():
                    return f.expr
        return e

    def _rewrite_ast(self, e, fn):
        """Bottom-up AST rebuild: children first, then fn(node) may
        return a replacement. Subquery boundaries are not crossed."""
        import dataclasses
        if dataclasses.is_dataclass(e) and isinstance(e, ast.ExprNode) \
                and not isinstance(e, (ast.SubqueryExpr,
                                       ast.ExistsSubquery)):
            updates = {}
            for fld in dataclasses.fields(e):
                v = getattr(e, fld.name)
                if isinstance(v, ast.ExprNode):
                    nv = self._rewrite_ast(v, fn)
                    if nv is not v:
                        updates[fld.name] = nv
                elif isinstance(v, list):
                    nl = [self._rewrite_ast_item(x, fn) for x in v]
                    if any(a is not b for a, b in zip(nl, v)):
                        updates[fld.name] = nl
            if updates:
                e = dataclasses.replace(e, **updates)
        return fn(e)

    def _rewrite_ast_item(self, x, fn):
        """List element: an expr, or a tuple holding exprs (CASE's
        when_clauses are (cond, result) pairs)."""
        if isinstance(x, ast.ExprNode):
            return self._rewrite_ast(x, fn)
        if isinstance(x, tuple) and any(
                isinstance(y, ast.ExprNode) for y in x):
            nt = tuple(self._rewrite_ast(y, fn)
                       if isinstance(y, ast.ExprNode) else y for y in x)
            return x if all(a is b for a, b in zip(nt, x)) else nt
        return x

    def _rewrite_values_fn(self, e, info):
        """ON DUPLICATE KEY UPDATE ... VALUES(col) -> the candidate
        row's value (ref: executor/write.go onDuplicateUpdate;
        expression/builtin_other.go valuesFunctionClass)."""
        tname = info.name.lower()
        def fn(node):
            if isinstance(node, ast.FuncCall) and \
                    node.name.upper() == "VALUES":
                if len(node.args) != 1 or \
                        not isinstance(node.args[0], ast.ColName):
                    raise PlanError("VALUES() takes a single column name")
                c = node.args[0]
                if (c.table and c.table.lower() != tname) or \
                        info.col_by_name(c.name) is None:
                    raise PlanError(f"Unknown column '{c.name}'")
                return ast.ColName(name="__values__" + c.name.lower())
            return node
        return self._rewrite_ast(e, fn)

    def _fold_default(self, e, info, target: str | None = None):
        """DEFAULT(col) / bare DEFAULT in a SET assignment -> the
        column's default value as a literal. A NOT NULL column without
        a default has no value to give (MySQL error 1364)."""
        def fn(node):
            cname = None
            if isinstance(node, ast.FuncCall) and \
                    node.name.upper() == "DEFAULT":
                if len(node.args) != 1 or \
                        not isinstance(node.args[0], ast.ColName):
                    raise PlanError("DEFAULT() takes a single column name")
                cname = node.args[0].name
            elif isinstance(node, ast.DefaultExpr):
                if target is None:
                    raise PlanError("DEFAULT not valid here")
                cname = target
            if cname is None:
                return node
            ci = info.col_by_name(cname)
            if ci is None:
                raise PlanError(f"Unknown column '{cname}'")
            if not ci.has_default and ci.ft.not_null:
                raise PlanError(
                    f"Field '{ci.name}' doesn't have a default value")
            return ast.Literal(ci.default if ci.has_default else None)
        return self._rewrite_ast(e, fn)

    @staticmethod
    def _column_shadows(schema: PlanSchema | None, name: str) -> bool:
        """MySQL GROUP BY/HAVING resolution order: a FROM-clause column
        of the same name wins over a select-list alias (ORDER BY is the
        opposite — callers there pass schema=None). Ambiguity among the
        FROM columns stays a hard error."""
        if schema is None:
            return False
        try:
            schema.find(name, "")
            return True
        except ColumnAmbiguousError:
            raise
        except ResolveError:
            return False

    def _resolve_order(self, stmt, in_schema: PlanSchema,
                       out_schema: PlanSchema, proj_exprs, order_keys):
        """Order keys run BELOW the projection, over in_schema."""
        by = []
        for i, bi in enumerate(stmt.order_by):
            if order_keys is not None and order_keys[i] is not None:
                by.append((order_keys[i][0], order_keys[i][1]))
                continue
            target = self._maybe_alias_target(bi.expr, stmt)
            if isinstance(target, ast.Literal) and \
                    isinstance(target.value, int) and \
                    1 <= target.value <= len(proj_exprs):
                # ORDER BY <position> over a SELECT * projection (the
                # alias map can't expand a Star field)
                by.append((proj_exprs[target.value - 1], bi.desc))
                continue
            # alias/output name -> reuse the projection expression
            try:
                oi = out_schema.find(
                    target.name if isinstance(target, ast.ColName) else "",
                    target.table if isinstance(target, ast.ColName) else "")
                by.append((proj_exprs[oi], bi.desc))
                continue
            except (ResolveError, AttributeError):
                pass
            by.append((Resolver(in_schema).resolve(target), bi.desc))
        return by

    # -- DML -----------------------------------------------------------------

    def plan_insert(self, stmt: ast.InsertStmt) -> ph.PhysInsert:
        _db, info = self._table_info(stmt.table)
        cols = stmt.columns or [c.name for c in info.public_columns()]
        for c in cols:
            if info.col_by_name(c) is None:
                raise PlanError(f"Unknown column '{c}'")
        if stmt.select is not None:
            source = self._plan_query(stmt.select)
            if len(source.schema) != len(cols):
                raise PlanError("Column count doesn't match value count")
        else:
            r = Resolver(PlanSchema([]))
            rows = []
            for vr in stmt.values:
                if len(vr) == 0 and not stmt.columns:
                    # INSERT t VALUES (): every column takes its default.
                    # Only legal without an explicit column list (MySQL
                    # 1136 otherwise — the count check below raises)
                    vr = [ast.DefaultExpr() for _ in cols]
                if len(vr) != len(cols):
                    raise PlanError("Column count doesn't match value count")
                rows.append([None if isinstance(v, ast.DefaultExpr)
                             else r.resolve(self._fold_default(v, info))
                             for v in vr])
            source = ph.PhysValues(rows=rows)
        dup = []
        if stmt.on_duplicate:
            # assignments may reference existing row columns; VALUES(c)
            # refers to the would-be inserted value and resolves against
            # a second column set appended after the existing row (the
            # executor evaluates over an [old | candidate] chunk) under
            # reserved __values__-prefixed names so bare refs stay
            # unambiguous
            pub = info.public_columns()
            schema = PlanSchema(
                [SchemaCol(c.name.lower(), info.name.lower(), c.ft, c.id)
                 for c in pub] +
                [SchemaCol("__values__" + c.name.lower(), "", c.ft, c.id)
                 for c in pub])
            r2 = Resolver(schema)
            for a in stmt.on_duplicate:
                if info.col_by_name(a.col.name) is None:
                    raise PlanError(f"Unknown column '{a.col.name}'")
                e2 = self._rewrite_values_fn(
                    self._fold_default(a.expr, info, a.col.name), info)
                dup.append((a.col.name.lower(), r2.resolve(e2)))
        return ph.PhysInsert(table=info, columns=[c.lower() for c in cols],
                             source=source, on_duplicate=dup,
                             is_replace=stmt.is_replace, ignore=stmt.ignore)

    def _plan_writable_reader(self, ts: ast.TableSource,
                              where: ast.ExprNode | None):
        """Reader emitting all public columns + trailing _handle col."""
        _db, info = self._table_info(ts)
        cols = info.public_columns()
        schema = PlanSchema(
            [SchemaCol(c.name.lower(), ts.ref_name.lower(), c.ft, c.id)
             for c in cols] +
            [SchemaCol("_handle", ts.ref_name.lower(), st.new_int_field())])
        cop = ph.CopPlan(table=info, cols=list(cols),
                         handle_col=len(cols))
        plan = ph.PhysTableReader(schema=schema, cop=cop)
        if where is not None:
            r = Resolver(schema)
            for c_ast in split_conjuncts(where):
                # EXISTS / IN / <cmp> (SELECT) filter applies preserve
                # the reader schema exactly (cols + _handle), so DML
                # WHERE supports them like SELECT does; scalar LIFTS
                # would append columns and stay unsupported here
                if _reads_table(c_ast, _db, info.name, self.db or ""):
                    # Halloween guard, like MySQL error 1093: the
                    # subquery must not read the table being written
                    raise PlanError(
                        f"You can't specify target table "
                        f"'{info.name}' for update in FROM clause")
                applied = self._try_subquery_conjunct(plan, c_ast)
                if applied is not None:
                    plan = applied
                    continue
                plan = self._assign_cond(plan, r.resolve(c_ast), True)
        return info, plan

    def _order_limit_reader(self, reader, order_by, limit):
        """UPDATE/DELETE ... [ORDER BY ...] [LIMIT n]: restrict the
        writable reader to the ordered first-n rows (MySQL semantics —
        ignoring these silently would write/delete EVERY match)."""
        if not order_by and limit is None:
            return reader
        if order_by:
            r = Resolver(reader.schema)
            by = [(r.resolve(item.expr), item.desc) for item in order_by]
            reader = ph.PhysSort(schema=reader.schema, children=[reader],
                                 by=by)
        if limit is not None:
            reader = ph.PhysLimit(schema=reader.schema, children=[reader],
                                  count=limit)
        return reader

    def plan_update(self, stmt: ast.UpdateStmt) -> ph.PhysPlan:
        if not isinstance(stmt.table, ast.TableSource):
            return self.plan_multi_update(stmt)
        info, reader = self._plan_writable_reader(stmt.table, stmt.where)
        reader = self._order_limit_reader(reader, stmt.order_by,
                                          stmt.limit)
        assigns = []
        r = Resolver(reader.schema)
        for a in stmt.assignments:
            if info.col_by_name(a.col.name) is None:
                raise PlanError(f"Unknown column '{a.col.name}'")
            assigns.append((a.col.name.lower(), r.resolve(
                self._fold_default(a.expr, info, a.col.name))))
        return ph.PhysUpdate(table=info, reader=reader, assignments=assigns)

    def plan_multi_update(self, stmt: ast.UpdateStmt) -> ph.PhysPlan:
        """UPDATE t1, t2 SET ... / UPDATE <join> SET ... (ref:
        executor/write.go:479 multi-table UpdateExec): targets are the
        tables whose columns are assigned; their readers carry row
        handles through the join; assignments may read any table."""
        if stmt.order_by or stmt.limit is not None:
            raise PlanError(
                "multi-table UPDATE does not allow ORDER BY/LIMIT")
        sources: dict[str, ast.TableSource] = {}

        def walk(node):
            if isinstance(node, ast.TableSource):
                sources[node.ref_name.lower()] = node
            elif isinstance(node, ast.Join):
                walk(node.left)
                walk(node.right)
            elif node is not None:
                raise PlanError(
                    "multi-table UPDATE supports plain table joins")
        walk(stmt.table)

        def target_of(col: ast.ColName) -> str:
            if col.table:
                key = col.table.lower()
                if key in sources and (not col.db or (
                        sources[key].db or self.db).lower()
                        == col.db.lower()):
                    return key
                for k, ts in sources.items():   # db-qualified, aliased
                    if ts.name.lower() == col.table.lower() and \
                            (not col.db or (ts.db or self.db).lower()
                             == col.db.lower()):
                        return k
                raise PlanError(f"Unknown table '{col.table}' in UPDATE")
            cands = [k for k, ts in sources.items()
                     if self._table_info(ts)[1].col_by_name(col.name)]
            if len(cands) > 1:
                raise PlanError(f"Column '{col.name}' is ambiguous")
            if not cands:
                raise PlanError(f"Unknown column '{col.name}'")
            return cands[0]

        per_ref: dict[str, list] = {}
        for a in stmt.assignments:
            per_ref.setdefault(target_of(a.col), []).append(a)

        self._handle_refs = set(per_ref)
        try:
            plan = self.build_from(stmt.table)
            if stmt.where is not None:
                r = Resolver(plan.schema)
                for c_ast in split_conjuncts(stmt.where):
                    plan = self._assign_cond(plan, r.resolve(c_ast), True)
        finally:
            self._handle_refs = set()

        r = Resolver(plan.schema)
        targets = []
        for key, assigns_ast in per_ref.items():
            _db, info = self._table_info(sources[key])
            handle_idx = col_start = None
            for i, sc in enumerate(plan.schema.cols):
                if sc.table != key:
                    continue
                if col_start is None:
                    col_start = i
                if sc.name == "_handle":
                    handle_idx = i
            if handle_idx is None:
                raise PlanError(f"no handle for target '{key}'")
            assigns = []
            for a in assigns_ast:
                if info.col_by_name(a.col.name) is None:
                    raise PlanError(f"Unknown column '{a.col.name}'")
                assigns.append((a.col.name.lower(), r.resolve(
                    self._fold_default(a.expr, info, a.col.name))))
            targets.append((info, col_start, handle_idx, assigns))
        return ph.PhysMultiUpdate(targets=targets, reader=plan)

    def plan_delete(self, stmt: ast.DeleteStmt):
        if stmt.targets:
            return self.plan_multi_delete(stmt)
        info, reader = self._plan_writable_reader(stmt.table, stmt.where)
        reader = self._order_limit_reader(reader, stmt.order_by,
                                          stmt.limit)
        return ph.PhysDelete(table=info, reader=reader)

    def plan_multi_delete(self, stmt: ast.DeleteStmt) -> ph.PhysMultiDelete:
        """DELETE t1, t2 FROM <join> ... (ref: executor/write.go
        deleteMultiTables + ast/dml.go IsMultiTable): target tables'
        readers carry their row handle through the join; each matched
        row deletes from every target (deduped per handle)."""
        # collect the referenced table sources by ref name
        sources: dict[str, ast.TableSource] = {}

        def walk(node):
            if isinstance(node, ast.TableSource):
                sources[node.ref_name.lower()] = node
            elif isinstance(node, ast.Join):
                walk(node.left)
                walk(node.right)
            elif node is not None:
                raise PlanError(
                    "multi-table DELETE supports plain table joins")
        walk(stmt.refs)

        want: list[tuple[str, ast.TableSource]] = []
        for tgt in stmt.targets:
            key = tgt.ref_name.lower()
            if key not in sources:
                raise PlanError(f"Unknown table '{tgt.name}' in "
                                "MULTI DELETE")
            want.append((key, sources[key]))

        self._handle_refs = {k for k, _ in want}
        try:
            plan = self.build_from(stmt.refs)
            if stmt.where is not None:
                r = Resolver(plan.schema)
                for c_ast in split_conjuncts(stmt.where):
                    plan = self._assign_cond(plan, r.resolve(c_ast), True)
        finally:
            self._handle_refs = set()

        targets = []
        for key, ts in want:
            _db, info = self._table_info(ts)
            handle_idx = col_start = None
            for i, sc in enumerate(plan.schema.cols):
                if sc.table != key:
                    continue
                if col_start is None:
                    col_start = i
                if sc.name == "_handle":
                    handle_idx = i
            if handle_idx is None:
                raise PlanError(f"no handle for target '{ts.name}'")
            targets.append((info, col_start, handle_idx))
        return ph.PhysMultiDelete(targets=targets, reader=plan)


def _type_word(ft) -> str:
    from tidb_tpu.sqltypes import TypeCode
    return {TypeCode.LONGLONG: "bigint", TypeCode.LONG: "int",
            TypeCode.DOUBLE: "double", TypeCode.NEWDECIMAL: "decimal",
            TypeCode.VARCHAR: "varchar", TypeCode.STRING: "char",
            TypeCode.DATE: "date", TypeCode.DATETIME: "datetime",
            TypeCode.TIMESTAMP: "timestamp", TypeCode.ENUM: "enum",
            TypeCode.SET: "set",
            TypeCode.JSON: "json"}.get(ft.tp, "unknown")


def _union_ft(fts):
    """Unified output type of one UNION column position: numeric widening
    (int < decimal < real); any other mix coerces to string (MySQL)."""
    from tidb_tpu.sqltypes import (EvalType, new_decimal_field,
                                   new_double_field, new_string_field)
    ets = [ft.eval_type for ft in fts]
    if all(e == ets[0] for e in ets):
        if ets[0] == EvalType.DECIMAL:
            frac = max(ft.frac for ft in fts)
            flen = max(ft.flen for ft in fts)
            return new_decimal_field(flen, frac)
        return fts[0]
    numeric = {EvalType.INT, EvalType.REAL, EvalType.DECIMAL}
    if all(e in numeric for e in ets):
        if EvalType.REAL in ets:
            return new_double_field()
        frac = max(ft.frac for ft in fts
                   if ft.eval_type == EvalType.DECIMAL)
        return new_decimal_field(30, frac)
    return new_string_field(255)


def _in_as_scalar(left, sel) -> ast.SubqueryExpr:
    """`left IN (sel)` as a scalar aggregate with IN's three-valued
    semantics: 0 for the empty set, 1 on a match, NULL when undecided
    (left NULL or a NULL among the non-matching set), else 0. SUM
    skips NULL comparisons, which is exactly the counting needed."""
    import dataclasses
    first = sel.selects[0] if isinstance(sel, ast.UnionStmt) else sel
    if len(first.fields) != 1:
        raise PlanError("subquery must return 1 column for IN")
    if isinstance(first.fields[0].expr, ast.Star):
        raise PlanError("IN (SELECT *) in expression position needs "
                        "the column named explicitly")
    nf = dataclasses.replace(first.fields[0], alias="__v")
    nfirst = dataclasses.replace(first, fields=[nf])
    sel = dataclasses.replace(sel, selects=[nfirst] + sel.selects[1:]) \
        if isinstance(sel, ast.UnionStmt) else nfirst
    y = ast.ColName(name="__v", table="__in")
    lit = ast.Literal
    eq_sum = ast.AggregateCall(name="SUM",
                               args=[ast.BinaryOp("=", y, left)])
    null_sum = ast.AggregateCall(name="SUM",
                                 args=[ast.IsNullExpr(expr=y)])
    case = ast.CaseExpr(operand=None, when_clauses=[
        (ast.BinaryOp("=", ast.AggregateCall(name="COUNT", star=True),
                      lit(0)), lit(0)),
        (ast.BinaryOp(">", eq_sum, lit(0)), lit(1)),
        (ast.BinaryOp("OR", ast.IsNullExpr(expr=left),
                      ast.BinaryOp(">", null_sum, lit(0))), lit(None)),
    ], else_clause=lit(0))
    return ast.SubqueryExpr(select=ast.SelectStmt(
        fields=[ast.SelectField(expr=case)],
        from_clause=ast.SubqueryTable(select=sel, alias="__in")))


def _iter_nodes(e, stop: tuple = ()):
    """Yield `e` and every ast.Node under it (fields, lists, tuples of
    nodes). Nodes of a `stop` type are yielded but not descended into."""
    yield e
    if isinstance(e, stop):
        return
    for f in vars(e).values():
        if isinstance(f, ast.Node):
            yield from _iter_nodes(f, stop)
        elif isinstance(f, (list, tuple)):
            for x in f:
                if isinstance(x, ast.Node):
                    yield from _iter_nodes(x, stop)
                elif isinstance(x, tuple):
                    for y in x:
                        if isinstance(y, ast.Node):
                            yield from _iter_nodes(y, stop)


def _reads_table(e, db: str, name: str, cur_db: str) -> bool:
    """Does any subquery under `e` scan table `db.name`? (DML WHERE
    may not read its own target table — MySQL error 1093.) An
    unqualified TableSource resolves against the session db."""
    db, name = db.lower(), name.lower()
    return any(isinstance(n, ast.TableSource) and
               n.name.lower() == name and
               (n.db or cur_db).lower() == db
               for n in _iter_nodes(e))


def _contains_scalar_subquery(e) -> bool:
    """True when a subquery appears in expression position inside `e`
    and the lift can rewrite it (scalar, IN-subquery via its items
    node, EXISTS); does not cross into nested subquery bodies."""
    stop = (ast.SubqueryExpr, ast.ExistsSubquery, ast.QuantSubquery,
            ast.SelectStmt, ast.UnionStmt)
    return any(isinstance(n, (ast.SubqueryExpr, ast.ExistsSubquery))
               for n in _iter_nodes(e, stop))


def _contains_agg(stmt: ast.SelectStmt) -> bool:
    found = False

    def walk(n):
        nonlocal found
        if found or n is None or not isinstance(n, ast.Node):
            return
        if isinstance(n, ast.AggregateCall):
            found = True
            return
        if isinstance(n, (ast.SubqueryExpr, ast.ExistsSubquery)):
            return  # inner aggregates belong to the subquery
        for f in vars(n).values():
            if isinstance(f, ast.Node):
                walk(f)
            elif isinstance(f, (list, tuple)):
                for x in f:
                    if isinstance(x, ast.Node):
                        walk(x)
                    elif isinstance(x, tuple):
                        for y in x:
                            walk(y) if isinstance(y, ast.Node) else None
    for f in stmt.fields:
        walk(f.expr)
    walk(stmt.having)
    for bi in stmt.order_by:
        walk(bi.expr)
    return found


def _field_name(e: ast.ExprNode) -> str:
    if isinstance(e, ast.ColName):
        return e.name.lower()
    if isinstance(e, ast.AggregateCall):
        return f"{e.name.lower()}({'*' if e.star else '...'})"
    if isinstance(e, ast.Literal):
        return str(e.value)
    if isinstance(e, ast.SubqueryExpr):
        return "(subquery)"
    if isinstance(e, ast.ExistsSubquery):
        return "exists(subquery)"
    if isinstance(e, ast.InExpr) and \
            isinstance(e.items, ast.SubqueryExpr):
        return f"{_field_name(e.expr)} in (subquery)"
    return type(e).__name__.lower()


class _AggResolver:
    """Resolves select/having/order exprs over an aggregation's output:
    whole-or-sub expressions matching a GROUP BY item become group column
    refs; AggregateCalls land in the agg list; bare columns not in GROUP BY
    get implicit FIRST_ROW (MySQL loose group-by, like the reference's
    aggregation builder)."""

    def __init__(self, in_schema: PlanSchema, aggs: list[AggDesc],
                 num_group: int, group_reprs: list[str],
                 group_exprs: list[Expression]):
        self.in_schema = in_schema
        self.aggs = aggs
        self.num_group = num_group
        self.group_reprs = group_reprs
        self.group_exprs = group_exprs

    def resolve_over_agg(self, e: ast.ExprNode) -> Expression:
        # whole-expr group match
        er = repr(e)
        for i, gr in enumerate(self.group_reprs):
            if er == gr:
                return ColumnRef(i, self.group_exprs[i].ft)
        if isinstance(e, ast.AggregateCall):
            r = Resolver(self.in_schema, agg_collector=self.aggs,
                         agg_base=self.num_group)
            return r._r_AggregateCall(e)
        if isinstance(e, ast.ColName):
            # bare column not in group -> implicit first_row
            r = Resolver(self.in_schema)
            inner = r.resolve(e)
            desc = AggDesc(AggFunc.FIRST_ROW, inner)
            for i, d in enumerate(self.aggs):
                if repr(d) == repr(desc):
                    return ColumnRef(self.num_group + i, d.result_ft)
            self.aggs.append(desc)
            return ColumnRef(self.num_group + len(self.aggs) - 1,
                             desc.result_ft)
        if isinstance(e, ast.Literal):
            return Resolver(self.in_schema).resolve(e)
        # composite: rebuild node with resolved children
        sub = _SubResolver(self)
        return sub.resolve(e)


class _SubResolver(Resolver):
    """Resolver whose leaf ColName/AggregateCall handling delegates to the
    surrounding _AggResolver (group/agg output refs)."""

    def __init__(self, parent: _AggResolver):
        super().__init__(parent.in_schema)
        self.parent = parent

    def resolve(self, e: ast.ExprNode) -> Expression:
        er = repr(e)
        for i, gr in enumerate(self.parent.group_reprs):
            if er == gr:
                return ColumnRef(i, self.parent.group_exprs[i].ft)
        if isinstance(e, (ast.ColName, ast.AggregateCall)):
            return self.parent.resolve_over_agg(e)
        return super().resolve(e)
