"""Mesh routing: the TPU equivalent of the copTask pushdown decision.

The reference planner closes a pushdown region per-operator via
copTask/rootTask costing (/root/reference/plan/task.go:116-499): work that
can run next to the data is serialized into the storage request. Here the
"storage" for analytical work is the device mesh — this post-pass walks a
finished physical plan and, when a process mesh is configured
(tidb_tpu.devplane), replaces qualifying subtrees with mesh
operators:

* PhysMeshAgg — a pushed-down group-by aggregation over one table scan
  (TPC-H Q1 shape) runs as ops/meshagg.MeshAggKernel: rows sharded
  over the ("batch",) device plane, all_gather merge over ICI.
* PhysMeshLookupAgg — an inner-join star over one fact table plus
  unique-keyed dimension tables feeding a group-by (Q3/Q5 shape) runs as
  ops/meshjoin.MeshLookupAggKernel: fused filter -> lookup chain ->
  aggregate, dimensions replicated per chip.

Every mesh node keeps the original subtree as `fallback`; the executor
delegates to it when no mesh is active at run time or the kernel rejects
the data (capacity overflow, hash collision, duplicate build keys).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace

from tidb_tpu.expression import ColumnRef, Expression
from tidb_tpu.expression.core import Op, ScalarFunc, func
from tidb_tpu.plan import physical as ph
from tidb_tpu.plan.resolver import PlanSchema, SchemaCol
from tidb_tpu.sqltypes import new_int_field

__all__ = ["PhysMeshAgg", "PhysMeshLookupAgg", "MeshLookupDesc",
           "route_mesh"]


@dataclass
class PhysMeshAgg(ph.PhysPlan):
    """Group-by aggregation executed on the device mesh. children[0] is
    the raw scan (the agg-pushdown cop stripped of its agg); group/agg
    expressions index the scan schema."""

    group_exprs: list = field(default_factory=list)
    aggs: list = field(default_factory=list)
    num_group_cols: int = 0
    filter_expr: Expression = None   # device-safe filter lifted from the cop
    fallback: ph.PhysPlan = None

    def _explain_info(self):
        return f" group:{self.group_exprs!r} aggs:{self.aggs!r}"


@dataclass
class MeshLookupDesc:
    """One dimension lookup of a PhysMeshLookupAgg. key_exprs index the
    virtual schema (probe columns, then payloads of earlier lookups);
    build offsets index the build plan's schema."""

    key_exprs: list
    build_plan: ph.PhysPlan
    build_key_offsets: list
    payload_offsets: list


@dataclass
class PhysMeshLookupAgg(ph.PhysPlan):
    """Star join + aggregation on the mesh. children[0] is the probe
    (fact) scan; filter/group/agg expressions index the virtual schema."""

    lookups: list = field(default_factory=list)
    filter_expr: Expression = None
    group_exprs: list = field(default_factory=list)
    aggs: list = field(default_factory=list)
    num_group_cols: int = 0
    fallback: ph.PhysPlan = None

    def _explain_info(self):
        dims = ",".join(lk.build_plan.cop.table.name for lk in self.lookups)
        return (f" dims:[{dims}] group:{self.group_exprs!r} "
                f"aggs:{self.aggs!r}")


def route_mesh(plan: ph.PhysPlan) -> ph.PhysPlan:
    """Rewrite qualifying agg subtrees to mesh operators. No-op when no
    process mesh is configured — or when the mesh is a single device:
    sharding over one chip only adds gather/replication overhead, and it
    routes scans around the storage-side columnar caches (the copTask
    path serves repeated scans from the HBM device cache and fuses
    scan->filter->partial-agg into one dispatch; measured 1.2-2.6x
    faster warm on TPC-H Q1/Q3/Q5 than the 1-device mesh kernels). The
    decision depends only on the mesh itself, so plans stay coherent
    with the mesh_generation() plan-cache key."""
    from tidb_tpu import devplane as config

    mesh = config.active_mesh()
    if mesh is None or mesh.devices.size <= 1:
        return plan
    return _route(plan)


def _route(plan: ph.PhysPlan) -> ph.PhysPlan:
    routed = None
    if isinstance(plan, ph.PhysFinalAgg):
        routed = _try_mesh_agg(plan)
    elif isinstance(plan, ph.PhysHashAgg):
        routed = _try_mesh_lookup_agg(plan)
    if routed is not None:
        return routed
    for i, c in enumerate(plan.children):
        plan.children[i] = _route(c)
    if isinstance(plan, ph.PhysApply) and plan.inner is not None:
        plan.inner = _route(plan.inner)
    return plan


# -- pattern A: pushed-down group agg over one scan (Q1) --------------------

def _try_mesh_agg(final: ph.PhysFinalAgg):
    reader = final.children[0]
    if not isinstance(reader, ph.PhysTableReader):
        return None
    cop = reader.cop
    if not cop.is_agg or not cop.group_exprs:
        return None
    if any(a.distinct for a in cop.aggs):
        return None
    if not _exprs_mesh_safe(cop.group_exprs, cop.aggs, None):
        return None
    raw_cop = replace(cop, group_exprs=None, aggs=None)
    # lift a device-safe scan filter into the mesh kernel: the raw scan
    # then serves identical (cacheable) chunks to every query and the
    # filter runs fused on device instead of per-query host numpy
    dev_filter = None
    if raw_cop.filter is not None and raw_cop.filter.is_device_safe():
        dev_filter = raw_cop.filter
        raw_cop = replace(raw_cop, filter=None)
    # the stripped reader yields the raw scan columns, not the agg output:
    # give it a schema to match (advisor r2: children[0].schema must not lie)
    raw_cols = [SchemaCol(c.name.lower(), cop.table.name.lower(), c.ft, c.id)
                for c in raw_cop.cols]
    if raw_cop.handle_col is not None:
        raw_cols.insert(raw_cop.handle_col,
                        SchemaCol("_handle", cop.table.name.lower(),
                                  new_int_field()))
    raw_reader = ph.PhysTableReader(schema=PlanSchema(raw_cols), cop=raw_cop)
    return PhysMeshAgg(schema=final.schema, children=[raw_reader],
                       group_exprs=list(cop.group_exprs),
                       aggs=list(cop.aggs),
                       num_group_cols=final.num_group_cols,
                       filter_expr=dev_filter,
                       fallback=final)


def _exprs_mesh_safe(group_exprs, aggs, filter_expr) -> bool:
    """Plan-time device-safety screen (the kernels re-validate): group
    keys must be device-safe or bare (dict-encodable) column refs; agg
    args and filters must be fully device-safe."""
    for g in group_exprs:
        if not g.is_device_safe() and not isinstance(g, ColumnRef):
            return False
    for a in aggs:
        if a.arg is not None and not a.arg.is_device_safe():
            return False
    if filter_expr is not None and not filter_expr.is_device_safe():
        return False
    return True


# -- pattern B: star join + group agg (Q3/Q5) -------------------------------

def _try_mesh_lookup_agg(agg: ph.PhysHashAgg):
    if not agg.group_exprs or any(a.distinct for a in agg.aggs):
        return None
    # Peel selections between the agg and the join root; their conditions
    # join the filter set (they are in the join-output = global frame).
    node = agg.children[0]
    extra_conds = []
    while isinstance(node, ph.PhysSelection):
        extra_conds.append(node.cond)
        node = node.children[0]
    if not isinstance(node, ph.PhysHashJoin):
        return None
    flat = _flatten_joins(node, 0)
    if flat is None:
        return None
    leaves, eq_conds, other_conds = flat
    if len(leaves) < 2:
        return None
    other_conds = other_conds + extra_conds

    order = _probe_preference(leaves, eq_conds)
    for probe_i in order:
        chain = _build_chain(leaves, eq_conds, probe_i)
        if chain is None:
            continue
        routed = _assemble(agg, leaves, probe_i, chain, other_conds)
        if routed is not None:
            return routed
    return None


def _flatten_joins(p: ph.PhysPlan, base: int):
    """-> (leaves [(reader, base, width)], eq_conds [(lexpr, rexpr)] in the
    global frame, other_conds [expr]) or None if the tree has a shape the
    lookup pipeline cannot express."""
    if isinstance(p, ph.PhysHashJoin):
        if p.join_type != "inner" or not p.left_keys:
            return None
        nl = len(p.children[0].schema)
        left = _flatten_joins(p.children[0], base)
        right = _flatten_joins(p.children[1], base + nl)
        if left is None or right is None:
            return None
        leaves = left[0] + right[0]
        eq = left[1] + right[1]
        other = left[2] + right[2]
        for lk, rk in zip(p.left_keys, p.right_keys):
            eq.append((_shift(lk, base), _shift(rk, base + nl)))
        if p.other_cond is not None:
            other.append(_shift(p.other_cond, base))
        return leaves, eq, other
    if isinstance(p, ph.PhysTableReader) and not p.cop.is_agg and \
            p.cop.limit is None and p.cop.index is None:
        return [(p, base, len(p.schema))], [], []
    return None


def _shift(e: Expression, base: int) -> Expression:
    if base == 0:
        return e
    return e.map_columns({i: i + base for i in e.columns_used()})


def _probe_preference(leaves, eq_conds) -> list:
    """Try leaves as the probe side: leaves that cannot serve as a
    dimension (their join columns are not unique-keyed) first — the fact
    table — then by estimated size descending."""
    def dimmable(i):
        reader, base, width = leaves[i]
        offs = set()
        for a, b in eq_conds:
            for e in (a, b):
                if isinstance(e, ColumnRef) and \
                        base <= e.idx < base + width:
                    offs.add(e.idx - base)
        return bool(offs) and _is_unique_key(reader, offs)

    def key(i):
        reader, _b, _w = leaves[i]
        est = reader.est_rows if reader.est_rows is not None else 0
        return (dimmable(i), -est)
    return sorted(range(len(leaves)), key=key)


def _leaf_of(cols: set, leaves) -> int | None:
    """Index of the single leaf containing every global column in cols."""
    for i, (_r, base, width) in enumerate(leaves):
        if all(base <= c < base + width for c in cols):
            return i
    return None


def _is_unique_key(reader: ph.PhysTableReader, local_offsets) -> bool:
    """Do the leaf-local key columns contain a primary/unique key?"""
    info = reader.cop.table
    names = {reader.cop.cols[o].name.lower() for o in local_offsets}
    if info.pk_is_handle and info.pk_col_name.lower() in names:
        return True
    for idx in info.indexes:
        if idx.unique and \
                all(c.lower() in names for c in idx.columns):
            return True
    return False


def _build_chain(leaves, eq_conds, probe_i):
    """Greedy lookup-chain construction. -> ([(leaf_i, key_pairs)],
    leftover) where key_pairs is [(covered_side_expr_global,
    dim_local_offset)] and leftover holds equality conds with both sides
    covered (they become payload-equality filters), or None when no
    complete chain exists from this probe."""
    covered = {probe_i}
    pending = list(range(len(eq_conds)))
    chain = []
    leftover = []
    while True:
        # conds with both sides covered become filters
        still = []
        for ci in pending:
            a, b = eq_conds[ci]
            if _covered(a, leaves, covered) and \
                    _covered(b, leaves, covered):
                leftover.append((a, b))
            else:
                still.append(ci)
        pending = still
        if not pending:
            break
        # usable: per uncovered leaf, the conds that could key it NOW
        usable: dict[int, list] = {}
        for ci in pending:
            a, b = eq_conds[ci]
            la = _leaf_of(a.columns_used(), leaves)
            lb = _leaf_of(b.columns_used(), leaves)
            if _covered(a, leaves, covered) and lb is not None and \
                    lb not in covered and isinstance(b, ColumnRef):
                usable.setdefault(lb, []).append(
                    (ci, a, b.idx - leaves[lb][1]))
            elif _covered(b, leaves, covered) and la is not None and \
                    la not in covered and isinstance(a, ColumnRef):
                usable.setdefault(la, []).append(
                    (ci, b, a.idx - leaves[la][1]))
        picked = None
        for li, triples in usable.items():
            if _is_unique_key(leaves[li][0], [o for _ci, _e, o in triples]):
                picked = (li, triples)
                break
        if picked is None:
            return None        # stuck: remaining conds can't key any dim
        li, triples = picked
        chain.append((li, [(e, o) for _ci, e, o in triples]))
        covered.add(li)
        consumed = {ci for ci, _e, _o in triples}
        pending = [ci for ci in pending if ci not in consumed]
    if len(covered) != len(leaves):
        return None            # disconnected table (cross join residue)
    return chain, leftover


def _covered(e: Expression, leaves, covered) -> bool:
    cols = e.columns_used()
    if not cols:
        return False
    ranges = [(leaves[i][1], leaves[i][1] + leaves[i][2]) for i in covered]
    return all(any(lo <= c < hi for lo, hi in ranges) for c in cols)


def _assemble(agg, leaves, probe_i, chain_leftover, other_conds):
    chain, leftover = chain_leftover
    probe_reader, probe_base, probe_w = leaves[probe_i]

    # needed global columns beyond the probe: later keys, groups, aggs,
    # filters (leftover equalities + other/selection conds)
    needed = set()
    for _li, pairs in chain:
        for e, _o in pairs:
            needed |= e.columns_used()
    for g in agg.group_exprs:
        needed |= g.columns_used()
    for a in agg.aggs:
        if a.arg is not None:
            needed |= a.arg.columns_used()
    for a, b in leftover:
        needed |= a.columns_used() | b.columns_used()
    for c in other_conds:
        needed |= c.columns_used()

    # virtual schema: probe columns first, then payloads in chain order
    vmap = {probe_base + i: i for i in range(probe_w)}
    nxt = probe_w
    lookups = []
    for li, pairs in chain:
        reader, base, width = leaves[li]
        pay = sorted({c - base for c in needed
                      if base <= c < base + width})
        for o in pay:
            vmap[base + o] = nxt
            nxt += 1
        lookups.append((li, pairs, pay))

    def remap(e):
        used = e.columns_used()
        if not all(c in vmap for c in used):
            raise KeyError
        return e.map_columns({c: vmap[c] for c in used})

    try:
        descs = []
        for li, pairs, pay in lookups:
            descs.append(MeshLookupDesc(
                key_exprs=[remap(e) for e, _o in pairs],
                build_plan=leaves[li][0],
                build_key_offsets=[o for _e, o in pairs],
                payload_offsets=pay))
        filt = None
        for a, b in leftover:
            filt = _and(filt, func(Op.EQ, remap(a), remap(b)))
        for c in other_conds:
            filt = _and(filt, remap(c))
        group_exprs = [remap(g) for g in agg.group_exprs]
        aggs = [replace(a, arg=remap(a.arg)) if a.arg is not None else a
                for a in agg.aggs]
    except KeyError:
        return None
    if not _exprs_mesh_safe(group_exprs, aggs, filt):
        return None
    for d in descs:
        if not all(e.is_device_safe() for e in d.key_exprs):
            return None
    return PhysMeshLookupAgg(schema=agg.schema, children=[probe_reader],
                             lookups=descs, filter_expr=filt,
                             group_exprs=group_exprs, aggs=aggs,
                             num_group_cols=len(agg.group_exprs),
                             fallback=agg)


def _and(a, b):
    if a is None:
        return b
    return func(Op.AND, a, b)
