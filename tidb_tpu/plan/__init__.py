from tidb_tpu.plan.planner import Planner, PlanError
from tidb_tpu.plan import physical

__all__ = ["Planner", "PlanError", "physical"]
