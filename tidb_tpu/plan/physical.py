"""Physical plan nodes.

Reference: /root/reference/plan/physical_plans.go + the copTask/rootTask
split of plan/task.go:31-49 — `CopPlan` is the pushed-down subplan a
storage node executes next to the data (the tipb.DAGRequest analogue,
plan/plan_to_pb.go:30), everything else runs at the root.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from tidb_tpu.expression import AggDesc, Expression
from tidb_tpu.kv import KVRange
from tidb_tpu.plan.resolver import PlanSchema
from tidb_tpu.schema.model import ColumnInfo, IndexInfo, TableInfo

__all__ = ["CopPlan", "PhysPlan", "PhysTableReader", "PhysIndexReader",
           "PhysIndexLookUp", "PhysPointGet", "PhysSelection",
           "PhysProjection", "PhysHashAgg", "PhysFinalAgg", "PhysStreamAgg",
           "PhysHashJoin", "PhysMergeJoin", "PhysIndexJoin",
           "PhysApply", "PhysSort", "PhysLimit", "PhysTopN", "PhysInsert",
           "PhysUpdate", "PhysDelete", "PhysMultiDelete", "PhysValues"]


@dataclass
class CopPlan:
    """Storage-side subplan: scan -> [host_filter] -> [filter] ->
    [partial agg] -> [limit], executed per region."""

    table: TableInfo
    cols: list[ColumnInfo]                  # scan output, in order
    handle_col: Optional[int] = None        # emit handle at this position
    ranges: Optional[list[KVRange]] = None  # None = whole table
    filter: Optional[Expression] = None     # device-safe conjuncts
    host_filter: Optional[Expression] = None  # string/varlen conjuncts
    group_exprs: Optional[list[Expression]] = None
    aggs: Optional[list[AggDesc]] = None
    limit: Optional[int] = None             # only when no aggs
    desc: bool = False
    index: Optional[IndexInfo] = None       # index scan: decode index keys
    # (col_id, DatumRanges) of a pure pk-range scan: the reader reports
    # actual row counts back to the stats handle (query feedback)
    feedback: Optional[tuple] = None
    # USE/IGNORE/FORCE INDEX hints from the table factor
    index_hints: list = field(default_factory=list)

    @property
    def is_agg(self) -> bool:
        return self.aggs is not None


@dataclass
class PhysPlan:
    schema: PlanSchema = field(default_factory=PlanSchema)
    children: list = field(default_factory=list)

    est_rows = None   # CBO row estimate, set by the planner when stats exist
    cacheable = True  # False when plan-time folds are volatile (NOW(), ...)

    def explain(self, depth: int = 0) -> str:
        name = type(self).__name__.replace("Phys", "")
        line = "  " * depth + name + self._explain_info()
        if self.est_rows is not None:
            line += f" est_rows:{self.est_rows:.0f}"
        return "\n".join([line] + [c.explain(depth + 1)
                                   for c in self.children])

    def explain_nodes(self, depth: int = 0):
        """(depth, node) pairs in tree order — the per-node form of
        explain(), so EXPLAIN ANALYZE can pair each rendered line with
        the node's runtime stats. Sub-plans hanging off dedicated
        attributes (Apply's inner, DML readers/sources) are included."""
        yield depth, self
        for c in self.children:
            yield from c.explain_nodes(depth + 1)
        for attr in ("inner", "reader", "source"):
            sub = getattr(self, attr, None)
            if isinstance(sub, PhysPlan):
                yield from sub.explain_nodes(depth + 1)

    def explain_line(self) -> str:
        """One node's operator name + info (no children; PhysApply's
        _explain_info embeds the inner tree inline — strip it)."""
        name = type(self).__name__.replace("Phys", "")
        return name + self._explain_info().split("\n", 1)[0]

    def _explain_info(self) -> str:
        return ""


@dataclass
class PhysTableReader(PhysPlan):
    cop: CopPlan = None
    keep_order: bool = False   # handle-ordered delivery (merge join feeds)

    def _explain_info(self):
        parts = [f" table:{self.cop.table.name}"]
        if self.keep_order:
            parts.append(" keep_order")
        if self.cop.filter is not None:
            parts.append(f" pushed_filter:{self.cop.filter!r}")
        if self.cop.host_filter is not None:
            parts.append(f" host_filter:{self.cop.host_filter!r}")
        if self.cop.is_agg:
            parts.append(f" partial_agg:{self.cop.aggs!r}")
        if self.cop.limit is not None:
            parts.append(f" limit:{self.cop.limit}")
        return ",".join(parts)


@dataclass
class PhysIndexReader(PhysPlan):
    """Covering-index scan: the cop subplan scans index keys only and its
    decoded columns satisfy the whole reader schema (ref:
    executor/distsql.go:412 IndexReaderExecutor)."""

    cop: CopPlan = None

    def _explain_info(self):
        return (f" table:{self.cop.table.name} index:{self.cop.index.name}"
                f" ranges:{len(self.cop.ranges or [])}")


@dataclass
class PhysIndexLookUp(PhysPlan):
    """Index scan -> handles -> batched row fetch (ref:
    executor/distsql.go:524 IndexLookUpExecutor). `index_cop` scans and
    decodes index entries (index cols + handle); residual filters over the
    fetched full rows live in `table_cop` (ranges unused there)."""

    index_cop: CopPlan = None
    table_cop: CopPlan = None
    keep_order: bool = False

    def _explain_info(self):
        parts = [f" table:{self.table_cop.table.name}"
                 f" index:{self.index_cop.index.name}"
                 f" ranges:{len(self.index_cop.ranges or [])}"]
        if self.table_cop.filter is not None:
            parts.append(f" filter:{self.table_cop.filter!r}")
        if self.table_cop.host_filter is not None:
            parts.append(f" host_filter:{self.table_cop.host_filter!r}")
        return ",".join(parts)


@dataclass
class PhysPointGet(PhysPlan):
    """Single-row fetch by handle or unique index point (ref: the point-get
    fast path, executor/adapter.go:381). Bypasses the coprocessor."""

    table: TableInfo = None
    cols: list = field(default_factory=list)   # ColumnInfo to emit
    handle_col: Optional[int] = None
    handle: Optional[int] = None               # pk-is-handle point
    index: Optional[IndexInfo] = None          # or unique-index point
    index_values: Optional[list] = None
    filter: Optional[Expression] = None        # residual conjuncts

    def _explain_info(self):
        via = f"handle:{self.handle}" if self.index is None else \
            f"index:{self.index.name}"
        return f" table:{self.table.name} {via}"


@dataclass
class PhysSelection(PhysPlan):
    cond: Expression = None

    def _explain_info(self):
        return f" cond:{self.cond!r}"


@dataclass
class PhysProjection(PhysPlan):
    exprs: list = field(default_factory=list)

    def _explain_info(self):
        return f" exprs:{self.exprs!r}"


@dataclass
class PhysHashAgg(PhysPlan):
    """Root-side complete aggregation (input = raw rows)."""

    group_exprs: list = field(default_factory=list)
    aggs: list = field(default_factory=list)

    def _explain_info(self):
        return f" group:{self.group_exprs!r} aggs:{self.aggs!r}"


@dataclass
class PhysFinalAgg(PhysPlan):
    """Root-side merge of storage-side partial agg results."""

    aggs: list = field(default_factory=list)
    num_group_cols: int = 0

    def _explain_info(self):
        return f" aggs:{self.aggs!r}"


@dataclass
class PhysStreamAgg(PhysPlan):
    """Sort-based aggregation: sort child rows by the group keys, then
    segment-reduce on device (ref: executor/aggregate.go:150-170
    StreamAggExec over sorted input). Chosen by the cost pass when the
    estimated group cardinality would blow the hash kernel's device
    table, or when the child already delivers key-contiguous rows
    (sorted_input=True skips the sort)."""

    group_exprs: list = field(default_factory=list)
    aggs: list = field(default_factory=list)
    sorted_input: bool = False

    def _explain_info(self):
        s = " sorted" if self.sorted_input else ""
        return f"{s} group:{self.group_exprs!r} aggs:{self.aggs!r}"


@dataclass
class PhysHashJoin(PhysPlan):
    left_keys: list = field(default_factory=list)
    right_keys: list = field(default_factory=list)
    # inner/left/right, plus semi/anti (decorrelated EXISTS/IN: emit
    # probe rows by match existence, never the joined width)
    join_type: str = "inner"
    other_cond: Optional[Expression] = None

    def _explain_info(self):
        return (f" type:{self.join_type} lkeys:{self.left_keys!r} "
                f"rkeys:{self.right_keys!r}")


@dataclass
class PhysMergeJoin(PhysPlan):
    """Sorted-merge equi-join (ref: executor/merge_join.go:34). Both
    children deliver rows sorted ascending by their single join key (the
    planner guarantees it: pk-handle table scans are key-ordered, and
    index readers with keep_order deliver index order); the executor
    streams both sides with a bounded window — no full build-side
    materialization."""

    left_keys: list = field(default_factory=list)   # single-expr today
    right_keys: list = field(default_factory=list)
    join_type: str = "inner"       # inner/left
    other_cond: Optional[Expression] = None

    def _explain_info(self):
        return (f" type:{self.join_type} lkeys:{self.left_keys!r} "
                f"rkeys:{self.right_keys!r}")


@dataclass
class PhysIndexJoin(PhysPlan):
    """Index nested-loop join (ref: executor/index_lookup_join.go:87
    IndexLookUpJoin): children = [outer, inner_reader]. The outer side
    streams; for each outer batch the executor collects distinct join-key
    values and fetches only the matching inner rows through the inner
    table's index (or pk handle) — never scanning the inner table. The
    inner reader's cop carries the inner scan schema + residual filters;
    its ranges are synthesized per batch."""

    left_keys: list = field(default_factory=list)   # exprs over outer schema
    right_keys: list = field(default_factory=list)  # ColumnRefs, inner schema
    inner_index: Optional[IndexInfo] = None     # None = pk-handle lookup
    join_type: str = "inner"                    # inner/left
    other_cond: Optional[Expression] = None     # over joined schema

    def _explain_info(self):
        via = self.inner_index.name if self.inner_index else "handle"
        return (f" type:{self.join_type} "
                f"inner:{self.children[1].cop.table.name} "
                f"via:{via} okeys:{self.left_keys!r}")


@dataclass
class PhysApply(PhysPlan):
    """Correlated-subquery apply: for each outer row, bind the correlated
    cells and run the inner plan; the predicate decides whether the row
    survives (ref: executor/join.go:447 NestedLoopApplyExec). With no
    correlated cells the inner runs once and the predicate vectorizes
    (the reference's uncorrelated EvalSubquery rewrite)."""

    inner: "PhysPlan" = None
    mode: str = "exists"           # exists | in | cmp | scalar
    negated: bool = False
    left: Optional[Expression] = None      # IN target / cmp left side
    cmp_op: Optional[object] = None        # expression Op for cmp mode
    quant: str = ""                # cmp mode: "" | "any" | "all"
    corr: list = field(default_factory=list)   # [(outer_idx, CorrelatedCol)]

    def _explain_info(self):
        neg = "not " if self.negated else ""
        corr = "correlated" if self.corr else "uncorrelated"
        info = f" {neg}{self.mode} ({corr})"
        return info + "\n" + self.inner.explain(2)


@dataclass
class PhysSort(PhysPlan):
    by: list = field(default_factory=list)     # [(Expression, desc)]

    def _explain_info(self):
        return f" by:{[(repr(e), d) for e, d in self.by]}"


@dataclass
class PhysTopN(PhysPlan):
    by: list = field(default_factory=list)
    count: int = 0
    offset: int = 0

    def _explain_info(self):
        return f" by:{[(repr(e), d) for e, d in self.by]} n:{self.count}"


@dataclass
class PhysLimit(PhysPlan):
    count: int = 0
    offset: int = 0

    def _explain_info(self):
        return f" n:{self.count} offset:{self.offset}"


@dataclass
class PhysValues(PhysPlan):
    """Constant rows (SELECT without FROM / INSERT VALUES source)."""

    rows: list = field(default_factory=list)   # [[Expression]]


@dataclass
class PhysUnion(PhysPlan):
    """UNION ALL of the children's chunk streams (column types unified to
    the schema's; DISTINCT is a HashAgg grouped on every column layered
    on top by the planner — ref: executor/union handling via builder.go
    UnionExec)."""

    def _explain_info(self):
        return f" branches:{len(self.children)}"


@dataclass
class PhysInsert(PhysPlan):
    table: TableInfo = None
    columns: list = field(default_factory=list)     # column names, in order
    source: PhysPlan = None                         # PhysValues or select
    on_duplicate: list = field(default_factory=list)  # [(col_name, Expression)]
    is_replace: bool = False
    ignore: bool = False


@dataclass
class PhysUpdate(PhysPlan):
    table: TableInfo = None
    reader: PhysPlan = None        # scan emitting full row + handle
    assignments: list = field(default_factory=list)  # [(col_name, Expression)]


@dataclass
class PhysDelete(PhysPlan):
    table: TableInfo = None
    reader: PhysPlan = None


@dataclass
class PhysMultiUpdate(PhysPlan):
    """UPDATE t1, t2 SET ... (ref: executor/write.go:479). Per target:
    (TableInfo, col_start, handle_idx, [(col_name, Expression)])."""

    targets: list = field(default_factory=list)
    reader: PhysPlan = None


@dataclass
class PhysMultiDelete(PhysPlan):
    """DELETE t1, t2 FROM <join> (ref: executor/write.go:194
    deleteMultiTables). Per target: (TableInfo, col_start, handle_idx)
    locating its column block + handle inside the join output."""

    targets: list = field(default_factory=list)
    reader: PhysPlan = None
