"""Fleet member identity + the ephemeral membership registry.

Every server process of a fleet — the N stateless SQL servers AND the
store plane itself — mints one stable identity at startup: the host and
status port it serves on plus a random 32-bit start nonce. The nonce
does double duty:

  * it makes the member id unique across restarts (a member that
    SIGKILLs and comes back on the same ports is a NEW member — its
    caches are cold, its meters are zero, and joining its old rows to
    its new ones would be wrong), and
  * it is folded into every trace id this process mints
    (trace.ensure_id), so trace ids are fleet-unique and a store-plane
    ring record's `origin_trace_id` joins unambiguously back to the SQL
    member that issued the statement.

Membership is advertised through the store plane the same way the
schema-sync heartbeats are (session Domain.publish_schema_version):
a lease-stamped JSON record under an EPHEMERAL key prefix
(mockstore/mvcc.py EPHEMERAL_PREFIXES — heartbeats never bump
data_version, so a 1/s membership beat cannot re-cold the fleet's
chunk/HBM caches), republished every `tidb_tpu_member_heartbeat_ms` by
a supervised worker and expiring `tidb_tpu_member_ttl_ms` after the
last beat. Any member enumerates live peers with one snapshot range
scan (`live_members`); a SIGKILLed member simply stops beating and
ages out within one TTL — there is no deregistration path to miss.

Ref: the reference's infosync.InfoSyncer (domain/infosync/info.go) —
every tidb-server publishes a TTL'd ServerInfo record to etcd and the
CLUSTER_INFO/CLUSTER_PROCESSLIST memtables enumerate it."""

from __future__ import annotations

import json
import logging
import os
import threading
import time

from tidb_tpu import codec, kv

__all__ = ["MEMBER_PREFIX", "nonce", "set_identity", "identity",
           "member_id", "start_unix", "publish_once", "live_members",
           "local_state", "start_heartbeat", "stop_heartbeat",
           "reset_for_tests"]

log = logging.getLogger("tidb_tpu.member")

# ephemeral membership namespace (declared in EPHEMERAL_PREFIXES):
# key = MEMBER_PREFIX + member_id, value = the JSON identity record
# with an `expiry` wall-clock stamp
MEMBER_PREFIX = b"m_member_"

_mu = threading.Lock()
_nonce: int | None = None           # guarded-by: _mu
_identity: dict | None = None       # guarded-by: _mu
_start_unix = time.time()
_hb_stop: threading.Event | None = None   # guarded-by: _mu


def nonce() -> int:
    """This process's 32-bit start nonce (minted once, first use).
    Folded into trace ids by trace.ensure_id — two members minting
    trace ids concurrently never collide, and a restarted member never
    reuses its dead predecessor's id space."""
    global _nonce
    with _mu:
        if _nonce is None:
            _nonce = int.from_bytes(os.urandom(4), "big") or 1
        return _nonce


def set_identity(host: str, status_port: int, role: str) -> str:
    """Record this process's fleet identity (called once at server
    startup, before the heartbeat starts). role is "sql" or "store".
    -> the member id."""
    global _identity
    ident = {
        "id": f"{host}:{status_port}:{nonce():08x}",
        "host": host,
        "status_port": int(status_port),
        "role": role,
        "nonce": nonce(),
        "start_unix": _start_unix,
    }
    with _mu:
        _identity = ident
    return ident["id"]


def identity() -> dict:
    """The recorded identity — or a local-process placeholder when no
    server ever registered one (in-process sessions, unit tests): the
    cluster surfaces still render, scoped to this process."""
    with _mu:
        if _identity is not None:
            return dict(_identity)
    return {"id": f"local:0:{nonce():08x}", "host": "local",
            "status_port": 0, "role": "local", "nonce": nonce(),
            "start_unix": _start_unix}


def member_id() -> str:
    return identity()["id"]


def start_unix() -> float:
    return _start_unix


def publish_once(storage) -> None:
    """One membership beat: write this member's lease-stamped record
    under its ephemeral key (same txn path as the schema-sync
    heartbeat — Domain.publish_schema_version). A failed beat logs and
    returns: the record expires within one TTL, so peers treat a
    member that cannot reach the store plane as dead, which it
    operationally is."""
    from tidb_tpu import config
    ident = identity()
    ident["expiry"] = int(time.time() * 1000) + config.member_ttl_ms()
    key = MEMBER_PREFIX + ident["id"].encode()
    txn = storage.begin()
    try:
        txn.set(key, json.dumps(ident).encode())
        txn.commit()
    except kv.KVError as e:
        log.warning("membership heartbeat failed: %s", e)
        if getattr(txn, "valid", False):
            txn.rollback()


def live_members(storage) -> list[dict]:
    """Unexpired membership records, sorted by member id — the fan-out
    list for the cluster_* tables and the /fleet/* endpoints. One
    snapshot range scan over the ephemeral prefix."""
    now = int(time.time() * 1000)
    out: list[dict] = []
    snap = storage.snapshot(storage.current_ts())
    end = codec.prefix_next(MEMBER_PREFIX)
    for _k, v in snap.iter_range(MEMBER_PREFIX, end):
        try:
            rec = json.loads(v)
            if int(rec["expiry"]) > now:
                out.append(rec)
        except (ValueError, KeyError, TypeError):
            continue
    out.sort(key=lambda r: r.get("id", ""))
    return out


def local_state() -> dict:
    """This member's cluster-state document — the payload GET
    /cluster/state serves and the cluster_* memtables consume, one
    fetch per member: identity, live sessions, per-tenant resource
    meters, and retained trace summaries (origin-stamped, so a
    store-plane member's records join back to SQL statements). Also
    the degraded local-only document when no registry exists
    (in-process sessions, unit tests)."""
    from tidb_tpu import meter, profiler, trace
    from tidb_tpu.session import processlist_snapshot
    return {
        "member": identity(),
        "processlist": processlist_snapshot(),
        "resource_usage": {
            "server": meter.server_snapshot(),
            "users": meter.users_snapshot(),
            "sessions": meter.sessions_snapshot(),
        },
        "traces": trace.ring_snapshot(),
        "kernel_profile": profiler.snapshot(),
    }


def start_heartbeat(storage) -> None:
    """Start the supervised membership heartbeat (idempotent). The
    worker republishes every `tidb_tpu_member_heartbeat_ms`; a crashing
    beat is counted in tidb_tpu_worker_restarts_total and backed off
    by the supervisor, never silently swallowed."""
    global _hb_stop
    from tidb_tpu import config
    from tidb_tpu.util import supervisor
    with _mu:
        if _hb_stop is not None:
            return
        _hb_stop = threading.Event()
        stop = _hb_stop
    publish_once(storage)       # registered before the first tick
    supervisor.supervise("member-heartbeat",
                         lambda: publish_once(storage), stop,
                         config.member_heartbeat_ms() / 1000.0)


def stop_heartbeat() -> None:
    global _hb_stop
    with _mu:
        stop = _hb_stop
        _hb_stop = None
    if stop is not None:
        stop.set()


def reset_for_tests() -> None:
    """Drop the recorded identity and heartbeat (test isolation). The
    nonce stays — trace ids minted earlier in the process must not
    collide with ones minted after."""
    global _identity
    stop_heartbeat()
    with _mu:
        _identity = None
