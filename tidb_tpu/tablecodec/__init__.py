"""Row/index <-> ordered-KV key layout.

Reference: /root/reference/tablecodec/tablecodec.go:37-65 —
    row:    t{tableID}_r{handle}            (tableID, handle: comparable int64)
    index:  t{tableID}_i{indexID}{values}   (values: memcomparable datums)
Row value is a colID->datum pair sequence; non-unique index values append the
handle to the key so entries stay unique, unique index values carry the
handle in the value.
"""

from __future__ import annotations

from tidb_tpu import codec

__all__ = [
    "TABLE_PREFIX", "RECORD_SEP", "INDEX_SEP",
    "record_key", "record_prefix", "decode_record_key",
    "index_key", "index_prefix", "decode_index_key",
    "encode_row", "decode_row", "table_prefix_range",
]

TABLE_PREFIX = b"t"
RECORD_SEP = b"_r"
INDEX_SEP = b"_i"


def record_prefix(table_id: int) -> bytes:
    return TABLE_PREFIX + codec.encode_int(table_id) + RECORD_SEP


def record_key(table_id: int, handle: int) -> bytes:
    return record_prefix(table_id) + codec.encode_int(handle)


def decode_record_key(key: bytes) -> tuple[int, int]:
    """-> (table_id, handle). Raises ValueError on non-record/short keys."""
    if not key.startswith(TABLE_PREFIX) or len(key) < 19:
        raise ValueError("not a record key")
    tid, off = codec.decode_int(key, 1)
    if key[off:off + 2] != RECORD_SEP:
        raise ValueError("not a record key")
    handle, _ = codec.decode_int(key, off + 2)
    return tid, handle


def index_prefix(table_id: int, index_id: int) -> bytes:
    return TABLE_PREFIX + codec.encode_int(table_id) + INDEX_SEP + \
        codec.encode_int(index_id)


def index_key(table_id: int, index_id: int, values, handle: int | None = None) -> bytes:
    """Non-unique indexes pass `handle` to keep entries distinct."""
    k = index_prefix(table_id, index_id) + codec.encode_key(values)
    if handle is not None:
        k += codec.encode_datum(handle)
    return k


def decode_index_key(key: bytes) -> tuple[int, int, bytes]:
    """-> (table_id, index_id, encoded_values_suffix)."""
    if not key.startswith(TABLE_PREFIX) or len(key) < 19:
        raise ValueError("not an index key")
    tid, off = codec.decode_int(key, 1)
    if key[off:off + 2] != INDEX_SEP:
        raise ValueError("not an index key")
    iid, off = codec.decode_int(key, off + 2)
    return tid, iid, key[off:]


def table_prefix_range(table_id: int) -> tuple[bytes, bytes]:
    """[start, end) covering every key of a table (prefix-successor end,
    safe at table_id = int64 max)."""
    p = TABLE_PREFIX + codec.encode_int(table_id)
    return p, codec.prefix_next(p)


def encode_row(col_ids, values) -> bytes:
    """Row value: flat [colID, value, colID, value, ...] datum sequence.
    Ref: tablecodec.go EncodeRow (datum-pairs codec)."""
    flat = []
    for cid, v in zip(col_ids, values, strict=True):
        flat.append(cid)
        flat.append(v)
    return codec.encode_key(flat)


def decode_row(value: bytes) -> dict:
    """-> {col_id: python value}."""
    flat = codec.decode_key(value)
    if len(flat) % 2 != 0:
        raise ValueError("malformed row value")
    return {flat[i]: flat[i + 1] for i in range(0, len(flat), 2)}
