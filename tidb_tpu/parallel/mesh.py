"""Mesh construction helpers."""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

__all__ = ["build_mesh", "default_axes"]


def default_axes(n_devices: int) -> tuple[int, int]:
    """Factor n_devices into (dp, tp). tp gets the smallest prime factor >1
    so both mesh axes are exercised whenever possible."""
    if n_devices <= 1:
        return (1, 1)
    for p in (2, 3, 5, 7):
        if n_devices % p == 0:
            return (n_devices // p, p)
    return (n_devices, 1)


def build_mesh(n_devices: int | None = None,
               devices=None) -> Mesh:
    """A 2-D ('dp', 'tp') mesh over the first n_devices jax devices."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    dp, tp = default_axes(len(devices))
    arr = np.array(devices[: dp * tp]).reshape(dp, tp)
    return Mesh(arr, axis_names=("dp", "tp"))
