"""Compatibility shim: mesh construction lives in tidb_tpu/devplane.py.
The plane is 1-D ``("batch",)`` — the old ('dp','tp') factoring is gone."""

from __future__ import annotations

from tidb_tpu.devplane import build_mesh

__all__ = ["build_mesh"]
