"""Compatibility shim: process mesh configuration lives in
tidb_tpu/devplane.py (one device plane). State is shared — these ARE the
devplane functions, so a mesh enabled through either path is visible to
both."""

from __future__ import annotations

from tidb_tpu.devplane import (active_mesh, configure_mesh, disable_mesh,
                               enable_mesh, mesh_generation,
                               on_topology_change)

__all__ = ["configure_mesh", "enable_mesh", "disable_mesh", "active_mesh",
           "mesh_generation", "on_topology_change"]
