"""Process-level mesh configuration.

The reference wires its distributed execution through per-session
concurrency knobs + the store's region topology (store/tikv/coprocessor.go
fan-out); chip topology is the TPU analogue and is a process property:
one device mesh serves every session in the process. The planner consults
``active_mesh()`` when deciding to route qualifying plans to the mesh
executors, and bumps ``mesh_generation()`` into the plan-cache key so
cached plans never outlive a topology change.
"""

from __future__ import annotations

from tidb_tpu.parallel.mesh import build_mesh

__all__ = ["configure_mesh", "enable_mesh", "disable_mesh", "active_mesh",
           "mesh_generation", "on_topology_change"]

_mesh = None
_generation = 0
_listeners: list = []


def on_topology_change(fn) -> None:
    """Register fn() to run after every mesh (re)configuration — kernel
    caches keyed on the generation use this to release compiled programs
    that can never be hit again (e.g. after disable_mesh)."""
    _listeners.append(fn)


def configure_mesh(mesh) -> None:
    """Install `mesh` (a jax.sharding.Mesh or None) as the process mesh."""
    global _mesh, _generation
    _mesh = mesh
    _generation += 1
    for fn in _listeners:
        fn()


def enable_mesh(n_devices: int | None = None) -> None:
    """Build a ('dp','tp') mesh over the first n jax devices and install it."""
    configure_mesh(build_mesh(n_devices))


def disable_mesh() -> None:
    configure_mesh(None)


def active_mesh():
    return _mesh


def mesh_generation() -> int:
    return _generation
