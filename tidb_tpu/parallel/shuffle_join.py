"""Compatibility shim: the shuffle hash join lives in
tidb_tpu/ops/meshshuffle.py on the unified ``("batch",)`` device plane."""

from __future__ import annotations

from tidb_tpu.ops.meshshuffle import (MeshShuffleJoinKernel,
                                      ShuffleOverflowError)

__all__ = ["MeshShuffleJoinKernel", "ShuffleOverflowError"]
