"""Mesh-distributed star-join + aggregation pipeline.

The reference executes Q3/Q5-shaped plans as a chain of HashJoinExecs
(executor/join.go:37: build a hash table per join, probe row-at-a-time in
goroutines) feeding a HashAggExec. On a TPU mesh the idiomatic program is
one fused XLA computation per probe shard:

    probe rows sharded over ('dp','tp')   [the fact table: lineitem]
    build tables replicated on every chip [the dimension tables]
    filter -> lookup chain -> group-by aggregate -> all_gather merge

Each lookup is an O(log n) searchsorted against the dimension table's
sorted key hashes plus an exact-bits verify — the join never materializes:
matched rows flow straight into the aggregation, so HBM traffic is one
pass over the probe shard. Build keys must be unique (dimension tables:
customer, orders, nation, ...); the executor layer falls back to the
host hash join otherwise. This is the "pmap-partitioned build/probe with
psum/all_gather merge" shape of BASELINE.json configs 3-4.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P
from jax.sharding import Mesh

from tidb_tpu.chunk import Chunk, Column
from tidb_tpu.expression import AggDesc, AggFunc, Expression
from tidb_tpu.ops import runtime
from tidb_tpu.ops.hashagg import (_hash_keys, _key_bits,
                                  _validate_device_exprs,
                                  finalize_group_result)
from tidb_tpu.parallel.dist_agg import MeshKernelBase, group_merge_program

__all__ = ["LookupSpec", "MeshLookupAggKernel", "BuildError",
           "host_lookup_agg"]

_KEY_SEED = 0x9E6D55A3C1B70F27


class BuildError(Exception):
    """Build side unusable for the lookup kernel (dup/NULL keys, strings
    in key columns, hash collision) — caller falls back to the host join."""


@dataclass
class LookupSpec:
    """One dimension-table lookup in the chain.

    key_exprs index the CURRENT virtual schema (probe columns, then the
    payloads of earlier lookups, in order). build_key_offsets/payload
    offsets index build_chunk's columns; payload columns are appended to
    the virtual schema for later key_exprs / group_exprs / aggs."""

    key_exprs: list
    build_chunk: Chunk
    build_key_offsets: list[int]
    payload_offsets: list[int] = field(default_factory=list)


class _BuildTable:
    """Host-prepared replicated lookup table: sorted key hashes, exact key
    bit lanes, payload lanes (strings dict-encoded for the device; original
    values kept for host finalize)."""

    def __init__(self, spec: LookupSpec):
        ch = spec.build_chunk
        keys = [ch.columns[o] for o in spec.build_key_offsets]
        n = ch.num_rows
        valid = np.ones(n, dtype=bool)
        for k in keys:
            valid &= np.asarray(k.valid)
        if not valid.all():
            # NULL join keys never match anything: drop them here
            ch = ch.filter(valid)
            keys = [ch.columns[o] for o in spec.build_key_offsets]
            n = ch.num_rows
        key_lanes = []
        for k in keys:
            if k.data.dtype == np.dtype(object):
                raise BuildError("string build keys need the host join")
            key_lanes.append((np.asarray(k.data),
                              np.ones(n, dtype=bool)))
        h = _hash_keys(np, key_lanes, n, seed=_KEY_SEED)
        order = np.argsort(h, kind="stable")
        hs = h[order]
        if n > 1 and (hs[1:] == hs[:-1]).any():
            # duplicate hash: either duplicate keys (not a dimension
            # table) or a 2^-64 collision — both go to the host join
            raise BuildError("duplicate build keys / hash collision")
        self.chunk = ch                         # NULL-free build rows
        self.n = n
        self.h_sorted = hs
        self.key_bits = [np.asarray(_key_bits(np, d))[order]
                         for d, _v in key_lanes]
        self.pay_data = []
        self.pay_valid = []
        for o in spec.payload_offsets:
            c = ch.columns[o]
            d = np.asarray(c.data)
            if d.dtype == np.dtype(object):
                codes = np.empty(n, dtype=np.int64)
                seen: dict = {}
                for i, v in enumerate(d):
                    codes[i] = seen.setdefault(v, len(seen))
                d = codes
            self.pay_data.append(d[order])
            self.pay_valid.append(np.asarray(c.valid)[order])
        self._key_lanes = key_lanes
        self._row_by_key = None
        self._dev = None

    @property
    def row_by_key(self) -> dict:
        """Host-side exact map for finalize / reference impl, keyed in the
        chunk-layer value domain (raw int64/float64; decimals scaled) to
        match host expression eval output. Built lazily — the device path
        only touches it for a handful of representative rows, and a large
        dimension table (orders at SF>=1) costs seconds to enumerate."""
        if self._row_by_key is None:
            m = {}
            for i in range(self.n):
                m[tuple(d[i].item() for d, _v in self._key_lanes)] = i
            self._row_by_key = m
        return self._row_by_key

    def device_arrays(self, sharding=None):
        """Build lanes on device (replicated under `sharding`), memoized:
        one batched device_put on first use, zero transfer when a cached
        kernel re-executes against unchanged dimension data. Keyed by the
        mesh GENERATION (id(mesh) could be recycled after a reconfigure)."""
        from tidb_tpu.parallel import config as mesh_config
        key = mesh_config.mesh_generation() if sharding is not None else None
        if self._dev is None or self._dev[0] != key:
            tree = (self.h_sorted, tuple(self.key_bits),
                    tuple(self.pay_data), tuple(self.pay_valid))
            self._dev = (key, jax.device_put(tree, sharding))
        return self._dev[1]


class MeshLookupAggKernel(MeshKernelBase):
    """filter -> unique-key lookup chain -> group-by agg over a mesh."""

    def __init__(self, mesh: Mesh, filter_expr: Expression | None,
                 lookups: Sequence[LookupSpec],
                 group_exprs: Sequence[Expression],
                 aggs: Sequence[AggDesc], capacity: int = 4096,
                 builds: list | None = None):
        self.mesh = mesh
        self.filter_expr = filter_expr
        self.lookups = list(lookups)
        self.group_exprs = list(group_exprs)
        self.aggs = list(aggs)
        _validate_device_exprs(filter_expr, self.group_exprs, self.aggs)
        for lk in self.lookups:
            _validate_device_exprs(None, lk.key_exprs, [])
        self.builds = builds if builds is not None \
            else [_BuildTable(lk) for lk in self.lookups]
        self._setup_mesh(mesh, capacity, n_extra_args=1)

    # -- traced program ------------------------------------------------------

    def _kernel(self, cols, nrows, builds):
        ln = cols[0][0].shape[0]
        xp = jnp
        di = lax.axis_index("dp")
        ti = lax.axis_index("tp")
        offs = (di * self.tp + ti).astype(jnp.int64) * ln
        alive = (offs + xp.arange(ln)) < nrows
        mask = runtime.filter_mask_xp(xp, self.filter_expr, cols, ln) & alive

        virt = list(cols)
        for lk, b in zip(self.lookups, builds):
            h_sorted, key_bits, pay_data, pay_valid = b
            key_cols = [e.eval_xp(xp, virt, ln) for e in lk.key_exprs]
            ph = _hash_keys(xp, key_cols, ln, seed=_KEY_SEED)
            nb = h_sorted.shape[0]
            pos = xp.searchsorted(h_sorted, ph)
            cand = xp.clip(pos, 0, max(nb - 1, 0))
            hit = mask
            for d, v in key_cols:
                hit = hit & v               # NULL keys match nothing
            if nb == 0:
                hit = hit & False
            else:
                hit = hit & (pos < nb) & (h_sorted[cand] == ph)
                # exact verify: hash equality is not key equality
                for (d, _v), bb in zip(key_cols, key_bits):
                    hit = hit & (_key_bits(xp, d) == bb[cand])
            mask = hit                      # inner join semantics
            safe = xp.where(hit, cand, 0)
            for d, v in zip(pay_data, pay_valid):
                virt.append((d[safe], v[safe] & hit))

        return group_merge_program(xp, virt, mask, ln, offs, ti,
                                   self.group_exprs, self.aggs, self._C,
                                   self.ndev, self.tp)

    # -- host driver ---------------------------------------------------------

    def launch(self, probe: Chunk, bucket: bool = False):
        """Asynchronous half: host→HBM transfer + kernel dispatch (see
        MeshAggKernel.launch). Build tables are device-memoized by
        _BuildTable.device_arrays, so per-batch launches re-send nothing."""
        cols, _ln = self._shard_probe(probe, bucket=bucket)
        rep_sh = NamedSharding(self.mesh, P())
        builds = tuple(b.device_arrays(rep_sh) for b in self.builds)
        return self._jit(cols, jnp.int64(probe.num_rows), builds)

    def finish(self, outs, probe: Chunk):
        gidx, rep_rows, lanes_at, counts = self._postprocess(outs)
        return self._finalize(probe, gidx, rep_rows, lanes_at, counts)

    def __call__(self, probe: Chunk):
        return self.finish(self.launch(probe), probe)

    def _finalize(self, probe: Chunk, gidx, rep_rows, lanes_at, counts):
        """Re-run the lookup chain on the handful of representative rows
        (and FIRST_ROW rows) host-side so group keys / first values come
        back as exact original values, strings included."""
        needed = set(int(r) for r in rep_rows)
        for a, ls in zip(self.aggs, lanes_at):
            if a.fn == AggFunc.FIRST_ROW:
                for i, has in zip(ls[0], ls[1]):
                    if has > 0:
                        needed.add(int(i))
        order = sorted(needed)
        pos = {g: i for i, g in enumerate(order)}
        mini = self._host_chain(probe.take(np.array(order, dtype=np.int64)))
        rep_local = np.array([pos[int(r)] for r in rep_rows],
                             dtype=np.int64)
        fixed_lanes = []
        for a, ls in zip(self.aggs, lanes_at):
            if a.fn == AggFunc.FIRST_ROW:
                idx = np.array([pos.get(int(i), 0) for i in ls[0]],
                               dtype=np.int64)
                fixed_lanes.append([idx, ls[1]])
            else:
                fixed_lanes.append(ls)
        return finalize_group_result(mini, self.group_exprs, self.aggs,
                                     gidx, rep_local, fixed_lanes, counts)

    def _host_chain(self, mini: Chunk) -> Chunk:
        """Append payload columns for the (matched) mini rows on the host,
        with original (undecoded) build values."""
        out_cols = list(mini.columns)
        for lk, b in zip(self.lookups, self.builds):
            virt = Chunk(out_cols)
            n = virt.num_rows
            keyvals = []
            for e in lk.key_exprs:
                d, v = e.eval(virt)
                keyvals.append([None if not v[i] else
                                (d[i].item() if hasattr(d[i], "item")
                                 else d[i]) for i in range(n)])
            rows = []
            for i in range(n):
                rows.append(b.row_by_key.get(
                    tuple(kv[i] for kv in keyvals)))
            for o in lk.payload_offsets:
                src = b.chunk.columns[o]
                vals = [None if r is None else src.get(r) for r in rows]
                out_cols.append(Column.from_values(src.ft, vals))
        return Chunk(out_cols)


def host_lookup_agg(probe: Chunk, filter_expr, lookups: Sequence[LookupSpec],
                    group_exprs, aggs, builds=None):
    """Pure-host reference implementation (ground truth for tests, the
    dryrun cross-check, and the per-batch fallback of the streaming mesh
    path — which passes its prebuilt `builds` so dimension hash tables
    are not rebuilt per batch)."""
    from tidb_tpu.ops.hostagg import host_hash_agg
    mask = runtime.eval_filter_host(filter_expr, probe)
    ch = probe.filter(mask)
    if builds is None:
        builds = [_BuildTable(lk) for lk in lookups]
    cols = list(ch.columns)
    for lk, b in zip(lookups, builds):
        virt = Chunk(cols)
        n = virt.num_rows
        keyvals = []
        for e in lk.key_exprs:
            d, v = e.eval(virt)
            keyvals.append([None if not v[i] else
                            (d[i].item() if hasattr(d[i], "item") else d[i])
                            for i in range(n)])
        rows = np.empty(n, dtype=object)
        keep = np.zeros(n, dtype=bool)
        for i in range(n):
            r = b.row_by_key.get(tuple(kv[i] for kv in keyvals))
            rows[i] = r
            keep[i] = r is not None
        cols = [c.take(np.flatnonzero(keep)) for c in cols]
        matched = [int(r) for r in rows[keep]]
        for o in lk.payload_offsets:
            src = b.chunk.columns[o]
            cols.append(Column.from_values(
                src.ft, [src.get(r) for r in matched]))
    combined = Chunk(cols)
    return host_hash_agg(combined, None, group_exprs, aggs)
