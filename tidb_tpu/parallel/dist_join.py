"""Compatibility shim: the distributed star-join + aggregation pipeline
lives in tidb_tpu/ops/meshjoin.py on the unified ``("batch",)`` device
plane."""

from __future__ import annotations

from tidb_tpu.ops.meshjoin import (BuildError, LookupSpec,
                                   MeshLookupAggKernel, _BuildTable,
                                   host_lookup_agg)

__all__ = ["LookupSpec", "MeshLookupAggKernel", "BuildError",
           "host_lookup_agg"]
