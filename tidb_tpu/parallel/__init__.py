"""Compatibility shims over the one device plane.

Everything that used to live here — mesh construction, process mesh
configuration, the distributed agg/join/shuffle kernels — is now the
unified ``("batch",)`` device plane: tidb_tpu/devplane.py owns the mesh
and layout language, tidb_tpu/ops/meshagg.py / meshjoin.py /
meshshuffle.py own the kernels. These re-exports keep historical import
paths (tests, external callers) working; package code imports the real
modules directly (lint: no-parallel-import)."""

from tidb_tpu.devplane import (active_mesh, build_mesh, configure_mesh,
                               disable_mesh, enable_mesh, mesh_generation)
from tidb_tpu.ops.meshagg import MeshAggKernel

__all__ = ["build_mesh", "MeshAggKernel", "active_mesh", "configure_mesh",
           "disable_mesh", "enable_mesh", "mesh_generation"]
