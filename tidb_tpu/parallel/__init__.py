"""Multi-chip parallelism: device meshes + distributed operators.

The reference scales reads by splitting key ranges into regions and fanning
out goroutine workers (/root/reference/store/tikv/coprocessor.go:263,342).
On TPU the same two axes become mesh axes (SURVEY.md §2.7, §5.7-5.8):

* ``dp`` — data parallel over rows: each chip aggregates its shard of the
  scan, the moral equivalent of per-region coprocessor workers.
* ``tp`` — state parallel over the group-hash-table: the merged aggregate
  state is reduce-scattered so each chip owns a slice of the buckets, the
  analogue of sharding a hash join/agg build side across nodes.

All cross-chip traffic is XLA collectives (psum / pmin / pmax /
psum_scatter) riding ICI — never host RPC.
"""

from tidb_tpu.parallel.mesh import build_mesh, default_axes
from tidb_tpu.parallel.dist_agg import MeshAggKernel
from tidb_tpu.parallel.config import (active_mesh, configure_mesh,
                                      disable_mesh, enable_mesh,
                                      mesh_generation)

__all__ = ["build_mesh", "default_axes", "MeshAggKernel",
           "active_mesh", "configure_mesh", "disable_mesh", "enable_mesh",
           "mesh_generation"]
