"""Compatibility shim: the distributed group-by aggregation kernel lives
in tidb_tpu/ops/meshagg.py on the unified ``("batch",)`` device plane."""

from __future__ import annotations

from tidb_tpu.ops.meshagg import (MeshAggKernel, MeshKernelBase,
                                  group_merge_program)

__all__ = ["MeshAggKernel", "MeshKernelBase", "group_merge_program"]
