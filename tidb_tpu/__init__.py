"""tidb_tpu — a TPU-native distributed HTAP SQL framework.

A ground-up rebuild of the capabilities of TiDB (reference: /root/reference,
Go, ~192k LoC) designed TPU-first:

* Control plane (SQL -> plan -> schema -> txn protocol) is host Python/C++,
  structurally mirroring the reference's session/planner/kv layers.
* Data plane (scan/filter/project/join/aggregate/sort over columns) is
  JAX/XLA: jit kernels per operator, shard_map over a `jax.sharding.Mesh`
  for multi-chip group-by/join with psum/all_gather merges.
* Storage is a Percolator-style MVCC transactional KV store partitioned
  into regions, with an in-process mock cluster (the reference's mocktikv
  move) providing hermetic multi-"node" testing on one host.

Layer map (cf. SURVEY.md §1):

    session/    Session API: Execute, txn lifecycle          (ref: session.go)
    parser/     SQL -> AST                                   (ref: parser/, ast/)
    plan/       logical/physical planner, copTask model      (ref: plan/)
    executor/   volcano-over-chunks executors                (ref: executor/)
    expression/ expr trees, numpy + jax evaluation           (ref: expression/)
    ops/        TPU kernels: filter/agg/join/sort            (ref: executor/ hot ops)
    parallel/   device mesh, sharded kernels                 (new, TPU-native)
    kv/         engine-neutral txn KV contract               (ref: kv/)
    store/      distributed client: regions, 2PC, cop fanout (ref: store/tikv/)
    mockstore/  in-process MVCC cluster + coprocessor        (ref: store/tikv/mocktikv/)
    table/      row <-> KV mapping                           (ref: table/, tablecodec/)
    meta/       schema metadata on KV                        (ref: meta/, structure/)
    schema/     model + infoschema                           (ref: model/, infoschema/)
    codec/      memcomparable datum codec                    (ref: util/codec/)
    chunk/      Arrow-layout columnar batches                (ref: util/chunk/)
    sqltypes/   field types, eval types, decimal             (ref: types/)
"""

__version__ = "0.1.0"

# The device data plane is built on int64 lanes (scaled decimals, epoch-micros
# datetimes, memcomparable-ordered keys). JAX defaults to 32-bit; without x64
# the compute silently truncates — so the framework requires it globally.
import jax as _jax

_jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache: operator kernels are compiled per
# (program, shape-bucket) and identical HLO must never recompile — not
# across kernel instances, not across processes. Large-batch programs
# cost tens of seconds of XLA compile; this turns them into disk hits.
# util/compile_cache owns the wiring (directory from TIDB_TPU_COMPILE_CACHE
# or ~/.cache/tidb_tpu_xla; "0" disables) and counts hits/misses for
# bench.py / the server log.
from tidb_tpu.util import compile_cache as _compile_cache

_compile_cache.enable()

# Debug lock-order sanitizer (default off, zero overhead): with
# TIDB_TPU_LOCK_SANITIZER=1 the threading lock factories are patched
# here — before any runtime module constructs its locks — so every
# registered lock created from now on is order-checked against the
# statically-derived DAG (docs/CONCURRENCY.md, util/lockorder.py).
from tidb_tpu.util import lockorder as _lockorder

_lockorder.enable_from_env()
