"""tidb-tpu server process: `python -m tidb_tpu [flags]`.

Reference: /root/reference/tidb-server/main.go:127-152 — flag/config
merge, store open, bootstrap, MySQL wire server + HTTP status server,
signal-driven graceful close. Config precedence: built-in defaults <
TIDB_TPU_* environment < --config TOML file < explicit CLI flags.
"""

from __future__ import annotations

import argparse
import logging
import signal
import sys
import threading


def _apply_config_file(path: str) -> dict:
    """TOML config tree (ref: config/config.go:29). Returns the flat
    {sysvar_name: value} dict of the [variables] table plus top-level
    server keys."""
    import tomllib
    with open(path, "rb") as f:
        return tomllib.load(f)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tidb_tpu", description="TPU-native HTAP SQL server")
    # None defaults distinguish "flag given" from "use config/default":
    # precedence is defaults < env < config file < explicit flags
    p.add_argument("--host", default=None)
    p.add_argument("-P", "--port", type=int, default=None)
    p.add_argument("--status-port", type=int, default=None)
    p.add_argument("--no-status", action="store_true",
                   help="disable the HTTP status server")
    p.add_argument("--config", help="TOML config file")
    p.add_argument("--mesh", type=int, default=None, metavar="N",
                   help="enable an N-device mesh (default: all devices)")
    p.add_argument("--no-mesh", action="store_true")
    p.add_argument("--token-limit", type=int, default=1000,
                   help="max concurrent connections (ref: TokenLimit)")
    p.add_argument("--log-level", default="info")
    p.add_argument("--slow-threshold-ms", type=int, default=None)
    p.add_argument("--set", action="append", default=[], metavar="VAR=V",
                   help="set a tidb_tpu_* sysvar (repeatable)")
    p.add_argument("--store", default=None, metavar="HOST:PORT",
                   help="connect to a store-plane server (fleet mode: "
                        "this process is a stateless SQL server with "
                        "its own coherent caches) instead of hosting an "
                        "in-process store")
    return p


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "storeserve":
        # store-plane server: one MVCCStore + TSO + region map behind
        # the wire protocol, shared by N stateless SQL servers
        from tidb_tpu.store.remote import serve_main
        return serve_main(argv[1:])
    args = build_parser().parse_args(argv)
    logging.basicConfig(
        level=getattr(logging, args.log_level.upper(), logging.INFO),
        format="%(asctime)s %(levelname)s %(name)s %(message)s")
    log = logging.getLogger("tidb_tpu.server")

    from tidb_tpu import config
    if args.config:
        tree = _apply_config_file(args.config)
        for k, v in (tree.get("variables") or {}).items():
            config.set_var(k, v)
        # explicit CLI flags beat the file (main.go:257 overrideConfig)
        if args.host is None:
            args.host = tree.get("host")
        if args.port is None and "port" in tree:
            args.port = int(tree["port"])
        if args.status_port is None and "status_port" in tree:
            args.status_port = int(tree["status_port"])
    args.host = args.host or "127.0.0.1"
    args.port = 4000 if args.port is None else args.port
    args.status_port = 10080 if args.status_port is None \
        else args.status_port
    if args.slow_threshold_ms is not None:
        config.set_var("tidb_tpu_slow_query_ms", args.slow_threshold_ms)
    for kv in args.set:
        name, _, val = kv.partition("=")
        config.set_var(name, val)

    # the package import already pointed jax at the persistent compile
    # cache; surface where (first-compile stalls vanish on warm starts)
    from tidb_tpu.util import compile_cache
    cc = compile_cache.stats()
    log.info("XLA compile cache: %s (%s entries)",
             cc["dir"] or "disabled", cc["entries"])
    # the kernel profiling plane rides every dispatch; say up front
    # whether it is armed and how much history it may keep
    from tidb_tpu import profiler
    ks = profiler.stats()
    log.info("kernel profiler: %s (cap %d profiles, compile-cache "
             "hits=%d misses=%d)",
             "on" if ks["enabled"] else "off", ks["cap"],
             compile_cache.counters()["hits"],
             compile_cache.counters()["misses"])
    log.info("serving: scheduler inflight=%d (bytes gate %d), "
             "server mem quota=%d (admission %s, timeout %dms)",
             config.sched_inflight(), config.sched_inflight_bytes(),
             config.server_mem_quota(),
             "on" if config.server_mem_quota() else "off",
             config.admission_timeout_ms())

    from tidb_tpu import devplane as mesh_config
    if args.no_mesh:
        mesh_config.disable_mesh()
    else:
        try:
            mesh_config.enable_mesh(args.mesh)
            mesh = mesh_config.active_mesh()
            log.info("device mesh: %s", mesh.devices.shape
                     if mesh is not None else None)
        except Exception as e:  # noqa: BLE001 - no devices is survivable
            log.warning("mesh unavailable (%s); host execution only", e)

    from tidb_tpu.server import Server
    from tidb_tpu.server.status import StatusServer

    if args.store:
        from tidb_tpu.store.remote import connect
        h, _, pt = args.store.rpartition(":")
        storage = connect(h or "127.0.0.1", int(pt), local_cache=True)
        log.info("fleet mode: store plane at %s", args.store)
    else:
        from tidb_tpu.store.storage import new_mock_storage
        storage = new_mock_storage()
    server = Server(storage, host=args.host, port=args.port,
                    token_limit=args.token_limit)
    server.start()
    log.info("MySQL protocol on %s:%d", args.host, server.port)
    status = None
    if not args.no_status:
        status = StatusServer(storage, server, host=args.host,
                              port=args.status_port)
        status.start()
        log.info("status API on %s:%d", args.host, status.port)
        # fleet membership (tidb_tpu/member.py): identity = the status
        # port peers fan cluster_* queries out to, so registration is
        # tied to the status server being up. The heartbeat publishes
        # through whichever storage this process uses — the shared
        # store plane in fleet mode, the in-process store standalone
        # (where this member is then the whole visible fleet).
        from tidb_tpu import member
        member.set_identity(args.host, status.port, "sql")
        member.start_heartbeat(storage)

    stop = threading.Event()

    def _on_signal(_sig, _frm):
        stop.set()

    signal.signal(signal.SIGINT, _on_signal)
    signal.signal(signal.SIGTERM, _on_signal)
    stop.wait()
    log.info("shutting down")
    if status is not None:
        from tidb_tpu import member
        member.stop_heartbeat()
        status.close()
    server.close()
    storage.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
