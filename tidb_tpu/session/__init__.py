"""Session: the SQL entry point.

Reference: /root/reference/session.go — Session.Execute (parse -> compile ->
run, :691-774), txn lifecycle with autocommit (tidb.go:155-177), and the
Domain role (domain/domain.go) of caching infoschema versions. Optimistic
retry on commit conflict replays the statement history
(session.go:287,393-470).
"""

from __future__ import annotations

import logging
import threading
import time
import weakref
from dataclasses import dataclass, field

from tidb_tpu import errcode, kv, tablecodec
from tidb_tpu.executor import (ExecContext, ExecError, build_executor)
from tidb_tpu.ddl import DDLExecutor
from tidb_tpu.meta import Meta
from tidb_tpu.parser import ParseError, ast, parse
from tidb_tpu.plan import Planner
from tidb_tpu.plan.planner import PlanError
from tidb_tpu.plan.resolver import ResolveError
from tidb_tpu.schema.infoschema import InfoSchema, SchemaError
from tidb_tpu.sqltypes import (EvalType, TypeCode, format_datetime,
                               scaled_to_decimal)

__all__ = ["Session", "ResultSet", "Domain", "SQLError"]

COMMIT_RETRY_LIMIT = 10  # ref: tidb.go:109 commitRetryLimit

# dedicated slow-query logger (ref: util/logutil/log.go:228-248 separate
# slow-query log file; executor/adapter.go:353 emit site)
slow_log = logging.getLogger("tidb_tpu.slow_query")

# live sessions for SHOW PROCESSLIST (ref: util.SessionManager backing
# SHOW PROCESSLIST in the server package)
_SESSIONS: "weakref.WeakSet[Session]" = weakref.WeakSet()

# statement kinds subject to server admission control (tidb_tpu/sched.py):
# the ones that build executors and allocate scan/agg/join memory.
# Everything else (SET/SHOW/KILL/BEGIN/COMMIT/DDL...) always runs, so an
# operator can SET quotas, SHED and KILL a busy server out of trouble.
_ADMISSION_STMTS = (ast.SelectStmt, ast.UnionStmt, ast.InsertStmt,
                    ast.UpdateStmt, ast.DeleteStmt, ast.LoadDataStmt,
                    ast.AnalyzeStmt, ast.ExplainStmt, ast.ExecuteStmt,
                    ast.DoStmt, ast.TraceStmt)


def _needs_admission(stmt) -> bool:
    if isinstance(stmt, ast.ExplainStmt):
        # plain EXPLAIN only plans (the operator's diagnostic tool on a
        # busy server — must always answer); EXPLAIN ANALYZE executes
        return bool(getattr(stmt, "analyze", False))
    return isinstance(stmt, _ADMISSION_STMTS)
_session_seq = 0
_session_seq_lock = threading.Lock()


def processlist_snapshot() -> list[dict]:
    """Live sessions as plain dicts — the /cluster/state export the
    cluster_processlist memtable fans out over (the JSON-able twin of
    SHOW PROCESSLIST's rows)."""
    out = []
    now = time.time()
    with _session_seq_lock:   # adds are serialized with snapshot
        live = list(_SESSIONS)
    for s in sorted(live, key=lambda x: x.session_id):
        sql = s.current_sql
        tracker = getattr(s, "mem_tracker", None)
        rm = getattr(s, "res_meter", None)
        mtot = rm.totals() if rm is not None else {}
        out.append({
            "id": s.session_id,
            "user": s.user,
            "host": s.host,
            "db": s.current_db or None,
            "command": "Query" if sql else "Sleep",
            "time_s": int(now - s.created_at),
            "info": (sql or "")[:100] or None,
            "mem_bytes": tracker.total() if tracker is not None else 0,
            "device_ms": mtot.get("device_ns", 0) // 1_000_000,
            "rows_sent": mtot.get("rows_sent", 0),
        })
    return out


class SQLError(Exception):
    pass


@dataclass
class ResultSet:
    columns: list[str]
    rows: list[tuple]
    field_types: list | None = None   # FieldType per column (wire protocol)

    def __repr__(self):
        return f"ResultSet({self.columns}, {len(self.rows)} rows)"


class Domain:
    """Caches the InfoSchema per schema version (ref: domain.Reload,
    domain/domain.go:267). One per storage."""

    _instances: dict = {}
    _lock = threading.Lock()

    def __init__(self, storage):
        self.storage = storage
        self._schema: InfoSchema | None = None
        self._mu = threading.Lock()
        self._stats = None
        self._plan_cache = None
        self._priv = None
        self._ddl_owner = None
        self._schema_stop = None
        self._stats_stop = None

    def priv_cache(self):
        """Grant-table cache (ref: privilege/privileges/cache.go:104)."""
        if self._priv is None:
            from tidb_tpu.privilege import PrivilegeCache
            self._priv = PrivilegeCache(self.storage)
        return self._priv

    # -- multi-server schema plane (ref: owner/manager.go election,
    # ddl/syncer.go version publication, domain/domain.go reload loop) -------

    SCHEMA_SYNC_PREFIX = b"m_schema_sync_"
    SCHEMA_LEASE_MS = 2000

    def ddl_owner(self):
        """This domain's DDL election participant (lazy singleton)."""
        with self._mu:
            if self._ddl_owner is None:
                from tidb_tpu.owner import OwnerManager
                self._ddl_owner = OwnerManager(
                    self.storage, lease_ms=self.SCHEMA_LEASE_MS)
            return self._ddl_owner

    def schema_worker_running(self) -> bool:
        return self._schema_stop is not None

    def publish_schema_version(self) -> None:
        """Advertise this server's loaded schema version (ref:
        ddl/syncer.go:58 UpdateSelfVersion): a lease-stamped sync record
        the DDL owner polls for convergence."""
        ver = self.info_schema().version
        key = self.SCHEMA_SYNC_PREFIX + self.ddl_owner().id.encode()
        import json as _json
        expiry = int(time.time() * 1000) + 2 * self.SCHEMA_LEASE_MS
        txn = self.storage.begin()
        try:
            txn.set(key, _json.dumps({"ver": ver,
                                      "expiry": expiry}).encode())
            txn.commit()
        except kv.KVError as e:
            # the record expires in 2x lease, so the owner would treat
            # this server as dead — say so rather than vanish silently
            logging.getLogger("tidb_tpu.domain").warning(
                "schema version publish failed: %s", e)
            if getattr(txn, "valid", False):
                txn.rollback()

    def live_schema_versions(self) -> dict[str, int]:
        """Unexpired published versions by server id (ref: syncer.go
        OwnerCheckAllVersions reading etcd)."""
        import json as _json
        from tidb_tpu import codec as _codec
        now = int(time.time() * 1000)
        out: dict[str, int] = {}
        snap = self.storage.snapshot(self.storage.current_ts())
        end = _codec.prefix_next(self.SCHEMA_SYNC_PREFIX)
        for k, v in snap.iter_range(self.SCHEMA_SYNC_PREFIX, end):
            try:
                o = _json.loads(v)
                if int(o["expiry"]) > now:
                    out[k[len(self.SCHEMA_SYNC_PREFIX):].decode()] = \
                        int(o["ver"])
            except (ValueError, KeyError):
                continue
        return out

    def wait_schema_convergence(self, target_ver: int,
                                timeout_ms: int | None = None) -> bool:
        """Block until every live server published >= target_ver, capped
        at 2x lease (dead servers expire out; ref: ddl_worker's
        waitSchemaChanged + 2*lease convergence rule, ddl/ddl.go)."""
        deadline = time.time() + (timeout_ms or
                                  2 * self.SCHEMA_LEASE_MS) / 1000.0
        me = self.ddl_owner().id
        while True:
            vers = self.live_schema_versions()
            lagging = [s for s, v in vers.items()
                       if s != me and v < target_ver]
            if not lagging:
                return True
            if time.time() >= deadline:
                return False
            time.sleep(0.02)

    def schema_worker_tick(self) -> None:
        """One maintenance beat: campaign for DDL ownership, drain the job
        queue when owner, reload + publish the schema version."""
        owner = self.ddl_owner()
        from tidb_tpu.ddl.worker import DDLWorker
        worker = DDLWorker(self.storage)
        # re-campaign EVERY step: long drains (backfills, convergence
        # waits) must renew the lease or stop when ownership moves
        while owner.campaign():
            try:
                job = worker.run_one_step()
            except kv.RetryableError:
                break    # a competing stepper raced us: yield to it
            if job is None:
                break
            self.wait_schema_convergence(self.info_schema().version)
        self.publish_schema_version()

    def start_schema_worker(self, interval: float | None = None) -> None:
        """Background reload/election/DDL loop (ref: domain.go:320
        loadSchemaInLoop + ddl owner worker)."""
        with self._mu:
            if self._schema_stop is not None:
                return
            self._schema_stop = threading.Event()
            stop = self._schema_stop
        tick = interval if interval is not None \
            else self.SCHEMA_LEASE_MS / 2000.0
        # supervised (util/supervisor.py): a crashing tick is counted
        # in tidb_tpu_worker_restarts_total{worker="schema-worker"}
        # and backed off instead of silently swallowed
        from tidb_tpu.util import supervisor
        supervisor.supervise("schema-worker", self.schema_worker_tick,
                             stop, tick)

    def stop_schema_worker(self) -> None:
        with self._mu:
            stop = self._schema_stop
            self._schema_stop = None
        if stop is not None:
            stop.set()

    # -- auto analyze (ref: statistics/handle.go auto-analyze +
    # RunAutoAnalyze wiring, tidb-server/main.go:341) -------------------------

    def auto_analyze_tick(self) -> list[int]:
        """Analyze every table whose DML delta crossed the ratio; returns
        the analyzed table ids. Called by the background stats worker and
        directly by tests."""
        from tidb_tpu.statistics import analyze_table
        handle = self.stats_handle()
        done = []
        for tid in handle.pending_tables():
            located = self.info_schema().table_by_id(tid)
            if located is None:
                handle._deltas.pop(tid, None)   # dropped table
                continue
            _db, info = located
            try:
                stats = analyze_table(self.storage,
                                      self.storage.current_ts(), info)
                handle.save(stats)
                done.append(tid)
            except Exception:  # noqa: BLE001 - next tick retries
                continue
        return done

    def start_stats_worker(self, interval: float = 30.0) -> None:
        """Idempotent background auto-analyze loop."""
        with self._mu:
            if self._stats_stop is not None:
                return
            self._stats_stop = threading.Event()
            stop = self._stats_stop

        from tidb_tpu.util import supervisor
        supervisor.supervise("stats-auto-analyze",
                             self.auto_analyze_tick, stop, interval)

    def stop_stats_worker(self) -> None:
        with self._mu:
            stop = self._stats_stop
            self._stats_stop = None
        if stop is not None:
            stop.set()

    def stats_handle(self):
        """Lazy per-store stats cache (ref: statistics/handle.go:32)."""
        if self._stats is None:
            from tidb_tpu.statistics import StatsHandle
            self._stats = StatsHandle(self.storage)
        return self._stats

    def plan_cache(self):
        """Shared LRU of compiled SELECT plans keyed by (sql, db,
        schema version, stats version) — ref: plan/cache.go + the
        kvcache-backed plan cache wired in tidb-server/main.go:349."""
        if self._plan_cache is None:
            from tidb_tpu.util import LRUCache
            self._plan_cache = LRUCache(200)
        return self._plan_cache

    @classmethod
    def get(cls, storage) -> "Domain":
        with cls._lock:
            d = cls._instances.get(id(storage))
            if d is None:
                d = cls(storage)
                cls._instances[id(storage)] = d
            return d

    def info_schema(self) -> InfoSchema:
        txn = self.storage.begin()
        try:
            meta = Meta(txn)
            ver = meta.schema_version()
            with self._mu:
                if self._schema is not None and self._schema.version == ver:
                    return self._schema
                self._schema = InfoSchema.load(meta)
                return self._schema
        finally:
            txn.rollback()

    def check_schema_valid(self, start_ver: int, table_ids) -> None:
        """Commit-time schema validation (ref: domain/schema_validator.go:
        35-47): a txn that planned against schema version `start_ver` may
        commit iff no later version changed a table it wrote. Versions with
        no diff record are treated as changing everything."""
        txn = self.storage.begin()
        try:
            m = Meta(txn)
            cur = m.schema_version()
            if cur == start_ver:
                return
            for v in range(start_ver + 1, cur + 1):
                diff = m.schema_diff(v)
                if diff is None or any(t in table_ids for t in diff):
                    raise kv.SchemaChangedError(
                        f"schema changed (v{start_ver} -> v{cur}), "
                        f"txn must retry")
        finally:
            txn.rollback()


class Session:
    """Ref: session.go Session iface (:62-86)."""

    def __init__(self, storage, db: str = "", user: str = "root",
                 host: str = "%", internal: bool = False):
        self.storage = storage
        self.domain = Domain.get(storage)
        self.current_db = db
        self.user = user
        self.host = host
        # internal sessions (bootstrap, privilege loader, background
        # workers) bypass privilege checks — ref: ExecRestrictedSQL
        self.internal = internal
        self.txn: kv.Transaction | None = None
        self.autocommit = True
        self.vars: dict[str, object] = {}
        self.sys_vars: dict[str, object] = {"autocommit": 1,
                                            "sql_mode": "STRICT_TRANS_TABLES"}
        self._history: list[ast.StmtNode] = []  # stmt replay for retry
        self._prepared: dict = {}               # id/name -> _Prepared
        self._next_stmt_id = 0
        global _session_seq
        with _session_seq_lock:
            _session_seq += 1
            self.session_id = _session_seq
            self.created_at = time.time()
            self.current_sql: str | None = None  # for SHOW PROCESSLIST
            self._stmt_start = 0.0
            self.killed = False           # KILL QUERY flag (cooperative)
            self.kill_hook = None         # server sets: closes the conn
            self.mem_tracker = None       # session memory root (memtrack)
            self.res_meter = None         # resource meter (meter.py)
            if not internal:
                _SESSIONS.add(self)
                from tidb_tpu import memtrack, meter
                self.mem_tracker = memtrack.session_root(self.session_id)
                # the per-tenant work ledger: retained (bounded) after
                # the session closes, so device-seconds done by a
                # finished connection still reconcile in resource_usage
                self.res_meter = meter.session_meter(self.session_id,
                                                     self.user or "")
                # mark the meter evictable once the session dies —
                # eviction past the registry cap prefers closed
                # sessions, so a live tenant never drops off the
                # attribution surfaces
                self._meter_finalizer = weakref.finalize(
                    self, meter.session_closed, self.session_id)
                # sessions are not reliably close()d (pools, tests): the
                # finalizer detaches the tracker from the server root so
                # information_schema.memory_usage never lists the dead
                self._mem_finalizer = weakref.finalize(
                    self, self.mem_tracker.detach)

    # -- public API ----------------------------------------------------------

    def add_warning(self, level: str, code: int, message: str) -> None:
        """Append to the statement diagnostics area (read by SHOW
        WARNINGS/ERRORS, cleared at the start of the next statement).
        Ref: sessionctx stmtctx AppendWarning, statement.go."""
        if not hasattr(self, "_warnings"):
            self._warnings = []
        self._warnings.append((level, code, message))

    def execute(self, sql: str):
        """Execute semicolon-separated statements; returns a list of
        ResultSet (queries) / int (affected rows) / None (commands)."""
        t0 = time.perf_counter_ns()
        stmts = parse(sql)
        # batch parse cost is attributed evenly across its statements
        self._parse_ns = (time.perf_counter_ns() - t0) // max(len(stmts), 1)
        out = []
        single = sql if len(stmts) == 1 else None
        # auth statements never expose credentials in the processlist or
        # the slow log (the reference redacts before logging) — the WHOLE
        # batch text is redacted if any statement in it carries one
        if any(isinstance(s, (ast.CreateUserStmt, ast.SetPasswordStmt))
               for s in stmts):
            sql = "<redacted: batch containing credentials>" \
                if len(stmts) > 1 else "<redacted: credential statement>"
        for i, stmt in enumerate(stmts):
            out.append(self._timed_stmt(
                stmt, sql, sql_text=single,
                batch_no=i if len(stmts) > 1 else None))
        return out

    def _timed_stmt(self, stmt, sql: str, sql_text: str | None,
                    batch_no: int | None = None):
        """Statement lifecycle wrapper: processlist state, duration
        metrics, slow-query log (ref: ExecStmt adapter, adapter.go:189 +
        slow-log emit at :353). Internal bookkeeping sessions skip the
        instrumentation entirely — their catalog lookups are not client
        queries and would pollute the metrics."""
        from tidb_tpu import (config, memtrack, meter, metrics, perfschema,
                              sched, trace)
        from tidb_tpu import runtime_stats as rs
        if self.internal:
            # internal catalog work must neither appear in perfschema nor
            # attach spans to the enclosing client statement's trace —
            # nor record its scans into that statement's operator stats,
            # bill its buffers to that statement's memory quota, or
            # credit its device work to that statement's tenant meter
            token = trace.detach()
            try:
                with rs.suspended(), memtrack.suspended(), \
                        meter.suspended():
                    return self._run_stmt(stmt, sql_text=sql_text)
            finally:
                trace.restore(token)
        self.current_sql = sql
        self._stmt_start = time.perf_counter()
        self.killed = False   # a kill that landed while idle is a no-op
        self._last_plan = None    # executed physical plan (EXPLAIN
        self._last_stats = None   # ANALYZE / slow log / bench read these)
        # each statement resets the diagnostics area, except the SHOWs
        # that read it (MySQL: SHOW WARNINGS does not clear warnings)
        if not (isinstance(stmt, ast.ShowStmt)
                and getattr(stmt, "tp", None) in ("warnings", "errors")):
            self._warnings = []
        kind = type(stmt).__name__.removesuffix("Stmt").lower()
        ev = perfschema.stmt_begin(self.session_id, sql)
        overlay = {k: v for k, v in self.sys_vars.items()
                   if config.is_known(k)}
        # the sampling decision happens at begin: install the overlay
        # around it so a session-scope SET tidb_tpu_trace_sample is
        # honored (like every other session-shadowed knob). Only when
        # the session actually shadows something — the common empty
        # case must not pay a second overlay install per statement
        if overlay:
            with config.session_overlay(overlay):
                root = trace.begin("statement", type=kind)
        else:
            root = trace.begin("statement", type=kind)
        if isinstance(stmt, ast.TraceStmt):
            # TRACE forces retention; _exec_trace reads the live tree
            root.forced = True
        # parse happened batch-wide before dispatch: record this
        # statement's share as a pre-closed phase span, and back-date the
        # root so timer_wait covers it (phases must sum <= total)
        pspan = trace.Span("parse")
        pspan.start_ns = root.start_ns - getattr(self, "_parse_ns", 0)
        pspan.end_ns = root.start_ns
        root.start_ns = pspan.start_ns
        root.children.append(pspan)
        err: str | None = None
        res = None
        # per-statement memory root: operators hang their tracker nodes
        # off it, it rolls up into the session root, and it carries the
        # mem-quota + OOM-action chain. on_cancel flips the cooperative
        # kill flag so concurrent fan-out workers stop at their next
        # interrupt check while the quota error unwinds this thread.
        quota_cancel: list[str] = []

        def _on_quota_cancel(msg: str) -> None:
            quota_cancel.append(msg)
            self.killed = True

        mt = memtrack.statement_root(
            parent=self.mem_tracker,
            on_cancel=_on_quota_cancel,
            label=f"stmt-{self.session_id}")
        self._last_mem = mt
        # server admission (tidb_tpu/sched.py): executable statements
        # check their projected footprint (this digest's historical
        # peak) against tidb_tpu_server_mem_quota BEFORE running —
        # shed / queue / retryable-reject here replaces the mid-query
        # OOM cancel a full server used to hand an innocent statement.
        # Control statements (SET/SHOW/KILL/COMMIT...) always run: an
        # operator must be able to work a busy server out of trouble.
        adm = sched.admission()
        admission_ticket = None
        # per-statement resource meter (meter.py): rolls up live into
        # the session/user/SERVER ledgers; installed around admission
        # too so the admission wait attributes to this tenant
        sm = meter.statement_meter(self.res_meter)
        try:
            with config.session_overlay(overlay), meter.metering(sm):
                mt.quota = config.mem_quota_query()   # session-shadowed
                try:
                    if _needs_admission(stmt):
                        # the admission wait is the first thing tail
                        # latency hides behind on a busy server: a span
                        # makes it attributable per statement
                        with trace.span("admission"):
                            admission_ticket = adm.admit(
                                projected=perfschema.digest_max_mem(sql),
                                label=f"session-{self.session_id}")
                    with memtrack.tracking(mt):
                        res = self._run_stmt(stmt, sql_text=sql_text)
                except memtrack.QuotaExceededError as e:
                    # OOM cancel: statement dies with ER_MEM_EXCEED_QUOTA,
                    # the transaction rolls back, the session survives
                    self._rollback()
                    raise SQLError(str(e)) from None
                except Exception as e:
                    if quota_cancel and "interrupted" in str(e).lower():
                        # the cancel fired on a fan-out worker: this
                        # thread's cooperative-kill check raised a
                        # generic interrupt before the worker's exception
                        # drained — surface the honest quota error (and
                        # its rollback) instead of ER_QUERY_INTERRUPTED.
                        # Only interrupt-shaped errors are rewritten: an
                        # unrelated concurrent failure must keep its own
                        # message and code
                        self._rollback()
                        raise SQLError(quota_cancel[0]) from None
                    raise
                finally:
                    # effective (session-shadowed) slow-log/trace knobs
                    # — captured INSIDE the overlay because the outer
                    # finally below runs after it has exited
                    slow_ms = config.get_var("tidb_tpu_slow_query_ms")
                    trace_on = config.get_var("tidb_tpu_trace_log")
                    slow_trace = config.get_var("tidb_tpu_slow_trace_ms")
        except Exception as e:
            metrics.counter(metrics.QUERY_ERRORS)
            err = str(e)
            raise
        finally:
            trace.end(root)
            dur = time.perf_counter() - self._stmt_start
            # peaks survive detach; the gauges sample the last statement
            metrics.gauge(metrics.QUERY_MEM, mt.host_peak,
                          {"kind": "host"})
            metrics.gauge(metrics.QUERY_MEM, mt.device_peak,
                          {"kind": "device"})
            # the process-global backend watermark stays a SERVER gauge
            # only — concurrent statements contaminate it, so it must
            # never feed per-statement columns again
            metrics.gauge(metrics.DEVICE_PEAK, rs.device_watermark())
            metrics.counter(metrics.QUERIES_TOTAL, {"type": kind})
            metrics.histogram(metrics.QUERY_DURATIONS, dur)
            nrows = len(res.rows) if isinstance(res, ResultSet) else \
                (res if isinstance(res, int) else 0)
            perfschema.stmt_end(ev, root=root, rows=nrows, error=err)
            # digest summary + per-operator metric families
            coll = getattr(self, "_last_stats", None)
            ops = coll.ops() if coll is not None else []
            phases = {"parse": trace.phase_ns(root, "parse"),
                      "plan": trace.phase_ns(root, "plan"),
                      "exec": trace.phase_ns(root, "execute"),
                      "commit": trace.phase_ns(root, "commit")}
            # sampled / slow / TRACE-forced trees retain into the
            # server trace ring; the id links the digest summary and
            # the slow log to the concrete timeline
            trace_id = trace.finish_statement(root, sql, error=err,
                                              slow_ms=slow_trace)
            digest, _norm = perfschema.digest_record(
                sql, int(dur * 1e9), phases=phases, rows=nrows,
                error=err, op_stats=[s.to_dict() for s in ops],
                mem_bytes=mt.host_peak + mt.device_peak,
                tag=None if batch_no is None
                else f"stmt#{batch_no}:{kind}",
                trace_id=trace_id)
            # mode-history memo: record what each operator *actually*
            # ran (direct/hash/sort/fused/hybrid/host) keyed by digest —
            # the read side for feedback-driven mode selection
            if config.kernel_profile():
                perfschema.memo_record(
                    digest, [s.to_dict() for s in ops if s.mode])
            # rows served + statement count land on the meter here (the
            # one place the row count is known), then the statement's
            # metered totals fold into the per-digest rollup /top ranks
            sm.add(rows_sent=nrows, statements=1)
            meter.finish_statement(sm, digest, _norm)
            for s in ops:
                if not s.loops:
                    continue   # operator never produced (cached sub-plan)
                metrics.histogram(metrics.OP_DURATIONS, s.time_ns / 1e9,
                                  {"op": s.name})
                metrics.counter(metrics.OP_ROWS, {"op": s.name},
                                inc=s.act_rows)
                if s.device_time_ns:
                    metrics.histogram(metrics.OP_DEVICE_DURATIONS,
                                      s.device_time_ns / 1e9,
                                      {"op": s.name})
                if s.superchunks:
                    metrics.counter(metrics.SUPERCHUNKS, {"op": s.name},
                                    inc=s.superchunks)
                    metrics.counter(metrics.SUPERCHUNK_SOURCES,
                                    {"op": s.name},
                                    inc=s.coalesced_chunks)
                    metrics.counter(metrics.SUPERCHUNK_FILL_ROWS,
                                    {"op": s.name},
                                    inc=s.superchunk_fill_rows)
                    metrics.counter(metrics.SUPERCHUNK_BUCKET_ROWS,
                                    {"op": s.name},
                                    inc=s.superchunk_bucket_rows)
                if s.pipeline_stall_ns:
                    metrics.histogram(metrics.PIPELINE_STALLS,
                                      s.pipeline_stall_ns / 1e9,
                                      {"op": s.name})
            if trace_on:
                trace.log_tree(root, sql)
            self.killed = False
            if dur * 1000 >= slow_ms:
                metrics.counter(metrics.SLOW_QUERIES)
                slow_log.warning(
                    "%s", self._slow_log_record(sql, dur, digest, ops,
                                                err, mem=mt,
                                                trace_id=trace_id))
            # release the executed plan tree: an idle pooled session
            # must not pin a multi-MB INSERT's literal plan (the sealed
            # collector keeps only name+number OpStats for bench)
            self._last_plan = None
            if coll is not None:
                coll.seal()
            # release-on-close: credit everything still held back to the
            # session root (leaving it at zero between statements) and
            # drop the plan pins; peaks stay readable on _last_mem
            mt.detach()
            adm.finish(admission_ticket)
            self.current_sql = None
        return res

    def _slow_log_record(self, sql: str, dur: float, digest: str,
                         ops, err: str | None, mem=None,
                         trace_id=None) -> str:
        """Structured slow-log record: digest, executed plan, and
        per-operator stats ride with the SQL (ref: the reference's
        multi-line slow log, executor/adapter.go:353 +
        infoschema slow_query parsing contract)."""
        from tidb_tpu import runtime_stats as rs
        lines = [f"slow query: {dur:.3f}s user={self.user} "
                 f"db={self.current_db} digest={digest}"
                 + (" error=1" if err else "")]
        if trace_id is not None:
            # the captured slow trace: fetch the timeline via
            # GET /trace/<id> or information_schema.statement_traces
            lines.append(f"# Trace_id: {trace_id}")
        if mem is not None:
            lines.append(
                f"# Mem: {rs.fmt_bytes(mem.host_peak + mem.device_peak)}"
                f" host={rs.fmt_bytes(mem.host_peak)}"
                f" device={rs.fmt_bytes(mem.device_peak)}")
        plan = getattr(self, "_last_plan", None)
        if plan is not None:
            try:
                for ln in plan.explain().split("\n"):
                    lines.append("# Plan: " + ln)
            except Exception:  # noqa: BLE001 - logging must not fail stmts
                pass
        kb = kns = 0
        for s in ops:
            if not s.loops and not s.time_ns:
                continue
            ln = (f"# Op: {s.name} act_rows={s.act_rows} "
                  f"loops={s.loops} time={rs.fmt_ns(s.time_ns)}")
            if s.device_time_ns:
                ln += f" device_time={rs.fmt_ns(s.device_time_ns)}"
            if s.cop_tasks:
                ln += f" cop_tasks={s.cop_tasks}"
            if s.superchunks:
                ln += (f" superchunks={s.superchunks}"
                       f" fill={s.fill_ratio():.2f}"
                       f" stall={rs.fmt_ns(s.pipeline_stall_ns)}")
            if s.kernel_family:
                ln += f" kernel={s.kernel_family}"
                if s.kernel_compile:
                    ln += f" compile={s.kernel_compile}"
                if s.mode:
                    ln += f" mode={s.mode}"
                kb += s.kernel_bytes
                kns += s.kernel_busy_ns
            lines.append(ln)
        if kns:
            # statement-level roofline: all kernel bytes over all kernel
            # busy time vs the platform's memory-bandwidth peak
            from tidb_tpu import profiler
            g = profiler.achieved_gbps(kb, kns)
            if g is not None:
                frac = profiler.roofline_fraction(kb, kns)
                ln = f"# Kernel: bytes={rs.fmt_bytes(kb)} " \
                     f"busy={rs.fmt_ns(kns)} achieved={g:.2f}GB/s"
                if frac is not None:
                    ln += f" roofline={frac:.3f}"
                lines.append(ln)
        lines.append("# SQL: " + sql[:2048])
        return "\n".join(lines)

    # -- prepared statements (ref: session.go:777-855 PrepareStmt /
    # ExecutePreparedStmt; the binary protocol and SQL PREPARE share it) ----

    def prepare(self, sql: str, name: str | None = None):
        """-> (stmt_id, num_params). Parses once; EXECUTE binds the
        collected parameter markers in order."""
        stmts = parse(sql)
        if len(stmts) != 1:
            raise SQLError("can only prepare a single statement")
        markers = ast_params(stmts[0])
        self._next_stmt_id += 1
        sid = self._next_stmt_id
        p = _Prepared(stmt=stmts[0], markers=markers, sql=sql, sid=sid,
                      name=name.lower() if name else None)
        self._prepared[sid] = p
        if p.name is not None:
            self._prepared[p.name] = p
        return sid, len(markers)

    def _lookup_prepared(self, stmt_id):
        return self._prepared.get(stmt_id if not isinstance(stmt_id, str)
                                  else stmt_id.lower())

    def prepared_columns(self, stmt_id):
        """Result-column metadata of a prepared statement at PREPARE time,
        for the COM_STMT_PREPARE_OK response (standard MySQL drivers read
        the prepare-time column definitions; ref server/conn_stmt.go).
        Plans the SELECT with params bound to NULL — result column names
        and types come from the schema, not the parameter values. Memoized
        on the prepared statement (prepare-time metadata is a snapshot).
        -> (names, field_types), or (None, None) for non-resultset stmts
        or when planning with unbound params fails."""
        p = self._lookup_prepared(stmt_id)
        if p is None:
            return (None, None)
        if p.columns_meta is not None:
            return p.columns_meta
        sel = p.stmt
        if isinstance(sel, ast.UnionStmt):
            sel = sel.selects[0]     # UNION metadata = first branch's
        if not isinstance(sel, ast.SelectStmt):
            return (None, None)
        saved = [(m.value, m.bound) for m in p.markers]
        try:
            for m in p.markers:
                m.value, m.bound = None, True
            plan = self._planner().plan(sel)
            p.columns_meta = ([c.name for c in plan.schema.cols],
                              [c.ft for c in plan.schema.cols])
            return p.columns_meta
        except Exception:
            return (None, None)
        finally:
            for m, (v, b) in zip(p.markers, saved):
                m.value, m.bound = v, b

    def execute_prepared(self, stmt_id, params=()):
        p = self._lookup_prepared(stmt_id)
        if p is None:
            raise SQLError(f"unknown prepared statement {stmt_id!r}")
        if len(params) != len(p.markers):
            raise SQLError(f"expected {len(p.markers)} parameters, "
                           f"got {len(params)}")
        for m, v in zip(p.markers, params):
            m.value = v
            m.bound = True
        if self.current_sql is not None:
            # SQL-level EXECUTE: already inside this statement's
            # _timed_stmt frame — don't double-record
            return self._run_stmt(p.stmt)
        # binary-protocol COM_STMT_EXECUTE: full instrumentation (events,
        # spans, metrics, slow log), parse cost paid at prepare time
        self._parse_ns = 0
        return self._timed_stmt(p.stmt, p.sql, sql_text=None)

    def deallocate_prepared(self, stmt_id) -> None:
        key = stmt_id.lower() if isinstance(stmt_id, str) else stmt_id
        p = self._prepared.pop(key, None)
        if p is not None:   # drop BOTH registrations
            self._prepared.pop(p.sid, None)
            if p.name is not None:
                self._prepared.pop(p.name, None)

    def query(self, sql: str) -> ResultSet:
        res = self.execute(sql)
        for r in res:
            if isinstance(r, ResultSet):
                return r
        raise SQLError("statement returned no result set")

    def plan(self, sql: str):
        """Plan a single SELECT and return the physical plan (no
        execution, no plan cache) — the programmatic EXPLAIN."""
        stmts = parse(sql)
        if len(stmts) != 1:
            raise SQLError("plan() takes a single statement")
        try:
            return self._planner().plan(stmts[0])
        except (PlanError, ResolveError) as e:
            raise SQLError(str(e)) from None

    def close(self):
        from tidb_tpu import perfschema
        if not self.internal:
            perfschema.session_closed(self.session_id)
            if self.mem_tracker is not None:
                self._mem_finalizer()   # detach from the server root
            if self.res_meter is not None:
                # an explicit close must not wait for GC to mark the
                # meter evictable (registry eviction prefers closed)
                self._meter_finalizer()
        if self.txn is not None:
            self.txn.rollback()
            self.txn = None

    # -- txn lifecycle -------------------------------------------------------

    def _attach_schema_checker(self, txn) -> None:
        start_ver = self.domain.info_schema().version
        txn.schema_checker = lambda: self.domain.check_schema_valid(
            start_ver, txn.related_tables)

    def _begin_txn(self):
        if self.txn is None:
            self.txn = self.storage.begin()
            self._history = []
            self._attach_schema_checker(self.txn)
        return self.txn

    def _read_ts(self) -> int:
        if self.txn is not None:
            return self.txn.start_ts
        return self.storage.current_ts()

    def _commit(self):
        """Commit with optimistic retry: on retryable conflict, replay the
        txn's statement history at a fresh ts (ref: session.go:287
        doCommitWithRetry + retry :393)."""
        from tidb_tpu import trace
        txn = self.txn
        self.txn = None
        if txn is None:
            return
        history = self._history
        self._history = []
        # one span covers first attempt AND replay retries: commit_ns must
        # reflect the slow, conflicted commits most of all
        with trace.span("commit") as cspan:
            try:
                txn.commit()
                return
            except kv.UndeterminedError:
                raise
            except kv.RetryableError as first_err:
                if getattr(txn, "for_update", False):
                    # FOR UPDATE promised the read rows stayed put:
                    # replaying silently would break that promise
                    # (ref: session.go retry disabled when ForUpdate)
                    raise
                last = first_err
                for _ in range(COMMIT_RETRY_LIMIT):
                    cspan.tags["retries"] = \
                        cspan.tags.get("retries", 0) + 1
                    retry_txn = self.storage.begin()
                    self._attach_schema_checker(retry_txn)
                    try:
                        self.txn = retry_txn
                        for stmt in history:
                            self._exec_dml_in_txn(stmt)
                        self.txn = None
                        retry_txn.commit()
                        return
                    except kv.RetryableError as e:
                        self.txn = None
                        last = e
                    except Exception:
                        self.txn = None
                        retry_txn.rollback()
                        raise
                raise last

    def _rollback(self):
        if self.txn is not None:
            self.txn.rollback()
            self.txn = None
        self._history = []

    # -- dispatch ------------------------------------------------------------

    def _run_stmt(self, stmt: ast.StmtNode, sql_text: str | None = None):
        t = type(stmt).__name__
        self._check_privileges(stmt)
        if isinstance(stmt, (ast.CreateUserStmt, ast.DropUserStmt,
                             ast.GrantStmt, ast.RevokeStmt,
                             ast.SetPasswordStmt)):
            return self._exec_account(stmt)
        if isinstance(stmt, (ast.SelectStmt, ast.UnionStmt)):
            stmt, folded = self._fold_session_exprs(stmt)
            return self._exec_query(
                stmt, sql_text=None if folded else sql_text)
        if isinstance(stmt, ast.PrepareStmt):
            text = stmt.sql
            if stmt.from_var is not None:
                text = self.vars.get(stmt.from_var.lower())
                if not isinstance(text, str):
                    raise SQLError(
                        f"variable {stmt.from_var} does not hold a "
                        "statement text")
            self.prepare(text, name=stmt.name)
            return None
        if isinstance(stmt, ast.ExecuteStmt):
            # user variable names are case-insensitive in MySQL
            params = [self.vars.get(v.lower()) for v in stmt.using]
            return self.execute_prepared(stmt.name, params)
        if isinstance(stmt, ast.DeallocateStmt):
            self.deallocate_prepared(stmt.name)
            return None
        if isinstance(stmt, (ast.InsertStmt, ast.UpdateStmt,
                             ast.DeleteStmt, ast.LoadDataStmt)):
            stmt, _ = self._fold_session_exprs(stmt)
            return self._exec_dml(stmt)
        if isinstance(stmt, ast.SplitTableStmt):
            return self._exec_split_table(stmt)
        if isinstance(stmt, ast.TraceStmt):
            return self._exec_trace(stmt)
        if isinstance(stmt, ast.KillStmt):
            return self._exec_kill(stmt)
        if isinstance(stmt, ast.DoStmt):
            # evaluate for side effects/errors, discard results (ref:
            # executor/simple.go DoStmt)
            from tidb_tpu.plan.resolver import PlanSchema, Resolver
            import numpy as _np
            stmt, _ = self._fold_session_exprs(stmt)  # @v / @v := ...
            r = Resolver(PlanSchema([]))
            for e in stmt.exprs:
                try:
                    expr = r.resolve(e)
                    expr.eval_xp(_np, [], 1)
                except (ResolveError, PlanError) as err:
                    raise SQLError(str(err)) from None
            return None
        if isinstance(stmt, ast.FlushStmt):
            if stmt.tp == "privileges":
                # re-read the grant tables (ref: executeFlush ->
                # LoadPrivilegeLoop notify)
                self.domain.priv_cache().invalidate()
            elif stmt.tp not in ("status", "tables"):
                raise SQLError(f"unsupported FLUSH {stmt.tp}")
            return None
        if isinstance(stmt, ast.CreateViewStmt):
            raise SQLError("CREATE VIEW is not supported")
        if isinstance(stmt, ast.DropViewStmt):
            if not stmt.if_exists:
                names = ", ".join(t.name for t in stmt.tables)
                raise SQLError(f"Unknown view '{names}'")
            return None     # IF EXISTS: nothing to drop, by construction
        if isinstance(stmt, ast.DropStatsStmt):
            db = stmt.table.db or self.current_db
            info = self.domain.info_schema().table(db, stmt.table.name)
            self.domain.stats_handle().drop(info.id)
            return None
        if isinstance(stmt, (ast.CreateDatabaseStmt, ast.CreateTableStmt,
                             ast.CreateIndexStmt, ast.DropTableStmt,
                             ast.DropDatabaseStmt, ast.DropIndexStmt,
                             ast.AlterTableStmt, ast.TruncateTableStmt,
                             ast.RenameTableStmt)):
            if self.txn is not None:
                self._commit()  # implicit commit before DDL (MySQL semantics)
            dropped = self._dropped_table_ids(stmt)
            if isinstance(stmt, ast.DropTableStmt) and stmt.if_exists:
                ischema = self.domain.info_schema()
                for t in stmt.tables:
                    db = t.db or self.current_db
                    if not ischema.has_table(db, t.name):
                        # MySQL: one Note per missing IF EXISTS target
                        self.add_warning(
                            "Note", errcode.ER_BAD_TABLE_ERROR,
                            f"Unknown table '{db}.{t.name}'")
            from tidb_tpu.ddl import DDLError
            try:
                DDLExecutor(self.storage).execute(stmt, self.current_db,
                                                  domain=self.domain)
            except DDLError as e:
                raise SQLError(str(e)) from None
            for tid in dropped:
                self.domain.stats_handle().drop(tid)
            return None
        if isinstance(stmt, ast.UseStmt):
            ischema = self.domain.info_schema()
            if stmt.db.lower() not in ("information_schema",
                                       "performance_schema") and \
                    not ischema.has_db(stmt.db):
                raise SQLError(f"Unknown database '{stmt.db}'")
            self.current_db = stmt.db
            return None
        if isinstance(stmt, ast.BeginStmt):
            if self.txn is not None:
                self._commit()
            self._begin_txn()
            return None
        if isinstance(stmt, ast.CommitStmt):
            self._commit()
            return None
        if isinstance(stmt, ast.RollbackStmt):
            self._rollback()
            return None
        if isinstance(stmt, ast.SetStmt):
            return self._exec_set(stmt)
        if isinstance(stmt, ast.ShowStmt):
            return self._exec_show(stmt)
        if isinstance(stmt, ast.ExplainStmt):
            return self._exec_explain(stmt)
        if isinstance(stmt, ast.AnalyzeStmt):
            return self._exec_analyze(stmt)
        if isinstance(stmt, ast.AdminStmt):
            return self._exec_admin(stmt)
        raise SQLError(f"unsupported statement {t}")

    # -- ADMIN (ref: util/admin/admin.go:42 GetDDLInfo, :231
    # CheckRecordAndIndex / CheckIndicesCount) -------------------------------

    def _exec_admin(self, stmt: ast.AdminStmt) -> ResultSet:
        if stmt.tp == "show_ddl":
            txn = self.storage.begin()
            try:
                m = Meta(txn)
                ver = m.schema_version()
            finally:
                txn.rollback()
            return ResultSet(["SCHEMA_VER", "OWNER", "SELF_ID"],
                             [(ver, "self", "self")])
        if stmt.tp == "show_ddl_jobs":
            # queue front-to-back, then recent history (ref: the ADMIN
            # SHOW DDL JOBS surface over meta's job queue/history)
            from tidb_tpu.ddl.job import Job
            txn = self.storage.begin()
            try:
                m = Meta(txn)
                rows = []
                for raw in m.t.litems(Meta.JOB_LIST_KEY):
                    j = Job.loads(raw)
                    rows.append((j.id, j.tp.value, j.schema_id,
                                 j.table_id, j.state.value,
                                 int(j.schema_state), "queue"))
                hist = m.t.hgetall(Meta.JOB_HISTORY_KEY)
                for _f, raw in sorted(hist, reverse=True)[:16]:
                    j = Job.loads(raw)
                    rows.append((j.id, j.tp.value, j.schema_id,
                                 j.table_id, j.state.value,
                                 int(j.schema_state), "history"))
            finally:
                txn.rollback()
            return ResultSet(["JOB_ID", "JOB_TYPE", "SCHEMA_ID",
                              "TABLE_ID", "STATE", "SCHEMA_STATE",
                              "SOURCE"], rows)
        if stmt.tp == "cancel_ddl_jobs":
            # flip still-QUEUEING jobs to CANCELLED in the meta queue
            # (ref: admin.CancelJobs — running jobs can't be cancelled
            # here; the single transition already commits atomically)
            from tidb_tpu.ddl.job import Job, JobState
            rows = []
            txn = self.storage.begin()
            try:
                m = Meta(txn)
                items = list(m.t.litems(Meta.JOB_LIST_KEY))
                for jid in stmt.job_ids:
                    found = False
                    for pos, raw in enumerate(items):
                        j = Job.loads(raw)
                        if j.id != jid:
                            continue
                        found = True
                        if j.state == JobState.QUEUEING:
                            j.state = JobState.CANCELLED
                            m.t.lset(Meta.JOB_LIST_KEY, pos, j.dumps())
                            rows.append((jid, "cancelled"))
                        else:
                            rows.append((jid, f"cannot cancel: "
                                              f"{j.state.value}"))
                        break
                    if not found:
                        rows.append((jid, "not found"))
                txn.commit()
            except Exception:
                txn.rollback()
                raise
            return ResultSet(["JOB_ID", "RESULT"], rows)
        if stmt.tp != "check_table":
            return ResultSet(columns=["info"], rows=[])
        from tidb_tpu import codec as _codec
        from tidb_tpu.schema.model import SchemaState
        snap = self.storage.snapshot(self.storage.current_ts())
        for ts in stmt.tables:
            info = self._resolve_table(ts)
            lo, hi = tablecodec.table_prefix_range(info.id)
            rp = tablecodec.record_prefix(info.id)
            rows: dict[int, dict] = {}            # handle -> {col_id: datum}
            actual: dict[int, set] = {}           # idx_id -> {(key, value)}
            for k, v in snap.iter_range(lo, hi):
                if k.startswith(rp):
                    h = tablecodec.decode_record_key(k)[1]
                    rows[h] = tablecodec.decode_row(v)
                    continue
                try:
                    _tid, iid, _suffix = tablecodec.decode_index_key(k)
                except ValueError:
                    continue
                actual.setdefault(iid, set()).add((k, v))
            for idx in info.indexes:
                if idx.state != SchemaState.PUBLIC:
                    continue
                # expected entries recomputed from the ROW VALUES, so
                # stale-value index corruption is caught, not just
                # count/handle drift (ref: admin.go CheckRecordAndIndex)
                expect: set = set()
                col_ids = [info.col_by_name(c).id for c in idx.columns]
                for h, rowvals in rows.items():
                    vals = [rowvals.get(cid) for cid in col_ids]
                    if idx.unique and all(x is not None for x in vals):
                        expect.add((
                            tablecodec.index_key(info.id, idx.id, vals),
                            _codec.encode_int(h)))
                    else:
                        expect.add((
                            tablecodec.index_key(info.id, idx.id, vals,
                                                 handle=h), b"0"))
                got = actual.get(idx.id, set())
                if got != expect:
                    missing = len(expect - got)
                    extra = len(got - expect)
                    raise SQLError(
                        f"admin check table {info.name} index "
                        f"{idx.name}: {missing} missing and {extra} "
                        f"unexpected index entries")
        return ResultSet(columns=["info"],
                         rows=[("check passed",)])

    # -- privileges (ref: privilege/privileges/privileges.go:56
    # RequestVerification, wired at plan time via visitInfo in the
    # reference's optimizer, plan/optimizer.go:73-77) ------------------------

    def _check_privileges(self, stmt) -> None:
        if self.internal:
            return
        from tidb_tpu.privilege import Priv
        ischema = self.domain.info_schema()
        if not ischema.has_db("mysql"):
            return   # bootstrap-less library mode: no grant tables yet
        cache = self.domain.priv_cache()

        def deny(what: str):
            raise SQLError(
                f"{what} command denied to user '{self.user}'@"
                f"'{self.host}'")

        def need(db: str, table: str, want: int, what: str):
            if not cache.request_verification(self.user, self.host,
                                              (db or "").lower(),
                                              (table or "").lower(), want):
                deny(what)

        if isinstance(stmt, (ast.CreateUserStmt, ast.DropUserStmt)):
            need("", "", Priv.CREATE_USER, "CREATE USER")
            return
        if isinstance(stmt, ast.SetPasswordStmt):
            # SET PASSWORD without FOR changes the session's own matched
            # account; ANY FOR form needs CREATE USER (stricter than
            # MySQL's current_user() carve-out, never laxer: a
            # same-username different-host account is a DIFFERENT
            # account)
            if stmt.user is not None:
                need("", "", Priv.CREATE_USER, "SET PASSWORD")
            return
        if isinstance(stmt, (ast.GrantStmt, ast.RevokeStmt)):
            # GRANT at the statement's own scope suffices (MySQL: you
            # may grant onward anything you hold WITH GRANT OPTION at
            # that scope; the hierarchy check handles global > db)
            gdb = "" if stmt.db == "*" else \
                (stmt.db or self.current_db or "").lower()
            gtbl = "" if stmt.table == "*" else (stmt.table or "").lower()
            need(gdb, gtbl, Priv.GRANT, "GRANT")
            return
        if isinstance(stmt, (ast.SelectStmt, ast.UnionStmt,
                             ast.AnalyzeStmt)):
            for db, tbl in _referenced_tables(stmt):
                db = (db or self.current_db or "").lower()
                if db in ("information_schema", "performance_schema"):
                    continue   # catalog metadata is world-readable
                need(db, tbl, Priv.SELECT, "SELECT")
            return
        if isinstance(stmt, ast.SplitTableStmt):
            need("", "", Priv.SUPER, "SPLIT TABLE")
            return
        if isinstance(stmt, ast.KillStmt):
            return   # target resolved ONCE in _exec_kill (no TOCTOU)
        if isinstance(stmt, ast.LoadDataStmt) and not stmt.local:
            # server-side file read: gated like MySQL's global FILE priv
            # (SUPER here) so table INSERT alone can't read server files
            need("", "", Priv.SUPER, "LOAD DATA INFILE (FILE)")
        if isinstance(stmt, ast.DeleteStmt) and stmt.targets:
            # multi-table DELETE: DELETE on every target, SELECT on
            # every table read by the join
            def _tdb(ts):
                return ((ts.db or self.current_db) or "").lower()
            for ts in stmt.targets:
                need(_tdb(ts), ts.name.lower(), Priv.DELETE, "DELETE")

            # the generic walker covers the join tree, ON-clause
            # subqueries, and WHERE subqueries alike
            for db, tbl in _referenced_tables([stmt.refs, stmt.where]):
                need(db or self.current_db, tbl, Priv.SELECT, "SELECT")
            return
        if isinstance(stmt, ast.UpdateStmt) and \
                not isinstance(stmt.table, ast.TableSource):
            # multi-table UPDATE: UPDATE+SELECT on every joined table
            # (conservative superset of MySQL's assigned-only UPDATE),
            # SELECT on tables read by WHERE/SET subqueries
            for db, tbl in _referenced_tables([stmt.table]):
                need(db or self.current_db, tbl, Priv.UPDATE, "UPDATE")
                need(db or self.current_db, tbl, Priv.SELECT, "SELECT")
            for db, tbl in _referenced_tables(
                    [stmt.where, stmt.assignments]):
                need(db or self.current_db, tbl, Priv.SELECT, "SELECT")
            return
        if isinstance(stmt, (ast.InsertStmt, ast.UpdateStmt,
                             ast.DeleteStmt, ast.LoadDataStmt)):
            want, what = {
                ast.InsertStmt: (Priv.INSERT, "INSERT"),
                ast.UpdateStmt: (Priv.UPDATE, "UPDATE"),
                ast.DeleteStmt: (Priv.DELETE, "DELETE"),
                ast.LoadDataStmt: (Priv.INSERT, "LOAD DATA"),
            }[type(stmt)]
            target = stmt.table
            tdb = (((target.db or self.current_db) or "") if
                   isinstance(target, ast.TableSource) else
                   (self.current_db or ""))
            tname = (target.name.lower()
                     if isinstance(target, ast.TableSource) else "")
            need(tdb, tname, want, what)
            # reading columns needs SELECT: a WHERE on the target (MySQL
            # checks column reads; a bare UPDATE t SET a=1 needs none)
            if getattr(stmt, "where", None) is not None:
                need(tdb, tname, Priv.SELECT, "SELECT")
            # every table in a READ position needs SELECT — the target
            # included when subqueries in WHERE / SET / VALUES / ON
            # DUPLICATE or an INSERT ... SELECT source read from it
            read_positions = [getattr(stmt, "where", None),
                              getattr(stmt, "select", None),
                              getattr(stmt, "values", None),
                              getattr(stmt, "assignments", None),
                              getattr(stmt, "on_duplicate", None)]
            for db, tbl in _referenced_tables(read_positions):
                need(db or self.current_db, tbl, Priv.SELECT, "SELECT")
            return
        if isinstance(stmt, ast.SetStmt):
            if any(getattr(a, "is_global", False)
                   for a in stmt.assignments):
                # only GLOBAL mutates shared state; session-scope SET of
                # registry variables shadows per session and is free
                need("", "", Priv.SUPER, "SUPER (SET GLOBAL)")
            return
        if isinstance(stmt, (ast.CreateDatabaseStmt, ast.DropDatabaseStmt)):
            # check against the TARGET database, not the session's current
            want = Priv.CREATE if isinstance(stmt, ast.CreateDatabaseStmt) \
                else Priv.DROP
            need(stmt.name, "", want, "DDL")
            return
        ddl_privs = {ast.CreateTableStmt: Priv.CREATE,
                     ast.CreateIndexStmt: Priv.INDEX,
                     ast.DropTableStmt: Priv.DROP,
                     ast.DropIndexStmt: Priv.INDEX,
                     ast.AlterTableStmt: Priv.ALTER,
                     ast.TruncateTableStmt: Priv.DROP,
                     ast.RenameTableStmt: Priv.ALTER}
        want = ddl_privs.get(type(stmt))
        if want is not None:
            for db, tbl in _referenced_tables(stmt) or [("", "")]:
                need(db or self.current_db, tbl, want, "DDL")
        # SHOW / SET / EXPLAIN / txn control / prepared mgmt: unchecked
        # (EXPLAIN checks happen when the prepared/inner stmt runs)

    # -- account management (ref: executor/grant.go, executor/simple.go
    # CREATE USER / DROP USER) ------------------------------------------------

    def _account_session(self) -> "Session":
        return Session(self.storage, db="mysql", internal=True)

    def _exec_account(self, stmt):
        from tidb_tpu.privilege import (ALL_PRIVS, PRIV_BY_NAME,
                                        encode_password)
        s = self._account_session()
        try:
            if isinstance(stmt, ast.SetPasswordStmt):
                if stmt.user is not None:
                    user, host = stmt.user.user, stmt.user.host
                else:
                    # own account: the MOST SPECIFIC stored row whose
                    # host pattern matches this session (CURRENT_USER()
                    # semantics: exact host beats patterns beats '%')
                    from tidb_tpu.privilege import _host_match
                    user = self.user or ""
                    my_host = self.host or ""
                    candidates = [
                        h for (h,) in s.query(
                            "SELECT host FROM mysql.user WHERE user = "
                            f"'{_q(user)}'").rows
                        if _host_match(h, my_host)]
                    if not candidates:
                        raise SQLError(
                            f"no account matches '{user}'@'{my_host}'")
                    candidates.sort(
                        key=lambda h: (h != my_host, h == "%",
                                       -len(h)))
                    host = candidates[0]
                if not s.query("SELECT user FROM mysql.user WHERE user ="
                               f" '{_q(user)}' AND host = '{_q(host)}'"
                               ).rows:
                    raise SQLError(
                        f"user '{user}'@'{host}' does not exist")
                auth = encode_password(stmt.password)
                s.execute("UPDATE mysql.user SET authentication_string ="
                          f" '{auth}' WHERE user = '{_q(user)}' AND "
                          f"host = '{_q(host)}'")
            elif isinstance(stmt, ast.CreateUserStmt):
                for u in stmt.users:
                    exists = s.query(
                        "SELECT user FROM mysql.user WHERE user = "
                        f"'{_q(u.user)}' AND host = '{_q(u.host)}'").rows
                    if exists:
                        if stmt.if_not_exists:
                            continue
                        raise SQLError(f"user '{u.user}'@'{u.host}' "
                                       "already exists")
                    auth = encode_password(u.password or "")
                    s.execute("INSERT INTO mysql.user VALUES "
                              f"('{_q(u.host)}', '{_q(u.user)}', "
                              f"'{auth}', 0)")
            elif isinstance(stmt, ast.DropUserStmt):
                for u in stmt.users:
                    exists = s.query(
                        "SELECT user FROM mysql.user WHERE user = "
                        f"'{_q(u.user)}' AND host = '{_q(u.host)}'").rows
                    if not exists and not stmt.if_exists:
                        raise SQLError(f"user '{u.user}'@'{u.host}' "
                                       "does not exist")
                    cond = (f"user = '{_q(u.user)}' AND "
                            f"host = '{_q(u.host)}'")
                    s.execute(f"DELETE FROM mysql.user WHERE {cond}")
                    s.execute(f"DELETE FROM mysql.db WHERE {cond}")
                    s.execute(
                        f"DELETE FROM mysql.tables_priv WHERE {cond}")
            else:
                is_grant = isinstance(stmt, ast.GrantStmt)
                bits = 0
                for p in stmt.privs:
                    bits |= PRIV_BY_NAME[p]
                db = stmt.db if stmt.db != "" else self.current_db
                if not db:
                    raise SQLError("No database selected")
                for u in stmt.users:
                    if not s.query(
                            "SELECT user FROM mysql.user WHERE user = "
                            f"'{_q(u.user)}' AND host = "
                            f"'{_q(u.host)}'").rows:
                        raise SQLError(
                            f"user '{u.user}'@'{u.host}' does not exist")
                    self._apply_grant(s, u, db.lower(), stmt.table.lower(),
                                      bits, is_grant)
        finally:
            s.close()
            # ALWAYS invalidate: a mid-loop error may follow committed
            # writes (autocommit per internal statement)
            self.domain.priv_cache().invalidate()
        return None

    @staticmethod
    def _apply_grant(s: "Session", u, db: str, table: str, bits: int,
                     is_grant: bool) -> None:
        cond = f"user = '{_q(u.user)}' AND host = '{_q(u.host)}'"
        if db == "*":                     # global level -> mysql.user
            tbl, cond2, ins = "mysql.user", cond, None
        elif table == "*":                # db level -> mysql.db
            tbl = "mysql.db"
            cond2 = cond + f" AND db = '{_q(db)}'"
            ins = (f"INSERT INTO mysql.db VALUES ('{_q(u.host)}', "
                   f"'{_q(u.user)}', '{_q(db)}', {{privs}})")
        else:                             # table level -> mysql.tables_priv
            tbl = "mysql.tables_priv"
            cond2 = cond + (f" AND db = '{_q(db)}' AND table_name = "
                            f"'{_q(table)}'")
            ins = (f"INSERT INTO mysql.tables_priv VALUES ('{_q(u.host)}',"
                   f" '{_q(u.user)}', '{_q(db)}', '{_q(table)}', "
                   "{privs}")
            ins += ")"
        rows = s.query(f"SELECT privs FROM {tbl} WHERE {cond2}").rows
        cur = int(rows[0][0]) if rows else 0
        new = (cur | bits) if is_grant else (cur & ~bits)
        if rows:
            if new == cur:
                return
            if new == 0 and tbl != "mysql.user":
                s.execute(f"DELETE FROM {tbl} WHERE {cond2}")
            else:
                s.execute(f"UPDATE {tbl} SET privs = {new} WHERE {cond2}")
        elif is_grant and ins is not None:
            s.execute(ins.format(privs=new))

    # -- queries -------------------------------------------------------------

    def _planner(self) -> Planner:
        # storage hands the planner the membership registry: the
        # information_schema.cluster_* memtables enumerate live members
        # from it and fan their /cluster/state fetches out at plan time
        return Planner(self.domain.info_schema(), self.current_db,
                       stats_handle=self.domain.stats_handle(),
                       storage=self.storage)

    def _stats_collector(self):
        """Active (or fresh) per-statement runtime-stats collector, None
        for internal sessions or with tidb_tpu_runtime_stats=0. EXPLAIN
        ANALYZE installs its own collector before dispatching the inner
        statement; that one wins (rs.current())."""
        from tidb_tpu import config, runtime_stats as rs
        if self.internal:
            # never instrument internal catalog sessions, even when a
            # client statement's collector is active on this thread
            return None
        active = rs.current()
        if active is not None:
            return active
        if not config.runtime_stats_enabled():
            return None
        return rs.StatsCollector(device=config.runtime_stats_device())

    def _exec_query(self, stmt, sql_text: str | None = None) -> ResultSet:
        from tidb_tpu import runtime_stats as rs, trace
        if getattr(stmt, "for_update", False) and self.txn is None and \
                not self.autocommit:
            # autocommit=0: the SELECT starts the transaction, so the
            # locks actually hold until COMMIT (MySQL semantics)
            self._begin_txn()
        plan = None
        cache_key = None
        if sql_text is not None and isinstance(stmt, (ast.SelectStmt,
                                                      ast.UnionStmt)):
            from tidb_tpu import devplane as mesh_config
            cache_key = (sql_text, self.current_db,
                         self.domain.info_schema().version,
                         self.domain.stats_handle().version,
                         mesh_config.mesh_generation())
            plan = self.domain.plan_cache().get(cache_key)
        if plan is None:
            with trace.span("plan", cached=False):
                planner = self._planner()
                try:
                    plan = planner.plan(stmt)
                except (PlanError, ResolveError) as e:
                    raise SQLError(str(e)) from None
                # degraded-but-answered notes (cluster_* fan-out with
                # an unreachable member) surface via SHOW WARNINGS; the
                # cluster memtables are cacheable=False, so a cache hit
                # can never skip a fan-out that would have warned
                for w in planner.warnings:
                    self.add_warning(*w)
            if cache_key is not None and _plan_cacheable(plan):
                self.domain.plan_cache().put(cache_key, plan)
        ctx = ExecContext(self.storage, self._read_ts(), self.txn,
                          interrupted=lambda: self.killed)
        coll = self._stats_collector()
        self._last_plan = plan
        try:
            with rs.collecting(coll):
                exe = build_executor(plan)
                with trace.span("execute",
                                executor=type(exe).__name__):
                    chunks = []
                    for ch in exe.chunks(ctx):
                        if self.killed:   # KILL QUERY: cooperative check
                            raise SQLError(
                                "Query execution was interrupted")
                        chunks.append(ch)
        except ExecError as e:
            raise SQLError(str(e)) from None
        finally:
            self._last_stats = coll
        if getattr(stmt, "for_update", False) and self.txn is not None:
            try:
                self._lock_rows_for_update(stmt)
            except ExecError as e:
                raise SQLError(str(e)) from None
        self._check_nested_for_update(stmt)
        names = [c.name for c in plan.schema.cols]
        rows = []
        for ch in chunks:
            rows.extend(_format_chunk(ch))
        return ResultSet(columns=names, rows=rows,
                         field_types=[c.ft for c in plan.schema.cols])

    # -- DML -----------------------------------------------------------------

    def _exec_dml(self, stmt) -> int:
        in_txn = self.txn is not None
        self._begin_txn()
        # statement-level atomicity: snapshot the write buffer so a failed
        # statement rolls back ITS writes without killing the txn
        # (ref: StmtCommit/StmtRollback semantics)
        saved = self.txn.us.membuf._d.copy()
        saved_size = self.txn.us.membuf.size
        saved_presumed = set(self.txn.us.presumed_not_exists)
        try:
            n = self._exec_dml_in_txn(stmt)
        except Exception:
            if self.txn is not None:
                self.txn.us.membuf._d = saved
                self.txn.us.membuf.size = saved_size
                self.txn.us.presumed_not_exists = saved_presumed
            if not in_txn and not self.autocommit:
                pass  # keep the implicit txn open
            elif not in_txn:
                self._rollback()
            raise
        self._history.append(stmt)
        self._note_dml_delta(stmt, n)
        if not in_txn and self.autocommit:
            self._commit()
        return n

    def _exec_dml_in_txn(self, stmt) -> int:
        from tidb_tpu import runtime_stats as rs, trace
        if isinstance(stmt, ast.LoadDataStmt):
            with trace.span("execute", executor="LoadData"):
                return self._load_data_in_txn(stmt)
        with trace.span("plan"):
            try:
                plan = self._planner().plan(stmt)
            except (PlanError, ResolveError) as e:
                raise SQLError(str(e)) from None
        from tidb_tpu.plan import physical as _ph
        if isinstance(plan, (_ph.PhysInsert, _ph.PhysUpdate,
                             _ph.PhysDelete)):
            # schema validation scope: tables this txn WRITES
            self.txn.related_tables.add(plan.table.id)
        elif isinstance(plan, _ph.PhysMultiDelete):
            for info, _cs, _hi in plan.targets:
                self.txn.related_tables.add(info.id)
        ctx = ExecContext(self.storage, self.txn.start_ts, self.txn,
                          interrupted=lambda: self.killed)
        coll = self._stats_collector()
        self._last_plan = plan
        try:
            with rs.collecting(coll):
                exe = build_executor(plan)
                with trace.span("execute", executor=type(exe).__name__):
                    out = exe.execute(ctx)
            lid = getattr(ctx, "last_insert_id", None)
            if lid is not None:
                self.last_insert_id = lid
            return out
        except ExecError as e:
            raise SQLError(str(e)) from None
        finally:
            self._last_stats = coll

    # session-context expressions (ref: expression/builtin_info.go
    # VERSION/USER/DATABASE/CONNECTION_ID; sessionctx sysvar reads) ----------

    _SESSION_FUNCS = ("VERSION", "USER", "SESSION_USER", "SYSTEM_USER",
                      "CURRENT_USER", "CONNECTION_ID", "DATABASE",
                      "SCHEMA", "LAST_INSERT_ID")
    _CLIENT_SYSVAR_DEFAULTS = {
        "version_comment": "tidb-tpu",
        "character_set_client": "utf8mb4",
        "character_set_results": "utf8mb4",
        "character_set_connection": "utf8mb4",
        "collation_connection": "utf8mb4_bin",
        "collation_server": "utf8mb4_bin",
        "max_allowed_packet": 67108864,
        "wait_timeout": 28800,
        "interactive_timeout": 28800,
        "lower_case_table_names": 1,
        "time_zone": "SYSTEM",
        "tx_isolation": "REPEATABLE-READ",
        "transaction_isolation": "REPEATABLE-READ",
    }

    def _session_expr_value(self, e):
        """-> (handled, value) for @@vars / @vars / session funcs."""
        from tidb_tpu import config
        if isinstance(e, ast.VariableExpr):
            if not e.is_system:
                return True, self.vars.get(
                    "@" + e.name.lstrip("@").lower())
            name = e.name.lower()
            if name in self.sys_vars and not e.is_global:
                return True, self.sys_vars[name]
            if config.is_known(name):
                return True, config.get_var(name)
            if name == "version":
                from tidb_tpu.server import SERVER_VERSION
                return True, SERVER_VERSION
            if name == "tidb_current_ts":
                # start ts of the open txn, 0 outside one (ref:
                # sessionctx/variable TiDBCurrentTS)
                return True, (self.txn.start_ts
                              if self.txn is not None else 0)
            if name in self._CLIENT_SYSVAR_DEFAULTS:
                return True, self._CLIENT_SYSVAR_DEFAULTS[name]
            raise SQLError(f"Unknown system variable '{e.name}'")
        if isinstance(e, ast.FuncCall) and \
                e.name.upper() in self._SESSION_FUNCS and not e.args:
            n = e.name.upper()
            if n == "VERSION":
                from tidb_tpu.server import SERVER_VERSION
                return True, SERVER_VERSION
            if n in ("USER", "SESSION_USER", "SYSTEM_USER",
                     "CURRENT_USER"):
                return True, f"{self.user}@{self.host}"
            if n == "CONNECTION_ID":
                return True, self.session_id
            if n == "LAST_INSERT_ID":
                return True, getattr(self, "last_insert_id", 0)
            return True, self.current_db or None   # DATABASE/SCHEMA
        return False, None

    def _eval_scalar_expr(self, e):
        """Evaluate a table-free AST expression to a python value (used
        by @v := assignments)."""
        import numpy as np
        from tidb_tpu import sqltypes as st2
        from tidb_tpu.expression.core import Constant
        from tidb_tpu.plan.resolver import PlanSchema, Resolver, \
            ResolveError

        def unwrap(val, ft):
            if val is None:
                return None
            if ft.eval_type == st2.EvalType.DECIMAL and ft.frac > 0:
                return st2.scaled_to_decimal(int(val), ft.frac)
            if isinstance(val, (np.integer,)):
                return int(val)
            if isinstance(val, np.floating):
                return float(val)
            return val

        try:
            r = Resolver(PlanSchema([])).resolve(e)
            if isinstance(r, Constant):
                return unwrap(r.value, r.ft)
            data, valid = r.eval_xp(np, [], 1)
        except (ResolveError, ExecError) as ex:
            # keep the SQLError API contract for @v := <bad expr>
            raise SQLError(str(ex)) from None
        if not bool(np.asarray(valid)[0]):
            return None
        return unwrap(np.asarray(data)[0], r.ft)

    def _fold_session_exprs(self, node):
        """Rebuild the AST with session-context expressions folded to
        literals (persistent: shared prepared-statement trees are never
        mutated). -> (node, changed)."""
        import dataclasses
        changed = False

        def walk(x):
            nonlocal changed
            if isinstance(x, ast.VarAssignExpr):
                # @v := expr: fold inner session refs, evaluate once per
                # statement (constant contexts — MySQL's per-row variable
                # reuse inside table scans is out of scope) and store
                val = self._eval_scalar_expr(walk(x.value))
                self.vars["@" + x.name.lstrip("@").lower()] = val
                changed = True
                return ast.Literal(val)
            if isinstance(x, ast.ExprNode):
                handled, val = self._session_expr_value(x)
                if handled:
                    changed = True
                    return ast.Literal(val)
            if dataclasses.is_dataclass(x) and isinstance(x, ast.Node):
                updates = {}
                for f in dataclasses.fields(x):
                    v = getattr(x, f.name)
                    nv = walk(v)
                    if nv is not v:
                        updates[f.name] = nv
                return dataclasses.replace(x, **updates) if updates else x
            if isinstance(x, list):
                out = [walk(v) for v in x]
                return out if any(a is not b for a, b in zip(out, x)) \
                    else x
            if isinstance(x, tuple):
                out = tuple(walk(v) for v in x)
                return out if any(a is not b for a, b in zip(out, x)) \
                    else x
            return x

        return walk(node), changed

    def _check_nested_for_update(self, stmt) -> None:
        """FOR UPDATE buried in a UNION branch, derived table or
        subquery would silently take no locks — refuse loudly."""
        import dataclasses

        def walk(x, top):
            if isinstance(x, ast.SelectStmt) and not top and \
                    x.for_update:
                raise SQLError("FOR UPDATE is only supported on "
                               "single-table queries")
            if dataclasses.is_dataclass(x) and isinstance(x, ast.Node):
                for f in dataclasses.fields(x):
                    walk(getattr(x, f.name), False)
            elif isinstance(x, (list, tuple)):
                for v in x:
                    walk(v, False)

        walk(stmt, isinstance(stmt, ast.SelectStmt))

    def _lock_rows_for_update(self, stmt) -> None:
        """SELECT ... FOR UPDATE inside a txn: lock every row the scan
        MATCHES (ref: executor/executor.go:389 SelectLockExec — keys
        buffered in the txn, conflict-checked at commit). Locks the full
        WHERE match even under LIMIT — stricter than the rows returned,
        like InnoDB locking every scanned row — via a second scan of the
        filter (the result plan may be an agg/projection with no
        handles)."""
        src = stmt.from_clause
        if src is None:
            return                # SELECT 1 FOR UPDATE: nothing to lock
        if not isinstance(src, ast.TableSource):
            # silently taking no locks would break the FOR UPDATE
            # promise — refuse loudly (the reference no-ops when no
            # handle exists; we choose the honest error)
            raise SQLError(
                "FOR UPDATE is only supported on single-table queries")
        try:
            info, reader = self._planner()._plan_writable_reader(
                src, stmt.where)
        except (PlanError, ResolveError) as e:
            raise SQLError(str(e)) from None
        self.txn.related_tables.add(info.id)
        ctx = ExecContext(self.storage, self.txn.start_ts, self.txn,
                          interrupted=lambda: self.killed)
        exe = build_executor(reader)
        for chunk in exe.chunks(ctx):
            hc = chunk.columns[-1]
            for i in range(chunk.num_rows):
                self.txn.lock_key(tablecodec.record_key(
                    info.id, int(hc.data[i])))

    # -- LOAD DATA (ref: executor/write.go:1373 LoadDataExec) ----------------

    def _load_data_in_txn(self, stmt: ast.LoadDataStmt) -> int:
        from tidb_tpu.executor.loaddata import (RowsInsertExec,
                                                convert_fields, parse_lines,
                                                read_text_chunks)
        info = self._resolve_table_or_err(stmt.table)
        col_names = [c.lower() for c in stmt.columns] \
            or [c.name.lower() for c in info.public_columns()]
        try:
            f = open(stmt.path, "r", encoding="utf-8", newline="")
        except OSError as e:
            raise SQLError(f"Can't get stat of '{stmt.path}': {e}") from None
        with f:
            self.txn.related_tables.add(info.id)
            ctx = ExecContext(self.storage, self.txn.start_ts, self.txn,
                              interrupted=lambda: self.killed)

            def rows():
                for i, fields in enumerate(
                        parse_lines(read_text_chunks(f), stmt)):
                    if i % 1024 == 0:
                        ctx.check_interrupt()
                    yield convert_fields(info, col_names, fields)

            return RowsInsertExec(info, rows(), stmt.dup_mode).execute(ctx)

    # -- KILL (ref: ast/misc.go:341 KillStmt; server.go:333 Kill) ------------

    def _exec_kill(self, stmt: ast.KillStmt) -> None:
        with _session_seq_lock:
            live = list(_SESSIONS)
        target = next((s for s in live
                       if s.session_id == stmt.conn_id), None)
        if target is None:
            raise SQLError(f"Unknown thread id: {stmt.conn_id}")
        # privilege check on the RESOLVED target (the pre-exec check
        # would race a new connection claiming the id)
        if target.user != self.user and not self.internal:
            from tidb_tpu.privilege import Priv
            ischema = self.domain.info_schema()
            if ischema.has_db("mysql") and not \
                    self.domain.priv_cache().request_verification(
                        self.user, self.host, "", "", Priv.SUPER):
                raise SQLError(
                    f"KILL command denied to user "
                    f"'{self.user}'@'{self.host}'")
        target.killed = True
        if not stmt.query_only:
            hook = target.kill_hook
            if hook is not None:
                try:
                    hook()            # server closes the connection
                except Exception:     # noqa: BLE001
                    pass
        return None

    # -- TRACE (ref: the reference's TRACE statement rendering its
    # per-statement span tree, executor/trace.go) ----------------------------

    def _exec_trace(self, stmt: ast.TraceStmt) -> ResultSet:
        """Execute the inner statement under THIS statement's (forced)
        trace root — admission, scheduler-slot, dispatch/finalize and
        worker spans all land on one tree — then render that tree: row
        form is the operator-facing indented table, json form one
        document (also retained in the ring under the returned
        trace_id, so GET /trace/<id> serves the same tree)."""
        from tidb_tpu import trace
        inner = stmt.stmt
        if isinstance(inner, ast.TraceStmt):
            raise SQLError("TRACE statements cannot nest")
        self._run_stmt(inner)    # result discarded: the tree IS the output
        root = trace.current_root()
        if root is None:
            raise SQLError("TRACE: no statement trace is active")
        tid = trace.ensure_id(root)
        snap = trace.tree(root)
        if stmt.format == "json":
            import json as _json
            return ResultSet(
                ["trace"],
                [(_json.dumps({"trace_id": tid, "spans": snap}),)])
        rows: list[tuple] = []

        def walk(d: dict, depth: int) -> None:
            op = "  " * depth + d["name"]
            tags = d.get("tags")
            if tags:
                op += " " + " ".join(f"{k}={v}" for k, v in
                                     sorted(tags.items()))
            rows.append((op, f"{d['start_us'] / 1e3:.3f}ms",
                         f"{d['duration_us'] / 1e3:.3f}ms"))
            for ev in d.get("events", ()):
                rows.append(("  " * (depth + 1) + "! " + ev["name"],
                             f"{ev['at_us'] / 1e3:.3f}ms", "-"))
            for c in d.get("children", ()):
                walk(c, depth + 1)

        walk(snap, 0)
        return ResultSet(["operation", "start", "duration"], rows)

    # -- SPLIT TABLE (ref: store/tikv/split_region.go:29; mocktikv
    # cluster.go:276 Split/SplitTable) ---------------------------------------

    def _exec_split_table(self, stmt: ast.SplitTableStmt) -> ResultSet:
        info = self._resolve_table_or_err(stmt.table)
        cluster = getattr(self.storage, "cluster", None)
        if cluster is None:
            raise SQLError("storage does not support region split")
        if stmt.regions:
            done = cluster.split_table(info.id, stmt.regions)
        else:
            done = 0
            for e in stmt.at_values:
                if not isinstance(e, ast.Literal) or \
                        not isinstance(e.value, int):
                    raise SQLError("SPLIT TABLE AT takes integer literals")
                try:
                    cluster.split(
                        tablecodec.record_key(info.id, int(e.value)))
                    done += 1
                except ValueError:   # already a region boundary
                    pass
        return ResultSet(["TOTAL_SPLIT_REGION"], [(done,)])

    # -- SET / SHOW / EXPLAIN ------------------------------------------------

    def _exec_set(self, stmt: ast.SetStmt):
        import dataclasses
        from tidb_tpu.plan.resolver import PlanSchema, Resolver
        r = Resolver(PlanSchema([]))
        for a in stmt.assignments:
            # fold user-var reads PER assignment, after the previous
            # ones applied: SET @a = 1, @b = @a + 1 is left-to-right
            if isinstance(a.value, ast.ExprNode):
                nv, changed = self._fold_session_exprs(a.value)
                if changed:
                    a = dataclasses.replace(a, value=nv)
            if isinstance(a.value, ast.ColName):
                val = a.value.name  # bare words like STRICT
            else:
                e = r.resolve(a.value)
                import numpy as np
                d, v = e.eval_xp(np, [], 1)
                if not v[0]:
                    val = None
                elif e.ft.eval_type == EvalType.DECIMAL:
                    # chunk layer stores scaled ints: unscale for the var
                    val = scaled_to_decimal(int(d[0]), e.ft.frac)
                else:
                    val = d[0].item() if hasattr(d[0], "item") else d[0]
            if a.is_system:
                from tidb_tpu import config
                if config.is_known(a.name):
                    # registry knobs (ref: sessionctx/variable/sysvar.go):
                    # GLOBAL writes the process registry; session scope
                    # shadows it via a per-statement overlay
                    try:
                        val = config.coerce(a.name, val)
                    except (TypeError, ValueError):
                        raise SQLError(
                            f"invalid value for @@{a.name}: {val!r}") \
                            from None
                    if getattr(a, "is_global", False):
                        config.set_var(a.name, val)
                    elif config.is_global_only(a.name):
                        # session-scope SET would shadow the value on
                        # this thread while the on_change side effect
                        # (failpoint arming) never fires — a chaos
                        # schedule that LOOKS armed but isn't. MySQL
                        # semantics: GLOBAL-only variables reject
                        # session writes
                        raise SQLError(
                            f"Variable '{a.name}' is a GLOBAL variable "
                            f"and should be set with SET GLOBAL")
                if getattr(a, "is_global", False):
                    # GLOBAL never touches the session scope (MySQL)
                    self._persist_global_var(a.name.lower(), val)
                else:
                    self.sys_vars[a.name.lower()] = val
                    if a.name.lower() == "autocommit":
                        self.autocommit = bool(int(val)) \
                            if val is not None else True
            else:
                self.vars[a.name.lower()] = val
        return None

    def _persist_global_var(self, name: str, val) -> None:
        """SET GLOBAL persists into mysql.global_variables (ref:
        session.go:588-640 SetGlobalSysVar) when the catalog exists."""
        if not self.domain.info_schema().has_db("mysql"):
            return
        s = Session(self.storage, db="mysql", internal=True)
        try:
            cond = f"variable_name = '{_q(name)}'"
            if s.query("SELECT variable_name FROM mysql.global_variables "
                       f"WHERE {cond}").rows:
                s.execute("UPDATE mysql.global_variables SET "
                          f"variable_value = '{_q(str(val))}' WHERE {cond}")
            else:
                s.execute("INSERT INTO mysql.global_variables VALUES "
                          f"('{_q(name)}', '{_q(str(val))}')")
        finally:
            s.close()

    @staticmethod
    def _filter_show_rows(rs: "ResultSet", where) -> "ResultSet":
        """Minimal SHOW ... WHERE evaluator: `col = literal` conjuncts
        over the result columns (the shape the reference's SHOW WHERE
        sees in practice)."""
        conds = []

        def walk(e):
            if isinstance(e, ast.BinaryOp) and e.op.upper() == "AND":
                walk(e.left)
                walk(e.right)
                return
            if isinstance(e, ast.BinaryOp) and e.op == "=" and \
                    isinstance(e.left, ast.ColName) and \
                    isinstance(e.right, ast.Literal):
                conds.append((e.left.name.lower(), e.right.value))
                return
            raise SQLError("unsupported SHOW ... WHERE (use col = "
                           "literal [AND ...])")

        walk(where)
        lower = [c.lower() for c in rs.columns]
        idx = []
        for name, val in conds:
            if name not in lower:
                raise SQLError(f"unknown column '{name}' in SHOW WHERE")
            idx.append((lower.index(name), val))
        # SHOW result columns carry utf8 ci collation in MySQL, so the
        # value comparison is case-insensitive
        rows = [r for r in rs.rows
                if all(str(r[i]).lower() == str(v).lower()
                       for i, v in idx)]
        return ResultSet(rs.columns, rows)

    def _show_stats(self, stmt: ast.ShowStmt) -> ResultSet:
        """SHOW STATS_META / STATS_HISTOGRAMS / STATS_BUCKETS (ref: the
        reference's statistics memtables surfaced through SHOW). WHERE
        filters on the text columns apply post-projection."""
        import datetime as _dt2
        handle = self.domain.stats_handle()
        is_ = self.domain.info_schema()
        meta_rows, hist_rows, bucket_rows = [], [], []
        for dbn in is_.db_names():
            if dbn.lower() in ("mysql",):
                continue
            for tn in is_.table_names(dbn):
                info = is_.table(dbn, tn)
                ts = handle.get(info.id)
                if ts.pseudo:
                    continue
                # stats version is a hybrid TSO ts: physical ms << 18
                upd = _dt2.datetime.fromtimestamp(
                    (ts.version >> 18) / 1e3).strftime(
                    "%Y-%m-%d %H:%M:%S") if ts.version else ""
                meta_rows.append((dbn, tn, upd, ts.modify_count,
                                  ts.count))
                for cid, cs in ts.columns.items():
                    col = next((c for c in info.columns if c.id == cid),
                               None)
                    h = getattr(cs, "hist", None) or getattr(
                        cs, "histogram", None)
                    if col is None:
                        continue
                    ndv = getattr(h, "ndv", 0) if h else 0
                    nulls = getattr(h, "null_count", 0) if h else 0
                    hist_rows.append((dbn, tn, col.name, 0, upd, ndv,
                                      nulls))
                    if h:
                        for bi in range(len(h.uppers)):
                            cnt = h.counts[bi] - (h.counts[bi - 1]
                                                  if bi else 0)
                            bucket_rows.append(
                                (dbn, tn, col.name, 0, bi, cnt,
                                 str(h.lowers[bi]), str(h.uppers[bi])))
        if stmt.tp == "stats_meta":
            rs = ResultSet(["Db_name", "Table_name", "Update_time",
                            "Modify_count", "Row_count"], meta_rows)
        elif stmt.tp == "stats_histograms":
            rs = ResultSet(["Db_name", "Table_name", "Column_name",
                            "Is_index", "Update_time", "Distinct_count",
                            "Null_count"], hist_rows)
        else:
            rs = ResultSet(["Db_name", "Table_name", "Column_name",
                            "Is_index", "Bucket_id", "Count",
                            "Lower_Bound", "Upper_Bound"], bucket_rows)
        if stmt.where is not None:
            rs = self._filter_show_rows(rs, stmt.where)
        return rs

    def _exec_show(self, stmt: ast.ShowStmt) -> ResultSet:
        ischema = self.domain.info_schema()
        if stmt.tp == "databases":
            return ResultSet(["Database"],
                             [(n,) for n in ischema.db_names()])
        if stmt.tp == "tables":
            db = stmt.db or self.current_db
            if db.lower() == "information_schema":
                from tidb_tpu.plan.planner import Planner as _P
                return ResultSet([f"Tables_in_{db}"],
                                 [(n,) for n in _P._MEMTABLES])
            if db.lower() == "performance_schema":
                from tidb_tpu.plan.planner import Planner as _P
                return ResultSet([f"Tables_in_{db}"],
                                 [(n,) for n in _P._PERF_TABLES])
            try:
                names = ischema.table_names(db)
            except SchemaError as e:
                raise SQLError(str(e)) from None
            return ResultSet([f"Tables_in_{db}"],
                             [(n,) for n in names])
        if stmt.tp == "columns":
            db = stmt.table.db or self.current_db
            t = ischema.table(db, stmt.table.name)
            rows = []
            for c in t.public_columns():
                rows.append((c.name, _type_name(c),
                             "NO" if c.ft.not_null else "YES",
                             "PRI" if (t.pk_is_handle and
                                       c.name == t.pk_col_name) else "",
                             None, ""))
            return ResultSet(["Field", "Type", "Null", "Key", "Default",
                              "Extra"], rows)
        if stmt.tp == "variables":
            from tidb_tpu import config
            # all_vars() already applies this thread's session overlay;
            # non-registry session sysvars layer on top
            merged = dict(config.all_vars())
            merged.update(self.sys_vars)
            rows = sorted((k, str(v)) for k, v in merged.items())
            if stmt.pattern:
                import re
                from tidb_tpu.expression.core import _like_to_regex
                rx = re.compile(_like_to_regex(stmt.pattern))
                rows = [r for r in rows if rx.fullmatch(r[0])]
            rs = ResultSet(["Variable_name", "Value"], rows)
            return self._filter_show_rows(rs, stmt.where) \
                if getattr(stmt, "where", None) is not None else rs
        if stmt.tp == "processlist":
            rows = []
            now = time.time()
            with _session_seq_lock:   # adds are serialized with snapshot
                live = list(_SESSIONS)
            for s in sorted(live, key=lambda x: x.session_id):
                sql = s.current_sql
                tracker = getattr(s, "mem_tracker", None)
                rm = getattr(s, "res_meter", None)
                mtot = rm.totals() if rm is not None else {}
                rows.append((s.session_id, s.user, s.host,
                             s.current_db or None,
                             "Query" if sql else "Sleep",
                             int(now - s.created_at),
                             "" if sql else None,
                             # SHOW FULL PROCESSLIST: untruncated SQL
                             ((sql or "") if stmt.full
                              else (sql or "")[:100]) or None,
                             tracker.total() if tracker is not None
                             else 0,
                             # cumulative metered work (meter.py):
                             # device busy-time in ms + rows served
                             mtot.get("device_ns", 0) // 1_000_000,
                             mtot.get("rows_sent", 0)))
            return ResultSet(["Id", "User", "Host", "db", "Command",
                              "Time", "State", "Info", "Mem",
                              "DeviceTime", "RowsSent"], rows)
        if stmt.tp == "create_table":
            db = stmt.table.db or self.current_db
            t = ischema.table(db, stmt.table.name)

            def col_sql(c):
                out = f"`{c.name}` {_type_name(c)}"
                if c.ft.is_ci:
                    # non-default collation must round-trip dump/restore
                    out += f" COLLATE {c.ft.collation}"
                if c.ft.not_null:
                    out += " NOT NULL"
                if c.auto_increment:
                    out += " AUTO_INCREMENT"
                return out

            parts = [col_sql(c) for c in t.public_columns()]
            if t.pk_is_handle and t.pk_col_name:
                parts.append(f"PRIMARY KEY (`{t.pk_col_name}`)")
            from tidb_tpu.schema.model import SchemaState
            for idx in t.indexes:
                if idx.state != SchemaState.PUBLIC:
                    continue
                cols_s = ",".join(f"`{c}`" for c in idx.columns)
                if idx.primary:
                    parts.append(f"PRIMARY KEY ({cols_s})")
                elif idx.unique:
                    parts.append(
                        f"UNIQUE KEY `{idx.name}` ({cols_s})")
                else:
                    parts.append(f"KEY `{idx.name}` ({cols_s})")
            body = ",\n  ".join(parts)
            return ResultSet(["Table", "Create Table"],
                             [(t.name,
                               f"CREATE TABLE `{t.name}` (\n  {body}\n)")])
        if stmt.tp == "index":
            from tidb_tpu.schema.model import SchemaState
            t = self._resolve_table_or_err(stmt.table)
            rows = []
            if t.pk_is_handle and t.pk_col_name:
                rows.append((t.name, 0, "PRIMARY", 1,
                             t.pk_col_name.lower(), "BTREE"))
            for idx in t.indexes:
                if idx.state != SchemaState.PUBLIC:
                    continue
                for seq, cn in enumerate(idx.columns, 1):
                    rows.append((t.name, 0 if idx.unique else 1,
                                 idx.name.lower(), seq, cn.lower(),
                                 "BTREE"))
            return ResultSet(["Table", "Non_unique", "Key_name",
                              "Seq_in_index", "Column_name",
                              "Index_type"], rows)
        if stmt.tp == "status":
            from tidb_tpu import metrics
            rows = sorted((k, str(v))
                          for k, v in metrics.snapshot().items())
            return ResultSet(["Variable_name", "Value"], rows)
        if stmt.tp == "engines":
            return ResultSet(
                ["Engine", "Support", "Comment"],
                [("tidb-tpu", "DEFAULT",
                  "MVCC KV with XLA analytical executors")])
        if stmt.tp == "collation":
            # the two implemented collations (sqltypes.FieldType.is_ci;
            # _general_ci approximated by unicode casefold)
            return ResultSet(
                ["Collation", "Charset", "Default"],
                [("utf8mb4_bin", "utf8mb4", "Yes"),
                 ("utf8mb4_general_ci", "utf8mb4", ""),
                 ("utf8_bin", "utf8", ""),
                 ("utf8_general_ci", "utf8", "")])
        if stmt.tp in ("warnings", "errors"):
            # statement diagnostics area: populated by add_warning();
            # cleanly-executed statements leave it empty, like MySQL
            rows = [(lvl, code, msg)
                    for lvl, code, msg in getattr(self, "_warnings", [])]
            return ResultSet(["Level", "Code", "Message"],
                             rows if stmt.tp == "warnings" else
                             [r for r in rows if r[0] == "Error"])
        if stmt.tp == "plugins":
            return ResultSet(["Name", "Status", "Type", "Library",
                              "License"], [])
        if stmt.tp == "profiles":
            return ResultSet(["Query_ID", "Duration", "Query"], [])
        if stmt.tp == "triggers":
            return ResultSet(["Trigger", "Event", "Table", "Statement",
                              "Timing", "Created"], [])
        if stmt.tp == "events":
            return ResultSet(["Db", "Name", "Definer", "Time zone",
                              "Type", "Status"], [])
        if stmt.tp in ("procedure_status", "function_status"):
            return ResultSet(["Db", "Name", "Type", "Definer",
                              "Modified", "Created"], [])
        if stmt.tp == "master_status":
            return ResultSet(["File", "Position", "Binlog_Do_DB",
                              "Binlog_Ignore_DB"], [])
        if stmt.tp == "charset":
            return ResultSet(
                ["Charset", "Description", "Default collation",
                 "Maxlen"],
                [("utf8mb4", "UTF-8 Unicode", "utf8mb4_bin", 4),
                 ("utf8", "UTF-8 Unicode", "utf8_bin", 3),
                 ("binary", "Binary pseudo charset", "binary", 1)])
        if stmt.tp in ("stats_meta", "stats_histograms", "stats_buckets"):
            return self._show_stats(stmt)
        if stmt.tp == "grants":
            target = stmt.pattern or (self.user or "")
            user, _, host = target.partition("@")
            is_self = user == (self.user or "") and \
                (not host or host == (self.host or ""))
            if not is_self and not self.internal:
                # viewing ANOTHER account's grants needs catalog access
                # (MySQL: SELECT on the mysql schema)
                from tidb_tpu.privilege import Priv
                cache0 = self.domain.priv_cache()
                ischema0 = self.domain.info_schema()
                if ischema0.has_db("mysql") and not \
                        cache0.request_verification(
                            self.user, self.host, "mysql", "",
                            Priv.SELECT):
                    raise SQLError(
                        f"SHOW GRANTS denied to user '{self.user}'@"
                        f"'{self.host}'")
            cache = self.domain.priv_cache()
            grants = cache.describe_grants(user, host or None)
            if not grants:
                grants = [f"GRANT USAGE ON *.* TO '{user}'@'%'"]
            return ResultSet([f"Grants for {user}"],
                             [(g,) for g in grants])
        return ResultSet(["info"], [])

    # -- ANALYZE / stats -----------------------------------------------------

    def _resolve_table(self, ts):
        ischema = self.domain.info_schema()
        db = (getattr(ts, "db", "") or self.current_db)
        return ischema.table(db, ts.name)

    def _resolve_table_or_err(self, ts):
        from tidb_tpu.schema.infoschema import SchemaError
        try:
            return self._resolve_table(ts)
        except SchemaError:
            raise SQLError(f"Table '{ts.name}' doesn't exist") from None

    def _exec_analyze(self, stmt: ast.AnalyzeStmt):
        """ANALYZE TABLE: full-scan stats build + persist (ref:
        executor/analyze.go:42; statistics/handle.go)."""
        from tidb_tpu.statistics import analyze_table
        handle = self.domain.stats_handle()
        for ts in stmt.tables:
            try:
                info = self._resolve_table(ts)
            except Exception as e:
                raise SQLError(str(e)) from None
            stats = analyze_table(self.storage, self.storage.current_ts(),
                                  info)
            handle.save(stats)
        return None

    def _dropped_table_ids(self, stmt) -> list:
        """Table ids about to be dropped/truncated, for stats cleanup."""
        sources = []
        if isinstance(stmt, ast.DropTableStmt):
            sources = stmt.tables
        elif isinstance(stmt, ast.TruncateTableStmt):
            sources = [stmt.table]
        elif isinstance(stmt, ast.DropDatabaseStmt):
            ischema = self.domain.info_schema()
            if ischema.has_db(stmt.name):
                return [ischema.table(stmt.name, n).id
                        for n in ischema.table_names(stmt.name)]
        out = []
        for ts in sources:
            try:
                out.append(self._resolve_table(ts).id)
            except Exception:
                pass
        return out

    def _note_dml_delta(self, stmt, n: int) -> None:
        ts = stmt.table
        if isinstance(ts, ast.TableSource):
            try:
                self.domain.stats_handle().note_dml(
                    self._resolve_table(ts).id, n)
            except Exception:
                pass

    def _exec_explain(self, stmt: ast.ExplainStmt) -> ResultSet:
        if stmt.analyze:
            return self._exec_explain_analyze(stmt.stmt)
        plan = self._planner().plan(stmt.stmt)
        lines = plan.explain().split("\n")
        return ResultSet(["plan"], [(l,) for l in lines])

    def _exec_explain_analyze(self, inner: ast.StmtNode) -> ResultSet:
        """EXPLAIN ANALYZE: execute the statement for real under a
        runtime-stats collector, then render the executed plan annotated
        with per-operator actuals (ref: the reference's EXPLAIN ANALYZE
        over RuntimeStatsColl, executor/explain.go)."""
        from tidb_tpu import config, memtrack, runtime_stats as rs
        if not isinstance(inner, (ast.SelectStmt, ast.UnionStmt,
                                  ast.InsertStmt, ast.UpdateStmt,
                                  ast.DeleteStmt)):
            raise SQLError(
                "EXPLAIN ANALYZE supports SELECT/UNION and DML statements")
        device = config.runtime_stats_device()
        coll = rs.StatsCollector(device=device)
        self._last_plan = None
        with rs.collecting(coll):
            self._run_stmt(inner)
        plan = self._last_plan
        if plan is None:
            raise SQLError("EXPLAIN ANALYZE: no plan was executed")
        # per-op mem comes from the statement's memory-tracker nodes
        # (host + device ledgers), collected by default — NOT from the
        # process-global backend watermark, which a concurrent
        # statement's allocations would contaminate
        mt = memtrack.current()
        rows = []
        for depth, node in plan.explain_nodes():
            st = coll.get(node)
            mnode = mt.get(node) if mt is not None else None
            mem = rs.fmt_bytes(mnode.peak_total()) \
                if mnode is not None else "-"
            est = "" if node.est_rows is None else f"{node.est_rows:.0f}"
            if st is None:
                rows.append(("  " * depth + node.explain_line(), est,
                             0, 0, "-", "-", mem, 0, "-", "-"))
                continue
            rows.append((
                "  " * depth + node.explain_line(), est,
                st.act_rows, st.loops, rs.fmt_ns(st.time_ns),
                rs.fmt_ns(st.device_time_ns) if device else "-",
                mem, st.cop_tasks, _fmt_pipeline(st), _fmt_kernel(st)))
        return ResultSet(["id", "est_rows", "act_rows", "loops", "time",
                          "device_time", "mem", "cop_tasks", "pipeline",
                          "kernel"],
                         rows)


def _fmt_pipeline(st) -> str:
    """EXPLAIN ANALYZE `pipeline` cell: how the operator's device work
    was coalesced (superchunks/source chunks), how full the padded
    buckets were, how long the host sat blocked on readback — and how
    often the operator fell back to the host path (the note that makes
    an invisible device->host cliff visible in the plan)."""
    from tidb_tpu import runtime_stats as rs
    fb = f" fallback={st.fallbacks}" if st.fallbacks else ""
    # encoded-execution mode (encoded / decoded / direct-agg /
    # fused:<fragment>): how the operator consumed its dict columns —
    # the note that makes an encoded->decoded regression diagnosable
    # from the operator's chair
    enc = f" enc={st.encoding}" if st.encoding else ""
    if not st.superchunks:
        return f"-{fb}{enc}" if fb or enc else "-"
    return (f"{st.superchunks}sc/{st.coalesced_chunks}ch "
            f"fill={st.fill_ratio():.2f} "
            f"stall={rs.fmt_ns(st.pipeline_stall_ns)}{fb}{enc}")


def _fmt_kernel(st) -> str:
    """EXPLAIN ANALYZE `kernel` cell: which kernel family served the
    operator, whether this statement paid a compile (miss) or rode the
    in-process (cached) / persistent (hit) compile cache, the achieved
    memory bandwidth, and where that sits against the platform's memory
    roofline — e.g. `hashagg compile=cached 12.3GB/s roof=0.18`."""
    if not st.kernel_family or not st.kernel_dispatches:
        return "-"
    from tidb_tpu import profiler
    s = st.kernel_family
    if st.kernel_compile:
        s += f" compile={st.kernel_compile}"
    if st.mode:
        s += f" mode={st.mode}"
    g = profiler.achieved_gbps(st.kernel_bytes, st.kernel_busy_ns)
    if g is not None:
        s += f" {g:.1f}GB/s"
        frac = profiler.roofline_fraction(st.kernel_bytes,
                                          st.kernel_busy_ns)
        if frac is not None:
            s += f" roof={frac:.2f}"
    return s


@dataclass
class _Prepared:
    stmt: ast.StmtNode
    markers: list          # ParamMarkers in occurrence order
    sql: str
    sid: int = 0
    name: str | None = None
    columns_meta: tuple | None = None   # memoized (names, field_types)


def _q(s: str) -> str:
    """Escape a string literal for the internal account SQL."""
    return str(s).replace("\\", "\\\\").replace("'", "\\'")


def _referenced_tables(stmt) -> list[tuple[str, str]]:
    """(db, table) pairs of every TableSource in the statement tree
    (subqueries included) — the privilege-check surface."""
    out: list[tuple[str, str]] = []
    seen: set[int] = set()

    def walk(x):
        if id(x) in seen or x is None:
            return
        seen.add(id(x))
        if isinstance(x, ast.TableSource):
            out.append(((x.db or "").lower(), x.name.lower()))
            return
        if isinstance(x, (list, tuple)):
            for item in x:
                walk(item)
            return
        if hasattr(x, "__dataclass_fields__"):
            for f in x.__dataclass_fields__:
                walk(getattr(x, f))

    walk(stmt)
    # dedupe, keep order
    uniq = []
    for p in out:
        if p not in uniq:
            uniq.append(p)
    return uniq


def ast_params(node) -> list:
    """Collect ParamMarker nodes of a statement in occurrence order."""
    out = []
    seen = set()

    def walk(x):
        if id(x) in seen:
            return
        seen.add(id(x))
        if isinstance(x, ast.ParamMarker):
            out.append(x)
            return
        if isinstance(x, (list, tuple)):
            for item in x:
                walk(item)
            return
        if hasattr(x, "__dataclass_fields__"):
            for f in x.__dataclass_fields__:
                walk(getattr(x, f))

    walk(node)
    return out


def _plan_cacheable(plan) -> bool:
    """Plans with correlated apply cells mutate during execution, and
    plans with volatile plan-time folds (NOW()) go stale — never share
    those via the cache."""
    from tidb_tpu.plan import physical as _ph
    if not plan.cacheable:
        return False
    if isinstance(plan, _ph.PhysApply) and plan.corr:
        return False
    for c in plan.children:
        if not _plan_cacheable(c):
            return False
    inner = getattr(plan, "inner", None)
    if inner is not None and not _plan_cacheable(inner):
        return False
    return True


def _type_name(c) -> str:
    ft = c.ft
    names = {TypeCode.LONGLONG: "bigint", TypeCode.LONG: "int",
             TypeCode.SHORT: "smallint", TypeCode.TINY: "tinyint",
             TypeCode.DOUBLE: "double", TypeCode.FLOAT: "float",
             TypeCode.NEWDECIMAL: f"decimal({ft.flen},{ft.frac})",
             TypeCode.VARCHAR: f"varchar({ft.flen})",
             TypeCode.STRING: f"char({ft.flen})",
             TypeCode.BLOB: "text", TypeCode.DATE: "date",
             TypeCode.DATETIME: "datetime",
             TypeCode.TIMESTAMP: "timestamp",
             TypeCode.DURATION: "time", TypeCode.YEAR: "year",
             TypeCode.JSON: "json"}
    if ft.tp in (TypeCode.ENUM, TypeCode.SET):
        kind = "enum" if ft.tp == TypeCode.ENUM else "set"
        members = ",".join(f"'{e}'" for e in ft.elems)
        return f"{kind}({members})"
    return names.get(ft.tp, "unknown")


def _format_chunk(ch) -> list[tuple]:
    """Chunk-layer values -> client values (Decimal objects, datetime
    strings)."""
    rows = []
    cols = ch.columns
    for i in range(ch.num_rows):
        row = []
        for c in cols:
            if not c.valid[i]:
                row.append(None)
                continue
            v = c.data[i]
            et = c.ft.eval_type
            if et == EvalType.DECIMAL:
                row.append(scaled_to_decimal(int(v), c.ft.frac))
            elif et == EvalType.DATETIME:
                row.append(format_datetime(int(v), c.ft.tp))
            elif et == EvalType.DURATION:
                from tidb_tpu.sqltypes import format_duration
                row.append(format_duration(int(v), c.ft.frac))
            elif isinstance(v, bytes) and c.ft.tp == TypeCode.JSON:
                # JSON text reaches clients as str; BLOB bytes stay raw
                row.append(v.decode("utf8", "replace"))
            elif hasattr(v, "item"):
                row.append(v.item())
            else:
                row.append(v)
        rows.append(tuple(row))
    return rows
