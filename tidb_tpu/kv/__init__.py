"""Engine-neutral transactional KV contract.

Reference: /root/reference/kv/kv.go:75-254 — Retriever/Mutator/MemBuffer/
Transaction/Snapshot/Storage/Iterator interfaces, isolation levels, request
types, and the membuffer/unionstore overlay (kv/memdb_buffer.go,
kv/union_store.go). Error taxonomy mirrors store/tikv errors so retry
machinery upstack is engine-independent.
"""

from __future__ import annotations

import abc
import threading
from dataclasses import dataclass, field
from enum import Enum, IntEnum
from typing import Iterable, Iterator, Optional

from tidb_tpu.util.sorteddict import SortedDict

__all__ = [
    "IsolationLevel", "Priority", "ReqType",
    "KVError", "KeyLockedError", "WriteConflictError", "TxnAbortedError",
    "RegionError", "NotFoundError", "RetryableError", "ServerBusyError",
    "EpochNotMatchError", "NotLeaderError", "StoreUnavailableError",
    "UndeterminedError", "StreamInterruptedError",
    "LockInfo", "Mutation", "MutationOp",
    "MemBuffer", "UnionStore", "Snapshot", "Transaction", "Storage",
    "KVRange", "CopRequest", "CopResponse", "Client",
    "TXN_ENTRY_SIZE_LIMIT", "TXN_TOTAL_SIZE_LIMIT",
]

# ref: kv/kv.go:65-72 size limits
TXN_ENTRY_SIZE_LIMIT = 6 * 1024 * 1024
TXN_TOTAL_SIZE_LIMIT = 100 * 1024 * 1024


class IsolationLevel(Enum):
    SI = "SI"   # snapshot isolation (default)
    RC = "RC"   # read committed: readers skip others' locks


class Priority(IntEnum):
    LOW = 0
    NORMAL = 1
    HIGH = 2


class ReqType(IntEnum):
    """Coprocessor request types. Ref: kv/kv.go:143-204 (Select/Index/DAG/
    Analyze)."""

    DAG = 103
    ANALYZE = 104


# ---------------------------------------------------------------------------
# Errors

class KVError(Exception):
    pass


class NotFoundError(KVError):
    pass


class RetryableError(KVError):
    """Base for errors the client may retry after backoff."""


class GCTooEarlyError(KVError):
    """Read snapshot is older than the GC safepoint (ref: safepoint.go;
    ErrGCTooEarly) — its MVCC versions may already be pruned."""


class SchemaChangedError(RetryableError):
    """The schema a txn planned against changed before its commit ts
    (ref: domain/schema_validator.go:35 + 2pc.go:653 checkSchemaValid).
    Retryable: the session replays the statement history against the
    fresh schema."""


@dataclass
class LockInfo:
    primary: bytes
    start_ts: int
    key: bytes
    ttl_ms: int = 3000


class KeyLockedError(RetryableError):
    def __init__(self, lock: LockInfo):
        super().__init__(f"key locked by txn {lock.start_ts}")
        self.lock = lock

    def __reduce__(self):
        # errors with non-message ctor args must rebuild from them (they
        # cross the storage-process RPC boundary, store/remote.py)
        return (KeyLockedError, (self.lock,))


class WriteConflictError(RetryableError):
    def __init__(self, key: bytes, start_ts: int, conflict_ts: int):
        super().__init__(f"write conflict on {key!r}: txn {start_ts} vs commit {conflict_ts}")
        self.key = key
        self.start_ts = start_ts
        self.conflict_ts = conflict_ts

    def __reduce__(self):
        return (WriteConflictError,
                (self.key, self.start_ts, self.conflict_ts))


class TxnAbortedError(KVError):
    """Txn was rolled back (e.g. by a lock resolver); commit must fail."""


class UndeterminedError(KVError):
    """Commit outcome unknown (network error on primary commit).
    Ref: store/tikv/2pc.go:421-431."""


class RegionError(RetryableError):
    """Base for region routing errors; client refreshes its region cache."""


class NotLeaderError(RegionError):
    def __init__(self, region_id: int, leader_store: int | None = None):
        super().__init__(f"region {region_id}: not leader")
        self.region_id = region_id
        self.leader_store = leader_store

    def __reduce__(self):
        return (NotLeaderError, (self.region_id, self.leader_store))


class EpochNotMatchError(RegionError):
    def __init__(self, region_id: int):
        super().__init__(f"region {region_id}: epoch not match")
        self.region_id = region_id

    def __reduce__(self):
        return (EpochNotMatchError, (self.region_id,))


class StoreUnavailableError(RegionError):
    """The targeted store is down (connection refused / dropped peer).
    A RegionError so clients invalidate + re-route exactly like the
    reference's store failover (region_request.go onSendFail)."""

    def __init__(self, region_id: int, store_id: int):
        super().__init__(f"region {region_id}: store {store_id} down")
        self.region_id = region_id
        self.store_id = store_id

    def __reduce__(self):
        return (StoreUnavailableError, (self.region_id, self.store_id))


class ServerBusyError(RetryableError):
    pass


class StreamInterruptedError(RetryableError):
    """A streamed coprocessor reply died mid-region (network drop,
    server restart, failpoint). Retryable: the client re-issues the
    stream from the last acked range boundary (store/copr.py), so no
    row is duplicated or lost. Ref: the stream-recreate path of
    copIteratorWorker.handleCopStreamResult, store/tikv/coprocessor.go."""


# ---------------------------------------------------------------------------
# Mutations

class MutationOp(Enum):
    PUT = "put"
    DELETE = "delete"
    LOCK = "lock"  # prewrite-only existence lock (PresumeKeyNotExists checks)


@dataclass
class Mutation:
    op: MutationOp
    key: bytes
    value: bytes = b""


# ---------------------------------------------------------------------------
# MemBuffer / UnionStore (txn-local write overlay)

_TOMBSTONE = object()


class MemBuffer:
    """Sorted txn-local write buffer. Ref: kv/memdb_buffer.go (red-black
    tree); here a SortedDict. Deletions are tombstones so they shadow the
    snapshot through the union overlay."""

    def __init__(self):
        self._d = SortedDict()
        self.size = 0

    def set(self, key: bytes, value: bytes) -> None:
        if len(value) > TXN_ENTRY_SIZE_LIMIT:
            raise KVError("entry too large")
        old = self._d.get(key)
        self._d[key] = value
        self.size += len(key) + len(value) - (len(old) if isinstance(old, bytes) else 0)
        if self.size > TXN_TOTAL_SIZE_LIMIT:
            raise KVError("transaction too large")

    def delete(self, key: bytes) -> None:
        self._d[key] = _TOMBSTONE

    def get(self, key: bytes):
        """-> value bytes, _TOMBSTONE, or None if absent."""
        return self._d.get(key)

    def __len__(self):
        return len(self._d)

    def iter_range(self, start: bytes | None, end: bytes | None):
        """Yields (key, value_or_tombstone) in [start, end) order."""
        keys = self._d.irange(start, end, inclusive=(True, False))
        for k in keys:
            yield k, self._d[k]

    def items(self):
        return self.iter_range(None, None)


class Snapshot(abc.ABC):
    """Point-in-time read view. Ref: kv/kv.go Snapshot."""

    @abc.abstractmethod
    def get(self, key: bytes) -> Optional[bytes]: ...

    @abc.abstractmethod
    def batch_get(self, keys: list[bytes]) -> dict[bytes, bytes]: ...

    @abc.abstractmethod
    def iter_range(self, start: bytes | None, end: bytes | None,
                   ) -> Iterator[tuple[bytes, bytes]]: ...


class UnionStore:
    """MemBuffer overlaid on a Snapshot (ref: kv/union_store.go +
    kv/union_iter.go merge iterator)."""

    def __init__(self, snapshot: Snapshot):
        self.membuf = MemBuffer()
        self.snapshot = snapshot
        # keys registered with presume-not-exists for lazy dup-key checks
        # (ref: kv/kv.go PresumeKeyNotExists option)
        self.presumed_not_exists: set[bytes] = set()

    def get(self, key: bytes) -> Optional[bytes]:
        v = self.membuf.get(key)
        if v is _TOMBSTONE:
            return None
        if v is not None:
            return v
        if key in self.presumed_not_exists:
            return None
        return self.snapshot.get(key)

    def set(self, key: bytes, value: bytes) -> None:
        self.membuf.set(key, value)

    def delete(self, key: bytes) -> None:
        self.membuf.delete(key)

    def iter_range(self, start: bytes | None, end: bytes | None):
        """Merge iterator: buffer entries shadow snapshot entries."""
        buf = self.membuf.iter_range(start, end)
        snap = self.snapshot.iter_range(start, end)
        bk, bv = next(buf, (None, None))
        sk, sv = next(snap, (None, None))
        while bk is not None or sk is not None:
            if sk is None or (bk is not None and bk <= sk):
                if bk == sk:
                    sk, sv = next(snap, (None, None))
                if bv is not _TOMBSTONE:
                    yield bk, bv
                bk, bv = next(buf, (None, None))
            else:
                yield sk, sv
                sk, sv = next(snap, (None, None))


# ---------------------------------------------------------------------------
# Transaction / Storage / coprocessor client

class Transaction(abc.ABC):
    """Ref: kv/kv.go Transaction."""

    start_ts: int

    @abc.abstractmethod
    def get(self, key: bytes) -> Optional[bytes]: ...

    @abc.abstractmethod
    def set(self, key: bytes, value: bytes) -> None: ...

    @abc.abstractmethod
    def delete(self, key: bytes) -> None: ...

    @abc.abstractmethod
    def iter_range(self, start, end) -> Iterator[tuple[bytes, bytes]]: ...

    @abc.abstractmethod
    def commit(self) -> None: ...

    @abc.abstractmethod
    def rollback(self) -> None: ...


@dataclass
class KVRange:
    start: bytes
    end: bytes  # exclusive


@dataclass
class CopRequest:
    """Pushed-down subplan request. Ref: kv/kv.go Request (Tp=DAG) +
    tipb.DAGRequest; `plan` is our serialized physical subplan."""

    tp: ReqType
    ranges: list[KVRange]
    plan: object
    start_ts: int
    concurrency: int = 0   # 0 = the tidb_tpu_cop_concurrency sysvar
    keep_order: bool = False
    desc: bool = False
    priority: Priority = Priority.NORMAL
    isolation: IsolationLevel = IsolationLevel.SI


@dataclass
class CopResponse:
    """One partial result (per region task)."""

    chunk: object  # tidb_tpu.chunk.Chunk
    range: KVRange | None = None


class Client(abc.ABC):
    """Coprocessor client: fans a CopRequest out per region.
    Ref: kv/kv.go Client, store/tikv/coprocessor.go CopClient."""

    @abc.abstractmethod
    def send(self, req: CopRequest) -> Iterable[CopResponse]: ...


class Storage(abc.ABC):
    """Ref: kv/kv.go Storage."""

    @abc.abstractmethod
    def begin(self) -> Transaction: ...

    @abc.abstractmethod
    def snapshot(self, ts: int) -> Snapshot: ...

    @abc.abstractmethod
    def current_ts(self) -> int: ...

    @abc.abstractmethod
    def client(self) -> Client: ...

    def close(self) -> None:
        pass
