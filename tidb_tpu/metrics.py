"""In-process metrics registry with Prometheus text exposition.

Reference: the per-package metrics.go files (10 of them — parse/compile/
execute histograms at session.go:682,739,755, 2PC action durations, cop
task counts, backoff totals). No client library dependency: counters and
histograms are plain atomics-under-lock, and /metrics on the status
server renders the standard text format scrapers consume — including
`# HELP` / `# TYPE` metadata so real Prometheus ingestion works, and
labeled histogram series (the per-operator tidb_tpu_op_* families need
an `op` label per series).
"""

from __future__ import annotations

import threading

__all__ = ["counter", "histogram", "gauge", "expose", "snapshot",
           "gauges_snapshot",
           "QUERY_DURATIONS", "QUERIES_TOTAL", "SLOW_QUERIES",
           "CONNECTIONS", "COP_TASKS", "QUERY_ERRORS",
           "COP_STREAM_FRAMES", "COP_STREAM_BYTES",
           "COP_STREAM_CREDIT_STALLS", "COP_STREAM_RESUMES",
           "OP_DURATIONS", "OP_ROWS", "OP_DEVICE_DURATIONS",
           "SUPERCHUNKS", "SUPERCHUNK_SOURCES", "SUPERCHUNK_FILL_ROWS",
           "SUPERCHUNK_BUCKET_ROWS", "PIPELINE_STALLS",
           "QUERY_MEM", "MEM_QUOTA_EXCEEDED", "DEVICE_PEAK",
           "HBM_CACHE_HITS", "HBM_CACHE_MISSES", "HBM_CACHE_EVICTIONS",
           "DEVICE_FALLBACKS", "JOIN_SPILL_PARTITIONS", "JOIN_HOT_ROWS",
           "CONNECTIONS_CURRENT", "ADMISSIONS", "ADMISSION_WAITS",
           "ADMISSION_QUEUE_DEPTH", "SCHED_STALLS", "SCHED_BYPASSES",
           "DELTA_ROWS", "DELTA_MERGES", "CACHE_DELTA_SERVES",
           "FLEET_JOURNAL_PULLS", "FLEET_PATCHED_ROWS",
           "FLEET_RPC_SECONDS", "FLEET_LOCAL_COP",
           "BYTES_ENCODED", "BYTES_DECODED_EQUIV",
           "FAILPOINT_FIRES", "WORKER_RESTARTS", "DISPATCH_TIMEOUTS",
           "DEVICE_QUARANTINES", "TRACES",
           "CLUSTER_SCRAPES", "MEMBER_START_TIME",
           "DEVICE_UTILIZATION", "HBM_OCCUPANCY", "CHIP_UTILIZATION",
           "COMPILE_CACHE_HITS", "COMPILE_CACHE_MISSES",
           "KERNEL_COMPILE_SECONDS", "KERNEL_DISPATCHES"]

_lock = threading.Lock()
_counters: dict[tuple[str, tuple], float] = {}       # guarded-by: _lock
_histograms: dict[tuple[str, tuple], "_Hist"] = {}   # guarded-by: _lock
_gauges: dict[tuple[str, tuple], float] = {}         # guarded-by: _lock

_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0)


class _Hist:
    __slots__ = ("buckets", "counts", "total", "sum")

    def __init__(self):
        self.buckets = _BUCKETS
        self.counts = [0] * (len(_BUCKETS) + 1)
        self.total = 0
        self.sum = 0.0

    def observe(self, v: float) -> None:
        i = 0
        for i, b in enumerate(self.buckets):
            if v <= b:
                break
        else:
            i = len(self.buckets)
        self.counts[i] += 1
        self.total += 1
        self.sum += v


def _label_key(labels: dict | None) -> tuple:
    return tuple(sorted((labels or {}).items()))


def _label_str(labels: tuple, extra: str = "") -> str:
    parts = [f'{k}="{v}"' for k, v in labels]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def counter(name: str, labels: dict | None = None, inc: float = 1) -> None:
    key = (name, _label_key(labels))
    with _lock:
        _counters[key] = _counters.get(key, 0) + inc


def histogram(name: str, value: float, labels: dict | None = None) -> None:
    key = (name, _label_key(labels))
    with _lock:
        h = _histograms.get(key)
        if h is None:
            h = _histograms[key] = _Hist()
        h.observe(value)


def gauge(name: str, value: float, labels: dict | None = None) -> None:
    """Set a gauge series to its current value (last write wins)."""
    key = (name, _label_key(labels))
    with _lock:
        _gauges[key] = float(value)


def gauges_snapshot() -> dict:
    """Gauge series only (flattened name{labels} keys) — the history
    sampler copies these per tick, and the conftest gauge-hygiene check
    asserts the *_current/*_depth families drain to zero."""
    with _lock:
        return {name + _label_str(labels): v
                for (name, labels), v in _gauges.items()}


def snapshot() -> dict:
    """Plain dict of counter/histogram values (tests / status JSON).
    Unlabeled series keep the historical flat keys (name, name_count,
    name_sum); labeled series append their label set."""
    with _lock:
        out = {}
        for (name, labels), v in _counters.items():
            out[name + _label_str(labels)] = v
        for (name, labels), v in _gauges.items():
            out[name + _label_str(labels)] = v
        for (name, labels), h in _histograms.items():
            lbl = _label_str(labels)
            out[name + "_count" + lbl] = h.total
            out[name + "_sum" + lbl] = round(h.sum, 6)
        return out


def expose() -> str:
    """Prometheus text exposition format, with # HELP/# TYPE per family
    so real scrapers ingest the endpoint cleanly."""
    lines = []
    with _lock:
        seen_meta: set[str] = set()

        def meta(name: str, tp: str) -> None:
            if name in seen_meta:
                return
            seen_meta.add(name)
            lines.append(f"# HELP {name} {_HELP.get(name, name)}")
            lines.append(f"# TYPE {name} {tp}")

        for (name, labels), v in sorted(_counters.items()):
            meta(name, "counter")
            lines.append(f"{name}{_label_str(labels)} {v}")
        for (name, labels), v in sorted(_gauges.items()):
            meta(name, "gauge")
            lines.append(f"{name}{_label_str(labels)} {v}")
        for (name, labels), h in sorted(_histograms.items()):
            meta(name, "histogram")
            acc = 0
            for b, c in zip(h.buckets, h.counts):
                acc += c
                le = 'le="%s"' % b
                lines.append(
                    f"{name}_bucket{_label_str(labels, le)} {acc}")
            inf = 'le="+Inf"'
            lines.append(
                f"{name}_bucket{_label_str(labels, inf)} {h.total}")
            lines.append(f"{name}_count{_label_str(labels)} {h.total}")
            lines.append(f"{name}_sum{_label_str(labels)} {h.sum}")
    return "\n".join(lines) + "\n"


# metric names (one place, mirroring the reference's metric families)
QUERY_DURATIONS = "tidb_tpu_query_duration_seconds"
QUERIES_TOTAL = "tidb_tpu_queries_total"
SLOW_QUERIES = "tidb_tpu_slow_queries_total"
CONNECTIONS = "tidb_tpu_connections_total"
COP_TASKS = "tidb_tpu_cop_tasks_total"
QUERY_ERRORS = "tidb_tpu_query_errors_total"
# streaming coprocessor (store/stream.py): framed partial responses,
# credit-window backpressure, mid-stream resume counts
COP_STREAM_FRAMES = "tidb_tpu_cop_stream_frames_total"
COP_STREAM_BYTES = "tidb_tpu_cop_stream_bytes_total"
COP_STREAM_CREDIT_STALLS = "tidb_tpu_cop_stream_credit_stalls_total"
COP_STREAM_RESUMES = "tidb_tpu_cop_stream_resumes_total"
# per-operator runtime stats (runtime_stats.py), labeled {op="HashAgg"}
OP_DURATIONS = "tidb_tpu_op_duration_seconds"
OP_ROWS = "tidb_tpu_op_act_rows_total"
OP_DEVICE_DURATIONS = "tidb_tpu_op_device_seconds"
# superchunk pipeline (ops/runtime.py), labeled {op=...}: fill ratio is
# derived as fill_rows / bucket_rows; stall is host time blocked on
# device readback inside the dispatch-ahead pipeline
SUPERCHUNKS = "tidb_tpu_superchunks_total"
SUPERCHUNK_SOURCES = "tidb_tpu_superchunk_source_chunks_total"
SUPERCHUNK_FILL_ROWS = "tidb_tpu_superchunk_fill_rows_total"
SUPERCHUNK_BUCKET_ROWS = "tidb_tpu_superchunk_bucket_rows_total"
PIPELINE_STALLS = "tidb_tpu_pipeline_stall_seconds"
# hierarchical memory tracking (memtrack.py): per-statement peak bytes
# (gauge, last statement's peak, labeled kind=host|device), quota
# OOM-action firings (counter, labeled action=spill|cancel), and the
# process-wide backend allocator watermark kept ONLY as a server-root
# gauge — per-op mem comes from the trackers, never the watermark
QUERY_MEM = "tidb_tpu_query_mem_bytes"
MEM_QUOTA_EXCEEDED = "tidb_tpu_mem_quota_exceeded_total"
DEVICE_PEAK = "tidb_tpu_device_peak_bytes"
# HBM-resident columnar region-block cache (store/device_cache.py): a
# hit serves a dispatch straight from device-resident columns (zero
# host->device bytes); evictions count LRU/budget drops AND stale-
# version invalidation drops
HBM_CACHE_HITS = "tidb_tpu_hbm_cache_hits_total"
HBM_CACHE_MISSES = "tidb_tpu_hbm_cache_misses_total"
HBM_CACHE_EVICTIONS = "tidb_tpu_hbm_cache_evictions_total"
# device->host execution fallbacks (labeled {op=...,reason=capacity|
# collision|unsupported|mesh}): every time an operator planned for the
# device lands on the host numpy path instead. Before the hybrid
# join/agg this happened invisibly inside broad except nets; now each
# one is counted and surfaced in EXPLAIN ANALYZE
DEVICE_FALLBACKS = "tidb_tpu_device_fallback_total"
# hybrid hash join (ops/hybrid.py): build partitions shed from HBM to
# host staging by the memtrack quota spill action, and probe rows routed
# through the heavy-hitter broadcast lane
JOIN_SPILL_PARTITIONS = "tidb_tpu_join_spill_partitions_total"
JOIN_HOT_ROWS = "tidb_tpu_join_hot_lane_rows_total"
# concurrent serving (tidb_tpu/sched.py + server accept loop): live
# connection count, statement admission outcomes/wait/queue against
# tidb_tpu_server_mem_quota, and the device scheduler's dispatch-slot
# stalls (time statements spent waiting for their round-robin grant)
# and bypasses (dispatches that proceeded unscheduled past the valve)
CONNECTIONS_CURRENT = "tidb_tpu_connections_current"
ADMISSIONS = "tidb_tpu_admission_total"
ADMISSION_WAITS = "tidb_tpu_admission_wait_seconds"
ADMISSION_QUEUE_DEPTH = "tidb_tpu_admission_queue_depth"
SCHED_STALLS = "tidb_tpu_sched_stall_seconds"
SCHED_BYPASSES = "tidb_tpu_sched_bypass_total"
# MVCC delta store (store/delta.py): staged committed-row deltas kept
# per table so cached columnar blocks serve base + delta under OLTP
# writes instead of re-colding; merges fold deltas back into base
# blocks (labeled by what triggered them)
DELTA_ROWS = "tidb_tpu_delta_rows_current"
DELTA_MERGES = "tidb_tpu_delta_merge_total"
CACHE_DELTA_SERVES = "tidb_tpu_cache_served_with_delta_total"
# fleet serving (store/fleetcop.py, store/remote.py): N SQL-server
# processes share one store plane; each keeps its own chunk + HBM
# caches coherent by pulling delta-journal windows over the wire
FLEET_JOURNAL_PULLS = "tidb_tpu_fleet_journal_pulls_total"
FLEET_PATCHED_ROWS = "tidb_tpu_fleet_journal_patched_rows_total"
FLEET_RPC_SECONDS = "tidb_tpu_fleet_remote_rpc_seconds"
FLEET_LOCAL_COP = "tidb_tpu_fleet_local_cop_total"
# encoded execution (ops/encoded.py): input bytes device dispatches
# actually staged/read (dict codes + validity at the padded bucket) vs
# the decoded-equivalent footprint of the same inputs — BENCH's
# per-query bytes_touched column diffs these to audit the compression
# win (ROADMAP item 4)
BYTES_ENCODED = "tidb_tpu_device_bytes_encoded_total"
BYTES_DECODED_EQUIV = "tidb_tpu_device_bytes_decoded_equiv_total"
# fault injection + device-plane recovery (util/failpoint.py, sched.py,
# util/supervisor.py): armed failpoint firings (labeled {name=...}),
# supervised background workers restarted after a crash (labeled
# {worker=...}), dispatch-watchdog cancellations past
# tidb_tpu_dispatch_timeout_ms, and device quarantine transitions
# (labeled {event=quarantine|readmit})
FAILPOINT_FIRES = "tidb_tpu_failpoint_fires_total"
WORKER_RESTARTS = "tidb_tpu_worker_restarts_total"
DISPATCH_TIMEOUTS = "tidb_tpu_dispatch_timeout_total"
DEVICE_QUARANTINES = "tidb_tpu_device_quarantine_total"
# statement tracing (trace.py): span trees retained into the bounded
# server trace ring, labeled by what retained them
# (sampled|slow|forced)
TRACES = "tidb_tpu_statement_traces_total"
# cluster fan-out (util/statusclient.fetch_all): per-member fetch
# outcomes of the cluster_* / /fleet/* surfaces. Labeled by outcome
# only — NEVER by member (the metric-cardinality rule: members churn,
# and the per-member attribution lives in cluster_members itself)
CLUSTER_SCRAPES = "tidb_tpu_cluster_scrape_total"
# member identity stamp on the /metrics exposition (server/status.py
# renders it with the member id + role as labels — hand-rendered
# there, not a registry series, because the id is per-process)
MEMBER_START_TIME = "tidb_tpu_member_start_time_seconds"
# continuous resource metering (meter.py + metrics_history.py): the
# history sampler derives these each tick — device busy-ns per wall
# interval (can exceed 1.0 under dispatch overlap; that overlap IS the
# pipeline working) and the HBM region-block cache's resident bytes
# over its tidb_tpu_device_cache_bytes budget
DEVICE_UTILIZATION = "tidb_tpu_device_utilization_ratio"
HBM_OCCUPANCY = "tidb_tpu_hbm_occupancy_ratio"
# per-chip slot busy-time over the sampler interval, labeled {chip}
# (bounded by the plane's device count): the scheduler's placement
# signal surfaced as a series, and the serve bench's balance figure
CHIP_UTILIZATION = "tidb_tpu_chip_utilization_ratio"
# kernel profiling plane (tidb_tpu/profiler.py + util/compile_cache.py):
# persistent XLA compile-cache hit/miss counts promoted from BENCH-json-
# only to first-class families, per-family kernel first-call compile
# wall time (trace+compile+load, attributed hit|miss|cached by diffing
# the persistent-cache counters around it), and per-family dispatch
# counts. Labeled {family} only (hashagg|scalaragg|streamagg|fragment|
# mesh|plane — a bounded vocabulary, per the cardinality rule)
COMPILE_CACHE_HITS = "tidb_tpu_compile_cache_hits_total"
COMPILE_CACHE_MISSES = "tidb_tpu_compile_cache_misses_total"
KERNEL_COMPILE_SECONDS = "tidb_tpu_kernel_compile_seconds"
KERNEL_DISPATCHES = "tidb_tpu_kernel_dispatch_total"

_HELP = {
    QUERY_DURATIONS: "Statement wall time through Session.execute.",
    QUERIES_TOTAL: "Statements executed, by statement type.",
    SLOW_QUERIES: "Statements at/above tidb_tpu_slow_query_ms.",
    CONNECTIONS: "Client connections accepted.",
    COP_TASKS: "Coprocessor region tasks dispatched.",
    QUERY_ERRORS: "Statements that raised an error.",
    COP_STREAM_FRAMES: "Streamed coprocessor frames produced.",
    COP_STREAM_BYTES: "Raw bytes carried by streamed frames.",
    COP_STREAM_CREDIT_STALLS:
        "Producer stalls waiting for client credit.",
    COP_STREAM_RESUMES: "Mid-stream resumes after interruption.",
    OP_DURATIONS: "Per-operator host wall time per statement, by op.",
    OP_ROWS: "Per-operator actual output rows, by op.",
    OP_DEVICE_DURATIONS:
        "Per-operator device time (block_until_ready), by op.",
    SUPERCHUNKS: "Coalesced superchunk device dispatches, by op.",
    SUPERCHUNK_SOURCES:
        "Source chunks folded into superchunks, by op.",
    SUPERCHUNK_FILL_ROWS:
        "Live rows carried by superchunks, by op.",
    SUPERCHUNK_BUCKET_ROWS:
        "Padded bucket rows dispatched for superchunks, by op.",
    PIPELINE_STALLS:
        "Per-operator host time blocked on device readback, by op.",
    QUERY_MEM:
        "Last statement's peak tracked bytes, by ledger kind.",
    MEM_QUOTA_EXCEEDED:
        "Quota OOM-action firings, by action (spill|cancel).",
    DEVICE_PEAK:
        "Backend allocator peak-bytes watermark (process-wide).",
    HBM_CACHE_HITS:
        "Dispatches served from the HBM region-block cache.",
    HBM_CACHE_MISSES:
        "HBM region-block cache misses (upload paid).",
    HBM_CACHE_EVICTIONS:
        "HBM region-block cache entries dropped (LRU/stale/shed).",
    DEVICE_FALLBACKS:
        "Device operators that fell back to the host path, "
        "by op and reason.",
    JOIN_SPILL_PARTITIONS:
        "Hybrid-join build partitions spilled from HBM under quota.",
    JOIN_HOT_ROWS:
        "Probe rows routed through the heavy-hitter join lane.",
    CONNECTIONS_CURRENT: "Client connections currently open.",
    ADMISSIONS:
        "Statement admission decisions, by outcome "
        "(admitted|queued|shed|rejected).",
    ADMISSION_WAITS:
        "Time statements spent in the admission controller.",
    ADMISSION_QUEUE_DEPTH:
        "Statements currently waiting for admission.",
    SCHED_STALLS:
        "Time statements spent waiting for a device dispatch slot.",
    SCHED_BYPASSES:
        "Dispatches that proceeded unscheduled past the bypass valve.",
    DELTA_ROWS:
        "Committed row deltas currently staged in the delta store.",
    DELTA_MERGES:
        "Delta-store merges into new base blocks, by trigger "
        "(rows|ratio|shed|close).",
    CACHE_DELTA_SERVES:
        "Cache reads served as base + delta instead of re-scanning.",
    FLEET_JOURNAL_PULLS:
        "Journal-window pulls from the store plane, by outcome "
        "(window|empty|stale|meta).",
    FLEET_PATCHED_ROWS:
        "Rows patched into resident fleet cache blocks from shipped "
        "journal windows.",
    FLEET_RPC_SECONDS:
        "Remote store RPC latency by method.",
    FLEET_LOCAL_COP:
        "Fleet coprocessor reads, by serving path (cached|store).",
    BYTES_ENCODED:
        "Input bytes device dispatches actually staged or read "
        "(dictionary codes + validity at the padded bucket).",
    BYTES_DECODED_EQUIV:
        "Decoded-equivalent footprint of the same dispatch inputs.",
    FAILPOINT_FIRES:
        "Armed failpoint firings, by declared point name.",
    WORKER_RESTARTS:
        "Supervised background workers restarted after a crash, "
        "by worker.",
    DISPATCH_TIMEOUTS:
        "Statements cancelled by the dispatch watchdog past "
        "tidb_tpu_dispatch_timeout_ms.",
    DEVICE_QUARANTINES:
        "Device quarantine transitions after repeated faults, "
        "by event (quarantine|readmit).",
    TRACES:
        "Statement traces retained into the server trace ring, "
        "by reason (sampled|slow|forced).",
    CLUSTER_SCRAPES:
        "Cluster fan-out fetches against member status ports, "
        "by outcome (ok|timeout|error).",
    MEMBER_START_TIME:
        "This member's process start time (unix seconds), labeled "
        "with its fleet member id and role.",
    DEVICE_UTILIZATION:
        "Device busy-time per wall second over the last history "
        "sampler interval (dispatch overlap can push it past 1.0).",
    HBM_OCCUPANCY:
        "HBM region-block cache resident bytes over its budget.",
    CHIP_UTILIZATION:
        "Per-chip scheduler-slot busy time per wall second over the "
        "last history sampler interval, labeled by plane chip index.",
    COMPILE_CACHE_HITS:
        "Persistent XLA compile-cache hits (jax.monitoring events).",
    COMPILE_CACHE_MISSES:
        "Persistent XLA compile-cache misses (compiles paid).",
    KERNEL_COMPILE_SECONDS:
        "Kernel first-call wall time (trace+compile+cache load), "
        "by kernel family.",
    KERNEL_DISPATCHES:
        "Device kernel dispatches, by kernel family.",
}
