"""In-process metrics registry with Prometheus text exposition.

Reference: the per-package metrics.go files (10 of them — parse/compile/
execute histograms at session.go:682,739,755, 2PC action durations, cop
task counts, backoff totals). No client library dependency: counters and
histograms are plain atomics-under-lock, and /metrics on the status
server renders the standard text format scrapers consume.
"""

from __future__ import annotations

import threading

__all__ = ["counter", "histogram", "expose", "snapshot",
           "QUERY_DURATIONS", "QUERIES_TOTAL", "SLOW_QUERIES",
           "CONNECTIONS", "COP_TASKS", "QUERY_ERRORS",
           "COP_STREAM_FRAMES", "COP_STREAM_BYTES",
           "COP_STREAM_CREDIT_STALLS", "COP_STREAM_RESUMES"]

_lock = threading.Lock()
_counters: dict[tuple[str, tuple], float] = {}
_histograms: dict[str, "_Hist"] = {}

_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0)


class _Hist:
    __slots__ = ("buckets", "counts", "total", "sum")

    def __init__(self):
        self.buckets = _BUCKETS
        self.counts = [0] * (len(_BUCKETS) + 1)
        self.total = 0
        self.sum = 0.0

    def observe(self, v: float) -> None:
        i = 0
        for i, b in enumerate(self.buckets):
            if v <= b:
                break
        else:
            i = len(self.buckets)
        self.counts[i] += 1
        self.total += 1
        self.sum += v


def counter(name: str, labels: dict | None = None, inc: float = 1) -> None:
    key = (name, tuple(sorted((labels or {}).items())))
    with _lock:
        _counters[key] = _counters.get(key, 0) + inc


def histogram(name: str, value: float) -> None:
    with _lock:
        h = _histograms.get(name)
        if h is None:
            h = _histograms[name] = _Hist()
        h.observe(value)


def snapshot() -> dict:
    """Plain dict of counter values (tests / status JSON)."""
    with _lock:
        out = {}
        for (name, labels), v in _counters.items():
            key = name if not labels else \
                name + "{" + ",".join(f'{k}="{val}"'
                                      for k, val in labels) + "}"
            out[key] = v
        for name, h in _histograms.items():
            out[name + "_count"] = h.total
            out[name + "_sum"] = round(h.sum, 6)
        return out


def expose() -> str:
    """Prometheus text exposition format."""
    lines = []
    with _lock:
        for (name, labels), v in sorted(_counters.items()):
            lbl = "{" + ",".join(f'{k}="{val}"' for k, val in labels) + "}" \
                if labels else ""
            lines.append(f"{name}{lbl} {v}")
        for name, h in sorted(_histograms.items()):
            acc = 0
            for b, c in zip(h.buckets, h.counts):
                acc += c
                lines.append(f'{name}_bucket{{le="{b}"}} {acc}')
            lines.append(f'{name}_bucket{{le="+Inf"}} {h.total}')
            lines.append(f"{name}_count {h.total}")
            lines.append(f"{name}_sum {h.sum}")
    return "\n".join(lines) + "\n"


# metric names (one place, mirroring the reference's metric families)
QUERY_DURATIONS = "tidb_tpu_query_duration_seconds"
QUERIES_TOTAL = "tidb_tpu_queries_total"
SLOW_QUERIES = "tidb_tpu_slow_queries_total"
CONNECTIONS = "tidb_tpu_connections_total"
COP_TASKS = "tidb_tpu_cop_tasks_total"
QUERY_ERRORS = "tidb_tpu_query_errors_total"
# streaming coprocessor (store/stream.py): framed partial responses,
# credit-window backpressure, mid-stream resume counts
COP_STREAM_FRAMES = "tidb_tpu_cop_stream_frames_total"
COP_STREAM_BYTES = "tidb_tpu_cop_stream_bytes_total"
COP_STREAM_CREDIT_STALLS = "tidb_tpu_cop_stream_credit_stalls_total"
COP_STREAM_RESUMES = "tidb_tpu_cop_stream_resumes_total"
